"""Elastic coordination server: group view, degraded-world aggregation,
epoch-aware barriers, crash-safe snapshots.

This is the server half of the elastic dist KVStore (kvstore.py,
``MXNET_KV_ELASTIC=1``). The reference's ps-lite stack could only
*detect* a dead node (kvstore.h:235 get_num_dead_node); here worker
failure is a recoverable membership event, the property TensorFlow's
coordinated membership + state restore gives (Abadi et al., 2016):

- **GroupView** — the live-rank set plus a monotonically increasing
  membership epoch. A heartbeat lapse past ``MXNET_KV_EVICT_AFTER``
  evicts the rank and bumps the epoch; a (re-)registration admits the
  rank at the boundary the bump creates.
- **Aggregator** — server-side sync parameter aggregation (the role of
  the reference's sync UpdateBuf, kvstore_dist_server.h:164-198). Each
  live rank contributes one gradient per key per round; a round
  completes when every live rank has contributed. An eviction drops the
  dead rank's in-flight contributions and re-checks pending rounds
  against the reduced group, rescaling the sum by
  ``world / contributors`` so the update magnitude matches the
  fault-free run (a *degraded step*). Contributions may arrive as
  low-precision wire payloads (``MXNET_KV_QUANTIZE``, mxnet_tpu/
  quantize.py): they are stored encoded and dequant-summed at round
  completion, so the guardian guard and the optimizer always ride the
  dequantized values while the TCP bytes shrink ~4x.
- **Sharded weight update** (``MXNET_KV_SHARD_UPDATE=1``, ZeRO-1 after
  arXiv 2004.13336) — the optimizer runs on the *workers*, each owning
  a byte-balanced shard of the keys: a completed round parks the merged
  gradient; the owner's next pull is answered ``status="update"`` with
  that gradient (quantized when the worker asked for a wire mode — a
  merged gradient is still a gradient), the owner applies its local
  optimizer and ships the new weight back via ``put_weight``, and
  everyone else's pull blocks on the weight round, not the merge
  round. Ownership is recomputed from the live set at every membership
  epoch (an evicted owner's pending update is handed to the key's next
  owner; its optimizer state for the reassigned keys restarts — the
  documented ZeRO-1 elasticity cost), and rejoiners receive the shard
  map with their register reply. Weights are NEVER quantized — only
  gradients cross the wire low-precision
  (docs/how_to/low_precision_comms.md).
- **Barriers** — generation-counted arrival sets re-checked on every
  view change, so survivors rendezvous on the reduced group instead of
  deadlocking on a corpse.
- **Snapshots** — every ``MXNET_KV_SNAPSHOT_SECS`` the full server
  state (weights via model._write_params_atomic, optimizer pickle +
  membership + round counters via the same tmp→fsync→rename discipline)
  lands on disk, so a restarted coordinator resumes where it died.

The server is deliberately jax-free at import time (stdlib + numpy);
the optimizer updater and the .params codec are imported lazily so a
standalone coordinator (``python -m mxnet_tpu.elastic``) starts fast
and never touches an accelerator.
"""
from __future__ import annotations

import logging
import os
import pickle
import socket
import socketserver
import threading
import time

import numpy as _np

from ..base import MXNetError
from ..resilience import faults as _faults
from .. import quantize as _quant
from .. import telemetry as _tel
from . import protocol

__all__ = ["GroupView", "Aggregator", "ElasticCoordinator"]

# server-side cap on a long-poll park (pull/barrier_wait "wait" field):
# must sit comfortably below protocol.call's 30s socket timeout, or a
# not-ready reply lands after the client's recv deadline and a healthy
# coordinator reads as a transport failure
_WAIT_CAP = 25.0


class GroupView:
    """Live-rank set + membership epoch. Pure state machine (no IO, no
    clock of its own — callers pass ``now``), so membership logic is
    unit-testable without sockets or sleeps."""

    def __init__(self, world, evict_after=10.0):
        if world < 1:
            raise MXNetError("GroupView world size must be >= 1")
        self.world = int(world)          # nominal size (rescale target)
        self.evict_after = float(evict_after)
        self.epoch = 0
        self.live = set()
        self.evicted = set()
        self.departed = set()            # graceful leave(): not a failure
        self.beats = {}                  # rank -> last beat (caller clock)
        self.seen = set()                # every rank ever registered
        self.evictions_total = 0
        self.rejoins_total = 0

    def register(self, rank, now):
        """Admit ``rank`` into the view (initial join or rejoin). Any
        membership change bumps the epoch — the boundary at which the
        joiner enters. Returns (epoch, rejoined). A rejoin is a
        RE-ADMISSION (seen before, not currently live): a duplicated or
        retried register RPC from a live rank must not inflate
        rejoins_total — chaos legs treat that counter as proof of a
        real recovery."""
        rank = int(rank)
        rejoined = rank in self.seen and rank not in self.live
        self.seen.add(rank)
        self.beats[rank] = now
        if rank not in self.live:
            self.live.add(rank)
            self.evicted.discard(rank)
            self.departed.discard(rank)
            self.epoch += 1
        if rejoined:
            self.rejoins_total += 1
        return self.epoch, rejoined

    def beat(self, rank, now):
        """Record liveness; beats from non-members are ignored (a zombie
        evictee learns its fate from its next real op, not here)."""
        if rank in self.live:
            self.beats[rank] = now

    def lapsed(self, now):
        """Ranks whose heartbeat is older than evict_after."""
        return [r for r in sorted(self.live)
                if now - self.beats.get(r, now) > self.evict_after]

    def evict(self, rank):
        """Remove a dead rank; bumps the epoch. Idempotent."""
        if rank not in self.live:
            return False
        self.live.discard(rank)
        self.evicted.add(rank)
        self.epoch += 1
        self.evictions_total += 1
        return True

    def leave(self, rank):
        """Graceful departure (end of training): the rank exits the
        view — and so exits every completion condition — without being
        counted as a casualty."""
        if rank not in self.live:
            return False
        self.live.discard(rank)
        self.departed.add(rank)
        self.epoch += 1
        return True

    def snapshot_state(self):
        return {
            "world": self.world, "epoch": self.epoch,
            "live": sorted(self.live), "evicted": sorted(self.evicted),
            "departed": sorted(self.departed), "seen": sorted(self.seen),
            "evictions_total": self.evictions_total,
            "rejoins_total": self.rejoins_total,
        }

    def restore_state(self, st, now):
        self.world = int(st["world"])
        self.epoch = int(st["epoch"])
        # a restarted coordinator cannot know which of its former live
        # ranks survived the outage: give them all a fresh grace period
        # and let heartbeats (or their absence) sort it out
        self.live = set(st["live"])
        self.evicted = set(st["evicted"])
        self.departed = set(st["departed"])
        self.seen = set(st["seen"])
        self.beats = {r: now for r in self.live}
        self.evictions_total = int(st["evictions_total"])
        self.rejoins_total = int(st["rejoins_total"])


class Aggregator:
    """Per-key round aggregation with degraded-world rescaling.

    Sync workers push key k's round r+1 only after pulling round r, so
    at most one round per key is ever open — ``pending[key]`` holds the
    contributions for round ``done[key] + 1``. Completion is checked
    against the *current* live set: contributors ⊇ live completes the
    round (contributions from since-departed ranks still count; an
    evicted rank's are dropped by ``drop_rank`` first, per the
    in-flight-loss contract)."""

    def __init__(self, world):
        self.world = int(world)
        self.weights = {}        # key -> numpy array (authoritative copy)
        self.done = {}           # key -> completed (merged) round count
        self.w_done = {}         # key -> rounds whose WEIGHT landed; lags
        #                          done only in shard mode, between a
        #                          merge and the owner's put_weight
        self.pending = {}        # key -> {rank: numpy grad | wire payload}
        self._acc = {}           # key -> [running sum, n folded, encoded]:
        #                          contributions fold into the sum as they
        #                          ARRIVE (overlapped with the other ranks'
        #                          transfers) so round completion pays only
        #                          the rescale, not an O(world) decode+sum
        #                          on the critical path. Dropped on
        #                          eviction/replacement/mixed-precision
        #                          rounds — complete_ready rebuilds from
        #                          pending (the slow exact path) whenever
        #                          the fold count mismatches.
        self.grads = {}          # key -> merged grad awaiting its owner
        #                          (shard mode only)
        self.opt_blob = None     # pickled optimizer, as shipped
        self._updater = None
        self.shard_update = False
        self.degraded_steps_total = 0
        self.updates_total = 0
        self.guard_skips_total = 0      # poisoned rounds nobody applied
        self.guard_nonfinite_total = 0  # of those, non-finite merges

    # -- optimizer -------------------------------------------------------------
    def set_optimizer(self, blob, shard=False, preloaded=None):
        """First optimizer wins: set_optimizer is SPMD (every worker
        ships the same pickle) and a rejoiner's re-ship must not reset
        the server's accumulated optimizer state (momentum etc.).

        With ``shard`` (MXNET_KV_SHARD_UPDATE=1 on the workers) the
        blob is kept only for rejoiners to adopt — the update runs
        WORKER-side on each key's owner, so no server updater is built
        and per-rank (and per-server) optimizer-state memory scales
        ~1/world instead of full replicas.

        With ``preloaded`` the caller already unpickled the blob
        OUTSIDE the coordinator's state lock (the dispatch path does —
        the same discipline as push decode), so the lock-held section
        only builds the updater."""
        if self.opt_blob is not None:
            return False
        if shard:
            self.shard_update = True
            self.opt_blob = blob
            return True
        from .. import optimizer as opt  # lazy: needs the jax stack

        # the in-line pickle.loads fallback only runs from lock-free
        # callers (snapshot restore at construction); the dispatch path
        # always hands in ``preloaded`` decoded outside the state lock
        self._updater = opt.get_updater(
            pickle.loads(blob) if preloaded is None  # mxlint: disable
            else preloaded)
        self.opt_blob = blob
        return True

    # -- keys ------------------------------------------------------------------
    def init_key(self, key, arr):
        """First init wins; later inits (other ranks, rejoiners) adopt
        the server copy — the reference server's init semantics."""
        if key not in self.weights:
            self.weights[key] = _np.array(arr, copy=True)
            self.done[key] = 0
            self.w_done[key] = 0
        return self.weights[key], self.done[key]

    # -- gradient rounds -------------------------------------------------------
    def contribute(self, key, rank, rnd, arr, decoded=None):
        """Record rank's gradient for round ``rnd`` of ``key``.
        Returns 'ok' | 'stale' (round already completed — an idempotent
        retry after a lost ack, or a pre-eviction zombie catching up) |
        'resync' (the pusher is AHEAD of the server: a coordinator that
        restarted from a snapshot older than the group's progress; the
        lost rounds are lost — snapshot-cadence data loss — and the
        pusher must fast-BACKWARD to the restored round and replay)."""
        if key not in self.weights:
            raise MXNetError("elastic push of uninitialized key %r" % key)
        cur = self.done[key]
        if rnd <= cur:
            return "stale"
        if rnd != cur + 1:
            logging.warning(
                "elastic: rank %s pushed key %r round %d but server is at "
                "%d — resyncing the pusher (coordinator restarted from an "
                "older snapshot?)", rank, key, rnd, cur)
            return "resync"
        pend = self.pending.setdefault(key, {})
        if int(rank) in pend:
            # idempotent retry replacing an in-flight contribution: the
            # running sum can't subtract exactly in float — rebuild
            self._acc.pop(key, None)
            pend[int(rank)] = arr
            return "ok"
        pend[int(rank)] = arr
        self._fold(key, arr, first=len(pend) == 1, decoded=decoded)
        return "ok"

    def _fold(self, key, arr, first, decoded=None):
        """Fold one arriving contribution into the round's running sum
        (arrival order — exactly the order the completion loop would
        sum). All-quantized rounds accumulate f32, full-precision
        rounds f64; a MIXED round (some ranks with the codec off)
        drops the accumulator and lets complete_ready rebuild with the
        deterministic whole-set dtype choice."""
        enc = _quant.is_encoded(arr)
        if decoded is not None:
            dec = decoded  # dequantized outside the lock by the caller
        else:
            dec = _quant.decode(arr, dtype=_np.float32) if enc else arr
        if first:
            self._acc[key] = [
                dec.astype(_np.float32 if enc else _np.float64), 1, enc]
            return
        acc = self._acc.get(key)
        if acc is None:
            return  # already marked for rebuild
        if enc != acc[2]:
            self._acc.pop(key, None)
            return
        _np.add(acc[0], dec, out=acc[0])
        acc[1] += 1

    def drop_rank(self, rank):
        """Drop an evicted rank's in-flight contributions."""
        for key, contribs in self.pending.items():
            if contribs.pop(int(rank), None) is not None:
                self._acc.pop(key, None)  # rebuild without the corpse

    def complete_ready(self, live):
        """Finish every pending round whose contributors cover ``live``.
        Returns the list of completed keys. With live empty (everyone
        gone) nothing completes — there is nobody left to pull."""
        if not live:
            return []
        from ..context import cpu       # lazy: jax-backed
        from ..kvstore import _key_int
        from ..ndarray import NDArray

        finished = []
        for key in list(self.pending):
            contribs = self.pending[key]
            if not contribs or not live.issubset(contribs.keys()):
                continue
            if self.shard_update and \
                    self.w_done.get(key, 0) < self.done.get(key, 0):
                # the previous round's merged gradient is still parked
                # for its owner: merging now would overwrite it and
                # silently lose that round's weight update. Hold the
                # round; put_weight re-checks and completes it.
                continue
            acc = self._acc.pop(key, None)
            if acc is not None and acc[1] == len(contribs):
                # fast path: every contribution already folded at
                # arrival — completion pays only the rescale below
                total = acc[0]
            else:
                # rebuild: eviction, replacement, or a mixed-precision
                # round. f64 on the full-precision path (bit-stable
                # degraded rescale, the chaos-bisect contract); an all-
                # quantized round accumulates f32 — the codes carry ~8
                # bits of mantissa, so f64 buys nothing
                encoded = [_quant.is_encoded(v) for v in contribs.values()]
                acc_t = _np.float32 if all(encoded) else _np.float64
                total = None
                for arr in contribs.values():
                    arr = _quant.decode(arr, dtype=_np.float32) \
                        if _quant.is_encoded(arr) else arr
                    if total is None:
                        total = arr.astype(acc_t)  # contribs stay pristine
                    else:
                        _np.add(total, arr, out=total)
            scale = self.world / float(len(contribs))
            if len(contribs) < self.world:
                self.degraded_steps_total += 1
            merged = (total * scale).astype(
                self.weights[key].dtype, copy=False)
            if self._guard_poisoned(merged):
                # Training-run guardian, server half (docs/how_to/
                # guardrails.md): a poisoned merged gradient — one NaN
                # contribution poisons the whole sum — is SKIPPED for
                # the entire group at once: the round completes with
                # the weights untouched, every live rank pulls the same
                # unchanged value, and the skip is counted (mirrored to
                # guardian.skipped_steps in every worker's journal).
                # This IS the any-rank-poisons→all-ranks-skip vote,
                # riding the round protocol with zero extra RPCs.
                del self.pending[key]
                self.done[key] += 1
                # a skipped round leaves the weights untouched, so its
                # weight is "ready" immediately — also in shard mode,
                # where no owner update will ever come for it
                self.w_done[key] = self.done[key]
                self.guard_skips_total += 1
                finished.append(key)
                logging.warning(
                    "elastic guardian: skipped poisoned round %d of key "
                    "%r for the whole group (%d skips total)",
                    self.done[key], key, self.guard_skips_total)
                continue
            if self.shard_update:
                # park the merged gradient for the key's owner: the
                # round is MERGED (done advances, so next-round pushes
                # are accepted) but its weight is not ready until the
                # owner's put_weight lands (w_done lags)
                self.grads[key] = merged
                del self.pending[key]
                self.done[key] += 1
                finished.append(key)
                continue
            if self._updater is not None:
                # the server-side optimizer update (device math + D2H)
                # runs inside the coordinator's critical section BY
                # DESIGN: the non-shard round protocol's weights must
                # be updated atomically with the round counters, and
                # MXNET_KV_SHARD_UPDATE=1 is the fix-by-configuration
                # that moves this work onto the owners' side entirely
                w = NDArray(self.weights[key], cpu(0))
                self._updater(_key_int(key), NDArray(merged, cpu(0)), w)
                self.weights[key] = w.asnumpy()  # mxlint: disable
            else:
                self.weights[key] = merged
            # contributions are consumed only once the update LANDED: an
            # updater exception must leave the round pending (retryable
            # on the next recheck) instead of wedging it forever
            del self.pending[key]
            self.done[key] += 1
            self.w_done[key] = self.done[key]
            self.updates_total += 1
            finished.append(key)
        return finished

    # -- sharded weight update (ZeRO-1 worker-side optimizer) ------------------
    def take_update(self, key):
        """(round, merged grad) awaiting the key's owner, or None."""
        if key in self.grads and self.w_done.get(key, 0) < self.done[key]:
            return self.done[key], self.grads[key]
        return None

    def put_weight(self, key, rnd, arr, guard=True):
        """Land an owner's updated weight for round ``rnd``. 'stale'
        when that round's weight already landed (a reassigned owner and
        the original racing each other — first writer wins, the server
        copy is the single authority). A non-finite weight under the
        guardian is converted into a SKIP: old weight kept, round
        marked ready, counted — defense in depth behind the worker's
        own sentinel."""
        if key not in self.weights:
            raise MXNetError("elastic put_weight of uninitialized key %r"
                             % (key,))
        if rnd <= self.w_done.get(key, 0):
            return "stale"
        if guard and not _np.all(_np.isfinite(arr)):
            from ..resilience import guardian as _grd

            if _grd.enabled():
                self.w_done[key] = rnd
                self.grads.pop(key, None)
                self.guard_skips_total += 1
                self.guard_nonfinite_total += 1
                logging.warning(
                    "elastic guardian: rejected non-finite shard-update "
                    "weight for key %r round %d (old weight kept)",
                    key, rnd)
                return "ok"
        self.weights[key] = _np.array(arr, copy=True)
        self.w_done[key] = rnd
        self.grads.pop(key, None)
        self.updates_total += 1
        return "ok"

    @staticmethod
    def shard_map_for(weights, live):
        """Greedy byte-balanced key->rank assignment over the live set
        (largest keys first onto the least-loaded rank; deterministic
        tie-breaks). Recomputed at every membership epoch — eviction
        and rejoin reassign ownership."""
        ranks = sorted(live)
        if not ranks:
            return {}
        load = {r: 0 for r in ranks}
        assign = {}
        keys = sorted(weights, key=lambda k: (-weights[k].nbytes, repr(k)))
        for k in keys:
            r = min(ranks, key=lambda rr: (load[rr], rr))
            assign[k] = r
            load[r] += weights[k].nbytes
        return assign

    def _guard_poisoned(self, merged):
        """Server half of the guardian sentinel, gated on the same
        MXNET_GUARDIAN switch (the coordinator inherits the launcher's
        env). Non-finite always poisons; MXNET_GUARDIAN_GRADNORM_MAX
        adds an absolute merged-norm ceiling."""
        from ..resilience import guardian as _grd

        if not _grd.enabled():
            return False
        if not _np.all(_np.isfinite(merged)):
            self.guard_nonfinite_total += 1
            return True
        max_norm = _grd._env_float("MXNET_GUARDIAN_GRADNORM_MAX", 0.0)
        if max_norm > 0.0:
            # calibrated quantization-noise margin (1.0 with the codec
            # off): dequantized merges carry bounded codec noise that
            # must stay distinguishable from poisoning
            max_norm *= _quant.guard_norm_scale()
            gsq = float(_np.sum(_np.square(merged.astype(_np.float64))))
            return gsq > max_norm * max_norm
        return False

    def snapshot_state(self):
        return {
            "done": dict(self.done), "w_done": dict(self.w_done),
            "shard_update": self.shard_update, "opt_blob": self.opt_blob,
            "degraded_steps_total": self.degraded_steps_total,
            "updates_total": self.updates_total,
            "guard_skips_total": self.guard_skips_total,
            "guard_nonfinite_total": self.guard_nonfinite_total,
        }

    def restore_state(self, st, weights):
        self.weights = {k: _np.array(v, copy=True)
                        for k, v in weights.items()}
        self.done = {k: int(v) for k, v in st["done"].items()}
        # pre-shard snapshots lack w_done: weights always tracked done
        self.w_done = {k: int(v) for k, v in st.get(
            "w_done", st["done"]).items()}
        self.shard_update = bool(st.get("shard_update", False))
        # weights without a recorded round (snapshot raced an init):
        # treat as round 0
        for k in self.weights:
            self.done.setdefault(k, 0)
            self.w_done.setdefault(k, 0)
        if self.shard_update:
            # a merged-but-unapplied round's gradient died with the
            # coordinator: roll the merge counter back to the landed
            # weight so the round replays (the same snapshot-cadence
            # loss contract as pending contributions)
            for k in self.done:
                self.done[k] = min(self.done[k], self.w_done.get(k, 0))
        self.pending = {}  # in-flight contributions do not survive a crash
        self._acc = {}
        self.grads = {}
        self.degraded_steps_total = int(st["degraded_steps_total"])
        self.updates_total = int(st["updates_total"])
        # pre-guardian snapshots lack the guard counters
        self.guard_skips_total = int(st.get("guard_skips_total", 0))
        self.guard_nonfinite_total = int(st.get("guard_nonfinite_total", 0))
        if st["opt_blob"] is not None:
            self.set_optimizer(st["opt_blob"], shard=self.shard_update)


def _key_to_name(k):
    """KVStore keys are ints (Module key indices) or strings; the
    .params container wants names. 'i:'/'s:' prefixes keep the round
    trip lossless."""
    return ("i:%d" % k) if isinstance(k, int) else ("s:%s" % k)


def _name_to_key(name):
    return int(name[2:]) if name.startswith("i:") else name[2:]


def _atomic_pickle(path, obj):
    """Same tmp → fsync → rename discipline as model._write_params_atomic,
    for the non-tensor half of a snapshot."""
    tmp = "%s.tmp-%d" % (path, os.getpid())
    with open(tmp, "wb") as f:
        f.write(pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL))
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


class _Handler(socketserver.BaseRequestHandler):
    def handle(self):
        try:
            peer = "%s:%s" % tuple(self.client_address[:2])
            req = protocol.recv_msg(self.request, peer=peer, what="request")
            if req is None:
                return
            # cross-process trace propagation (docs/how_to/
            # observability.md): the caller's wire context rides the
            # request envelope; the handler span opens as its child so
            # the coordinator's work lands in the CALLER's trace.
            # Popped either way — dispatch must never see the envelope.
            wire = req.pop("_trace", None) if isinstance(req, dict) else None
            try:
                with _tel.span("elastic.serve.%s" % req.get("op"),
                               wire=wire):
                    resp = self.server.coordinator._dispatch(req)
            except MXNetError as e:
                # a semantic rejection (round ahead, uninited key) must
                # reach the caller as a reply — a dropped connection
                # reads as a transient and would be retried verbatim
                resp = {"status": "error", "message": str(e)}
            if _tel.ENABLED and isinstance(resp, dict):
                # server wall clock at reply time: the client's clock
                # records pair it with (t0, t1) for offset estimation
                resp.setdefault("_srv_t", time.time())
            protocol.send_msg(self.request, resp)
        except (OSError, protocol.ProtocolError):
            pass  # a dying client mid-frame must not log-spam the server


class _Server(socketserver.ThreadingTCPServer):
    allow_reuse_address = True
    daemon_threads = True


class ElasticCoordinator:
    """The coordinator process/thread: socket front-end over GroupView +
    Aggregator + barrier state, plus the eviction sweeper and snapshot
    writer threads. Thread-safe via one state lock (the workload is
    coordination, not bandwidth)."""

    def __init__(self, world, bind=("127.0.0.1", 0), evict_after=None,
                 snapshot_prefix=None, snapshot_secs=None):
        if evict_after is None:
            evict_after = float(os.environ.get("MXNET_KV_EVICT_AFTER", "10"))
            # jitter-aware floor (budget.check_budgets invariant,
            # docs/how_to/static_analysis.md pass 7): an env-configured
            # window below N heartbeat periods + scheduler-jitter slack
            # would evict healthy-but-delayed ranks on a contended box
            # — the chaos flake class — so the coordinator refuses to
            # run under it. Programmatic callers passing evict_after
            # explicitly (tests, simulators) keep full control.
            from . import budget as _budget

            hb = float(os.environ.get(
                "MXNET_KVSTORE_HEARTBEAT_INTERVAL", "2"))
            floor = _budget.evict_after_floor(hb)
            if evict_after < floor:
                logging.warning(
                    "elastic: MXNET_KV_EVICT_AFTER=%.3gs is below the "
                    "safe floor %.3gs (%d x %.3gs heartbeat + %.3gs "
                    "jitter slack) — raising the evict window to the "
                    "floor so scheduler jitter cannot evict healthy "
                    "ranks", evict_after, floor,
                    _budget.heartbeat_misses(), hb, _budget.jitter_slack())
                evict_after = floor
        if snapshot_secs is None:
            snapshot_secs = float(
                os.environ.get("MXNET_KV_SNAPSHOT_SECS", "0") or "0")
        # TracedLock under MXNET_ENGINE_VERIFY=1: acquires land in the
        # ambient lock trace for observed-lock-order verification
        from ..analysis.engine_verify import maybe_trace_lock

        self._lock = maybe_trace_lock(
            threading.Lock(), "elastic.ElasticCoordinator._lock")
        # long-poll rendezvous: pull/barrier_wait requests park on this
        # condition (releasing the state lock) until a mutating op
        # completes a round, lands a weight, or changes the view —
        # instead of hammering the accept loop with a connection every
        # few ms per waiting rank (a 4-rank poll storm costs more
        # coordinator CPU than the gradient traffic itself)
        self._cond = threading.Condition(self._lock)
        self.view = GroupView(world, evict_after)
        self.agg = Aggregator(world)
        self.barrier_gen = 0
        self._barrier_waiters = {}   # rank -> that rank's barrier count
        self._barrier_done = {}      # rank -> highest completed count
        self.snapshot_prefix = snapshot_prefix
        self.snapshot_secs = float(snapshot_secs)
        self.snapshots_total = 0
        self._shard_cache = None     # (epoch, nkeys, {key: owner rank})
        self._update_owner = {}      # key -> rank pinned at MERGE time
        #                              for the parked shard update: a
        #                              rejoin recomputes the shard map,
        #                              and moving a parked hand-out to
        #                              the rejoiner deadlocks the group
        #                              (the rejoiner's round frontier is
        #                              past the parked key, so it never
        #                              polls it — found by protosim,
        #                              replay (seed=2, index=3) of the
        #                              shard workload). Reassigned only
        #                              when the pinned owner leaves the
        #                              live set (the documented
        #                              owner-eviction handoff).
        self._wire_cache = {}        # key -> (round, mode, payload|raw)
        self._stop = threading.Event()
        if snapshot_prefix and os.path.exists(snapshot_prefix + ".meta"):
            self._restore_snapshot()
        if bind is None:
            # socketless coordinator: the protocol simulator (analysis/
            # protosim.py) drives _dispatch directly — same state
            # machine, no port, no background threads
            self._srv = None
            self.addr = None
        else:
            self._srv = _Server(bind, _Handler)
            self._srv.coordinator = self
            self.addr = self._srv.server_address[:2]
        self._threads = []

    # -- lifecycle -------------------------------------------------------------
    def start(self):
        if self._srv is None:
            raise MXNetError("socketless coordinator (bind=None) cannot "
                             "start(): it exists to be driven through "
                             "_dispatch by the protocol simulator")
        for name, target in (
                ("mxtpu-elastic-serve", self._srv.serve_forever),
                ("mxtpu-elastic-sweep", self._sweep_loop),
                ("mxtpu-elastic-snap", self._snapshot_loop)):
            t = threading.Thread(target=target, name=name, daemon=True)
            t.start()
            self._threads.append(t)
        return self

    def stop(self):
        self._stop.set()
        if self._srv is not None:
            self._srv.shutdown()
            self._srv.server_close()
        if self.snapshot_prefix:
            try:
                self.save_snapshot()
            except Exception:
                logging.exception("elastic: final snapshot failed")

    # -- background loops ------------------------------------------------------
    def _sweep_loop(self):
        interval = max(0.05, self.view.evict_after / 4.0)
        while not self._stop.wait(interval):
            try:
                self.sweep()
            except _faults.FaultInjected:
                # an injected kv.evict fault aborts THIS sweep; the dead
                # rank is still dead and the next sweep retries — the
                # delayed-eviction failure mode, on demand
                logging.warning("elastic: eviction sweep aborted by "
                                "injected kv.evict fault")
            except Exception:
                logging.exception("elastic: eviction sweep failed")

    def sweep(self, now=None):
        """One eviction pass: evict every heartbeat-lapsed rank, drop its
        in-flight gradients, re-check rounds and barriers against the
        reduced group. Returns the evicted ranks."""
        now = time.monotonic() if now is None else now
        with self._lock:
            lapsed = self.view.lapsed(now)
            evicted = []
            for r in lapsed:
                _faults.point("kv.evict")
                if self.view.evict(r):
                    self.agg.drop_rank(r)
                    evicted.append(r)
            if evicted:
                logging.warning(
                    "elastic: evicted rank(s) %s (heartbeat lapse > %.1fs) "
                    "-> epoch %d, live %s", evicted, self.view.evict_after,
                    self.view.epoch, sorted(self.view.live))
                self._recheck_locked()
        return evicted

    def _snapshot_loop(self):
        if not self.snapshot_prefix or self.snapshot_secs <= 0:
            return
        while not self._stop.wait(self.snapshot_secs):
            try:
                self.save_snapshot()
            except Exception:
                logging.exception("elastic: periodic snapshot failed")

    # -- snapshots -------------------------------------------------------------
    def save_snapshot(self):
        """Crash-safe state dump: weights through the same atomic .params
        writer checkpoints use (model._write_params_atomic), membership +
        rounds + optimizer pickle through the same rename discipline."""
        from ..model import _write_params_atomic  # lazy: heavy import

        with self._lock:
            weights = {_key_to_name(k): _np.array(v, copy=True)
                       for k, v in self.agg.weights.items()}
            meta = {
                "view": self.view.snapshot_state(),
                "agg": self.agg.snapshot_state(),
                "barrier_gen": self.barrier_gen,
            }
        _write_params_atomic(self.snapshot_prefix + ".params", weights)
        _atomic_pickle(self.snapshot_prefix + ".meta", meta)
        with self._lock:
            self.snapshots_total += 1

    def _restore_snapshot(self):
        from ..context import cpu
        from ..ndarray import load as nd_load

        with open(self.snapshot_prefix + ".meta", "rb") as f:
            meta = pickle.loads(f.read())
        weights = {}
        params_path = self.snapshot_prefix + ".params"
        if os.path.exists(params_path):
            loaded = nd_load(params_path, cpu(0))
            weights = {_name_to_key(k): v.asnumpy()
                       for k, v in loaded.items()}
        now = time.monotonic()
        self.view.restore_state(meta["view"], now)
        self.agg.restore_state(meta["agg"], weights)
        self.barrier_gen = int(meta["barrier_gen"])
        logging.info("elastic: restored snapshot %s (epoch %d, %d keys)",
                     self.snapshot_prefix, self.view.epoch, len(weights))

    # -- request dispatch ------------------------------------------------------
    def _counters_locked(self):
        return {
            "evictions": self.view.evictions_total,
            "rejoins": self.view.rejoins_total,
            "degraded": self.agg.degraded_steps_total,
            "updates": self.agg.updates_total,
            "snapshots": self.snapshots_total,
            "guard_skips": self.agg.guard_skips_total,
            "guard_nonfinite": self.agg.guard_nonfinite_total,
        }

    def _recheck_locked(self):
        """After any view change or contribution: complete coverable
        rounds, release coverable barriers, and wake every long-polling
        request so it re-evaluates against the new state."""
        finished = self.agg.complete_ready(self.view.live)
        if self.agg.shard_update:
            for key in finished:
                if self.agg.take_update(key) is not None:
                    self._update_owner[key] = \
                        self._shard_map_locked().get(key)
        if self._barrier_waiters and \
                self.view.live.issubset(self._barrier_waiters.keys()):
            self.barrier_gen += 1
            for r, c in self._barrier_waiters.items():
                self._barrier_done[r] = max(self._barrier_done.get(r, 0), c)
            self._barrier_waiters.clear()
        self._cond.notify_all()

    def _shard_map_locked(self):
        """Current key->owner map, cached per (membership epoch, key
        count) — any view change or late init invalidates it."""
        tag = (self.view.epoch, len(self.agg.weights))
        if self._shard_cache is None or self._shard_cache[0] != tag:
            self._shard_cache = (
                tag, Aggregator.shard_map_for(self.agg.weights,
                                              self.view.live))
        return self._shard_cache[1]

    @staticmethod
    def _wire_rng_for(key, rnd):
        """Dither stream for the server-side requant of (key, round):
        derived, not shared — a shared mutable Generator would force
        the encode to stay under the state lock (or corrupt under
        concurrent draws), and two threads racing the same round must
        produce the same bytes."""
        import zlib

        return _quant.default_rng(
            (1 << 20) + (zlib.crc32(repr(key).encode()) + rnd) % (1 << 19))

    def _wire_value_droplock(self, key, rnd, value, wire):
        """Encode a GRADIENT-like response value in the requested wire
        mode (pull of an all-reduce round, shard-update hand-out).
        Cached per (key, round): every rank must receive the exact same
        codes — per-rank re-dithering would fork the replicas.

        Must be called with the state lock HELD; returns with it held,
        but RELEASES it around the codec math — encoding a large key
        is tens of ms of pure compute, and holding the lock for it
        would stall every other RPC (heartbeats included) behind it.
        The derived per-(key, round) dither stream makes a racing
        duplicate encode byte-identical; first writer publishes."""
        if not wire or wire not in _quant.MODES:
            return value
        if value.dtype != _np.float32 or \
                value.nbytes < _quant.min_bytes():
            return value
        hit = self._wire_cache.get(key)
        if hit is not None and hit[0] == rnd and hit[1] == wire:
            return hit[2]
        self._lock.release()
        try:
            payload = _quant.encode(
                value, rng=self._wire_rng_for(key, rnd), mode_=wire)
        finally:
            self._lock.acquire()
        hit = self._wire_cache.get(key)
        if hit is not None and hit[0] == rnd and hit[1] == wire:
            return hit[2]  # racing encoder published first (same bytes)
        self._wire_cache[key] = (rnd, wire, payload)
        return payload

    def _update_owner_locked(self, key):
        """Owner of ``key``'s PARKED merged gradient: the rank pinned
        at merge time while it stays live (it is at the round frontier
        and will poll the key), else the current map's owner (the
        eviction handoff)."""
        owner = self._update_owner.get(key)
        if owner is None or owner not in self.view.live:
            owner = self._shard_map_locked().get(key)
            self._update_owner[key] = owner
        return owner

    def _require_live(self, rank):
        """None when rank is a member; an 'evicted' reply otherwise —
        the signal that sends a zombie or restarted worker into the
        rejoin path."""
        if rank in self.view.live:
            return None
        return {"status": "evicted", "epoch": self.view.epoch}

    def _dispatch(self, req):
        op = req.get("op")
        rank = int(req.get("rank", -1))
        now = time.monotonic()
        decoded = None
        if op == "push" and _quant.is_encoded(req.get("value")):
            # dequantize OUTSIDE the state lock: pure function of the
            # payload, so concurrent pushes decode in parallel handler
            # threads (numpy releases the GIL) and only the cheap
            # fold-into-the-running-sum serializes
            decoded = _quant.decode(req["value"], dtype=_np.float32)
        pre_opt = None
        if op == "set_optimizer" and not req.get("shard", False):
            # unpickle the optimizer blob outside the lock too (same
            # reasoning; a repeat ship from a rejoiner wastes the decode
            # but never stalls heartbeats behind it)
            pre_opt = pickle.loads(req["blob"])
        with self._lock:
            if op == "register":
                epoch, rejoined = self.view.register(rank, now)
                # a restarted incarnation's barrier count restarts at 1;
                # the old incarnation's completed counts must not make
                # its fresh arrivals look already-done
                self._barrier_done.pop(rank, None)
                self._barrier_waiters.pop(rank, None)
                self._recheck_locked()  # the new member may cover a barrier
                return {"status": "ok", "epoch": epoch,
                        "rejoined": rejoined,
                        "live": sorted(self.view.live),
                        "world": self.view.world,
                        "rounds": dict(self.agg.done),
                        "opt": self.agg.opt_blob,
                        # NB: no shard fields here — ownership is
                        # evaluated server-side per pull, and a
                        # restarted worker re-ships set_optimizer
                        # (whose reply carries the authoritative shard
                        # mode); the map is visible via "stats" for
                        # debugging
                        "counters": self._counters_locked()}
            if op == "beat":
                self.view.beat(rank, now)
                return {"status": "ok", "epoch": self.view.epoch,
                        "live": rank in self.view.live}
            if op == "view":
                return {"status": "ok", "epoch": self.view.epoch,
                        "live": sorted(self.view.live),
                        "evicted": sorted(self.view.evicted),
                        "world": self.view.world,
                        "counters": self._counters_locked()}
            if op == "init":
                err = self._require_live(rank)
                if err:
                    return err
                value, rnd = self.agg.init_key(req["key"], req["value"])
                self._cond.notify_all()  # wake pulls of a racing init
                return {"status": "ok", "value": value, "round": rnd}
            if op == "push":
                err = self._require_live(rank)
                if err:
                    return err
                st = self.agg.contribute(
                    req["key"], rank, int(req["round"]), req["value"],
                    decoded=decoded)
                if st == "ok":
                    self._recheck_locked()
                # round lets a stale pusher (rejoiner whose retried push
                # raced the group) fast-forward its counter to the
                # server's, instead of trailing stale for several steps
                return {"status": st,
                        "round": self.agg.done.get(req["key"], 0)}
            if op == "pull":
                key, min_round = req["key"], int(req["min_round"])
                wire = req.get("wire")
                # long-poll budget: the request parks on the condition
                # until the round is ready or the budget lapses ("wait"
                # absent/0 preserves the immediate-reply semantics).
                # Bounded waits: an evicted/restarted peer can never
                # strand this handler thread past the budget.
                deadline = now + min(float(req.get("wait", 0.0) or 0.0),
                                     _WAIT_CAP)
                while True:
                    err = self._require_live(rank)
                    if err:
                        return err
                    if key not in self.agg.done:
                        return {"status": "error",
                                "message": "key %r not initialized" % (key,)}
                    if self.agg.shard_update:
                        # ownership is evaluated HERE, against the
                        # current epoch's map: after an owner eviction,
                        # the next poll from the key's new owner
                        # receives the parked merged gradient — no
                        # client-side map refresh protocol needed for
                        # correctness
                        upd = self.agg.take_update(key)
                        if upd is not None and \
                                self._update_owner_locked(key) == rank:
                            rnd, grad = upd
                            return {"status": "update", "round": rnd,
                                    "epoch": self.view.epoch,
                                    "value": self._wire_value_droplock(
                                        key, rnd, grad, wire)}
                    ready = self.agg.w_done.get(key, self.agg.done[key])
                    if ready >= min_round:
                        break
                    remaining = deadline - time.monotonic()
                    if remaining <= 0 or not self._cond.wait(
                            min(remaining, 0.5)):
                        if time.monotonic() >= deadline:
                            return {"status": "pending", "round": ready,
                                    "epoch": self.view.epoch}
                value = self.agg.weights[key]
                if self.agg._updater is None and \
                        not self.agg.shard_update:
                    # no optimizer: the stored value IS the merged
                    # gradient (all-reduce mode) — requantizing it is
                    # the second shot of a two-shot quantized
                    # all-reduce. With an optimizer it is a WEIGHT and
                    # stays full precision. The FIRST pull of a round
                    # pins its wire representation for every later
                    # puller (clients decode unconditionally): a mixed
                    # group — some ranks with the codec off — must all
                    # adopt identical bytes or the codec's bounded
                    # error forks the quant-on replicas from the
                    # quant-off ones.
                    hit = self._wire_cache.get(key)
                    if hit is not None and hit[0] == ready:
                        value = hit[2]
                    elif wire:
                        value = self._wire_value_droplock(
                            key, ready, value, wire)
                    else:
                        self._wire_cache[key] = (ready, None, value)
                return {"status": "ok", "value": value,
                        "round": ready,
                        "epoch": self.view.epoch,
                        "counters": self._counters_locked()}
            if op == "put_weight":
                err = self._require_live(rank)
                if err:
                    return err
                st = self.agg.put_weight(
                    req["key"], int(req["round"]), req["value"])
                # full recheck (which also wakes parked pulls): a round
                # held back because THIS weight was in flight can
                # complete now
                self._recheck_locked()
                return {"status": st,
                        "round": self.agg.w_done.get(req["key"], 0),
                        "epoch": self.view.epoch}
            if op == "set_optimizer":
                shard = bool(req.get("shard", False))
                installed = self.agg.set_optimizer(
                    req["blob"], shard=shard, preloaded=pre_opt)
                return {"status": "ok", "installed": installed,
                        "shard": self.agg.shard_update}
            if op == "barrier":
                err = self._require_live(rank)
                if err:
                    return err
                count = int(req.get("count", 0))
                if count and count <= self._barrier_done.get(rank, 0):
                    # idempotent retry of an arrival whose barrier
                    # already completed (lost ack): re-queueing it would
                    # strand the rank waiting on the NEXT generation
                    return {"status": "ok", "gen": self.barrier_gen - 1,
                            "done": True}
                gen = self.barrier_gen
                self._barrier_waiters[rank] = count
                self._recheck_locked()
                return {"status": "ok", "gen": gen,
                        "done": self.barrier_gen > gen}
            if op == "barrier_wait":
                gen = int(req["gen"])
                deadline = now + min(float(req.get("wait", 0.0) or 0.0),
                                     _WAIT_CAP)
                while self.barrier_gen <= gen:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        break
                    self._cond.wait(min(remaining, 0.5))
                return {"status": "ok",
                        "done": self.barrier_gen > gen,
                        "epoch": self.view.epoch}
            if op == "leave":
                if self.view.leave(rank):
                    self._recheck_locked()
                return {"status": "ok", "epoch": self.view.epoch}
            if op == "evict":
                # admin/test hook: force an eviction without waiting for
                # the heartbeat lapse
                _faults.point("kv.evict")
                if self.view.evict(rank):
                    self.agg.drop_rank(rank)
                    self._recheck_locked()
                return {"status": "ok", "epoch": self.view.epoch,
                        "live": sorted(self.view.live)}
            if op == "stats":
                return {"status": "ok", "epoch": self.view.epoch,
                        "live": sorted(self.view.live),
                        "evicted": sorted(self.view.evicted),
                        "world": self.view.world,
                        "rounds": dict(self.agg.done),
                        "weight_rounds": dict(self.agg.w_done),
                        "shard": self.agg.shard_update,
                        "shard_map": (self._shard_map_locked()
                                      if self.agg.shard_update else {}),
                        "barrier_gen": self.barrier_gen,
                        "counters": self._counters_locked()}
        if op == "snapshot":
            if not self.snapshot_prefix:
                return {"status": "error",
                        "message": "coordinator has no snapshot prefix"}
            self.save_snapshot()  # takes the lock itself
            return {"status": "ok"}
        return {"status": "error", "message": "unknown op %r" % (op,)}


def serve(world, bind, evict_after=None, snapshot_prefix=None,
          snapshot_secs=None, ready_fd=None):
    """Run a coordinator in the foreground (the ``python -m
    mxnet_tpu.elastic`` entry point). Blocks until SIGTERM/KeyboardInterrupt."""
    coord = ElasticCoordinator(
        world, bind=bind, evict_after=evict_after,
        snapshot_prefix=snapshot_prefix, snapshot_secs=snapshot_secs)
    coord.start()
    print("elastic coordinator: serving %d-worker group on %s:%d"
          % (world, coord.addr[0], coord.addr[1]), flush=True)
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        pass
    finally:
        coord.stop()
