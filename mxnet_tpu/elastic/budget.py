"""Timeout-budget arithmetic for the elastic protocol (mxproto).

Every protocol-level timing bug this repo has paid for — the long-poll
cap landing after the client's socket deadline (PR 7), the chaos
heartbeat-starvation flake (healthy ranks evicted on a contended box
because scheduler jitter ate the evict window) — was a violated
ORDERING between timeout constants that live in different modules.
This module is the one place that ordering is written down as code:

- ``check_budgets(values)`` evaluates the invariant lattice over a dict
  of named constants and returns the violations. The static analyzer
  (``mxnet_tpu/analysis/proto_lint.py``, ``mxlint --proto``) derives
  the constants from the source defaults + env and calls this; runtime
  callers can hand in live values.
- ``evict_after_floor(heartbeat, jitter_slack, misses)`` is the
  smallest safe evict window: the coordinator refuses to run with an
  env-configured window below it (``ElasticCoordinator.__init__``
  raises the window to the floor with a warning), so the
  spurious-eviction flake class is prevented by construction instead
  of by "run it uncontended".
- ``measure_scheduler_jitter()`` measures how late this box's
  scheduler actually delivers a timed wait — the slack term. Chaos
  (``tools/chaos.py`` elastic legs) preflight-measures it and exports
  ``MXNET_KV_EVICT_JITTER_SLACK`` + a scaled ``MXNET_KV_EVICT_AFTER``.

Kept stdlib-only and import-light on purpose: tools load it by file
path (the trace_merge pattern) without paying the jax import.
"""
from __future__ import annotations

import os
import threading
import time

__all__ = ["heartbeat_misses", "jitter_slack", "evict_after_floor",
           "measure_scheduler_jitter", "check_budgets", "Violation"]


def heartbeat_misses(env=None):
    """Tolerated consecutive heartbeat misses before eviction is fair
    game (``MXNET_KV_HEARTBEAT_MISSES``, default 3): the evict window
    must fit this many full heartbeat periods plus the jitter slack."""
    env = os.environ if env is None else env
    try:
        return max(1, int(env.get("MXNET_KV_HEARTBEAT_MISSES", "3")))
    except ValueError:
        return 3


def jitter_slack(env=None):
    """Scheduler-jitter slack term in seconds
    (``MXNET_KV_EVICT_JITTER_SLACK``, default 1.0): how late a healthy
    worker's heartbeat may land purely because the OS scheduler was
    busy. Chaos preflight-measures the real value for its legs."""
    env = os.environ if env is None else env
    try:
        return max(0.0, float(env.get("MXNET_KV_EVICT_JITTER_SLACK", "1")))
    except ValueError:
        return 1.0


def evict_after_floor(heartbeat, slack=None, misses=None, env=None):
    """Smallest evict window that cannot evict a healthy-but-delayed
    rank: ``misses`` full heartbeat periods plus the jitter slack."""
    if misses is None:
        misses = heartbeat_misses(env)
    if slack is None:
        slack = jitter_slack(env)
    return misses * float(heartbeat) + float(slack)


def measure_scheduler_jitter(samples=25, interval=0.02):
    """Max observed overshoot (seconds) of a timed wait on this box,
    right now. A loaded/contended machine delivers ``Event.wait(t)``
    late by the scheduler's latency — exactly the lateness a heartbeat
    publish suffers. The max over a burst of short waits is a usable
    (slightly optimistic: the box can always get busier) slack floor."""
    ev = threading.Event()
    worst = 0.0
    for _ in range(int(samples)):
        t0 = time.monotonic()
        ev.wait(interval)
        worst = max(worst, (time.monotonic() - t0) - interval)
    return worst


class Violation:
    """One broken ordering invariant in the timeout lattice."""

    __slots__ = ("code", "message")

    def __init__(self, code, message):
        self.code = code
        self.message = message

    def __repr__(self):
        return "<Violation %s: %s>" % (self.code, self.message)


def _get(values, name):
    v = values.get(name)
    return None if v is None else float(v)


def check_budgets(values):
    """Evaluate the ordering invariants over named constants. ``values``
    maps constant names to numbers (missing entries skip the invariants
    that need them — the CALLER reports incompleteness; see
    proto_lint.derive_lattice):

    - ``client_timeout``  — RPC socket timeout (ElasticClient/protocol.call)
    - ``wait_cap``        — server long-poll park cap (_WAIT_CAP)
    - ``pull_wait``       — client-advertised long-poll budget
    - ``heartbeat``       — heartbeat publish period
    - ``evict_after``     — heartbeat-lapse eviction window
    - ``misses``          — tolerated consecutive heartbeat misses
    - ``jitter_slack``    — scheduler-jitter slack term
    - ``retry_attempts`` / ``retry_base`` / ``retry_max`` /
      ``retry_multiplier`` — the RPC retry policy shape
    - ``barrier_timeout`` — MXNET_KV_BARRIER_TIMEOUT (0 = disabled)

    Returns a list of :class:`Violation`.
    """
    out = []
    ct = _get(values, "client_timeout")
    cap = _get(values, "wait_cap")
    pw = _get(values, "pull_wait")
    hb = _get(values, "heartbeat")
    ev = _get(values, "evict_after")
    misses = _get(values, "misses")
    slack = _get(values, "jitter_slack")
    bt = _get(values, "barrier_timeout")

    if ct is not None and cap is not None and cap >= ct:
        out.append(Violation(
            "lattice-longpoll",
            "server long-poll cap %.3gs >= client socket timeout %.3gs: a "
            "not-ready reply from a HEALTHY coordinator lands after the "
            "client's recv deadline and reads as a transport failure (the "
            "PR 7 long-poll bug class)" % (cap, ct)))
    if pw is not None and cap is not None and pw > cap:
        out.append(Violation(
            "lattice-pullwait",
            "client long-poll budget %.3gs exceeds the server park cap "
            "%.3gs: the client asks for a wait the server will never "
            "honor, so every long poll degrades to an early 'pending' "
            "spin" % (pw, cap)))
    if hb is not None and ev is not None:
        m = misses if misses is not None else 3.0
        s = slack if slack is not None else 0.0
        floor = m * hb + s
        if ev < floor:
            out.append(Violation(
                "lattice-evict",
                "evict window %.3gs < %d heartbeat period(s) x %.3gs + "
                "%.3gs jitter slack = %.3gs: a healthy rank whose "
                "heartbeats are merely scheduler-delayed gets evicted "
                "(the chaos heartbeat-starvation flake class); raise "
                "MXNET_KV_EVICT_AFTER or shorten the heartbeat"
                % (ev, int(m), hb, s, floor)))
    if bt is not None and bt > 0 and ct is not None:
        attempts = _get(values, "retry_attempts") or 1.0
        base = _get(values, "retry_base") or 0.0
        mx = _get(values, "retry_max")
        mult = _get(values, "retry_multiplier") or 2.0
        backoff = 0.0
        for a in range(1, int(attempts)):
            d = base * (mult ** (a - 1))
            backoff += min(d, mx) if mx is not None else d
        budget = attempts * ct + backoff
        if budget >= bt:
            out.append(Violation(
                "lattice-retry-barrier",
                "worst-case RPC retry budget %.3gs (%d attempts x %.3gs "
                "socket timeout + %.3gs backoff) >= barrier deadline "
                "%.3gs: a single slow-failing coordinator op can eat the "
                "whole barrier timeout and the diagnostic fires while "
                "the RPC was still legitimately retrying"
                % (budget, int(attempts), ct, backoff, bt)))
    return out
