"""Elastic distributed training: membership epochs, eviction + rejoin,
degraded-world aggregation, coordinator snapshots.

The reference's ps-lite KVStore could only *count* dead nodes
(kvstore.h:235 get_num_dead_node); this package makes worker failure a
recoverable membership event, the property TensorFlow gets from
coordinated membership + state restore (Abadi et al., 2016). It is the
server half of ``kvstore.create("dist_sync")`` under
``MXNET_KV_ELASTIC=1``:

- :class:`GroupView` — live-rank set + monotonically increasing
  membership epoch (evictions and admissions each bump it).
- :class:`Aggregator` — server-side sync gradient rounds that complete
  against the *current* live set, rescaling by ``world/contributors``
  when the group is degraded.
- :class:`ElasticCoordinator` — the TCP service hosting both, plus
  epoch-aware barriers, the ``MXNET_KV_EVICT_AFTER`` eviction sweeper,
  and ``MXNET_KV_SNAPSHOT_SECS`` crash-safe snapshots.
- :class:`ElasticClient` — the worker-side RPC handle.

Run a standalone coordinator with ``python -m mxnet_tpu.elastic``;
``tools/launch.py --elastic`` does it for you. docs/how_to/
elastic_training.md covers the lifecycle end to end.
"""
from .client import ElasticClient, parse_addr
from .server import Aggregator, ElasticCoordinator, GroupView

__all__ = ["Aggregator", "ElasticClient", "ElasticCoordinator",
           "GroupView", "parse_addr"]
