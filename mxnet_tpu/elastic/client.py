"""Worker-side client for the elastic coordinator.

Thin RPC wrapper: every call is one connection-per-request round trip
(protocol.py) run under the same resilience discipline as the dist
KVStore's coordination RPCs — the ``kv.coord`` injection point followed
by ``MXNET_KV_RETRIES`` attempts of exponential backoff. A transient
coordinator hiccup (or restart — the server is stateless per
connection) heals here; a persistent outage surfaces after the budget.
"""
from __future__ import annotations

import os
import time

from ..base import MXNetError
from ..resilience import faults as _faults
from ..resilience.retry import RetryPolicy
from . import protocol

__all__ = ["ElasticClient", "parse_addr"]


def parse_addr(spec):
    """'host:port' -> (host, port). The MXNET_ELASTIC_COORD format."""
    host, sep, port = str(spec).rpartition(":")
    if not sep or not host:
        raise MXNetError(
            "elastic coordinator address must be host:port, got %r" % spec)
    try:
        return host, int(port)
    except ValueError:
        raise MXNetError(
            "elastic coordinator port must be an integer, got %r" % spec)


class ElasticClient:
    """One worker's handle on the coordinator. Stateless between calls
    (survives coordinator restarts); holds only the address, the rank,
    and the retry policy."""

    def __init__(self, addr, rank, timeout=30.0):
        self.addr = parse_addr(addr) if isinstance(addr, str) else tuple(addr)
        self.rank = int(rank)
        self.timeout = float(timeout)
        attempts = max(1, int(os.environ.get("MXNET_KV_RETRIES", "4")))
        self._policy = RetryPolicy(max_attempts=attempts, base_delay=0.05,
                                   max_delay=1.0, jitter=0.25)

    def call(self, op, check=True, **fields):
        """One RPC. Transport errors retry under the policy; an
        ``error`` status raises MXNetError (when ``check``); other
        non-ok statuses ('pending', 'evicted', 'stale') are protocol
        answers the caller dispatches on."""
        req = dict(fields)
        req["op"] = op
        req["rank"] = self.rank

        def _rpc():
            _faults.point("kv.coord")
            return protocol.call(self.addr, req, timeout=self.timeout)

        _rpc.__name__ = "elastic %s" % op
        resp = self._policy.call(_rpc)
        if check and resp.get("status") == "error":
            raise MXNetError("elastic coordinator rejected %s: %s"
                             % (op, resp.get("message", "(no message)")))
        return resp

    # -- conveniences ----------------------------------------------------------
    def register(self):
        return self.call("register")

    def beat(self):
        return self.call("beat")

    def view(self):
        return self.call("view")

    def leave(self):
        return self.call("leave")

    def stats(self):
        return self.call("stats")

    def wait_ready(self, deadline=30.0):
        """Block until the coordinator answers (launcher/test startup)."""
        end = time.monotonic() + deadline
        last = None
        while time.monotonic() < end:
            try:
                return self.view()
            except Exception as e:  # noqa: BLE001 - startup polling
                last = e
                time.sleep(0.05)
        raise MXNetError("elastic coordinator at %s:%d not ready after "
                         "%.0fs: %s" % (self.addr[0], self.addr[1],
                                        deadline, last))
