"""Worker-side client for the elastic coordinator.

Thin RPC wrapper: every call is one connection-per-request round trip
(protocol.py) run under the same resilience discipline as the dist
KVStore's coordination RPCs — the ``kv.coord`` injection point followed
by ``MXNET_KV_RETRIES`` attempts of exponential backoff. A transient
coordinator hiccup (or restart — the server is stateless per
connection) heals here; a persistent outage surfaces after the budget.
"""
from __future__ import annotations

import os
import time

from .. import quantize as _quant
from .. import telemetry as _tel
from ..base import MXNetError
from ..resilience import faults as _faults
from ..resilience.retry import RetryPolicy
from . import protocol

__all__ = ["ElasticClient", "parse_addr"]

# ops whose clock-sync pairs feed trace_merge's offset estimate: fast,
# never-parking handlers only — a long-polled pull's server timestamp
# lands seconds after the request midpoint and would skew the estimate
_CLOCK_OPS = frozenset(("register", "beat", "view", "leave"))


def _pull_wait():
    """Server-side long-poll budget per pull/barrier_wait request
    (seconds). 0 disables long-polling (immediate pending replies)."""
    try:
        return max(0.0, float(os.environ.get("MXNET_KV_PULL_WAIT", "0.25")))
    except ValueError:
        return 0.25


def parse_addr(spec):
    """'host:port' -> (host, port). The MXNET_ELASTIC_COORD format."""
    host, sep, port = str(spec).rpartition(":")
    if not sep or not host:
        raise MXNetError(
            "elastic coordinator address must be host:port, got %r" % spec)
    try:
        return host, int(port)
    except ValueError:
        raise MXNetError(
            "elastic coordinator port must be an integer, got %r" % spec)


class ElasticClient:
    """One worker's handle on the coordinator. Stateless between calls
    (survives coordinator restarts); holds only the address, the rank,
    and the retry policy."""

    def __init__(self, addr, rank, timeout=30.0):
        self.addr = parse_addr(addr) if isinstance(addr, str) else tuple(addr)
        self.rank = int(rank)
        self.timeout = float(timeout)
        attempts = max(1, int(os.environ.get("MXNET_KV_RETRIES", "4")))
        self._policy = RetryPolicy(max_attempts=attempts, base_delay=0.05,
                                   max_delay=1.0, jitter=0.25)
        # per-rank dither stream for the low-precision wire codec
        # (MXNET_KV_QUANTIZE): deterministic per rank, so a chaos run's
        # quantized bytes are bisectable like everything else
        self._quant_rng = _quant.default_rng(self.rank)

    def call(self, op, check=True, **fields):
        """One RPC. Transport errors retry under the policy; an
        ``error`` status raises MXNetError (when ``check``); other
        non-ok statuses ('pending', 'evicted', 'stale') are protocol
        answers the caller dispatches on.

        With telemetry on, the RPC runs inside an ``elastic.rpc.<op>``
        span whose trace context rides the request envelope
        (``_trace``) — the coordinator opens its handler span as a
        child of this one, so one trace crosses the process boundary.
        Replies from a telemetry-on coordinator carry ``_srv_t``; for
        fast ops the (t0, t1, srv_t) triple is journaled as a ``clock``
        record, which is what lets trace_merge estimate per-rank clock
        offsets against the coordinator's clock."""
        req = dict(fields)
        req["op"] = op
        if "rank" not in req:
            # admin ops (evict) address ANOTHER rank explicitly; every
            # ordinary op speaks for this client's own rank
            req["rank"] = self.rank
        # clock stamps taken INSIDE the attempt, around the single
        # round trip: retry backoff between attempts must not widen the
        # t0..t1 bracket (srv_t comes from the final attempt's reply,
        # so a bracket spanning the whole retry budget would skew the
        # midpoint offset estimate by seconds)
        stamps = {}

        def _rpc():
            _faults.point("kv.coord")
            stamps["t0"] = time.time()
            out = protocol.call(self.addr, req, timeout=self.timeout)
            stamps["t1"] = time.time()
            return out

        _rpc.__name__ = "elastic %s" % op
        if not _tel.ENABLED:
            resp = self._policy.call(_rpc)
        else:
            with _tel.span("elastic.rpc.%s" % op):
                req["_trace"] = _tel.wire_context()
                resp = self._policy.call(_rpc)
            srv_t = resp.get("_srv_t") if isinstance(resp, dict) else None
            if srv_t is not None and op in _CLOCK_OPS and "t1" in stamps:
                from ..telemetry import export as _export

                _export.emit({"kind": "clock", "op": op, "rank": self.rank,
                              "t0": stamps["t0"], "t1": stamps["t1"],
                              "srv_t": float(srv_t)})
        if check and resp.get("status") == "error":
            raise MXNetError("elastic coordinator rejected %s: %s"
                             % (op, resp.get("message", "(no message)")))
        return resp

    # -- gradient wire codec ---------------------------------------------------
    # These helpers are THE wire-protocol assembly, shared by the
    # elastic kvstore and tools/bandwidth/measure.py — a protocol
    # change made here reaches both; never re-inline it at a call site.
    def encode_grad(self, arr):
        """``arr`` encoded per ``MXNET_KV_QUANTIZE`` with this rank's
        deterministic dither stream, or ``None`` when it must stay
        full precision (codec off, non-float, too small to win)."""
        return _quant.encode_maybe(arr, rng=self._quant_rng)

    def pull_fields(self, key, min_round, wait=None):
        """Request fields for one pull poll. Advertises the configured
        wire mode (the server answers gradient-like values encoded,
        weights always raw — decode with ``mxnet_tpu.quantize.decode``
        on any value) and the long-poll budget ``wait`` (default
        ``MXNET_KV_PULL_WAIT``, 0.25s: the coordinator parks the
        request until the round is ready instead of the caller
        re-connecting every few milliseconds)."""
        fields = {"key": key, "min_round": min_round}
        m = _quant.mode()
        if m is not None:
            fields["wire"] = m
        w = _pull_wait() if wait is None else wait
        if w:
            fields["wait"] = w
        return fields

    def push_grad(self, key, rnd, arr, check=True):
        """Push one gradient contribution, encoding it per
        ``MXNET_KV_QUANTIZE`` so the TCP bytes (not just the math)
        shrink. Returns ``(resp, wire_payload_or_None)`` — the payload
        is handed back so the caller can account wire/logical bytes and
        the quantization-error gauge without re-encoding."""
        payload = self.encode_grad(arr)
        resp = self.call("push", check=check, key=key, round=rnd,
                         value=payload if payload is not None else arr)
        return resp, payload

    def pull_weights(self, key, min_round, check=True, wait=None):
        """One pull poll (see :meth:`pull_fields`)."""
        return self.call("pull", check=check,
                         **self.pull_fields(key, min_round, wait=wait))

    def put_weight(self, key, rnd, arr, check=True):
        """Land this rank's shard-update weight for ``rnd`` (weights
        cross full precision — see quantize.py's scope discipline)."""
        return self.call("put_weight", check=check, key=key, round=rnd,
                         value=arr)

    # -- conveniences ----------------------------------------------------------
    def register(self):
        return self.call("register")

    def beat(self):
        return self.call("beat")

    def view(self):
        return self.call("view")

    def leave(self):
        return self.call("leave")

    def stats(self):
        return self.call("stats")

    def snapshot(self):
        """Ask the coordinator to write a weight snapshot NOW (the
        ``snapshot_prefix`` it was started with): ``fit``-free
        checkpointing for elastic jobs, and the feed a wsync
        CheckpointWatcher publishes from (docs/how_to/weight_sync.md).
        Errors when the coordinator has no snapshot prefix."""
        return self.call("snapshot")

    def evict(self, rank):
        """Admin eviction of ``rank`` (the coordinator's force-evict
        hook): bumps the membership epoch and drops the rank's in-flight
        contributions without waiting for its heartbeat lapse. The
        mxctl ``evict_replace`` actuator's RPC
        (docs/how_to/control_plane.md)."""
        return self.call("evict", rank=int(rank))

    def wait_ready(self, deadline=30.0):
        """Block until the coordinator answers (launcher/test startup)."""
        end = time.monotonic() + deadline
        last = None
        while time.monotonic() < end:
            try:
                return self.view()
            except Exception as e:  # noqa: BLE001 - startup polling
                last = e
                time.sleep(0.05)
        raise MXNetError("elastic coordinator at %s:%d not ready after "
                         "%.0fs: %s" % (self.addr[0], self.addr[1],
                                        deadline, last))
