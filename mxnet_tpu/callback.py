"""Training callbacks (ref: python/mxnet/callback.py:1-123).

Speedometer prints samples/sec — the headline metric of every baseline
config (BASELINE.md; ref: example/image-classification README tables).
"""
from __future__ import annotations

import logging
import math
import time

from . import telemetry as _tel


def do_checkpoint(prefix, period=1, keep_n=None):
    """Epoch-end checkpoint callback (ref: callback.py:10).

    ``keep_n`` enables rolling retention (only the newest ``keep_n``
    epochs stay on disk). The returned closure carries ``.prefix`` so
    ``FeedForward.fit(..., resume=True)`` can discover where the run's
    checkpoints live (docs/how_to/fault_tolerance.md)."""
    from .model import save_checkpoint

    period = int(max(1, period))

    def _callback(iter_no, sym, arg, aux):
        if (iter_no + 1) % period == 0:
            save_checkpoint(prefix, iter_no + 1, sym, arg, aux,
                            keep_n=keep_n)

    _callback.prefix = prefix
    return _callback


def log_train_metric(period, auto_reset=False):
    """ref: callback.py:38."""

    def _callback(param):
        if param.nbatch % period == 0 and param.eval_metric is not None:
            name_value = param.eval_metric.get_name_value()
            for name, value in name_value:
                logging.info(
                    "Iter[%d] Batch[%d] Train-%s=%f", param.epoch, param.nbatch, name, value
                )
            if auto_reset:
                param.eval_metric.reset()

    return _callback


class Speedometer:
    """Log samples/sec every `frequent` batches (ref: callback.py:59)."""

    def __init__(self, batch_size, frequent=50):
        self.batch_size = batch_size
        self.frequent = frequent
        self.init = False
        self.tic = 0
        self.last_count = 0

    def __call__(self, param):
        count = param.nbatch
        if self.last_count > count:
            self.init = False
        self.last_count = count
        if self.init:
            if count % self.frequent == 0:
                elapsed = time.time() - self.tic
                if elapsed <= 0:
                    # a fast synthetic iterator can tick twice inside one
                    # clock quantum (and wall clocks can step backwards);
                    # an unmeasurable interval yields no speed line, not
                    # a ZeroDivisionError mid-training
                    self.tic = time.time()
                    return
                speed = self.frequent * self.batch_size / elapsed
                if _tel.ENABLED:
                    _tel.gauge("train.samples_per_sec").set(speed)
                if param.eval_metric is not None:
                    name_value = param.eval_metric.get_name_value()
                    for name, value in name_value:
                        logging.info(
                            "Epoch[%d] Batch [%d]\tSpeed: %.2f samples/sec\tTrain-%s=%f",
                            param.epoch, count, speed, name, value,
                        )
                else:
                    logging.info(
                        "Iter[%d] Batch [%d]\tSpeed: %.2f samples/sec",
                        param.epoch, count, speed,
                    )
                self.tic = time.time()
        else:
            self.init = True
            self.tic = time.time()


class ProgressBar:
    """ref: callback.py:104."""

    def __init__(self, total, length=80):
        self.bar_len = length
        self.total = total

    def __call__(self, param):
        count = param.nbatch
        filled_len = int(round(self.bar_len * count / float(self.total)))
        percents = math.ceil(100.0 * count / float(self.total))
        prog_bar = "=" * filled_len + "-" * (self.bar_len - filled_len)
        logging.info("[%s] %s%s\r", prog_bar, percents, "%")
