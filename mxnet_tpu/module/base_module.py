"""BaseModule: the abstract intermediate-level training API
(ref: python/mxnet/module/base_module.py:1-900, BaseModule.fit at :275).
"""
from __future__ import annotations

import logging
import time

import numpy as _np

from ..base import MXNetError
from .. import metric as metric_mod
from .. import io as io_mod
from .. import telemetry as _tel
from ..model import BatchEndParam, _multiple_callbacks
from ..resilience import guardian as _guardian


class BaseModule:
    def __init__(self, logger=logging):
        self.logger = logger
        self.binded = False
        self.for_training = False
        self.inputs_need_grad = False
        self.params_initialized = False
        self.optimizer_initialized = False
        self._symbol = None
        self._total_exec_bytes = 0

    # -- properties every subclass provides ------------------------------------
    @property
    def data_names(self):
        raise NotImplementedError()

    @property
    def output_names(self):
        raise NotImplementedError()

    @property
    def data_shapes(self):
        raise NotImplementedError()

    @property
    def label_shapes(self):
        raise NotImplementedError()

    @property
    def output_shapes(self):
        raise NotImplementedError()

    @property
    def symbol(self):
        return self._symbol

    # -- core abstract ops -----------------------------------------------------
    def bind(self, data_shapes, label_shapes=None, for_training=True,
             inputs_need_grad=False, force_rebind=False, shared_module=None,
             grad_req="write"):
        raise NotImplementedError()

    def init_params(self, initializer=None, arg_params=None, aux_params=None,
                    allow_missing=False, force_init=False):
        raise NotImplementedError()

    def init_optimizer(self, kvstore="local", optimizer="sgd",
                       optimizer_params=(("learning_rate", 0.01),), force_init=False):
        raise NotImplementedError()

    def forward(self, data_batch, is_train=None):
        raise NotImplementedError()

    def backward(self, out_grads=None):
        raise NotImplementedError()

    def update(self):
        raise NotImplementedError()

    def get_outputs(self, merge_multi_context=True):
        raise NotImplementedError()

    def get_input_grads(self, merge_multi_context=True):
        raise NotImplementedError()

    def get_params(self):
        raise NotImplementedError()

    def update_metric(self, eval_metric, labels):
        raise NotImplementedError()

    def install_monitor(self, mon):
        raise NotImplementedError()

    # -- conveniences (ref: base_module.py) ------------------------------------
    def forward_backward(self, data_batch):
        self.forward(data_batch, is_train=True)
        self.backward()

    def set_params(self, arg_params, aux_params, allow_missing=False, force_init=True):
        self.init_params(
            initializer=None, arg_params=arg_params, aux_params=aux_params,
            allow_missing=allow_missing, force_init=force_init,
        )

    def save_params(self, fname):
        """ref: base_module.py:485."""
        from ..ndarray import save as nd_save

        arg_params, aux_params = self.get_params()
        save_dict = {("arg:%s" % k): v for k, v in arg_params.items()}
        save_dict.update({("aux:%s" % k): v for k, v in aux_params.items()})
        nd_save(fname, save_dict)

    def load_params(self, fname):
        """ref: base_module.py:498."""
        from ..ndarray import load as nd_load

        save_dict = nd_load(fname)
        arg_params = {}
        aux_params = {}
        for k, value in save_dict.items():
            arg_type, name = k.split(":", 1)
            if arg_type == "arg":
                arg_params[name] = value
            elif arg_type == "aux":
                aux_params[name] = value
            else:
                raise ValueError("Invalid param file " + fname)
        self.set_params(arg_params, aux_params)

    def score(self, eval_data, eval_metric, num_batch=None, batch_end_callback=None,
              score_end_callback=None, reset=True, epoch=0):
        """ref: base_module.py:170."""
        assert self.binded and self.params_initialized
        if reset:
            eval_data.reset()
        if not isinstance(eval_metric, metric_mod.EvalMetric):
            eval_metric = metric_mod.create(eval_metric)
        eval_metric.reset()
        actual_num_batch = 0
        for nbatch, eval_batch in enumerate(eval_data):
            if num_batch is not None and nbatch == num_batch:
                break
            self.forward(eval_batch, is_train=False)
            self.update_metric(eval_metric, eval_batch.label)
            if batch_end_callback is not None:
                batch_end_params = BatchEndParam(
                    epoch=epoch, nbatch=nbatch, eval_metric=eval_metric, locals=locals()
                )
                _multiple_callbacks(batch_end_callback, batch_end_params)
            actual_num_batch += 1
        if score_end_callback:
            params = BatchEndParam(
                epoch=epoch, nbatch=actual_num_batch, eval_metric=eval_metric,
                locals=locals(),
            )
            _multiple_callbacks(score_end_callback, params)
        return eval_metric.get_name_value()

    def iter_predict(self, eval_data, num_batch=None, reset=True):
        """ref: base_module.py:222."""
        assert self.binded and self.params_initialized
        if reset:
            eval_data.reset()
        for nbatch, eval_batch in enumerate(eval_data):
            if num_batch is not None and nbatch == num_batch:
                break
            self.forward(eval_batch, is_train=False)
            pad = eval_batch.pad
            outputs = [out[0:out.shape[0] - pad] for out in self.get_outputs()]
            yield (outputs, nbatch, eval_batch)

    def predict(self, eval_data, num_batch=None, merge_batches=True, reset=True,
                always_output_list=False):
        """ref: base_module.py:241."""
        assert self.binded and self.params_initialized
        if reset:
            eval_data.reset()
        output_list = []
        for nbatch, eval_batch in enumerate(eval_data):
            if num_batch is not None and nbatch == num_batch:
                break
            self.forward(eval_batch, is_train=False)
            pad = eval_batch.pad
            outputs = [out[0:out.shape[0] - pad].copy() for out in self.get_outputs()]
            output_list.append(outputs)
        if len(output_list) == 0:
            return output_list
        if merge_batches:
            num_outputs = len(output_list[0])
            for out in output_list:
                assert len(out) == num_outputs, \
                    "Cannot merge batches, as num of outputs is not the same in mini-batches."
            from ..ndarray import concatenate

            output_list2 = [
                concatenate([out[i] for out in output_list]) for i in range(num_outputs)
            ]
            if num_outputs == 1 and not always_output_list:
                return output_list2[0]
            return output_list2
        return output_list

    def _try_scanned_fit(self, *args, **kwargs):
        """Overridden by Module; other module kinds use the per-batch
        loop unconditionally."""
        return False

    # -- guardian plumbing (docs/how_to/guardrails.md) -------------------------
    def _guardian_updater(self):
        """The updater whose device sentinel carries this module's
        per-step verdicts: the local one, or the kvstore-installed one."""
        upd = getattr(self, "_updater", None)
        if upd is not None:
            return upd
        kv = getattr(self, "_kvstore", None)
        return getattr(kv, "_updater", None) if kv is not None else None

    def _guardian_grads(self):
        """First-device gradient NDArrays (vote-path stats); [] when the
        module kind exposes no grad arrays."""
        fn = getattr(self, "_grad_arrays", None)
        if fn is None:
            return []
        return [g[0] for g in fn() if g and g[0] is not None]

    def _guardian_snapshot(self):
        arg_params, aux_params = self.get_params()
        return ({k: v.asnumpy().copy() for k, v in arg_params.items()},
                {k: v.asnumpy().copy() for k, v in aux_params.items()},
                _guardian.snapshot_updater_states(self._guardian_updater()))

    def _guardian_restore(self, payload):
        args, auxs, opt_states = payload
        self.set_params(args, auxs)
        _guardian.restore_updater_states(self._guardian_updater(), opt_states)

    def _guardian_disk_restore(self, args, auxs):
        self.set_params(args, auxs)
        # a .params checkpoint has no optimizer state; stale (possibly
        # poisoned) momenta must not survive the rollback
        _guardian.zero_updater_states(self._guardian_updater())

    def fit(self, train_data, eval_data=None, eval_metric="acc",
            epoch_end_callback=None, batch_end_callback=None, kvstore="local",
            optimizer="sgd", optimizer_params=(("learning_rate", 0.01),),
            eval_end_callback=None, eval_batch_end_callback=None,
            initializer=None, arg_params=None, aux_params=None,
            allow_missing=False, force_rebind=False, force_init=False,
            begin_epoch=0, num_epoch=None, validation_metric=None, monitor=None):
        """ref: python/mxnet/module/base_module.py:275."""
        assert num_epoch is not None, "please specify number of epochs"
        from ..initializer import Uniform

        if initializer is None:
            initializer = Uniform(0.01)
        self.bind(
            data_shapes=train_data.provide_data, label_shapes=train_data.provide_label,
            for_training=True, force_rebind=force_rebind,
        )
        if monitor is not None:
            self.install_monitor(monitor)
        self.init_params(
            initializer=initializer, arg_params=arg_params, aux_params=aux_params,
            allow_missing=allow_missing, force_init=force_init,
        )
        self.init_optimizer(
            kvstore=kvstore, optimizer=optimizer, optimizer_params=optimizer_params
        )
        if validation_metric is None:
            validation_metric = eval_metric
        if not isinstance(eval_metric, metric_mod.EvalMetric):
            eval_metric = metric_mod.create(eval_metric)

        # training-run guardian (MXNET_GUARDIAN=1): non-finite sentinel,
        # skip-steps, rollback-to-last-good — None when off
        guard = _guardian.TrainingGuardian.create(
            kvstore=getattr(self, "_kvstore", None),
            epoch_end_callback=epoch_end_callback, logger=self.logger)
        if guard is not None:
            # loss z-score channel: live when the eval metric is
            # loss-like (ce/perplexity/mse/...), inert for accuracy
            guard.attach_metric(eval_metric)
            # exact-resume bridge (docs/how_to/data_service.md): a
            # frontier-capable iterator replaces the approximate
            # fast-forward on rollback
            guard.attach_data_iter(train_data)

        # K-step-scanned fast path (parallel/fit_trainer.py) — plain
        # single-device Module only; returns False and falls through to
        # the per-batch loop otherwise
        if self._try_scanned_fit(
                train_data, eval_data, eval_metric, validation_metric,
                epoch_end_callback, batch_end_callback, eval_end_callback,
                eval_batch_end_callback, begin_epoch, num_epoch, monitor,
                guardian=guard):
            return

        def _fit_one_batch(epoch, nbatch, data_batch):
            # mxtel: "batch" span nests under the epoch span; step
            # walltime + samples/sec feed the train.* metrics
            with _tel.span("batch"):
                step_tic = time.monotonic() if _tel.ENABLED else 0.0
                if monitor is not None:
                    monitor.tic()
                self.forward_backward(data_batch)
                if guard is None:
                    self.update()
                    self.update_metric(eval_metric, data_batch.label)
                else:
                    # metric BEFORE the guarded update: the outputs do
                    # not depend on the update, and the guardian's loss
                    # feed reads this batch's metric delta
                    self.update_metric(eval_metric, data_batch.label)
                    action = guard.guard_batch(
                        self.update, grad_arrays_fn=self._guardian_grads,
                        updater=self._guardian_updater())
                    if action == "rollback":
                        guard.rollback(
                            self._guardian_restore,
                            disk_restore_fn=self._guardian_disk_restore,
                            data_iter=train_data)
                    else:
                        guard.maybe_snapshot(self._guardian_snapshot)
                if monitor is not None:
                    monitor.toc_print()
                if _tel.ENABLED:
                    dt = time.monotonic() - step_tic
                    _tel.histogram("train.step_secs").observe(dt)
                    if dt > 0 and getattr(train_data, "batch_size", 0):
                        _tel.gauge("train.samples_per_sec").set(
                            train_data.batch_size / dt)
                if batch_end_callback is not None:
                    batch_end_params = BatchEndParam(
                        epoch=epoch, nbatch=nbatch, eval_metric=eval_metric, locals=locals()
                    )
                    _multiple_callbacks(batch_end_callback, batch_end_params)

        for epoch in range(begin_epoch, num_epoch):
            tic = time.time()
            eval_metric.reset()
            with _tel.span("epoch"):
                for nbatch, data_batch in enumerate(train_data):
                    _fit_one_batch(epoch, nbatch, data_batch)
            for name, val in eval_metric.get_name_value():
                self.logger.info("Epoch[%d] Train-%s=%f", epoch, name, val)
            toc = time.time()
            self.logger.info("Epoch[%d] Time cost=%.3f", epoch, (toc - tic))

            arg_params, aux_params = self.get_params()
            self.set_params(arg_params, aux_params)
            if epoch_end_callback is not None:
                _multiple_callbacks(
                    epoch_end_callback, epoch, self.symbol, arg_params, aux_params
                )
            if eval_data:
                res = self.score(
                    eval_data, validation_metric,
                    score_end_callback=eval_end_callback,
                    batch_end_callback=eval_batch_end_callback, epoch=epoch,
                )
                for name, val in res:
                    self.logger.info("Epoch[%d] Validation-%s=%f", epoch, name, val)
            train_data.reset()
