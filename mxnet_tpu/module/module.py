"""Module: symbol + contexts + optimizer state
(ref: python/mxnet/module/module.py:1-622 and executor_group.py:68-551).

Data parallelism follows SURVEY §2.7 row 1: batch sliced per context,
one executor per device, gradient reduce + weight update via KVStore or a
local updater. On a TPU mesh the preferred path is mxnet_tpu.parallel's
pjit trainer; Module keeps reference-API parity and works over plural
Contexts (e.g. 8 virtual CPU devices in tests).
"""
from __future__ import annotations

import logging

import numpy as _np

from ..base import MXNetError
from ..context import Context, cpu, current_context
from ..initializer import Uniform
from ..ndarray import NDArray, zeros
from .. import optimizer as opt
from ..executor_manager import _split_input_slice, _check_arguments
from ..model import _create_kvstore, _initialize_kvstore, _update_params, \
    _update_params_on_kvstore
from .base_module import BaseModule


class Module(BaseModule):
    def __init__(self, symbol, data_names=("data",), label_names=("softmax_label",),
                 logger=logging, context=None, work_load_list=None):
        super().__init__(logger=logger)
        if context is None:
            context = [current_context()]
        if isinstance(context, Context):
            context = [context]
        self._context = context
        if work_load_list is None:
            work_load_list = [1] * len(self._context)
        assert len(work_load_list) == len(self._context)
        self._work_load_list = work_load_list

        self._symbol = symbol
        data_names = list(data_names) if data_names is not None else []
        label_names = list(label_names) if label_names is not None else []
        arg_names = symbol.list_arguments()
        input_names = data_names + label_names
        self._param_names = [x for x in arg_names if x not in input_names]
        self._aux_names = symbol.list_auxiliary_states()
        self._data_names = data_names
        self._label_names = label_names
        self._output_names = symbol.list_outputs()
        self._arg_params = None
        self._aux_params = None
        self._params_dirty = False
        self._optimizer = None
        self._kvstore = None
        self._update_on_kvstore = None
        self._updater = None
        self._preload_opt_states = None
        self._execs = []
        self._data_shapes = None
        self._label_shapes = None
        self._slices = None

    @staticmethod
    def load(prefix, epoch=None, load_optimizer_states=False, **kwargs):
        """ref: module.py:86. TPU extension: ``epoch=None`` resumes from
        the newest VALID checkpoint of the prefix (corrupt/partial
        epochs skipped — see model.find_latest_checkpoint and
        docs/how_to/fault_tolerance.md)."""
        from ..model import find_latest_checkpoint, load_checkpoint

        if epoch is None:
            epoch = find_latest_checkpoint(prefix)
            if epoch is None:
                from ..base import MXNetError

                raise MXNetError(
                    "Module.load(%r, epoch=None): no valid checkpoint found"
                    % (prefix,))
        sym, args, auxs = load_checkpoint(prefix, epoch)
        mod = Module(symbol=sym, **kwargs)
        mod._arg_params = args
        mod._aux_params = auxs
        mod.params_initialized = True
        if load_optimizer_states:
            mod._preload_opt_states = "%s-%04d.states" % (prefix, epoch)
        return mod

    def save_checkpoint(self, prefix, epoch, save_optimizer_states=False,
                        keep_n=None):
        """ref: module.py:119. The params file lands crash-safely (tmp +
        fsync + atomic rename); ``keep_n`` keeps only the newest N
        epochs on disk (rolling retention)."""
        from ..model import save_checkpoint as _save_ckpt

        self._sync_params_from_devices()
        _save_ckpt(prefix, epoch, self.symbol, *self.get_params()[:1],
                   self.get_params()[1], sync=True, keep_n=keep_n)
        if save_optimizer_states:
            state_name = "%s-%04d.states" % (prefix, epoch)
            self.save_optimizer_states(state_name)

    # -- properties ------------------------------------------------------------
    @property
    def data_names(self):
        return self._data_names

    @property
    def output_names(self):
        return self._output_names

    @property
    def data_shapes(self):
        assert self.binded
        return self._data_shapes

    @property
    def label_shapes(self):
        assert self.binded
        return self._label_shapes

    @property
    def output_shapes(self):
        assert self.binded
        return [
            (name, tuple(o.shape))
            for name, o in zip(self._output_names, self._execs[0].outputs)
        ]

    def get_params(self):
        """ref: module.py:175."""
        live = getattr(self, "_scan_live", None)
        if live is not None:
            # scanned fit in progress: the freshest weights live in the
            # trainer's device state, not the executor — sync so a
            # mid-epoch checkpoint callback never reads stale params
            trainer, ap, xp = live
            trainer.write_back(ap, xp, self._aux_names)
            return (ap, xp)
        assert self.binded or self._arg_params is not None
        if self.binded and self._params_dirty:
            self._sync_params_from_devices()
        return (self._arg_params, self._aux_params)

    # -- bind ------------------------------------------------------------------
    def bind(self, data_shapes, label_shapes=None, for_training=True,
             inputs_need_grad=False, force_rebind=False, shared_module=None,
             grad_req="write"):
        """ref: module.py:235."""
        if force_rebind:
            self._reset_bind()
        if self.binded:
            self.logger.warning("Already binded, ignoring bind()")
            return
        self.for_training = for_training
        self.inputs_need_grad = inputs_need_grad
        self.binded = True
        if not for_training:
            assert not inputs_need_grad

        from ..io import DataDesc

        data_shapes = [
            x if isinstance(x, DataDesc) else DataDesc(*x) for x in data_shapes
        ]
        label_shapes = [
            x if isinstance(x, DataDesc) else DataDesc(*x) for x in (label_shapes or [])
        ]
        self._data_shapes = data_shapes
        self._label_shapes = label_shapes

        batch_size = data_shapes[0].shape[0]
        self._slices = _split_input_slice(batch_size, self._work_load_list)

        self._grad_req = grad_req
        shared_execs = (
            shared_module._execs if shared_module is not None else [None] * len(self._context)
        )
        self._execs = []
        for i, ctx in enumerate(self._context):
            dev_batch = self._slices[i].stop - self._slices[i].start
            shapes = {}
            for d in data_shapes + label_shapes:
                shapes[d.name] = (dev_batch,) + tuple(d.shape[1:])
            reqs = {}
            for name in self._symbol.list_arguments():
                if name in self._param_names:
                    reqs[name] = grad_req if for_training else "null"
                elif inputs_need_grad and name in self._data_names:
                    reqs[name] = grad_req
                else:
                    reqs[name] = "null"
            exec_ = self._symbol.simple_bind(
                ctx, grad_req=reqs, shared_exec=shared_execs[i], **shapes
            )
            self._execs.append(exec_)

        if shared_module is not None and shared_module.params_initialized:
            self.set_params(*shared_module.get_params())

    def _reset_bind(self):
        self.binded = False
        self._execs = []

    # -- params ----------------------------------------------------------------
    def init_params(self, initializer=None, arg_params=None, aux_params=None,
                    allow_missing=False, force_init=False):
        """ref: module.py:155."""
        if self.params_initialized and not force_init:
            return
        assert self.binded, "call bind before initializing the parameters"
        if initializer is None:
            initializer = Uniform(0.01)

        if self._arg_params is None:
            self._arg_params = {
                name: zeros(self._execs[0].arg_dict[name].shape,
                            dtype=self._execs[0].arg_dict[name].dtype)
                for name in self._param_names
            }
        if self._aux_params is None:
            self._aux_params = {
                name: zeros(arr.shape, dtype=arr.dtype)
                for name, arr in zip(self._aux_names, self._execs[0].aux_arrays)
            }

        for name, arr in self._arg_params.items():
            if arg_params is not None and name in arg_params:
                arr[:] = arg_params[name].asnumpy() if isinstance(arg_params[name], NDArray) else arg_params[name]
            elif not allow_missing or initializer is not None:
                if initializer is not None:
                    initializer(name, arr)
        for name, arr in self._aux_params.items():
            if aux_params is not None and name in aux_params:
                arr[:] = aux_params[name].asnumpy() if isinstance(aux_params[name], NDArray) else aux_params[name]
            elif initializer is not None:
                initializer(name, arr)

        self.params_initialized = True
        self._params_dirty = False
        for exec_ in self._execs:
            exec_.copy_params_from(self._arg_params, self._aux_params)

    def init_optimizer(self, kvstore="local", optimizer="sgd",
                       optimizer_params=(("learning_rate", 0.01),), force_init=False):
        """ref: module.py:422."""
        assert self.binded and self.params_initialized
        if self.optimizer_initialized and not force_init:
            self.logger.warning("optimizer already initialized, ignoring...")
            return
        (kvstore, update_on_kvstore) = _create_kvstore(
            kvstore, len(self._context), self._arg_params
        )
        batch_size = self._data_shapes[0].shape[0]
        if kvstore and "dist" in kvstore.type and "_sync" in kvstore.type:
            batch_size *= kvstore.num_workers
        rescale_grad = 1.0 / batch_size

        if isinstance(optimizer, str):
            idx2name = {}
            if update_on_kvstore:
                idx2name.update(enumerate(self._param_names))
            else:
                for k in range(len(self._context)):
                    idx2name.update(
                        {i * len(self._context) + k: n for i, n in enumerate(self._param_names)}
                    )
            optimizer_params = dict(optimizer_params)
            if "rescale_grad" not in optimizer_params:
                optimizer_params["rescale_grad"] = rescale_grad
            optimizer = opt.create(
                optimizer, sym=self.symbol, param_idx2name=idx2name, **optimizer_params
            )
        else:
            assert isinstance(optimizer, opt.Optimizer)

        self._optimizer = optimizer
        self._kvstore = kvstore
        self._update_on_kvstore = update_on_kvstore
        self._updater = None
        if kvstore:
            _initialize_kvstore(
                kvstore=kvstore, param_arrays=self._param_arrays(),
                arg_params=self._arg_params, param_names=self._param_names,
                update_on_kvstore=update_on_kvstore,
            )
        if update_on_kvstore:
            kvstore.set_optimizer(self._optimizer)
        else:
            self._updater = opt.get_updater(optimizer)
        self.optimizer_initialized = True
        if self._preload_opt_states is not None:
            self.load_optimizer_states(self._preload_opt_states)
            self._preload_opt_states = None

    def _param_arrays(self):
        arg_names = self._symbol.list_arguments()
        idx = [arg_names.index(n) for n in self._param_names]
        return [[e.arg_arrays[i] for e in self._execs] for i in idx]

    def _grad_arrays(self):
        arg_names = self._symbol.list_arguments()
        idx = [arg_names.index(n) for n in self._param_names]
        return [[e.grad_arrays[i] for e in self._execs] for i in idx]

    # -- compute ---------------------------------------------------------------
    def forward(self, data_batch, is_train=None):
        """ref: module.py:459."""
        assert self.binded and self.params_initialized
        if is_train is None:
            is_train = self.for_training
        self._load_batch(data_batch)
        for exec_ in self._execs:
            exec_.forward(is_train=is_train)

    def _load_batch(self, data_batch):
        for name_list, arrays in (
            (self._data_names, data_batch.data),
            (self._label_names, data_batch.label or []),
        ):
            for name, src in zip(name_list, arrays):
                for exec_, sl in zip(self._execs, self._slices):
                    src[sl].copyto(exec_.arg_dict[name])

    def backward(self, out_grads=None):
        """ref: module.py:468."""
        assert self.binded and self.params_initialized
        for exec_ in self._execs:
            exec_.backward(out_grads=out_grads)

    def update(self):
        """ref: module.py:480."""
        assert self.binded and self.params_initialized and self.optimizer_initialized
        self._params_dirty = True
        if self._update_on_kvstore:
            _update_params_on_kvstore(
                self._param_arrays(), self._grad_arrays(), self._kvstore
            )
        else:
            _update_params(
                self._param_arrays(), self._grad_arrays(), updater=self._updater,
                num_device=len(self._context), kvstore=self._kvstore,
            )

    def get_outputs(self, merge_multi_context=True):
        """ref: module.py:500."""
        assert self.binded and self.params_initialized
        outputs = [exec_.outputs for exec_ in self._execs]
        if merge_multi_context:
            from ..ndarray import concatenate

            if len(outputs) == 1:
                return list(outputs[0])
            return [
                concatenate([outputs[d][i].as_in_context(cpu()) for d in range(len(outputs))])
                for i in range(len(outputs[0]))
            ]
        return outputs

    def get_input_grads(self, merge_multi_context=True):
        """ref: module.py:518."""
        assert self.binded and self.params_initialized and self.inputs_need_grad
        arg_names = self._symbol.list_arguments()
        idx = [arg_names.index(n) for n in self._data_names]
        grads = [[e.grad_arrays[i] for i in idx] for e in self._execs]
        if merge_multi_context:
            from ..ndarray import concatenate

            if len(grads) == 1:
                return list(grads[0])
            return [
                concatenate([grads[d][i].as_in_context(cpu()) for d in range(len(grads))])
                for i in range(len(grads[0]))
            ]
        return grads

    def update_metric(self, eval_metric, labels):
        """ref: module.py:537."""
        for exec_, sl in zip(self._execs, self._slices):
            labels_slice = [label[sl] for label in labels]
            eval_metric.update(labels_slice, exec_.outputs)

    def _sync_params_from_devices(self):
        """Average per-device copies back into _arg_params
        (ref: module.py:546 _sync_params_from_devices)."""
        for name in self._param_names:
            blocks = [e.arg_dict[name] for e in self._execs]
            w = blocks[0].copy()
            for b in blocks[1:]:
                w += b.as_in_context(w.context)
            w /= len(blocks)
            w.copyto(self._arg_params[name])
        for name in self._aux_names:
            blocks = [e.aux_dict[name] for e in self._execs]
            w = blocks[0].copy()
            for b in blocks[1:]:
                w += b.as_in_context(w.context)
            w /= len(blocks)
            w.copyto(self._aux_params[name])
        self._params_dirty = False

    def save_optimizer_states(self, fname):
        """ref: module.py:569."""
        assert self.optimizer_initialized
        import pickle

        with open(fname, "wb") as fout:
            fout.write(pickle.dumps(self._optimizer))

    def load_optimizer_states(self, fname):
        """ref: module.py:581."""
        assert self.optimizer_initialized
        import pickle

        with open(fname, "rb") as f:
            self._optimizer = pickle.loads(f.read())
        self._updater = opt.get_updater(self._optimizer)

    def install_monitor(self, mon):
        """ref: module.py:594."""
        assert self.binded
        for exec_ in self._execs:
            mon.install(exec_)

    # -- scanned fast path (parallel/fit_trainer.py) ---------------------------
    def _try_scanned_fit(self, train_data, eval_data, eval_metric,
                         validation_metric, epoch_end_callback,
                         batch_end_callback, eval_end_callback,
                         eval_batch_end_callback, begin_epoch, num_epoch,
                         monitor, guardian=None):
        """Run fit() as K-step compiled scans when eligible (the same
        fast path FeedForward uses, model._train_scanned): single
        device, local updates (no kvstore), scannable optimizer, no
        monitor. Observable semantics preserved: per-batch metrics and
        callbacks (Module numbers batches from 0), per-epoch Train-*
        logging, epoch_end callbacks with synced params, eval via
        score(). Returns False to fall back."""
        import os as _os
        import time as _time

        from ..base import MXNetError
        from ..model import (_buffer_batch, _desc_name, _desc_shape,
                             _multiple_callbacks, _scan_drain, _scan_flush,
                             _scan_k)
        from ..parallel.fit_trainer import make_fit_trainer, supports_optimizer

        K = _scan_k()
        # the scanned trainer has grad_req='write' semantics for every
        # param — a module bound with 'add'/'null' (frozen or accumulated
        # params) must keep the per-batch loop
        if (K <= 1 or len(self._context) != 1 or monitor is not None
                or self._kvstore is not None or self._update_on_kvstore
                or not train_data.provide_label
                or getattr(self, "_grad_req", "write") != "write"
                or not supports_optimizer(self._optimizer)):
            return False
        input_shapes = {
            _desc_name(d): _desc_shape(d)
            for d in (list(train_data.provide_data)
                      + list(train_data.provide_label))
        }
        arg_params, aux_params = self.get_params()
        try:
            trainer = make_fit_trainer(
                self._symbol, self._context[0], input_shapes,
                self._optimizer, arg_params, aux_params, self._param_names,
                compute_dtype=_os.environ.get("MXNET_COMPUTE_DTYPE") or None)
        except MXNetError as e:
            self.logger.debug("scanned fit unavailable (%s); per-batch "
                              "loop", e)
            return False
        except Exception as e:  # construction-only failures fall back
            self.logger.warning("scanned fit construction failed (%s: %s); "
                                "per-batch loop", type(e).__name__, e)
            return False
        input_names = trainer.input_names
        label_names = [_desc_name(d) for d in train_data.provide_label]

        def _drain(pending):
            action = _scan_drain(pending, eval_metric, label_names,
                                 batch_end_callback, nbatch_base=0,
                                 guardian=guardian)
            if guardian is not None and action == "rollback":
                guardian.rollback(trainer.restore_state,
                                  disk_restore_fn=trainer.load_params,
                                  data_iter=train_data)

        # while the scanned loop is live, get_params() syncs from the
        # trainer (a batch_end_callback that checkpoints mid-epoch must
        # not read epoch-start weights)
        self._scan_live = (trainer, arg_params, aux_params)
        try:
            for epoch in range(begin_epoch, num_epoch):
                tic = _time.time()
                eval_metric.reset()
                pending = None
                buf = []
                nbatch = 0
                for data_batch in train_data:
                    buf.append(_buffer_batch(data_batch, input_names))
                    nbatch += 1
                    if len(buf) == K:
                        new_pending = _scan_flush(trainer, buf, epoch,
                                                  nbatch - K,
                                                  guardian=guardian)
                        _drain(pending)
                        pending = new_pending
                        buf = []
                if buf:
                    new_pending = _scan_flush(trainer, buf, epoch,
                                              nbatch - len(buf),
                                              guardian=guardian)
                    _drain(pending)
                    pending = new_pending
                    buf = []
                _drain(pending)
                if guardian is not None:
                    # no chunk in flight across the epoch boundary
                    guardian.end_epoch()
                for name, val in eval_metric.get_name_value():
                    self.logger.info("Epoch[%d] Train-%s=%f", epoch, name,
                                     val)
                self.logger.info("Epoch[%d] Time cost=%.3f", epoch,
                                 _time.time() - tic)
                trainer.write_back(arg_params, aux_params, self._aux_names)
                self.set_params(arg_params, aux_params)
                if epoch_end_callback is not None:
                    _multiple_callbacks(epoch_end_callback, epoch,
                                        self.symbol, arg_params, aux_params)
                if eval_data:
                    res = self.score(eval_data, validation_metric,
                                     score_end_callback=eval_end_callback,
                                     batch_end_callback=eval_batch_end_callback,
                                     epoch=epoch)
                    for name, val in res:
                        self.logger.info("Epoch[%d] Validation-%s=%f",
                                         epoch, name, val)
                train_data.reset()
        finally:
            self._scan_live = None
        return True
