"""BucketingModule: one executor per bucket with shared parameters/memory
(ref: python/mxnet/module/bucketing_module.py:16-336, switch_bucket:195).

The reference shares the GraphStoragePool across bucket executors
(SURVEY §2.6); here buckets share parameter NDArrays via shared_module and
each bucket's jit program is cached by XLA keyed on shapes — the
"shape buckets + jit cache" mapping of SURVEY §2.7.
"""
from __future__ import annotations

import logging

from ..base import MXNetError
from .base_module import BaseModule
from .module import Module


class BucketingModule(BaseModule):
    def __init__(self, sym_gen, default_bucket_key=None, logger=logging,
                 context=None, work_load_list=None):
        super().__init__(logger=logger)
        assert default_bucket_key is not None
        self._default_bucket_key = default_bucket_key
        self._sym_gen = sym_gen
        self._context = context
        self._work_load_list = work_load_list
        self._buckets = {}
        self._curr_module = None

    def _reset_bind(self):
        self.binded = False
        self._buckets = {}
        self._curr_module = None

    @property
    def data_names(self):
        if self.binded:
            return self._curr_module.data_names
        _, data_names, _ = self._call_sym_gen(self._default_bucket_key)
        return data_names

    @property
    def output_names(self):
        if self.binded:
            return self._curr_module.output_names
        symbol, _, _ = self._call_sym_gen(self._default_bucket_key)
        return symbol.list_outputs()

    @property
    def data_shapes(self):
        assert self.binded
        return self._curr_module.data_shapes

    @property
    def label_shapes(self):
        assert self.binded
        return self._curr_module.label_shapes

    @property
    def output_shapes(self):
        assert self.binded
        return self._curr_module.output_shapes

    @property
    def symbol(self):
        assert self.binded
        return self._curr_module.symbol

    def _call_sym_gen(self, bucket_key):
        res = self._sym_gen(bucket_key)
        if isinstance(res, tuple):
            return res
        return (res, ("data",), ("softmax_label",))

    def get_params(self):
        assert self.binded and self.params_initialized
        return self._curr_module.get_params()

    def init_params(self, initializer=None, arg_params=None, aux_params=None,
                    allow_missing=False, force_init=False):
        """ref: bucketing_module.py:128."""
        if self.params_initialized and not force_init:
            return
        assert self.binded
        self._curr_module.init_params(
            initializer=initializer, arg_params=arg_params, aux_params=aux_params,
            allow_missing=allow_missing, force_init=force_init,
        )
        self.params_initialized = True

    def bind(self, data_shapes, label_shapes=None, for_training=True,
             inputs_need_grad=False, force_rebind=False, shared_module=None,
             grad_req="write"):
        """ref: bucketing_module.py:150 — binds the default bucket."""
        assert shared_module is None, "shared_module for BucketingModule is not supported"
        if force_rebind:
            self._reset_bind()
        if self.binded:
            self.logger.warning("Already binded, ignoring bind()")
            return
        self.for_training = for_training
        self.inputs_need_grad = inputs_need_grad
        self._grad_req = grad_req
        self.binded = True

        symbol, data_names, label_names = self._call_sym_gen(self._default_bucket_key)
        module = Module(
            symbol, data_names, label_names, logger=self.logger,
            context=self._context, work_load_list=self._work_load_list,
        )
        module.bind(
            data_shapes, label_shapes, for_training, inputs_need_grad,
            force_rebind=False, shared_module=None, grad_req=grad_req,
        )
        self._curr_module = module
        self._buckets[self._default_bucket_key] = module

    def switch_bucket(self, bucket_key, data_shapes, label_shapes=None):
        """ref: bucketing_module.py:195."""
        assert self.binded, "call bind before switching bucket"
        if bucket_key not in self._buckets:
            symbol, data_names, label_names = self._call_sym_gen(bucket_key)
            module = Module(
                symbol, data_names, label_names, logger=self.logger,
                context=self._context, work_load_list=self._work_load_list,
            )
            module.bind(
                data_shapes, label_shapes, self._curr_module.for_training,
                self._curr_module.inputs_need_grad, force_rebind=False,
                shared_module=self._buckets[self._default_bucket_key],
                grad_req=getattr(self, "_grad_req", "write"),
            )
            # a bucket created after init_optimizer must share the live
            # optimizer state too (ref bucketing_module.py:219-221)
            if self.optimizer_initialized:
                module.borrow_optimizer(
                    self._buckets[self._default_bucket_key])
            self._buckets[bucket_key] = module
        self._curr_module = self._buckets[bucket_key]

    def init_optimizer(self, kvstore="local", optimizer="sgd",
                       optimizer_params=(("learning_rate", 0.01),), force_init=False):
        """ref: bucketing_module.py:230."""
        assert self.binded and self.params_initialized
        if self.optimizer_initialized and not force_init:
            self.logger.warning("optimizer already initialized, ignoring.")
            return
        self._curr_module.init_optimizer(
            kvstore, optimizer, optimizer_params, force_init=force_init
        )
        for mod in self._buckets.values():
            if mod is not self._curr_module:
                mod.borrow_optimizer(self._curr_module)
        self.optimizer_initialized = True

    def forward(self, data_batch, is_train=None):
        """ref: bucketing_module.py:255."""
        assert self.binded and self.params_initialized
        bucket_key = getattr(data_batch, "bucket_key", None)
        if bucket_key is None:
            # batches from plain (non-bucket) iterators — e.g. an eval
            # iterator passed to score() — run under the default key
            bucket_key = self._default_bucket_key
        default_mod = self._buckets[self._default_bucket_key]
        provide_data = data_batch.provide_data
        if provide_data is None:
            provide_data = [(n, tuple(a.shape)) for n, a in zip(
                default_mod.data_names, data_batch.data)]
        provide_label = getattr(data_batch, "provide_label", None)
        if provide_label is None and getattr(data_batch, "label", None):
            provide_label = [(n, tuple(a.shape)) for n, a in zip(
                default_mod._label_names, data_batch.label)]
        self.switch_bucket(bucket_key, provide_data, provide_label)
        # share latest params into the switched module
        if self._curr_module.params_initialized is False:
            src = self._buckets[self._default_bucket_key]
            if src.params_initialized:
                self._curr_module.init_params(*(), arg_params=src.get_params()[0],
                                              aux_params=src.get_params()[1],
                                              force_init=True)
        self._curr_module.forward(data_batch, is_train=is_train)

    def backward(self, out_grads=None):
        assert self.binded and self.params_initialized
        self._curr_module.backward(out_grads=out_grads)

    def update(self):
        assert self.binded and self.params_initialized and self.optimizer_initialized
        self._curr_module.update()
        # Sibling buckets alias the same parameter NDArrays (the shared
        # memory pool in executor._simple_bind), so the update is already
        # visible to them — no per-step propagation. Only a bucket whose
        # executor did NOT share a buffer (shape/dtype mismatch) needs a
        # copy; detect by identity and copy just those.
        cur_execs = self._curr_module._execs
        for mod in self._buckets.values():
            if mod is self._curr_module or not mod.params_initialized:
                continue
            data_like = set(mod.data_names) | set(mod._label_names or ())
            stale = [
                name
                for name, arr in mod._execs[0].arg_dict.items()
                if name in cur_execs[0].arg_dict
                and arr is not cur_execs[0].arg_dict[name]
                and name not in data_like
            ] + [
                name
                for name, arr in mod._execs[0].aux_dict.items()
                if name in cur_execs[0].aux_dict
                and arr is not cur_execs[0].aux_dict[name]
            ]
            if stale:
                arg, aux = self._curr_module.get_params()
                mod.set_params(arg, aux)

    def get_outputs(self, merge_multi_context=True):
        assert self.binded and self.params_initialized
        return self._curr_module.get_outputs(merge_multi_context=merge_multi_context)

    def get_input_grads(self, merge_multi_context=True):
        assert self.binded and self.params_initialized and self.inputs_need_grad
        return self._curr_module.get_input_grads(merge_multi_context=merge_multi_context)

    def update_metric(self, eval_metric, labels):
        assert self.binded and self.params_initialized
        self._curr_module.update_metric(eval_metric, labels)

    def install_monitor(self, mon):
        assert self.binded
        for mod in self._buckets.values():
            mod.install_monitor(mon)


def _borrow_optimizer(self, shared_module):
    """Share optimizer state across bucket modules (ref: module.py
    borrow_optimizer)."""
    self._optimizer = shared_module._optimizer
    self._kvstore = shared_module._kvstore
    self._update_on_kvstore = shared_module._update_on_kvstore
    self._updater = shared_module._updater
    self.optimizer_initialized = True


Module.borrow_optimizer = _borrow_optimizer
