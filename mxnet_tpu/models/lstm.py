"""Explicitly-unrolled LSTM language model — baseline configs 3 & 4
(ref: example/rnn/lstm.py:17-41 lstm(), example/model-parallel-lstm/lstm.py:48-99).

Same construction as the reference: per-timestep weight sharing via shared
Variables, SliceChannel over the embedded sequence, gates as one 4*H
FullyConnected. For the model-parallel variant, layers are tagged with
AttrScope(ctx_group=...) exactly like the reference, and bind's group2ctx
places them (SURVEY §2.7 model parallelism row).
"""
from __future__ import annotations

from collections import namedtuple

from .. import symbol as sym
from ..attribute import AttrScope

LSTMState = namedtuple("LSTMState", ["c", "h"])
LSTMParam = namedtuple(
    "LSTMParam", ["i2h_weight", "i2h_bias", "h2h_weight", "h2h_bias"]
)


def lstm_cell(num_hidden, indata, prev_state, param, seqidx, layeridx, dropout=0.0):
    """One LSTM step (ref: example/rnn/lstm.py:17-41)."""
    if dropout > 0.0:
        indata = sym.Dropout(data=indata, p=dropout)
    i2h = sym.FullyConnected(
        data=indata, weight=param.i2h_weight, bias=param.i2h_bias,
        num_hidden=num_hidden * 4, name="t%d_l%d_i2h" % (seqidx, layeridx),
    )
    h2h = sym.FullyConnected(
        data=prev_state.h, weight=param.h2h_weight, bias=param.h2h_bias,
        num_hidden=num_hidden * 4, name="t%d_l%d_h2h" % (seqidx, layeridx),
    )
    gates = i2h + h2h
    slice_gates = sym.SliceChannel(
        gates, num_outputs=4, name="t%d_l%d_slice" % (seqidx, layeridx)
    )
    in_gate = sym.Activation(slice_gates[0], act_type="sigmoid")
    in_transform = sym.Activation(slice_gates[1], act_type="tanh")
    forget_gate = sym.Activation(slice_gates[2], act_type="sigmoid")
    out_gate = sym.Activation(slice_gates[3], act_type="sigmoid")
    next_c = (forget_gate * prev_state.c) + (in_gate * in_transform)
    next_h = out_gate * sym.Activation(next_c, act_type="tanh")
    return LSTMState(c=next_c, h=next_h)


def lstm_unroll(num_lstm_layer, seq_len, input_size, num_hidden, num_embed,
                num_label, dropout=0.0, group2ctx_layers=False,
                ignore_label=None):
    """Unrolled LSTM LM symbol (ref: example/rnn/lstm.py lstm_unroll:44).
    With group2ctx_layers=True, tags embed/layers/decode with ctx_group
    attrs like example/model-parallel-lstm/lstm.py:48-99.
    ignore_label: exclude padding rows from the loss — on padded
    sequence data the un-ignored label-0 positions otherwise teach the
    model to smear probability onto the padding class, monotonically
    worsening real-token perplexity while the optimized loss still
    falls (r5 finding, examples/rnn)."""

    def scoped(group):
        if group2ctx_layers:
            return AttrScope(ctx_group=group)
        return AttrScope()

    with scoped("embed"):
        embed_weight = sym.Variable("embed_weight")
    with scoped("decode"):
        cls_weight = sym.Variable("cls_weight")
        cls_bias = sym.Variable("cls_bias")
    param_cells = []
    last_states = []
    for i in range(num_lstm_layer):
        with scoped("layer%d" % i):
            param_cells.append(LSTMParam(
                i2h_weight=sym.Variable("l%d_i2h_weight" % i),
                i2h_bias=sym.Variable("l%d_i2h_bias" % i),
                h2h_weight=sym.Variable("l%d_h2h_weight" % i),
                h2h_bias=sym.Variable("l%d_h2h_bias" % i),
            ))
            last_states.append(LSTMState(
                c=sym.Variable("l%d_init_c" % i), h=sym.Variable("l%d_init_h" % i)
            ))

    with scoped("embed"):
        data = sym.Variable("data")
        embed = sym.Embedding(
            data=data, input_dim=input_size, weight=embed_weight,
            output_dim=num_embed, name="embed",
        )
        wordvec = sym.SliceChannel(
            data=embed, num_outputs=seq_len, axis=1, squeeze_axis=True, name="wordvec"
        )

    hidden_all = []
    for seqidx in range(seq_len):
        hidden = wordvec[seqidx]
        for i in range(num_lstm_layer):
            with scoped("layer%d" % i):
                next_state = lstm_cell(
                    num_hidden, indata=hidden, prev_state=last_states[i],
                    param=param_cells[i], seqidx=seqidx, layeridx=i,
                    dropout=dropout if i > 0 else 0.0,
                )
                hidden = next_state.h
                last_states[i] = next_state
        hidden_all.append(hidden)

    with scoped("decode"):
        # N-major rows so pred row (n, t) pairs with label[n, t] under
        # the metric's plain reshape(-1) — see models/_unroll.py for the
        # r5 finding behind this layout
        steps = [sym.Reshape(data=h, shape=(0, 1, -1)) for h in hidden_all]
        hidden_concat = sym.Concat(*steps, dim=1, num_args=len(steps))
        hidden_concat = sym.Reshape(data=hidden_concat,
                                    shape=(-1, num_hidden))
        if dropout > 0.0:
            hidden_concat = sym.Dropout(data=hidden_concat, p=dropout)
        pred = sym.FullyConnected(
            data=hidden_concat, num_hidden=num_label, weight=cls_weight,
            bias=cls_bias, name="pred",
        )
        label = sym.Variable("softmax_label")
        label = sym.Reshape(data=label, shape=(-1,))
        if ignore_label is not None:
            loss = sym.SoftmaxOutput(data=pred, label=label, name="softmax",
                                     use_ignore=True,
                                     ignore_label=ignore_label)
        else:
            loss = sym.SoftmaxOutput(data=pred, label=label, name="softmax")
    return loss


def lstm_group2ctx(num_lstm_layer, contexts):
    """Build the group2ctx map for model-parallel binding
    (ref: example/model-parallel-lstm/lstm_ptb.py:79-90)."""
    group2ctx = {"embed": contexts[0], "decode": contexts[-1]}
    for i in range(num_lstm_layer):
        group2ctx["layer%d" % i] = contexts[min(1 + i, len(contexts) - 1)]
    return group2ctx
