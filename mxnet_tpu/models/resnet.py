"""ResNet (v1) — baseline config 2, the bench.py flagship
(ref: example/image-classification/symbol_resnet.py; arch per He et al.).
Built bf16-friendly: convs accumulate f32 (ops/nn.py), BN in f32.
"""
from __future__ import annotations

from .. import symbol as sym


def _conv_bn(data, num_filter, kernel, stride, pad, name, act=True):
    conv = sym.Convolution(
        data=data, num_filter=num_filter, kernel=kernel, stride=stride, pad=pad,
        no_bias=True, name=name + "_conv",
    )
    bn = sym.BatchNorm(data=conv, fix_gamma=False, eps=2e-5, momentum=0.9,
                       name=name + "_bn")
    if act:
        return sym.Activation(data=bn, act_type="relu", name=name + "_relu")
    return bn


def _bottleneck(data, num_filter, stride, dim_match, name):
    b1 = _conv_bn(data, num_filter // 4, (1, 1), (1, 1), (0, 0), name + "_branch2a")
    b2 = _conv_bn(b1, num_filter // 4, (3, 3), stride, (1, 1), name + "_branch2b")
    b3 = _conv_bn(b2, num_filter, (1, 1), (1, 1), (0, 0), name + "_branch2c", act=False)
    if dim_match:
        shortcut = data
    else:
        shortcut = _conv_bn(
            data, num_filter, (1, 1), stride, (0, 0), name + "_branch1", act=False
        )
    fused = b3 + shortcut
    return sym.Activation(data=fused, act_type="relu", name=name + "_relu")


def get_resnet(num_classes=1000, num_layers=50):
    """ResNet-50/101/152 v1 for 224x224 input."""
    if num_layers == 50:
        units = [3, 4, 6, 3]
    elif num_layers == 101:
        units = [3, 4, 23, 3]
    elif num_layers == 152:
        units = [3, 8, 36, 3]
    else:
        raise ValueError("unsupported num_layers %d" % num_layers)
    filters = [256, 512, 1024, 2048]

    data = sym.Variable("data")
    body = _conv_bn(data, 64, (7, 7), (2, 2), (3, 3), "conv0")
    body = sym.Pooling(
        data=body, kernel=(3, 3), stride=(2, 2), pad=(1, 1), pool_type="max",
        name="pool0",
    )
    for stage, (n, f) in enumerate(zip(units, filters)):
        stride = (1, 1) if stage == 0 else (2, 2)
        body = _bottleneck(body, f, stride, False, "stage%d_unit1" % (stage + 1))
        for i in range(2, n + 1):
            body = _bottleneck(body, f, (1, 1), True, "stage%d_unit%d" % (stage + 1, i))
    pool = sym.Pooling(data=body, global_pool=True, kernel=(7, 7), pool_type="avg",
                       name="pool1")
    flat = sym.Flatten(data=pool)
    fc = sym.FullyConnected(data=flat, num_hidden=num_classes, name="fc1")
    return sym.SoftmaxOutput(data=fc, name="softmax")
