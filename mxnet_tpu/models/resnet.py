"""ResNet (v1) — baseline config 2, the bench.py flagship
(ref: example/image-classification/symbol_resnet.py; arch per He et al.).
Built bf16-friendly: BN statistics in f32; conv accumulation follows the
backend default (f32 on TPU MXU).
"""
from __future__ import annotations

from .. import symbol as sym


def _conv_bn(data, num_filter, kernel, stride, pad, name, act=True):
    conv = sym.Convolution(
        data=data, num_filter=num_filter, kernel=kernel, stride=stride, pad=pad,
        no_bias=True, name=name + "_conv",
    )
    bn = sym.BatchNorm(data=conv, fix_gamma=False, eps=2e-5, momentum=0.9,
                       name=name + "_bn")
    if act:
        return sym.Activation(data=bn, act_type="relu", name=name + "_relu")
    return bn


def _bottleneck(data, num_filter, stride, dim_match, name):
    b1 = _conv_bn(data, num_filter // 4, (1, 1), (1, 1), (0, 0), name + "_branch2a")
    b2 = _conv_bn(b1, num_filter // 4, (3, 3), stride, (1, 1), name + "_branch2b")
    b3 = _conv_bn(b2, num_filter, (1, 1), (1, 1), (0, 0), name + "_branch2c", act=False)
    if dim_match:
        shortcut = data
    else:
        shortcut = _conv_bn(
            data, num_filter, (1, 1), stride, (0, 0), name + "_branch1", act=False
        )
    fused = b3 + shortcut
    return sym.Activation(data=fused, act_type="relu", name=name + "_relu")


def get_resnet(num_classes=1000, num_layers=50):
    """ResNet-50/101/152 v1 for 224x224 input."""
    if num_layers == 50:
        units = [3, 4, 6, 3]
    elif num_layers == 101:
        units = [3, 4, 23, 3]
    elif num_layers == 152:
        units = [3, 8, 36, 3]
    else:
        raise ValueError("unsupported num_layers %d" % num_layers)
    filters = [256, 512, 1024, 2048]

    data = sym.Variable("data")
    body = _conv_bn(data, 64, (7, 7), (2, 2), (3, 3), "conv0")
    body = sym.Pooling(
        data=body, kernel=(3, 3), stride=(2, 2), pad=(1, 1), pool_type="max",
        name="pool0",
    )
    for stage, (n, f) in enumerate(zip(units, filters)):
        stride = (1, 1) if stage == 0 else (2, 2)
        body = _bottleneck(body, f, stride, False, "stage%d_unit1" % (stage + 1))
        for i in range(2, n + 1):
            body = _bottleneck(body, f, (1, 1), True, "stage%d_unit%d" % (stage + 1, i))
    pool = sym.Pooling(data=body, global_pool=True, kernel=(7, 7), pool_type="avg",
                       name="pool1")
    flat = sym.Flatten(data=pool)
    fc = sym.FullyConnected(data=flat, num_hidden=num_classes, name="fc1")
    return sym.SoftmaxOutput(data=fc, name="softmax")


def _basic_unit(data, num_filter, dim_match, name):
    """Basic (two 3x3) residual unit for the CIFAR-size net
    (ref: example/image-classification/symbol_resnet-28-small.py
    residual_factory)."""
    stride = (1, 1) if dim_match else (2, 2)
    c1 = _conv_bn(data, num_filter, (3, 3), stride, (1, 1), name + "_a")
    c2 = _conv_bn(c1, num_filter, (3, 3), (1, 1), (1, 1), name + "_b", act=False)
    if dim_match:
        shortcut = data
    else:
        shortcut = _conv_bn(data, num_filter, (1, 1), stride, (0, 0),
                            name + "_sc", act=False)
    return sym.Activation(data=c2 + shortcut, act_type="relu", name=name + "_relu")


def get_resnet_small(num_classes=10, n=3):
    """ResNet-(6n+2) for 28x28/32x32 inputs — CIFAR baseline config
    (ref: symbol_resnet-28-small.py get_symbol; n=3 → 20 layers)."""
    data = sym.Variable("data")
    body = _conv_bn(data, 16, (3, 3), (1, 1), (1, 1), "conv0")
    for stage, f in enumerate([16, 32, 64]):
        for i in range(n):
            dim_match = not (stage > 0 and i == 0)
            body = _basic_unit(body, f, dim_match,
                               "stage%d_unit%d" % (stage + 1, i + 1))
    pool = sym.Pooling(data=body, global_pool=True, kernel=(7, 7),
                       pool_type="avg", name="pool1")
    flat = sym.Flatten(data=pool)
    fc = sym.FullyConnected(data=flat, num_hidden=num_classes, name="fc1")
    return sym.SoftmaxOutput(data=fc, name="softmax")
