"""Explicitly-unrolled GRU language model (ref: example/rnn/gru.py).

Same construction discipline as models/lstm.py: per-timestep weight
sharing through shared Variables, SliceChannel over the embedded
sequence, the two gates (update, reset) as one 2*H FullyConnected and
the candidate transform as its own pair of projections — the reset gate
multiplies the PREVIOUS hidden state before the h2h transform (Chung et
al. 2014, the formulation the reference's gru() cell uses,
ref: example/rnn/gru.py:17-57).
"""
from __future__ import annotations

from collections import namedtuple

from .. import symbol as sym

GRUState = namedtuple("GRUState", ["h"])
GRUParam = namedtuple(
    "GRUParam",
    ["gates_i2h_weight", "gates_i2h_bias", "gates_h2h_weight",
     "gates_h2h_bias", "trans_i2h_weight", "trans_i2h_bias",
     "trans_h2h_weight", "trans_h2h_bias"],
)


def gru_cell(num_hidden, indata, prev_state, param, seqidx, layeridx,
             dropout=0.0):
    """One GRU step: z/r gates from a fused 2*H projection, candidate
    from the reset-scaled previous state, convex blend for the output."""
    if dropout > 0.0:
        indata = sym.Dropout(data=indata, p=dropout)
    gates = sym.FullyConnected(
        data=indata, weight=param.gates_i2h_weight,
        bias=param.gates_i2h_bias, num_hidden=num_hidden * 2,
        name="t%d_l%d_gates_i2h" % (seqidx, layeridx),
    ) + sym.FullyConnected(
        data=prev_state.h, weight=param.gates_h2h_weight,
        bias=param.gates_h2h_bias, num_hidden=num_hidden * 2,
        name="t%d_l%d_gates_h2h" % (seqidx, layeridx),
    )
    zr = sym.SliceChannel(gates, num_outputs=2,
                          name="t%d_l%d_slice" % (seqidx, layeridx))
    update = sym.Activation(zr[0], act_type="sigmoid")
    reset = sym.Activation(zr[1], act_type="sigmoid")
    cand = sym.FullyConnected(
        data=indata, weight=param.trans_i2h_weight,
        bias=param.trans_i2h_bias, num_hidden=num_hidden,
        name="t%d_l%d_trans_i2h" % (seqidx, layeridx),
    ) + sym.FullyConnected(
        data=prev_state.h * reset, weight=param.trans_h2h_weight,
        bias=param.trans_h2h_bias, num_hidden=num_hidden,
        name="t%d_l%d_trans_h2h" % (seqidx, layeridx),
    )
    cand = sym.Activation(cand, act_type="tanh")
    # next_h = (1 - z) * h + z * cand, written as h + z*(cand - h) so the
    # update gate literally gates the state CHANGE
    next_h = prev_state.h + update * (cand - prev_state.h)
    return GRUState(h=next_h)


def gru_unroll(num_gru_layer, seq_len, input_size, num_hidden, num_embed,
               num_label, dropout=0.0, ignore_label=None):
    """Unrolled GRU LM symbol; interface-identical to lstm_unroll so the
    bucketing example can swap cells (init states: h only, no c).
    ignore_label: exclude padding rows from the loss (see models/rnn.py)."""
    from ._unroll import unroll_lm

    def make_params(i):
        return GRUParam(
            gates_i2h_weight=sym.Variable("l%d_i2h_gates_weight" % i),
            gates_i2h_bias=sym.Variable("l%d_i2h_gates_bias" % i),
            gates_h2h_weight=sym.Variable("l%d_h2h_gates_weight" % i),
            gates_h2h_bias=sym.Variable("l%d_h2h_gates_bias" % i),
            trans_i2h_weight=sym.Variable("l%d_i2h_trans_weight" % i),
            trans_i2h_bias=sym.Variable("l%d_i2h_trans_bias" % i),
            trans_h2h_weight=sym.Variable("l%d_h2h_trans_weight" % i),
            trans_h2h_bias=sym.Variable("l%d_h2h_trans_bias" % i),
        )

    return unroll_lm(num_gru_layer, seq_len, input_size, num_hidden,
                     num_embed, num_label, make_params,
                     lambda i: GRUState(h=sym.Variable("l%d_init_h" % i)),
                     gru_cell, dropout=dropout, ignore_label=ignore_label)
