"""Model zoo: the baseline-config model families (SURVEY §2.B).

Symbol-based models mirror the reference examples (LeNet, MLP, ResNet,
Inception-BN, unrolled LSTM); jax-native models (transformer) target the
sharded parallel trainer for mesh-scale training.
"""
from .lenet import get_lenet
from .mlp import get_mlp
from .resnet import get_resnet, get_resnet_small
from .inception_bn import get_inception_bn, get_inception_bn_small
from .classic_convnets import (
    get_alexnet, get_vgg, get_googlenet, get_inception_v3,
)
from .unet import get_unet
from .lstm import lstm_unroll
from .gru import gru_unroll
from .rnn import rnn_unroll
from . import transformer

__all__ = [
    "get_lenet", "get_mlp", "get_resnet", "get_resnet_small",
    "get_inception_bn", "get_inception_bn_small",
    "get_alexnet", "get_vgg", "get_googlenet", "get_inception_v3",
    "get_unet",
    "lstm_unroll", "gru_unroll", "rnn_unroll", "transformer",
]
