"""Inception-BN: the reference's flagship ImageNet baseline network.

Two variants, matching the reference's symbol files:
- ``get_inception_bn_small`` — the 28x28 CIFAR throughput model (ref:
  example/image-classification/symbol_inception-bn-28-small.py,
  BASELINE.md row 1: 842→2943 img/s on 1→4 GTX 980);
- ``get_inception_bn`` — the full 224x224 model behind the headline
  ImageNet epoch times (ref: symbol_inception-bn.py; BASELINE.md:
  2,495 s/epoch at bs=512 on 4x Titan X, the bench.py baseline), and
  with ``num_classes=21841`` the full-ImageNet-21k config
  (symbol_inception-bn-full.py, imagenet_full.md).
Ioffe & Szegedy 2015 (arXiv:1502.03167)."""
from __future__ import annotations

from .. import symbol as sym


def _conv_factory(data, num_filter, kernel, stride=(1, 1), pad=(0, 0), name=None):
    conv = sym.Convolution(
        data=data, num_filter=num_filter, kernel=kernel, stride=stride, pad=pad,
        name="conv_%s" % name,
    )
    bn = sym.BatchNorm(data=conv, name="bn_%s" % name)
    act = sym.Activation(data=bn, act_type="relu", name="relu_%s" % name)
    return act


def _downsample_factory(data, ch_3x3, name):
    conv = _conv_factory(data, ch_3x3, (3, 3), (2, 2), (1, 1), "%s_3x3" % name)
    pool = sym.Pooling(
        data=data, kernel=(3, 3), stride=(2, 2), pad=(1, 1), pool_type="max",
        name="max_pool_%s" % name,
    )
    concat = sym.Concat(conv, pool, num_args=2, name="concat_%s" % name)
    return concat


def _simple_factory(data, ch_1x1, ch_3x3, name):
    conv1x1 = _conv_factory(data, ch_1x1, (1, 1), (1, 1), (0, 0), "%s_1x1" % name)
    conv3x3 = _conv_factory(data, ch_3x3, (3, 3), (1, 1), (1, 1), "%s_3x3" % name)
    concat = sym.Concat(conv1x1, conv3x3, num_args=2, name="concat_%s" % name)
    return concat


def get_inception_bn_small(num_classes=10):
    data = sym.Variable("data")
    conv1 = _conv_factory(data, 96, (3, 3), (1, 1), (1, 1), "1")
    in3a = _simple_factory(conv1, 32, 32, "3a")
    in3b = _simple_factory(in3a, 32, 48, "3b")
    in3c = _downsample_factory(in3b, 80, "3c")
    in4a = _simple_factory(in3c, 112, 48, "4a")
    in4b = _simple_factory(in4a, 96, 64, "4b")
    in4c = _simple_factory(in4b, 80, 80, "4c")
    in4d = _simple_factory(in4c, 48, 96, "4d")
    in4e = _downsample_factory(in4d, 96, "4e")
    in5a = _simple_factory(in4e, 176, 160, "5a")
    in5b = _simple_factory(in5a, 176, 160, "5b")
    pool = sym.Pooling(
        data=in5b, kernel=(7, 7), stride=(1, 1), pool_type="avg", global_pool=True,
        name="global_pool",
    )
    flatten = sym.Flatten(data=pool, name="flatten1")
    fc = sym.FullyConnected(data=flatten, num_hidden=num_classes, name="fc1")
    softmax = sym.SoftmaxOutput(data=fc, name="softmax")
    return softmax


def _inception_a(data, n1x1, n3x3r, n3x3, nd3x3r, nd3x3, pool, proj, name):
    """Spatial-preserving block: four towers concatenated on channels
    (ref: symbol_inception-bn.py InceptionFactoryA)."""
    c1x1 = _conv_factory(data, n1x1, (1, 1), name="%s_1x1" % name)
    c3x3 = _conv_factory(
        _conv_factory(data, n3x3r, (1, 1), name="%s_3x3_reduce" % name),
        n3x3, (3, 3), pad=(1, 1), name="%s_3x3" % name)
    cd = _conv_factory(data, nd3x3r, (1, 1),
                       name="%s_double_3x3_reduce" % name)
    cd = _conv_factory(cd, nd3x3, (3, 3), pad=(1, 1),
                       name="%s_double_3x3_0" % name)
    cd = _conv_factory(cd, nd3x3, (3, 3), pad=(1, 1),
                       name="%s_double_3x3_1" % name)
    pooling = sym.Pooling(data=data, kernel=(3, 3), stride=(1, 1),
                          pad=(1, 1), pool_type=pool,
                          name="%s_pool_%s_pool" % (pool, name))
    cproj = _conv_factory(pooling, proj, (1, 1), name="%s_proj" % name)
    return sym.Concat(c1x1, c3x3, cd, cproj, num_args=4,
                      name="ch_concat_%s_chconcat" % name)


def _inception_b(data, n3x3r, n3x3, nd3x3r, nd3x3, name):
    """Stride-2 downsampling block: two conv towers beside a max pool
    (ref: symbol_inception-bn.py InceptionFactoryB)."""
    c3x3 = _conv_factory(
        _conv_factory(data, n3x3r, (1, 1), name="%s_3x3_reduce" % name),
        n3x3, (3, 3), stride=(2, 2), pad=(1, 1), name="%s_3x3" % name)
    cd = _conv_factory(data, nd3x3r, (1, 1),
                       name="%s_double_3x3_reduce" % name)
    cd = _conv_factory(cd, nd3x3, (3, 3), pad=(1, 1),
                       name="%s_double_3x3_0" % name)
    cd = _conv_factory(cd, nd3x3, (3, 3), stride=(2, 2), pad=(1, 1),
                       name="%s_double_3x3_1" % name)
    pooling = sym.Pooling(data=data, kernel=(3, 3), stride=(2, 2),
                          pad=(1, 1), pool_type="max",
                          name="max_pool_%s_pool" % name)
    return sym.Concat(c3x3, cd, pooling, num_args=3,
                      name="ch_concat_%s_chconcat" % name)


def get_inception_bn(num_classes=1000):
    """Full Inception-BN for 224x224 inputs (ref: symbol_inception-bn.py
    get_symbol). num_classes=21841 gives the full-ImageNet-21k variant
    (ref: symbol_inception-bn-full.py)."""
    data = sym.Variable("data")
    # stem
    conv1 = _conv_factory(data, 64, (7, 7), stride=(2, 2), pad=(3, 3),
                          name="1")
    pool1 = sym.Pooling(data=conv1, kernel=(3, 3), stride=(2, 2),
                        pool_type="max", name="pool_1")
    conv2 = _conv_factory(
        _conv_factory(pool1, 64, (1, 1), name="2_red"),
        192, (3, 3), pad=(1, 1), name="2")
    pool2 = sym.Pooling(data=conv2, kernel=(3, 3), stride=(2, 2),
                        pool_type="max", name="pool_2")
    # stage 3
    body = _inception_a(pool2, 64, 64, 64, 64, 96, "avg", 32, "3a")
    body = _inception_a(body, 64, 64, 96, 64, 96, "avg", 64, "3b")
    body = _inception_b(body, 128, 160, 64, 96, "3c")
    # stage 4
    body = _inception_a(body, 224, 64, 96, 96, 128, "avg", 128, "4a")
    body = _inception_a(body, 192, 96, 128, 96, 128, "avg", 128, "4b")
    body = _inception_a(body, 160, 128, 160, 128, 160, "avg", 128, "4c")
    body = _inception_a(body, 96, 128, 192, 160, 192, "avg", 128, "4d")
    body = _inception_b(body, 128, 192, 192, 256, "4e")
    # stage 5
    body = _inception_a(body, 352, 192, 320, 160, 224, "avg", 128, "5a")
    body = _inception_a(body, 352, 192, 320, 192, 224, "max", 128, "5b")
    pool = sym.Pooling(data=body, kernel=(7, 7), stride=(1, 1),
                       pool_type="avg", global_pool=True,
                       name="global_pool")
    flatten = sym.Flatten(data=pool, name="flatten")
    fc1 = sym.FullyConnected(data=flatten, num_hidden=num_classes,
                             name="fc1")
    return sym.SoftmaxOutput(data=fc1, name="softmax")
