"""Shared explicit-unroll LM scaffold for the recurrent model zoo.

lstm/gru/rnn unrolls differ only in their per-layer parameter bundles
and cell step; the embedding -> SliceChannel -> timestep loop ->
Concat -> decoder -> SoftmaxOutput scaffold lives here once
(lstm_unroll keeps its own copy because it additionally tags layers
with AttrScope(ctx_group=...) for the model-parallel variant).
"""
from __future__ import annotations

from .. import symbol as sym


def unroll_lm(num_layers, seq_len, input_size, num_hidden, num_embed,
              num_label, make_params, make_state, cell, dropout=0.0,
              ignore_label=None):
    """Build an unrolled LM symbol.

    make_params(layer_idx) -> per-layer parameter bundle;
    make_state(layer_idx) -> initial state (Variables named l%d_init_*);
    cell(num_hidden, indata, prev_state, param, seqidx, layeridx,
    dropout) -> next state with ``.h``.
    """
    embed_weight = sym.Variable("embed_weight")
    cls_weight = sym.Variable("cls_weight")
    cls_bias = sym.Variable("cls_bias")
    param_cells = [make_params(i) for i in range(num_layers)]
    last_states = [make_state(i) for i in range(num_layers)]

    data = sym.Variable("data")
    embed = sym.Embedding(data=data, input_dim=input_size,
                          weight=embed_weight, output_dim=num_embed,
                          name="embed")
    wordvec = sym.SliceChannel(data=embed, num_outputs=seq_len, axis=1,
                               squeeze_axis=True, name="wordvec")

    hidden_all = []
    for seqidx in range(seq_len):
        hidden = wordvec[seqidx]
        for i in range(num_layers):
            next_state = cell(
                num_hidden, indata=hidden, prev_state=last_states[i],
                param=param_cells[i], seqidx=seqidx, layeridx=i,
                dropout=dropout if i > 0 else 0.0,
            )
            hidden = next_state.h
            last_states[i] = next_state
        hidden_all.append(hidden)

    # N-major prediction rows: [N, 1, H] per step -> [N, T, H] ->
    # [N*T, H], pairing row (n, t) with label[n, t].reshape(-1) — the
    # SAME flattening EvalMetric applies to the batch label, so the
    # in-graph loss and the reported metric read identical pairings.
    # (The t-major Concat(dim=0) + label-transpose form the reference's
    # lstm.py uses trains the same loss but scrambles every metric
    # reading against [T*N]-ordered predictions — r5 finding: measured
    # train perplexity could not beat the unigram floor on a corpus
    # whose true bigram perplexity was 4.3.)
    steps = [sym.Reshape(data=h, shape=(0, 1, -1)) for h in hidden_all]
    hidden_concat = sym.Concat(*steps, dim=1, num_args=len(steps))
    hidden_concat = sym.Reshape(data=hidden_concat, shape=(-1, num_hidden))
    if dropout > 0.0:
        hidden_concat = sym.Dropout(data=hidden_concat, p=dropout)
    pred = sym.FullyConnected(data=hidden_concat, num_hidden=num_label,
                              weight=cls_weight, bias=cls_bias, name="pred")
    label = sym.Variable("softmax_label")
    label = sym.Reshape(data=label, shape=(-1,))
    if ignore_label is not None:
        return sym.SoftmaxOutput(data=pred, label=label, name="softmax",
                                 use_ignore=True, ignore_label=ignore_label)
    return sym.SoftmaxOutput(data=pred, label=label, name="softmax")
