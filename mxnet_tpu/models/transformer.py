"""Decoder-only transformer LM — the mesh-scale flagship.

Not in the 2016 reference (its sequence model is the unrolled LSTM); this
is the long-context/distributed-first model family the north-star demands:
tensor-parallel attention/MLP (Megatron-style column→row sharding expressed
as PartitionSpecs, XLA inserts the all-reduces), data-parallel batch, and
ring-attention sequence parallelism (parallel/ring_attention.py) for
sequences longer than one chip's HBM.

Pure-function style: params are a pytree dict; forward is jit/vjp-friendly.
"""
from __future__ import annotations

import dataclasses
import functools

import numpy as _np


@dataclasses.dataclass
class TransformerConfig:
    vocab_size: int = 32000
    num_layers: int = 4
    d_model: int = 512
    num_heads: int = 8
    d_ff: int = 2048
    max_seq_len: int = 2048
    dtype: str = "bfloat16"
    use_ring_attention: bool = False
    seq_axis: str = "seq"  # mesh axis for sequence parallelism
    tensor_axis: str = "model"  # mesh axis for tensor parallelism

    @property
    def head_dim(self):
        return self.d_model // self.num_heads


def init_params(cfg: TransformerConfig, key):
    """Initialize a params pytree."""
    import jax
    import jax.numpy as jnp

    dtype = jnp.dtype(cfg.dtype)
    keys = jax.random.split(key, cfg.num_layers + 2)

    def dense(k, shape, scale=None):
        if scale is None:
            scale = 1.0 / _np.sqrt(shape[0])
        return (jax.random.normal(k, shape, jnp.float32) * scale).astype(dtype)

    params = {
        "embed": dense(keys[0], (cfg.vocab_size, cfg.d_model), scale=0.02),
        "pos_embed": dense(keys[1], (cfg.max_seq_len, cfg.d_model), scale=0.02),
        "layers": [],
        "ln_f": {"scale": jnp.ones((cfg.d_model,), jnp.float32),
                 "bias": jnp.zeros((cfg.d_model,), jnp.float32)},
    }
    for i in range(cfg.num_layers):
        k = jax.random.split(keys[2 + i], 6)
        params["layers"].append({
            "ln1": {"scale": jnp.ones((cfg.d_model,), jnp.float32),
                    "bias": jnp.zeros((cfg.d_model,), jnp.float32)},
            "wqkv": dense(k[0], (cfg.d_model, 3 * cfg.d_model)),
            "wo": dense(k[1], (cfg.d_model, cfg.d_model)),
            "ln2": {"scale": jnp.ones((cfg.d_model,), jnp.float32),
                    "bias": jnp.zeros((cfg.d_model,), jnp.float32)},
            "w1": dense(k[2], (cfg.d_model, cfg.d_ff)),
            "w2": dense(k[3], (cfg.d_ff, cfg.d_model)),
        })
    return params


def param_partition_specs(cfg: TransformerConfig):
    """Megatron-style tensor-parallel PartitionSpecs: qkv/w1 column-sharded,
    wo/w2 row-sharded on the tensor axis; embeddings sharded on vocab."""
    from jax.sharding import PartitionSpec as P

    t = cfg.tensor_axis
    layer = {
        "ln1": {"scale": P(), "bias": P()},
        "wqkv": P(None, t),
        "wo": P(t, None),
        "ln2": {"scale": P(), "bias": P()},
        "w1": P(None, t),
        "w2": P(t, None),
    }
    return {
        "embed": P(t, None),
        "pos_embed": P(),
        "layers": [dict(layer) for _ in range(cfg.num_layers)],
        "ln_f": {"scale": P(), "bias": P()},
    }


def _ablate(which):
    """Measurement knob: MXNET_LM_ABLATE is a comma set naming model
    pieces to stub out for time-attribution probes on the real chip
    ("ln" = layer norms become scale+bias only, "ce" = the loss head
    skips log-softmax). Default off; numbers in docs/perf_analysis.md.
    Same pattern as MXNET_BN_AUTODIFF / MXNET_BN_STATS_SAMPLE."""
    import os

    raw = os.environ.get("MXNET_LM_ABLATE", "")
    names = {t.strip() for t in raw.split(",") if t.strip()}
    unknown = names - {"ln", "ce"}
    if unknown:
        # a silently ignored typo would corrupt a recorded perf table
        raise ValueError("MXNET_LM_ABLATE: unknown piece(s) %s "
                         "(valid: ln, ce)" % sorted(unknown))
    return which in names


def _layer_norm(x, p, eps=1e-5):
    import jax.numpy as jnp

    if _ablate("ln"):  # stats passes removed; affine kept
        return (x.astype(jnp.float32) * p["scale"]
                + p["bias"]).astype(x.dtype)
    xf = x.astype(jnp.float32)
    mu = jnp.mean(xf, axis=-1, keepdims=True)
    var = jnp.var(xf, axis=-1, keepdims=True)
    out = (xf - mu) / jnp.sqrt(var + eps) * p["scale"] + p["bias"]
    return out.astype(x.dtype)


def _attention(q, k, v, causal=True):
    # Pallas flash kernel on TPU; flash_attention falls back to the plain
    # XLA path internally when disabled or untileable.
    from ..ops.pallas_kernels import flash_attention

    return flash_attention(q, k, v, causal=causal)


def forward(params, tokens, cfg: TransformerConfig, mesh=None):
    """tokens [B, T] int32 -> logits [B, T, vocab]."""
    import jax
    import jax.numpy as jnp

    B, T = tokens.shape
    x = jnp.take(params["embed"], tokens, axis=0)
    x = x + params["pos_embed"][:T][None].astype(x.dtype)

    if cfg.use_ring_attention and mesh is not None:
        from ..parallel.ring_attention import make_ring_attention

        attn_fn = make_ring_attention(mesh, seq_axis=cfg.seq_axis, causal=True)
    else:
        attn_fn = functools.partial(_attention, causal=True)

    H, D = cfg.num_heads, cfg.head_dim
    for lp in params["layers"]:
        h = _layer_norm(x, lp["ln1"])
        qkv = jnp.einsum("btd,de->bte", h, lp["wqkv"])
        q, k, v = jnp.split(qkv, 3, axis=-1)

        def heads(t):
            return t.reshape(B, T, H, D).transpose(0, 2, 1, 3)

        o = attn_fn(heads(q), heads(k), heads(v))
        o = o.transpose(0, 2, 1, 3).reshape(B, T, H * D)
        x = x + jnp.einsum("btd,de->bte", o, lp["wo"])
        h = _layer_norm(x, lp["ln2"])
        ff = jax.nn.gelu(jnp.einsum("btd,df->btf", h, lp["w1"]))
        x = x + jnp.einsum("btf,fd->btd", ff, lp["w2"])
    x = _layer_norm(x, params["ln_f"])
    logits = jnp.einsum("btd,vd->btv", x, params["embed"])
    return logits


def loss_fn(cfg: TransformerConfig, mesh=None):
    """Next-token cross-entropy loss closure for parallel.make_train_step.
    batch = dict(tokens=[B,T] int32)."""
    import jax
    import jax.numpy as jnp

    def f(params, batch, rng):
        del rng
        tokens = batch["tokens"]
        logits = forward(params, tokens[:, :-1], cfg, mesh=mesh)
        targets = tokens[:, 1:]
        if _ablate("ce"):  # keep the logits matmul, skip the softmax-CE
            return -jnp.mean(jnp.take_along_axis(
                logits.astype(jnp.float32), targets[..., None],
                axis=-1)[..., 0])
        logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
        nll = -jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]
        return jnp.mean(nll)

    return f
