"""Explicitly-unrolled vanilla (Elman) RNN language model
(ref: example/rnn/rnn.py).

The simplest recurrence the reference's rnn() cell implements:
``h_t = act(W x_t + U h_{t-1} + b)`` with an optional BatchNorm on the
hidden state — kept here because the reference exposes it and it
exercises BatchNorm inside a recurrence (per-timestep batch statistics).
Interface-identical to lstm_unroll/gru_unroll for bucketing reuse.
"""
from __future__ import annotations

from collections import namedtuple

from .. import symbol as sym

RNNState = namedtuple("RNNState", ["h"])
RNNParam = namedtuple(
    "RNNParam", ["i2h_weight", "i2h_bias", "h2h_weight", "h2h_bias"]
)


def rnn_cell(num_hidden, indata, prev_state, param, seqidx, layeridx,
             dropout=0.0, act_type="tanh", batch_norm=False):
    """One Elman step (ref: example/rnn/rnn.py rnn())."""
    if dropout > 0.0:
        indata = sym.Dropout(data=indata, p=dropout)
    hidden = sym.FullyConnected(
        data=indata, weight=param.i2h_weight, bias=param.i2h_bias,
        num_hidden=num_hidden, name="t%d_l%d_i2h" % (seqidx, layeridx),
    ) + sym.FullyConnected(
        data=prev_state.h, weight=param.h2h_weight, bias=param.h2h_bias,
        num_hidden=num_hidden, name="t%d_l%d_h2h" % (seqidx, layeridx),
    )
    hidden = sym.Activation(data=hidden, act_type=act_type)
    if batch_norm:
        hidden = sym.BatchNorm(data=hidden,
                               name="t%d_l%d_bn" % (seqidx, layeridx))
    return RNNState(h=hidden)


def rnn_unroll(num_rnn_layer, seq_len, input_size, num_hidden, num_embed,
               num_label, dropout=0.0, act_type="tanh", batch_norm=False,
               ignore_label=None):
    """Unrolled Elman-RNN LM symbol (ref: example/rnn/rnn.py
    rnn_unroll). ignore_label: exclude padding rows from the loss —
    without a gate structure the padding class otherwise dominates the
    sum-CE gradient on bucketed data (see examples/rnn/rnn_cell_demo)."""
    import functools

    from ._unroll import unroll_lm

    def make_params(i):
        return RNNParam(
            i2h_weight=sym.Variable("l%d_i2h_weight" % i),
            i2h_bias=sym.Variable("l%d_i2h_bias" % i),
            h2h_weight=sym.Variable("l%d_h2h_weight" % i),
            h2h_bias=sym.Variable("l%d_h2h_bias" % i),
        )

    cell = functools.partial(rnn_cell, act_type=act_type,
                             batch_norm=batch_norm)
    return unroll_lm(num_rnn_layer, seq_len, input_size, num_hidden,
                     num_embed, num_label, make_params,
                     lambda i: RNNState(h=sym.Variable("l%d_init_h" % i)),
                     cell, dropout=dropout, ignore_label=ignore_label)
