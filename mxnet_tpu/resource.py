"""Resource manager: per-device PRNG streams and temp workspaces.

Re-design of the reference resource layer (ref: include/mxnet/resource.h
:18-36,156, src/resource.cc — SURVEY §2.3). The reference hands operators
two resource kinds through ``ResourceManager::Get()->Request(ctx, req)``:

- ``kRandom``: a per-device mshadow PRNG seeded globally;
- ``kTempSpace``: a rotating set of scratch buffers per device
  (MXNET_CPU_TEMP_COPY / MXNET_GPU_TEMP_COPY copies, resource.cc:70-71).

TPU-natively, operator *compute* needs neither (XLA allocates scratch,
jax threads PRNG keys explicitly) — but the escape hatches do: CustomOp /
NumpyOp kernels and host-side pipeline stages ask the manager for
randomness and workspace exactly like reference custom ops
(``OpContext.requested``). So the API is preserved:

    r = ResourceManager.get().request(ctx, "random")
    key = r.next_key()                      # jax PRNG key stream
    w = ResourceManager.get().request(ctx, "temp_space")
    buf = w.get_space((1024,), "f4")        # recycled numpy scratch

Global seeding runs through mxnet_tpu.random.seed, which also reseeds
every live random resource — matching MXRandomSeed semantics
(c_api.h; src/resource.cc SeedRandom).
"""
from __future__ import annotations

import threading

import numpy as _np

from .base import MXNetError, env_int
from .context import Context, current_context
from .storage import Storage

__all__ = ["ResourceManager", "RandomResource", "TempSpaceResource"]


class RandomResource:
    """Per-device PRNG stream (ref: resource.h kRandom)."""

    def __init__(self, ctx, seed_state):
        self._ctx = ctx
        self._lock = threading.Lock()
        self.reseed(seed_state)

    def reseed(self, seed_state):
        import jax

        # distinct stream per device id, same global seed discipline as
        # resource.cc (seed + device offset); locked so a concurrent
        # next_key cannot resurrect the pre-seed stream
        key = jax.random.fold_in(
            jax.random.PRNGKey(seed_state), self._ctx.device_id)
        with self._lock:
            self._key = key

    def next_key(self):
        import jax

        with self._lock:
            # the split IS the guarded state transition (the stream
            # advance must be atomic); a cached-jit scalar op, not a
            # compile — vetted blocking-under-lock
            self._key, sub = jax.random.split(self._key)  # mxlint: disable
            return sub

    def uniform(self, shape, low=0.0, high=1.0, dtype="float32"):
        import jax

        return jax.random.uniform(
            self.next_key(), shape, minval=low, maxval=high,
            dtype=_np.dtype(dtype).name)

    def normal(self, shape, loc=0.0, scale=1.0, dtype="float32"):
        import jax

        k = self.next_key()
        return jax.random.normal(
            k, shape, dtype=_np.dtype(dtype).name) * scale + loc


class TempSpaceResource:
    """Rotating scratch buffers (ref: resource.h kTempSpace; copy count
    env MXNET_CPU_TEMP_COPY, resource.cc:70-71)."""

    def __init__(self, ctx, ncopy):
        self._ctx = ctx
        self._handles = [None] * ncopy
        self._turn = 0
        self._lock = threading.Lock()

    def get_space(self, shape, dtype="float32"):
        """A writable numpy scratch view; contents are undefined between
        calls — the reference's temp-space contract. Always host memory:
        custom-op kernels (the consumers of temp space here) run on the
        host via callbacks, and jax device buffers are immutable."""
        from .context import cpu

        dt = _np.dtype(dtype)
        nbytes = int(_np.prod(shape)) * dt.itemsize
        with self._lock:
            i = self._turn % len(self._handles)
            self._turn += 1
            h = self._handles[i]
            if h is None or h.size < nbytes:
                if h is not None:
                    Storage.get().free(h)
                h = Storage.get().alloc(nbytes, cpu(self._ctx.device_id))
                self._handles[i] = h
        return h.dptr[:nbytes].view(dt).reshape(shape)


class ResourceManager:
    """Singleton (ref: ResourceManager::Get, resource.h:156)."""

    _instance = None
    _lock = threading.Lock()

    def __init__(self):
        from . import random as _random

        self._random = {}
        self._temp = {}
        # honor a global mx.random.seed() issued before the manager existed
        self._seed = _random._state["seed"]
        self._mu = threading.Lock()

    @classmethod
    def get(cls):
        with cls._lock:
            if cls._instance is None:
                cls._instance = cls()
            return cls._instance

    def request(self, ctx, req):
        """req: 'random' | 'temp_space' (ref: ResourceRequest::Type)."""
        if ctx is None:
            ctx = current_context()
        if not isinstance(ctx, Context):
            raise MXNetError("request: ctx must be a Context")
        key = (ctx.device_type, ctx.device_id)
        with self._mu:
            if req == "random":
                r = self._random.get(key)
                if r is None:
                    r = self._random[key] = RandomResource(ctx, self._seed)
                return r
            if req == "temp_space":
                t = self._temp.get(key)
                if t is None:
                    ncopy = env_int(
                        "MXNET_CPU_TEMP_COPY"
                        if ctx.device_type.startswith("cpu")
                        else "MXNET_GPU_TEMP_COPY", 4)
                    t = self._temp[key] = TempSpaceResource(ctx, ncopy)
                return t
        raise MXNetError("unknown resource request: %r" % (req,))

    def seed(self, seed_state):
        """Reseed every live random resource (ref: resource.cc
        SeedRandom; called from mxnet_tpu.random.seed). The jax work in
        reseed() (a fold_in dispatch, a compile on first use) runs
        OUTSIDE the manager lock — holding _mu across it would
        serialize every concurrent request() behind device work; each
        resource's own lock makes the reseed itself atomic."""
        seed = int(seed_state)
        with self._mu:
            self._seed = seed
            live = list(self._random.values())
        for r in live:
            # reseed with the LOCAL value: re-reading self._seed here
            # would let two concurrent seed() calls leave resources on
            # a mix of the two values
            r.reseed(seed)
