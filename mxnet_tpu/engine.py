"""Dependency engine: host-task scheduler with read/write-var ordering.

Re-design of the reference engine (ref: include/mxnet/engine.h:74-226,
src/engine/threaded_engine.h:87-189, src/engine/engine.cc:13-39 —
SURVEY §2.1). On TPU, XLA already orders device work per stream, so this
engine schedules *host-side* tasks — IO/prefetch stages, checkpoint
writes, host reductions, custom-op callbacks — with the reference's exact
dependency semantics: reads on a variable run concurrently, a write waits
for prior reads to drain and runs alone, later ops queue in program order.

The scheduler core is native C++ (src/engine.cc, loaded via ctypes); a
pure-Python NaiveEngine fallback runs every op inline when native code is
unavailable or MXNET_NATIVE=0 — the same role the reference's NaiveEngine
plays for debugging (ref: src/engine/naive_engine.cc).

Engine choice follows the reference env protocol (src/engine/engine.cc:13):
MXNET_ENGINE_TYPE = ThreadedEngine | ThreadedEnginePerDevice (default) |
NaiveEngine. Worker count: MXNET_CPU_WORKER_NTHREADS.
"""
from __future__ import annotations

import atexit
import ctypes
import itertools
import logging
import os
import threading
import time
from contextlib import nullcontext as _null_context

from . import _native
from . import telemetry as _tel
from .base import MXNetError
from .resilience import faults as _faults

__all__ = ["Engine", "get", "push", "wait_for_all"]

_ENGINE_FN = ctypes.CFUNCTYPE(None, ctypes.c_void_p, ctypes.c_void_p)


def _wait_timeout():
    """MXNET_ENGINE_WAIT_TIMEOUT in seconds, or None when the watchdog
    is off. Read per wait so tests (and operators attaching to a hung
    job) can arm it at any time."""
    raw = os.environ.get("MXNET_ENGINE_WAIT_TIMEOUT", "").strip()
    if not raw:
        return None
    try:
        t = float(raw)
    except ValueError:
        raise MXNetError(
            "MXNET_ENGINE_WAIT_TIMEOUT must be a number of seconds, "
            "got %r" % raw)
    return t if t > 0 else None


def _engine_lib():
    lib = _native.load("engine")
    if lib is None or getattr(lib, "_eng_configured", False):
        return lib
    c = ctypes
    lib.EngineCreate.restype = c.c_void_p
    lib.EngineCreate.argtypes = [c.c_int, c.c_int]
    lib.EngineDestroy.argtypes = [c.c_void_p]
    lib.EngineNewVariable.restype = c.c_void_p
    lib.EngineNewVariable.argtypes = [c.c_void_p]
    lib.EngineDeleteVariable.argtypes = [c.c_void_p, c.c_void_p]
    lib.EnginePush.restype = c.c_int
    lib.EnginePush.argtypes = [
        c.c_void_p, _ENGINE_FN, c.c_void_p,
        c.POINTER(c.c_void_p), c.c_int,
        c.POINTER(c.c_void_p), c.c_int, c.c_int, c.c_int,
    ]
    lib.EngineOprComplete.argtypes = [c.c_void_p]
    lib.EngineWaitForVar.argtypes = [c.c_void_p, c.c_void_p]
    lib.EngineWaitForAll.argtypes = [c.c_void_p]
    lib.EnginePendingCount.restype = c.c_int64
    lib.EnginePendingCount.argtypes = [c.c_void_p]
    lib.EngineLastError.restype = c.c_char_p
    lib.EngineLastError.argtypes = [c.c_void_p]
    lib._eng_configured = True
    return lib


class VarHandle:
    """Opaque engine variable (ref: engine.h VarHandle). ``_uid`` is a
    stable process-wide id used by the verify/record trace (the native
    pointer is recycled by the allocator, uids never are)."""

    __slots__ = ("_ptr", "_engine", "_uid")

    _uids = itertools.count(1)

    def __init__(self, ptr, engine):
        self._ptr = ptr
        self._engine = engine
        self._uid = next(VarHandle._uids)


class Engine:
    """Singleton scheduler. API parity: engine.h:74-226."""

    _instance = None
    _instance_lock = threading.Lock()

    def __init__(self, engine_type=None, num_workers=None):
        if engine_type is None:
            engine_type = os.environ.get(
                "MXNET_ENGINE_TYPE", "ThreadedEnginePerDevice")
        if num_workers is None:
            num_workers = int(os.environ.get("MXNET_CPU_WORKER_NTHREADS", "0"))
        self.engine_type = engine_type
        # MXNET_ENGINE_INFO: log each push (ref: threaded_engine.h:253)
        self._verbose = os.environ.get("MXNET_ENGINE_INFO", "").strip() \
            not in ("", "0", "false")
        # MXNET_ENGINE_VERIFY: record every push's read/write var sets and
        # statically verify the trace (use-after-free, wait-cycles) on each
        # wait, raising on findings — see analysis/engine_verify.py
        self._verify = os.environ.get("MXNET_ENGINE_VERIFY", "").strip() \
            not in ("", "0", "false")
        self._trace = None
        if self._verify:
            from .analysis.engine_verify import EngineTrace, maybe_trace_lock

            self._trace = EngineTrace()
        threaded = 0 if engine_type == "NaiveEngine" else 1
        self._lib = _engine_lib()
        self._handle = None
        if self._lib is not None:
            self._handle = ctypes.c_void_p(
                self._lib.EngineCreate(threaded, num_workers))
        # keep callback objects alive until their op completes
        self._live = {}
        self._live_lock = threading.Lock()
        if self._verify:
            # runtime lock-order recording (analysis/engine_verify.py):
            # acquires/releases land in the ambient lock trace, whose
            # observed edges are checked for inversions and
            # cross-checked against lock_lint's static graph
            self._live_lock = maybe_trace_lock(
                self._live_lock, "engine.Engine._live_lock")
        self._next_key = 1
        self._errors = []
        # key -> fn name for ops dispatched to a worker but not yet
        # completed (the wait watchdog's "in-flight" dump; _live alone
        # cannot name them — its entry is popped at dispatch)
        self._inflight = {}
        lib = self._lib

        def _trampoline(argp, token):
            key = argp  # void* cast back to the int key
            with self._live_lock:
                fn, is_async, ev, ev_trace = self._live.pop(key)
                self._inflight[key] = getattr(fn, "__name__", None) or "fn"
            # pair ev with the trace it was recorded into at push time:
            # if a recording() block ended while this op was in flight,
            # the now-attached trace must not adopt a foreign seq as its
            # op context (waits would misattribute their waiter)
            ctx = ev_trace.op_context(ev) if ev is not None \
                else _null_context()
            t0 = time.monotonic() if _tel.ENABLED else 0.0
            if is_async:
                called = [False]

                def on_complete(_tok=token, _key=key):
                    if not called[0]:
                        called[0] = True
                        with self._live_lock:
                            self._inflight.pop(_key, None)
                        lib.EngineOprComplete(_tok)

                try:
                    with ctx:
                        _faults.point("engine.task")
                        fn(on_complete)
                except BaseException as e:  # surface on next wait()
                    with self._live_lock:
                        self._errors.append(e)
                    on_complete()
            else:
                try:
                    with ctx:
                        _faults.point("engine.task")
                        fn()
                except BaseException as e:
                    with self._live_lock:
                        self._errors.append(e)
                finally:
                    with self._live_lock:
                        self._inflight.pop(key, None)
            if _tel.ENABLED:
                # async latency covers fn's dispatch body (durability is
                # on_complete's clock, which may outlive this frame)
                _tel.histogram("engine.task_secs").observe(
                    time.monotonic() - t0)

        self._trampoline = _ENGINE_FN(_trampoline) if lib is not None else None

    def close(self):
        """Drain pending work and free the native engine + worker pool.

        Contract: close() must only run once all threads that push to or
        wait on this engine have quiesced (it is invoked from __del__ and
        interpreter exit). The locked swap makes the handle hand-off
        atomic — a thread that starts a push AFTER the swap falls back to
        inline execution — but a native call already in flight when
        EngineDestroy runs is undefined, same as the reference engine's
        shutdown (threaded_engine destructor joins its workers without
        fencing producers). Holding _live_lock across EngineDestroy is
        not an option: the worker-thread trampoline takes _live_lock, so
        destroy's drain would deadlock."""
        with self._live_lock:
            h, self._handle = self._handle, None
        if h is not None and self._lib is not None:
            self._lib.EngineDestroy(h)

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass

    # -- factory ---------------------------------------------------------------
    @classmethod
    def get(cls):
        with cls._instance_lock:
            if cls._instance is None:
                cls._instance = cls()
            return cls._instance

    @property
    def is_native(self):
        return self._handle is not None

    def _handle_snapshot(self):
        """Read the handle once under the lock; callers use the snapshot
        for the whole native call so a concurrent close() can never turn
        a passed None-check into a NULL dereference."""
        with self._live_lock:
            return self._handle

    # -- variables -------------------------------------------------------------
    def new_variable(self):
        h = self._handle_snapshot()
        if h is None:
            return VarHandle(None, self)
        return VarHandle(self._lib.EngineNewVariable(h), self)

    def delete_variable(self, var):
        """Deferred deletion after all pending ops (ref: engine.h:148-160)."""
        trace = self._trace
        if trace is not None:
            trace.delete_var(var._uid)
        h = self._handle_snapshot()
        if h is not None and var._ptr:
            self._lib.EngineDeleteVariable(h, var._ptr)
            var._ptr = None

    # -- record / verify -------------------------------------------------------
    def attach_trace(self, trace):
        """Attach an analysis.engine_verify.EngineTrace (or None) for
        recording; returns the previously attached trace. Programmatic
        counterpart of MXNET_ENGINE_VERIFY=1 — prefer the
        ``engine_verify.recording(engine)`` context manager. Verify
        progress lives ON the trace (verify_seq/verify_reported), so
        re-attaching a previous trace — recording() restoring it — must
        not re-raise hazards that were already reported once."""
        prev, self._trace = self._trace, trace
        return prev

    def _maybe_verify(self):
        """In MXNET_ENGINE_VERIFY mode, statically check the trace on
        each wait and raise the first new findings as MXNetError. Runs
        BEFORE the blocking native wait so a wait-cycle raises instead
        of deadlocking the worker pool."""
        trace = self._trace
        if not self._verify or trace is None:
            return
        from .analysis.engine_verify import verify

        # snapshot before verifying: a worker pushing concurrently must
        # not land inside [since_seq, verify_seq) unchecked. Taken under
        # the trace lock — an unlocked read could observe a seq whose
        # event is not yet appended, and that event would then be
        # skipped by every later incremental verify.
        with trace._lock:
            snap = trace._seq
        findings = verify(trace, since_seq=trace.verify_seq)
        trace.verify_seq = snap + 1
        new = [f for f in findings if f.key() not in trace.verify_reported]
        if not new:
            return
        trace.verify_reported.update(f.key() for f in new)
        raise MXNetError(
            "engine verify: %d hazard(s) detected:\n%s"
            % (len(new), "\n".join(str(f) for f in new)))

    # -- push ------------------------------------------------------------------
    def _check_dup(self, const_vars, mutable_vars):
        seen = set()
        for v in list(const_vars) + list(mutable_vars):
            if id(v) in seen:
                raise MXNetError(
                    "duplicate variable in const/mutable lists "
                    "(ref: threaded_engine.cc:205 CheckDuplicate)")
            seen.add(id(v))

    def push(self, fn, const_vars=(), mutable_vars=(), priority=0):
        """PushSync (ref: engine.h:197-207): fn() runs once deps are met;
        completion is automatic when it returns."""
        self._push(fn, const_vars, mutable_vars, priority, is_async=False)

    def push_async(self, fn, const_vars=(), mutable_vars=(), priority=0):
        """PushAsync (ref: engine.h:142-146): fn(on_complete) must invoke
        on_complete() when the op's effects are durable."""
        self._push(fn, const_vars, mutable_vars, priority, is_async=True)

    def _push(self, fn, const_vars, mutable_vars, priority, is_async):
        self._check_dup(const_vars, mutable_vars)
        if self._verbose:
            logging.info(
                "engine: push %s const=%d mutable=%d priority=%d async=%s",
                getattr(fn, "__name__", "fn"), len(const_vars),
                len(mutable_vars), priority, is_async)
        with self._live_lock:
            handle = self._handle
        for v in list(const_vars) + list(mutable_vars):
            if handle is not None and not v._ptr:
                raise MXNetError("engine variable used after delete_variable")
        trace = self._trace
        ev = None
        if trace is not None:
            ev = trace.push(getattr(fn, "__name__", None) or "fn",
                            [v._uid for v in const_vars],
                            [v._uid for v in mutable_vars])
        if _tel.ENABLED:
            _tel.counter("engine.push_total").inc()
        if handle is None:  # NaiveEngine fallback: run inline
            t0 = time.monotonic() if _tel.ENABLED else 0.0
            ctx = trace.op_context(ev) if ev is not None else _null_context()
            with ctx:
                _faults.point("engine.task")
                if is_async:
                    done = threading.Event()
                    fn(done.set)
                    done.wait()
                else:
                    fn()
            if _tel.ENABLED:
                _tel.histogram("engine.task_secs").observe(
                    time.monotonic() - t0)
            return
        with self._live_lock:
            key = self._next_key
            self._next_key += 1
            self._live[key] = (fn, is_async, ev, trace)
        n_c, n_m = len(const_vars), len(mutable_vars)
        c_arr = (ctypes.c_void_p * max(n_c, 1))(
            *[v._ptr for v in const_vars])
        m_arr = (ctypes.c_void_p * max(n_m, 1))(
            *[v._ptr for v in mutable_vars])
        rc = self._lib.EnginePush(
            handle, self._trampoline, ctypes.c_void_p(key),
            c_arr, n_c, m_arr, n_m, priority, 0 if is_async else 1)
        if _tel.ENABLED and rc == 0:
            _tel.gauge("engine.queue_depth").set(
                self._lib.EnginePendingCount(handle))
        if rc != 0:
            with self._live_lock:
                self._live.pop(key, None)
            if trace is not None and ev is not None:
                # roll back the recorded push: a phantom op that never
                # ran must not create happens-before edges in the trace
                trace.discard(ev)
            raise MXNetError(
                self._lib.EngineLastError(handle).decode())

    # -- sync ------------------------------------------------------------------
    def wait_for_var(self, var):
        """ref: engine.h:166 WaitForVar. With MXNET_ENGINE_WAIT_TIMEOUT
        set, a sentinel read op on the var bounds the wait: if it has
        not run by the deadline, raise the pending-op dump instead of
        blocking forever behind a task that never completes."""
        trace = self._trace
        if trace is not None:
            trace.wait(var._uid)
        if _tel.ENABLED:
            _tel.counter("engine.waits_total").inc()
        self._maybe_verify()
        h = self._handle_snapshot()
        if h is not None and var._ptr:
            timeout = _wait_timeout()
            if timeout is None:
                self._lib.EngineWaitForVar(h, var._ptr)
            else:
                reached = threading.Event()

                def __engine_wait_sentinel__():
                    reached.set()

                # ordinary read push: runs once every op queued on the
                # var before this wait has drained — exactly WaitForVar's
                # contract (ref: threaded_engine.cc:300)
                self.push(__engine_wait_sentinel__, const_vars=[var],
                          priority=1 << 20)
                if not reached.wait(timeout):
                    if _tel.ENABLED:
                        _tel.counter("engine.watchdog_fires_total").inc()
                    # a deferred task error is the likely ROOT CAUSE of
                    # the wedge (fn raised before calling on_complete);
                    # surface it in preference to the generic timeout
                    self._raise_pending()
                    raise MXNetError(
                        "engine wait_for_var exceeded "
                        "MXNET_ENGINE_WAIT_TIMEOUT=%gs\n%s"
                        % (timeout, self.pending_dump()))
        self._raise_pending()

    def wait_for_all(self):
        """ref: engine.h:170 WaitForAll. With MXNET_ENGINE_WAIT_TIMEOUT
        set, polls the pending count with a deadline and raises the
        pending-op dump instead of deadlocking."""
        trace = self._trace
        if trace is not None:
            trace.wait(None)
        if _tel.ENABLED:
            _tel.counter("engine.waits_total").inc()
        self._maybe_verify()
        h = self._handle_snapshot()
        if h is not None:
            timeout = _wait_timeout()
            if timeout is None:
                self._lib.EngineWaitForAll(h)
            elif not self._poll_pending(h, timeout):
                if _tel.ENABLED:
                    _tel.counter("engine.watchdog_fires_total").inc()
                self._raise_pending()  # root cause beats generic timeout
                raise MXNetError(
                    "engine wait_for_all exceeded "
                    "MXNET_ENGINE_WAIT_TIMEOUT=%gs\n%s"
                    % (timeout, self.pending_dump()))
        self._raise_pending()

    def _poll_pending(self, h, timeout):
        """Watchdog wait body: poll the native pending count until it
        drains (True) or the deadline passes (False)."""
        deadline = time.monotonic() + timeout
        while self._lib.EnginePendingCount(h) > 0:
            if time.monotonic() > deadline:
                return False
            time.sleep(0.002)
        return True

    def pending_count(self):
        h = self._handle_snapshot()
        if h is None:
            return 0
        return self._lib.EnginePendingCount(h)

    def pending_snapshot(self):
        """Structured pending-work snapshot: native pending count plus
        the queued (pushed, not yet dispatched) and in-flight
        (dispatched, not yet completed) task names. The wait watchdog's
        dump and the /enginez introspection endpoint both read this."""
        with self._live_lock:
            queued = [getattr(fn, "__name__", None) or "fn"
                      for fn, _a, _e, _t in self._live.values()]
            inflight = list(self._inflight.values())
        return {"pending": self.pending_count(), "queued": queued,
                "in_flight": inflight}

    def pending_dump(self):
        """Diagnostic snapshot for the wait watchdog: how many ops the
        native engine still counts pending, which tasks are queued
        (pushed, not yet dispatched), which are in flight (dispatched,
        on_complete never called), and — when a verify/record trace is
        attached (MXNET_ENGINE_VERIFY=1) — the trace tail with each
        op's declared var sets, which names the dependency chain the
        wait is stuck behind."""
        snap = self.pending_snapshot()
        lines = ["pending ops: %d native; queued: %s; in-flight: %s"
                 % (snap["pending"],
                    ", ".join(snap["queued"]) or "(none)",
                    ", ".join(snap["in_flight"]) or "(none)")]
        trace = self._trace
        if trace is not None and trace.events:
            tail = sorted(trace.events, key=lambda e: e.seq)[-8:]
            lines.append("verify-trace tail:")
            lines.extend("  %s const=%s mutable=%s"
                         % (e.label(), list(e.const), list(e.mutable))
                         for e in tail)
        lines.append(
            "likely cause: an async task never invoked on_complete, or a "
            "host task is blocked; see docs/how_to/fault_tolerance.md")
        return "\n".join(lines)

    def _raise_pending(self):
        with self._live_lock:
            if not self._errors:
                return
            err = self._errors[0]
            dropped = self._errors[1:]
            self._errors.clear()
        # Raise the first failure; the rest must not vanish silently
        # (two async checkpoint writes can both fail in one wait).
        for extra in dropped:
            logging.error("engine: additional deferred task error "
                          "(raised error takes precedence): %r", extra)
        raise err


@atexit.register
def _drain_at_exit():
    """Fence pending host tasks (async checkpoints etc.) at interpreter
    exit; a swallowed worker-thread error must not vanish silently.
    Honors MXNET_ENGINE_WAIT_TIMEOUT: a task wedged at exit logs the
    pending-op dump instead of hanging interpreter shutdown forever."""
    e = Engine._instance
    if e is None or e._handle is None:
        _tel.flush_at_exit()  # journal final flush rides the drain hook
        return
    try:
        timeout = _wait_timeout()
        if timeout is None:
            e._lib.EngineWaitForAll(e._handle)
        elif not e._poll_pending(e._handle, timeout):
            if _tel.ENABLED:
                _tel.counter("engine.watchdog_fires_total").inc()
            logging.error(
                "engine: exit drain exceeded "
                "MXNET_ENGINE_WAIT_TIMEOUT=%gs\n%s",
                timeout, e.pending_dump())
    except Exception:
        _tel.flush_at_exit()
        return
    for err in e._errors:
        logging.error("engine: pending task failed: %r", err)
    # metrics recorded by tasks that completed during the drain are now
    # final — flush them before the interpreter tears the journal down
    _tel.flush_at_exit()


def get():
    return Engine.get()


def push(fn, const_vars=(), mutable_vars=(), priority=0):
    Engine.get().push(fn, const_vars, mutable_vars, priority)


def wait_for_all():
    Engine.get().wait_for_all()
