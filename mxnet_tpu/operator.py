"""Custom operators written in Python/numpy.

TPU-native redesign of the reference's escape hatches (SURVEY §2.5):
- CustomOp/CustomOpProp (ref: python/mxnet/operator.py:394-533,
  src/operator/custom-inl.h, MXCustomOpRegister c_api.h:1418)
- NumpyOp/_Native (ref: python/mxnet/operator.py:124-222,
  src/operator/native_op-inl.h)
- NDArrayOp (ref: ndarray_op-inl.h)

Design: a registered custom op is an OpDef whose forward runs the user's
Python via ``jax.pure_callback`` (host callback inside the compiled
program — the analog of the C-callback vtable the reference drives from
the engine) and whose gradient is wired through ``jax.custom_vjp`` calling
the user's ``backward`` the same way.
"""
from __future__ import annotations

import functools

import numpy as _np

from .base import InferShapeFatal, MXNetError
from .ops.registry import Field, OpDef, register as _register_opdef

__all__ = ["CustomOp", "CustomOpProp", "NumpyOp", "NDArrayOp",
           "PythonOp", "register", "get_all_registered"]

_CUSTOM_REGISTRY = {}


class CustomOp:
    """Base class for user ops (ref: python/mxnet/operator.py:394)."""

    def forward(self, is_train, req, in_data, out_data, aux):
        raise NotImplementedError()

    def backward(self, req, out_grad, in_data, out_data, in_grad, aux):
        raise NotImplementedError()

    def assign(self, dst, req, src):
        """ref: operator.py:427 — honor kWriteTo/kAddTo."""
        if req == "null":
            return
        if req in ("write", "inplace"):
            dst[:] = src
        elif req == "add":
            dst[:] += src


class CustomOpProp:
    """Shape/type declaration for a CustomOp (ref: operator.py:447)."""

    def __init__(self, need_top_grad=True):
        self.need_top_grad_ = need_top_grad

    def infer_shape(self, in_shape):
        return in_shape, [in_shape[0]] * len(self.list_outputs()), []

    def infer_type(self, in_type):
        return (
            in_type,
            [in_type[0]] * len(self.list_outputs()),
            [in_type[0]] * len(self.list_auxiliary_states()),
        )

    def list_outputs(self):
        return ["output"]

    def list_arguments(self):
        return ["data"]

    def list_auxiliary_states(self):
        return []

    def declare_backward_dependency(self, out_grad, in_data, out_data):
        deps = []
        if self.need_top_grad_:
            deps.extend(out_grad)
        deps.extend(in_data)
        deps.extend(out_data)
        return deps

    def create_operator(self, ctx, in_shapes, in_dtypes):
        raise NotImplementedError()


class _HostArray:
    """Numpy view handed to user forward/backward; assignment-compatible
    with CustomOp.assign."""

    def __init__(self, arr):
        self._arr = arr

    def asnumpy(self):
        return self._arr

    @property
    def shape(self):
        return self._arr.shape


def register(reg_name):
    """Register a CustomOpProp subclass under a name usable as
    mx.sym.Custom(op_type=reg_name) (ref: operator.py:533 register)."""

    def do_register(prop_cls):
        _CUSTOM_REGISTRY[reg_name] = prop_cls
        return prop_cls

    return do_register


def get_all_registered():
    return dict(_CUSTOM_REGISTRY)


def register_custom_c_op(op_type, fns):
    """Register a custom op whose kernels are foreign-language callbacks
    (the C ABI's MXCustomOpRegister, ref: c_api.h:1418 + custom-inl.h).

    fns keys:
      num_inputs, num_outputs : ints
      forward(in_nps, out_nps) : fill the output numpy arrays (f32)
      backward(out_grad_nps, in_nps, in_grad_nps) : optional
      infer_shape(in_shapes) -> (in_shapes, out_shapes) : optional;
          default gives every output input[0]'s shape
    The op becomes usable as sym.Custom(..., op_type=op_type), same as
    Python-registered CustomOpProps.
    """
    num_in = int(fns.get("num_inputs", 1))
    num_out = int(fns.get("num_outputs", 1))

    class _CCallbackOp(CustomOp):
        def forward(self, is_train, req, in_data, out_data, aux):
            ins = [_np.asarray(a.asnumpy(), _np.float32) for a in in_data]
            outs = [_np.zeros(a.asnumpy().shape, _np.float32) for a in out_data]
            fns["forward"](ins, outs)
            for i, o in enumerate(outs):
                self.assign(out_data[i], req[i], o)

        def backward(self, req, out_grad, in_data, out_data, in_grad, aux):
            bwd = fns.get("backward")
            if bwd is None:
                raise MXNetError(
                    "custom C op %r declares no backward" % op_type)
            ogs = [_np.asarray(a.asnumpy(), _np.float32) for a in out_grad]
            ins = [_np.asarray(a.asnumpy(), _np.float32) for a in in_data]
            igs = [_np.zeros(a.asnumpy().shape, _np.float32) for a in in_grad]
            bwd(ogs, ins, igs)
            for i, g in enumerate(igs):
                self.assign(in_grad[i], req[i], g)

    class _CCallbackProp(CustomOpProp):
        def __init__(self, **kwargs):
            super().__init__(need_top_grad=bool(fns.get("need_top_grad", True)))

        def list_arguments(self):
            return ["data%d" % i for i in range(num_in)] if num_in != 1 else ["data"]

        def list_outputs(self):
            return (["output%d" % i for i in range(num_out)]
                    if num_out != 1 else ["output"])

        def infer_shape(self, in_shape):
            f = fns.get("infer_shape")
            if f is None:
                return in_shape, [in_shape[0]] * num_out, []
            ins, outs = f([list(s) for s in in_shape])
            return ins, outs, []

        def create_operator(self, ctx, in_shapes, in_dtypes):
            return _CCallbackOp()

    _CUSTOM_REGISTRY[op_type] = _CCallbackProp
    return 0


def _custom_fwd(params, inputs, aux, is_train, rng):
    import jax
    import jax.numpy as jnp

    op_type = params["op_type"]
    if op_type not in _CUSTOM_REGISTRY:
        raise MXNetError("Custom op %s not registered" % op_type)
    prop = _CUSTOM_REGISTRY[op_type](**(params.get("__kwargs__") or {}))
    n_out = len(prop.list_outputs())
    in_shapes = [tuple(x.shape) for x in inputs]
    _, out_shapes, _ = _norm_infer_shape(prop.infer_shape(list(map(list, in_shapes))))
    in_dtypes = [x.dtype for x in inputs]
    _, out_dtypes, _ = prop.infer_type(in_dtypes)
    op = prop.create_operator(None, in_shapes, in_dtypes)
    need_top = prop.need_top_grad_

    def host_forward(*host_inputs):
        ins = [_np.asarray(h) for h in host_inputs]
        outs = [_np.zeros(s, d) for s, d in zip(out_shapes, out_dtypes)]
        in_nd = [_HostND(a) for a in ins]
        out_nd = [_HostND(a) for a in outs]
        op.forward(True, ["write"] * n_out, in_nd, out_nd, [])
        return tuple(o._arr for o in out_nd)

    def host_backward(*args):
        ogs = [_np.asarray(a) for a in args[:n_out]]
        ins = [_np.asarray(a) for a in args[n_out:]]
        outs_again = [_np.zeros(s, d) for s, d in zip(out_shapes, out_dtypes)]
        out_nd = [_HostND(a) for a in outs_again]
        in_nd = [_HostND(a) for a in ins]
        op.forward(True, ["write"] * n_out, in_nd, out_nd, [])
        grads = [_np.zeros_like(a) for a in ins]
        grad_nd = [_HostND(g) for g in grads]
        op.backward(["write"] * len(ins), [_HostND(g) for g in ogs], in_nd, out_nd, grad_nd, [])
        return tuple(g._arr for g in grad_nd)

    out_spec = tuple(
        jax.ShapeDtypeStruct(tuple(s), _np.dtype(d)) for s, d in zip(out_shapes, out_dtypes)
    )
    in_spec = tuple(jax.ShapeDtypeStruct(tuple(x.shape), _np.dtype(x.dtype)) for x in inputs)

    @jax.custom_vjp
    def f(*xs):
        return jax.pure_callback(host_forward, out_spec, *xs)

    def fwd(*xs):
        return f(*xs), xs

    def bwd(xs, gs):
        grads = jax.pure_callback(host_backward, in_spec, *(tuple(gs) + tuple(xs)))
        return tuple(grads)

    f.defvjp(fwd, bwd)
    outs = f(*inputs)
    return list(outs), []


class _HostND:
    """Minimal NDArray-like wrapper over host numpy for user callbacks."""

    def __init__(self, arr):
        self._arr = arr

    def asnumpy(self):
        return self._arr

    @property
    def shape(self):
        return self._arr.shape

    def __getitem__(self, k):
        return self._arr[k]

    def __setitem__(self, k, v):
        if hasattr(v, "asnumpy"):  # mx NDArray / another host view
            v = v.asnumpy()
        self._arr[k] = _np.asarray(v)


def _norm_infer_shape(ret):
    """User infer_shape may return (in, out) — the 2016 API (ref:
    python/mxnet/operator.py:73-90) — or (in, out, aux)."""
    if len(ret) == 2:
        ins, outs = ret
        return ins, outs, []
    return ret


def _custom_infer_shape(params, in_shapes):
    op_type = params["op_type"]
    prop = _CUSTOM_REGISTRY[op_type](**(params.get("__kwargs__") or {}))
    # Partially-known inputs reach the user prop as empty lists (the
    # reference passes default TShapes into the prop's InferShape,
    # custom-inl.h:60-78) so props that derive label/output shapes from
    # the data shape alone can back-fill them — prediction binds without
    # a label (FeedForward._init_predictor) rely on this. A prop that
    # indexes an entry that is still unknown raises; the fixed-point
    # loop treats that as "not yet inferable" and retries next sweep.
    unknown = any(s is None for s in in_shapes)
    try:
        ins, outs, auxs = _norm_infer_shape(prop.infer_shape(
            [list(s) if s is not None else [] for s in in_shapes]))
    except MXNetError as exc:
        if unknown or isinstance(exc, InferShapeFatal):
            raise  # retryable (or already classified) — loop decides
        # every input was known, so the prop's complaint is a REAL
        # error: escalate so the fixed point surfaces it verbatim
        # instead of degrading it to "cannot determine shapes"
        raise InferShapeFatal("Custom(%s) infer_shape: %s" % (op_type, exc))
    except Exception:
        if unknown:
            # the prop indexed a not-yet-known entry: retryable — the
            # fixed point will call again once more inputs resolve
            raise MXNetError(
                "Custom(%s) infer_shape needs more input shapes" % op_type)
        raise  # real prop bug with full information: propagate as-is
    if unknown:
        # Under partial inputs, "not yet known" maps to None; the fixed
        # point skips None entries but KEEPS everything the prop did
        # fill (a back-filled label next to a still-unknown output), so
        # partial progress is never thrown away. Sentinel rule: unknown
        # inputs are passed to the prop as empty LISTS, so an echoed
        # empty list (or None) means "not yet" — while an empty TUPLE
        # () is an intentional 0-d scalar shape (mx.nd scalars exist)
        # and passes through even on partial sweeps.
        def _norm(s):
            if s is None or (isinstance(s, list) and not s):
                return None
            return tuple(s)

        ins = [_norm(s) for s in ins]
        outs = [_norm(s) for s in outs]
        auxs = [_norm(s) for s in auxs]
        if not outs:
            raise MXNetError("Custom(%s): output shapes unknown" % op_type)
        return ins, outs, auxs
    return ([tuple(s) for s in ins], [tuple(s) for s in outs],
            [tuple(s) for s in auxs])


def _custom_arguments(params):
    op_type = params.get("op_type")
    if op_type and op_type in _CUSTOM_REGISTRY:
        prop = _CUSTOM_REGISTRY[op_type](**(params.get("__kwargs__") or {}))
        return prop.list_arguments()
    return ["data"]


def _custom_outputs(params):
    op_type = params.get("op_type")
    if op_type and op_type in _CUSTOM_REGISTRY:
        prop = _CUSTOM_REGISTRY[op_type](**(params.get("__kwargs__") or {}))
        return prop.list_outputs()
    return ["output"]


def _custom_host_apply(params, ins_np, is_train, cache=None):
    """Eager host execution for the Executor's hybrid mode: the user
    CustomOp runs directly on host numpy — no pure_callback, no compiled
    program involved (the reference likewise runs Custom as a plain host
    function pushed to the engine, ref: custom-inl.h:1-211).

    `cache` is the owning Executor's per-binding dict: one operator
    instance per (node params, input signature), created once per bind
    like the reference, so stateful user CustomOps keep their state
    across batches and die with their executor."""
    op_type = params["op_type"]
    if op_type not in _CUSTOM_REGISTRY:
        raise MXNetError("Custom op %s not registered" % op_type)
    in_shapes = tuple(tuple(a.shape) for a in ins_np)
    in_dtypes = tuple(_np.dtype(a.dtype).str for a in ins_np)
    key = (id(params), in_shapes, in_dtypes)
    cached = cache.get(key) if cache is not None else None
    if cached is None:
        prop = _CUSTOM_REGISTRY[op_type](**(params.get("__kwargs__") or {}))
        n_out = len(prop.list_outputs())
        _, out_shapes, _ = _norm_infer_shape(
            prop.infer_shape(list(map(list, in_shapes))))
        _, out_dtypes, _ = prop.infer_type([a.dtype for a in ins_np])
        op = prop.create_operator(None, in_shapes, [a.dtype for a in ins_np])
        cached = (op, n_out, out_shapes, out_dtypes)
        if cache is not None:
            cache[key] = cached
    op, n_out, out_shapes, out_dtypes = cached
    outs = [_np.zeros(s, d) for s, d in zip(out_shapes, out_dtypes)]
    in_nd = [_HostND(_np.asarray(a)) for a in ins_np]
    out_nd = [_HostND(a) for a in outs]
    op.forward(bool(is_train), ["write"] * n_out, in_nd, out_nd, [])
    outs = [o._arr for o in out_nd]
    return outs, (op, in_nd, out_nd)


def _custom_host_grad(params, bwd_ctx, out_grads_np):
    """in_grads from the user CustomOp.backward, reusing the saved
    forward arrays (the pure_callback path must recompute forward in
    backward; here the residuals persist — strictly cheaper)."""
    op, in_nd, out_nd = bwd_ctx
    grads = [_np.zeros_like(a._arr) for a in in_nd]
    grad_nd = [_HostND(g) for g in grads]
    op.backward(["write"] * len(in_nd),
                [_HostND(_np.asarray(g)) for g in out_grads_np],
                in_nd, out_nd, grad_nd, [])
    return [g._arr for g in grad_nd]


_register_opdef(
    OpDef(
        "Custom",
        _custom_fwd,
        params={
            "op_type": Field("str", required=True),
            "__kwargs__": Field("any", default=None),
        },
        arguments=_custom_arguments,
        outputs=_custom_outputs,
        infer_shape=_custom_infer_shape,
        imperative=False,
        # loss-head semantics follow the user Prop's need_top_grad
        no_head_grad=lambda params: (
            params.get("op_type") in _CUSTOM_REGISTRY
            and not _CUSTOM_REGISTRY[params["op_type"]](
                **(params.get("__kwargs__") or {})
            ).need_top_grad_
        ),
        host_apply=_custom_host_apply,
        host_grad=_custom_host_grad,
    )
)


class NumpyOp:
    """Legacy numpy op base (ref: python/mxnet/operator.py:124). Wraps the
    subclass into a CustomOp-backed symbol on get_symbol()."""

    def __init__(self, need_top_grad=True):
        self.need_top_grad_ = need_top_grad

    def forward(self, in_data, out_data):
        raise NotImplementedError()

    def backward(self, out_grad, in_data, out_data, in_grad):
        raise NotImplementedError()

    def infer_shape(self, in_shape):
        return in_shape, [in_shape[0]]

    def list_arguments(self):
        return ["data"]

    def list_outputs(self):
        return ["output"]

    def get_symbol(self, *args, **kwargs):
        numpy_op = self

        class _Prop(CustomOpProp):
            def __init__(self):
                super().__init__(need_top_grad=numpy_op.need_top_grad_)

            def list_arguments(self):
                return numpy_op.list_arguments()

            def list_outputs(self):
                return numpy_op.list_outputs()

            def infer_shape(self, in_shape):
                ins, outs = numpy_op.infer_shape(in_shape)
                return ins, outs, []

            def create_operator(self, ctx, in_shapes, in_dtypes):
                class _Op(CustomOp):
                    def forward(self, is_train, req, in_data, out_data, aux):
                        numpy_op.forward(
                            [a.asnumpy() for a in in_data],
                            [a._arr for a in out_data],
                        )

                    def backward(self, req, out_grad, in_data, out_data, in_grad, aux):
                        numpy_op.backward(
                            [a.asnumpy() for a in out_grad],
                            [a.asnumpy() for a in in_data],
                            [a.asnumpy() for a in out_data],
                            [a._arr for a in in_grad],
                        )

                return _Op()

        reg_name = "_numpy_op_%s_%d" % (type(self).__name__, id(self))
        register(reg_name)(_Prop)
        from . import symbol as sym

        return sym.Custom(*args, op_type=reg_name, **kwargs)


NDArrayOp = NumpyOp  # same user surface; arrays arrive as host views
PythonOp = NumpyOp  # the reference's shared base (operator.py:124)

# reference NumpyOp instances are called directly to build the symbol
# (example/numpy-ops/numpy_softmax.py: mysoftmax(data=fc3, name='softmax'))
NumpyOp.__call__ = NumpyOp.get_symbol

# `Custom` is registered above AFTER ops.install() ran in __init__, so
# wire it into the symbol module here (mx.sym.Custom(op_type=...), ref:
# python/mxnet/symbol.py auto-generated Custom)
from . import symbol as _sym_mod  # noqa: E402

if not hasattr(_sym_mod, "Custom"):
    from .ops.registry import REGISTRY as _reg
    from .symbol import _make_op_func as _mk

    _sym_mod.Custom = _mk(_reg["Custom"], "Custom")
