"""Subscriber-side RPC client for the wsync publisher.

The same connection-per-request discipline as
:class:`~..elastic.client.ElasticClient`: each call is one
``protocol.call`` round trip behind the ``kv.coord`` fault-injection
point and ``MXNET_KV_RETRIES`` attempts of jittered exponential
backoff, with the ``elastic.rpc``-style telemetry span
(``wsync.rpc.<op>``) carrying the transaction's trace context over the
wire. A publisher restart mid-transaction heals here; a dead publisher
surfaces after the retry budget and the subscriber aborts the
transaction without touching the engine.
"""
from __future__ import annotations

import os

from .. import telemetry as _tel
from ..base import MXNetError
from ..elastic import protocol
from ..elastic.client import parse_addr
from ..resilience import faults as _faults
from ..resilience.retry import RetryPolicy

__all__ = ["WsyncClient"]


class WsyncClient:
    """One subscriber's handle on a publisher. Stateless between calls
    (survives publisher restarts); holds only the address, the rank,
    and the retry policy."""

    def __init__(self, addr, rank=-1, timeout=30.0):
        self.addr = parse_addr(addr) if isinstance(addr, str) else tuple(addr)
        self.rank = int(rank)
        self.timeout = float(timeout)
        attempts = max(1, int(os.environ.get("MXNET_KV_RETRIES", "4")))
        self._policy = RetryPolicy(max_attempts=attempts, base_delay=0.05,
                                   max_delay=1.0, jitter=0.25)

    def call(self, op, check=True, **fields):
        """One RPC. Transport errors retry under the policy; an
        ``error`` status raises MXNetError (when ``check``); 'pending'
        is a protocol answer the poll loop dispatches on."""
        req = dict(fields)
        req["op"] = op
        req["rank"] = self.rank

        def _rpc():
            _faults.point("kv.coord")
            return protocol.call(self.addr, req, timeout=self.timeout)

        _rpc.__name__ = "wsync %s" % op
        if not _tel.ENABLED:
            resp = self._policy.call(_rpc)
        else:
            with _tel.span("wsync.rpc.%s" % op):
                req["_trace"] = _tel.wire_context()
                resp = self._policy.call(_rpc)
        if check and resp.get("status") == "error":
            raise MXNetError("wsync publisher rejected %s: %s"
                             % (op, resp.get("message", "(no message)")))
        return resp

    # -- op wrappers -----------------------------------------------------------
    def poll_version(self, have, wait=None):
        """Newest published version, long-polling up to ``wait`` s when
        nothing newer than ``have`` exists yet ('pending' reply)."""
        fields = {"have": int(have)}
        if wait:
            fields["wait"] = float(wait)
        return self.call("wsync_poll", **fields)

    def fetch_manifest(self, version):
        """Per-tensor ``{path: {shape, dtype, fp}}`` of one version."""
        return self.call("wsync_manifest", version=int(version))

    def fetch_tensor(self, version, key):
        """One tensor of one version, full precision."""
        return self.call("wsync_fetch", version=int(version), key=key)

    def ack_version(self, version, outcome, check=True):
        """Report this subscriber's transaction outcome (applied /
        rejected:<reason> / aborted) — the publisher's delivery
        ledger."""
        return self.call("wsync_ack", check=check, version=int(version),
                         outcome=str(outcome))
