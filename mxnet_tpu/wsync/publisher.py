"""The wsync publisher: versioned weight sets served over elastic RPC.

One process-wide :class:`WeightPublisher` owns a bounded history of
complete, fingerprinted weight versions and answers the four
``wsync_*`` ops (connection-per-request, ``elastic/protocol.py``
framing, linted by ``mxlint --proto`` like every other speaker):

=================  ===========================================
``wsync_poll``     long-poll for a version newer than ``have``
``wsync_manifest`` per-tensor shape/dtype/fingerprint of a version
``wsync_fetch``    one tensor of one version, full precision
``wsync_ack``      subscriber outcome (applied/rejected/aborted)
=================  ===========================================

Versions arrive from either feed:

- the in-process trainer hook — :meth:`WeightPublisher.publish` called
  with the live params (and draft params) after an eval gate;
- a :class:`CheckpointWatcher` thread polling
  ``model.find_latest_checkpoint`` over a checkpoint directory, so any
  training job that only writes checkpoints still streams (the
  ``python -m mxnet_tpu.wsync.publisher`` entry point).

A publisher is only ever constructed explicitly (or by the CLI): the
serving-side ``MXNET_WSYNC`` switch gates the subscriber, and with it
unset nothing in this module runs — no thread, no socket.
"""
from __future__ import annotations

import os
import socketserver
import threading
import time

import numpy as np

from .. import telemetry as _tel
from ..base import MXNetError
from ..elastic import protocol
from . import common as _wc

__all__ = ["WeightPublisher", "CheckpointWatcher", "main"]

#: server-side cap on one poll's long-poll budget (seconds) — same
#: discipline (and value) as the elastic coordinator's wait cap: a
#: parked request never outlives the client's 30 s RPC timeout
_WSYNC_WAIT_CAP = 25.0


class _Handler(socketserver.BaseRequestHandler):
    def handle(self):
        try:
            req = protocol.recv_msg(self.request, what="wsync request")
            if req is None:
                return
            wire = req.pop("_trace", None)
            try:
                with _tel.span("wsync.serve.%s" % req.get("op"), wire=wire):
                    resp = self.server.publisher._dispatch(req)
            except MXNetError as e:
                resp = {"status": "error", "message": str(e)}
            if _tel.ENABLED:
                resp.setdefault("_srv_t", time.time())
            protocol.send_msg(self.request, resp)
        except (OSError, protocol.ProtocolError):
            pass  # client went away mid-request — its retry policy heals


class _Server(socketserver.ThreadingTCPServer):
    allow_reuse_address = True
    daemon_threads = True


class WeightPublisher:
    """Versioned weight-set store + RPC server.

    Parameters
    ----------
    bind : (host, port) or None
        RPC endpoint (port 0 picks an ephemeral port). ``None`` builds
        a socketless publisher for tests that drive ``_dispatch``
        directly.
    history : int, optional
        Complete versions kept fetchable (``MXNET_WSYNC_HISTORY``,
        default 4) — a slow subscriber mid-transaction can still finish
        fetching version N after N+1..N+history-1 landed.
    throttle : float
        Seconds slept inside each ``wsync_fetch`` reply — the chaos
        harness widens the mid-stream kill window with this; 0 (the
        default) for real deployments.
    """

    def __init__(self, bind=("127.0.0.1", 0), history=None, throttle=0.0):
        if history is None:
            history = max(1, int(_wc.env_float("MXNET_WSYNC_HISTORY", 4)))
        self.history = int(history)
        self.throttle = float(throttle)
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._versions = {}      # version -> {"tensors": flat, "manifest": m}
        self._order = []         # insertion order, oldest first
        self._latest = 0         # 0 = nothing published yet
        self._acks = []          # (version, rank, outcome) tail, bounded
        self._server = None
        self._thread = None
        if bind is not None:
            self._server = _Server(tuple(bind), _Handler)
            self._server.publisher = self

    # -- lifecycle -------------------------------------------------------------
    @property
    def addr(self):
        if self._server is None:
            raise MXNetError("publisher was built socketless (bind=None)")
        return self._server.server_address

    def start(self):
        """Serve in a daemon thread; returns the bound (host, port)."""
        if self._server is None:
            raise MXNetError("publisher was built socketless (bind=None)")
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._server.serve_forever, name="mx-wsync-pub",
                daemon=True)
            self._thread.start()
        return self.addr

    def close(self):
        if self._server is not None and self._thread is not None:
            self._server.shutdown()
            self._server.server_close()
            self._thread = None

    # -- the trainer hook ------------------------------------------------------
    def publish(self, params, draft_params=None, version=None):
        """Land one complete version (target + optional draft params in
        ONE version — the same-transaction draft refresh is structural:
        a version either carries both or the subscriber refreshes
        neither). Returns the version number.

        Host-snapshots every tensor at publish time, so the trainer may
        keep mutating its live params immediately."""
        flat = {k: np.ascontiguousarray(np.asarray(v))
                for k, v in _wc.combine_draft(params, draft_params).items()}
        manifest = _wc.manifest_of(flat)
        nbytes = int(sum(a.nbytes for a in flat.values()))
        with self._lock:
            v = int(version) if version is not None else self._latest + 1
            if v <= self._latest:
                raise MXNetError(
                    "wsync versions are monotonic: publish(version=%d) "
                    "after version %d" % (v, self._latest))
            self._versions[v] = {"tensors": flat, "manifest": manifest}
            self._order.append(v)
            while len(self._order) > self.history:
                del self._versions[self._order.pop(0)]
            self._latest = v
            self._cond.notify_all()
        if _tel.ENABLED:
            _tel.counter("wsync.versions_published_total").inc()
            _tel.gauge("wsync.published_version").set(v)
            _wc.journal("published", v, trace=_tel.mint_trace(),
                        tensors=len(flat), bytes=nbytes,
                        draft=draft_params is not None)
        return v

    # -- RPC dispatch ----------------------------------------------------------
    def _dispatch(self, req):
        op = req.get("op")
        rank = int(req.get("rank", -1))
        if op == "wsync_poll":
            have = int(req.get("have", 0) or 0)
            deadline = time.monotonic() + min(
                float(req.get("wait", 0.0) or 0.0), _WSYNC_WAIT_CAP)
            with self._lock:
                while self._latest <= have:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        return {"status": "pending", "version": self._latest}
                    self._cond.wait(min(remaining, 0.5))
                return {"status": "ok", "version": self._latest}
        if op == "wsync_manifest":
            v = int(req["version"])
            with self._lock:
                ent = self._versions.get(v)
                if ent is None:
                    return {"status": "error",
                            "message": "version %d not available (have %s)"
                                       % (v, sorted(self._versions))}
                return {"status": "ok", "version": v,
                        "tensors": ent["manifest"]}
        if op == "wsync_fetch":
            v = int(req["version"])
            key = req["key"]
            with self._lock:
                ent = self._versions.get(v)
                arr = ent["tensors"].get(key) if ent is not None else None
            if arr is None:
                return {"status": "error",
                        "message": "no tensor %r in version %d" % (key, v)}
            if self.throttle:
                time.sleep(self.throttle)
            # full precision always — the byte-parity contract
            # (weights never ride the lossy gradient codec)
            return {"status": "ok", "value": arr,
                    "fp": _wc.fingerprint(arr)}
        if op == "wsync_ack":
            v = int(req["version"])
            outcome = str(req["outcome"])
            with self._lock:
                self._acks.append((v, rank, outcome))
                del self._acks[:-256]
            if _tel.ENABLED:
                _tel.counter("wsync.acks_total").inc()
                _wc.journal("ack", v, rank=rank, outcome=outcome)
            return {"status": "ok"}
        return {"status": "error", "message": "unknown wsync op %r" % (op,)}

    def acks(self):
        """Recent (version, rank, outcome) subscriber acks (tests and
        the watcher's progress logging)."""
        with self._lock:
            return list(self._acks)


class CheckpointWatcher:
    """Poll a checkpoint prefix and publish every new complete epoch.

    Rides the crash-safe checkpoint discipline end to end:
    ``find_latest_checkpoint`` fences partial writes and validates
    structure, so a torn or in-flight checkpoint is never published.
    The epoch number IS the wsync version — exactly-once, monotonic.
    """

    def __init__(self, publisher, prefix, interval=None):
        self.publisher = publisher
        self.prefix = str(prefix)
        if interval is None:
            interval = _wc.env_float("MXNET_WSYNC_INTERVAL", 2.0)
        self.interval = max(0.05, float(interval))
        self._published = 0
        self._stop = threading.Event()
        self._thread = None

    def poll_once(self):
        """One scan; returns the version published, or None."""
        from ..model import find_latest_checkpoint

        epoch = find_latest_checkpoint(self.prefix)
        if epoch is None or epoch <= self._published:
            return None
        params, draft = _wc.load_weights_checkpoint(self.prefix, epoch)
        v = self.publisher.publish(params, draft, version=epoch)
        self._published = epoch
        return v

    def run(self):
        """Foreground watch loop (the CLI's body)."""
        while not self._stop.is_set():
            try:
                self.poll_once()
            except (OSError, MXNetError):
                pass  # torn/vanishing files heal on the next scan
            self._stop.wait(self.interval)

    def start(self):
        if self._thread is None:
            self._thread = threading.Thread(target=self.run,
                                            name="mx-wsync-watch",
                                            daemon=True)
            self._thread.start()

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join()
            self._thread = None


def main(argv=None):
    """``python -m mxnet_tpu.wsync.publisher --bind host:port --watch
    <ckpt_prefix>`` — the standalone publisher the chaos harness
    SIGKILLs mid-stream."""
    import argparse

    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--bind", default="127.0.0.1:0",
                   help="host:port to serve on (port 0 = ephemeral)")
    p.add_argument("--watch", required=True,
                   help="checkpoint prefix to poll "
                        "(model.find_latest_checkpoint)")
    p.add_argument("--interval", type=float, default=None,
                   help="watch poll interval (MXNET_WSYNC_INTERVAL)")
    p.add_argument("--throttle", type=float, default=0.0,
                   help="seconds slept per wsync_fetch reply (chaos "
                        "kill-window widener)")
    args = p.parse_args(argv)
    host, _, port = args.bind.rpartition(":")
    pub = WeightPublisher(bind=(host or "127.0.0.1", int(port)),
                          throttle=args.throttle)
    addr = pub.start()
    print("wsync publisher listening on %s:%d pid %d"
          % (addr[0], addr[1], os.getpid()), flush=True)
    watcher = CheckpointWatcher(pub, args.watch, interval=args.interval)
    try:
        watcher.run()
    except KeyboardInterrupt:
        pass
    finally:
        pub.close()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
