"""Shared wsync plumbing: param flattening, fingerprints, gates,
checkpoint round-trip, and the ``{"kind": "wsync"}`` journal record.

The wire unit is a *flat* param set — ``{"embed": arr,
"layers/0/wqkv": arr, ...}`` with the draft model's tensors under a
``draft/`` prefix — so the publisher can manifest, fingerprint, and
serve tensors individually (per-tensor deltas) while both ends agree on
one canonical naming for any params pytree. Weights cross the wire at
full precision always: the byte-parity contract (a hot-swapped engine
decodes byte-identically to a cold engine from the same checkpoint)
forbids the lossy gradient codec here, the same scope discipline
``quantize.py`` applies to ``put_weight``.
"""
from __future__ import annotations

import os
import time
import zlib

import numpy as np

from .. import telemetry as _tel
from ..base import MXNetError

__all__ = ["flatten_params", "unflatten_params", "split_draft",
           "fingerprint", "manifest_of", "param_manifest",
           "nonfinite_keys", "save_weights_checkpoint",
           "load_weights_checkpoint", "journal", "env_float"]

#: flat-key prefix carrying the draft model's tensors inside one
#: version (one checkpoint file, one transaction — target and draft
#: can never tear apart)
DRAFT_PREFIX = "draft/"


def env_float(name, default):
    raw = os.environ.get(name, "").strip()
    if not raw:
        return float(default)
    try:
        return float(raw)
    except ValueError:
        raise MXNetError("%s must be a number, got %r" % (name, raw))


# -- flat param sets -----------------------------------------------------------

def flatten_params(tree, prefix="", out=None):
    """A params pytree (nested dict/list/tuple of arrays) as one flat
    ``{path: array}`` dict with ``/``-joined, sorted-key paths. A dict
    that is already flat round-trips unchanged (leaf values are kept
    as-is — no host copy is forced here)."""
    if out is None:
        out = {}
    if isinstance(tree, dict):
        for k in sorted(tree):
            flatten_params(tree[k], "%s%s/" % (prefix, k), out)
    elif isinstance(tree, (list, tuple)):
        for i, v in enumerate(tree):
            flatten_params(v, "%s%d/" % (prefix, i), out)
    else:
        out[prefix[:-1]] = tree
    return out


def unflatten_params(flat):
    """Inverse of :func:`flatten_params`: rebuild the nested pytree
    (path components that are all decimal become a dense list)."""
    root = {}
    for path, arr in flat.items():
        parts = path.split("/")
        node = root
        for p in parts[:-1]:
            node = node.setdefault(p, {})
        node[parts[-1]] = arr

    def build(node):
        if not isinstance(node, dict):
            return node
        keys = list(node)
        if keys and all(k.isdigit() for k in keys):
            idx = sorted(int(k) for k in keys)
            if idx != list(range(len(idx))):
                raise MXNetError("non-dense list indices in flat params: %r"
                                 % sorted(keys))
            return [build(node[str(i)]) for i in idx]
        return {k: build(v) for k, v in node.items()}

    return build(root)


def split_draft(flat):
    """``(target_flat, draft_flat_or_None)`` from one combined flat set
    (the ``draft/`` prefix is the draft half)."""
    target, draft = {}, {}
    for k, v in flat.items():
        if k.startswith(DRAFT_PREFIX):
            draft[k[len(DRAFT_PREFIX):]] = v
        else:
            target[k] = v
    return target, (draft or None)


def combine_draft(params, draft_params=None):
    """One flat set from a target pytree plus an optional draft pytree
    (draft keys under ``draft/``)."""
    flat = flatten_params(params)
    if draft_params is not None:
        for k, v in flatten_params(draft_params).items():
            flat[DRAFT_PREFIX + k] = v
    return flat


# -- manifests and gates -------------------------------------------------------

def fingerprint(arr):
    """Content fingerprint of one tensor (crc32 over dtype/shape/bytes)
    — what makes the version stream *delta*-transferable: a subscriber
    skips every tensor whose fingerprint it already holds."""
    a = np.ascontiguousarray(np.asarray(arr))
    h = zlib.crc32(("%s:%r" % (a.dtype.str, a.shape)).encode())
    return zlib.crc32(a.tobytes(), h) & 0xFFFFFFFF


def manifest_of(flat):
    """Per-tensor wire manifest: ``{path: {"shape", "dtype", "fp"}}``.
    Forces a host snapshot of each leaf (the publisher stores host
    copies anyway — the wire is host-side by construction)."""
    out = {}
    for k, v in flat.items():
        a = np.asarray(v)
        out[k] = {"shape": tuple(int(d) for d in a.shape),
                  "dtype": a.dtype.str, "fp": fingerprint(a)}
    return out


def param_manifest(tree):
    """Shape/dtype map of a pytree WITHOUT materializing device arrays
    on the host — the Engine-side half of the hard shape/dtype gate
    (jitted programs keep their compiled shapes; a mismatched sync can
    never be allowed to trigger a recompile)."""
    out = {}
    for k, v in flatten_params(tree).items():
        out[k] = (tuple(int(d) for d in np.shape(v)),
                  np.dtype(getattr(v, "dtype", np.float32)).str)
    return out


def nonfinite_keys(flat):
    """Paths of tensors containing non-finite values — the guardian's
    finiteness discipline (``resilience/guardian.py``: a non-finite
    update never lands) applied to a staged weight set."""
    bad = []
    for k, v in flat.items():
        a = np.asarray(v)
        if np.issubdtype(a.dtype, np.floating) and not np.all(
                np.isfinite(a)):
            bad.append(k)
    return bad


# -- checkpoint round-trip -----------------------------------------------------

def save_weights_checkpoint(prefix, epoch, params, draft_params=None):
    """Write ``<prefix>-NNNN.params`` holding the combined flat set
    (draft under ``draft/``) via the crash-safe atomic writer — the
    file a :class:`~.publisher.CheckpointWatcher` picks up with
    ``model.find_latest_checkpoint``. Returns the path."""
    from ..model import _write_params_atomic

    path = "%s-%04d.params" % (prefix, int(epoch))
    flat = combine_draft(params, draft_params)
    _write_params_atomic(path, {k: np.asarray(v) for k, v in flat.items()})
    return path


def load_weights_checkpoint(prefix, epoch):
    """``(params, draft_params_or_None)`` pytrees from one epoch's
    weights checkpoint."""
    from ..ndarray import load as nd_load

    path = "%s-%04d.params" % (prefix, int(epoch))
    flat = {k: v.asnumpy() for k, v in nd_load(path).items()}
    target, draft = split_draft(flat)
    return (unflatten_params(target),
            unflatten_params(draft) if draft else None)


# -- journal -------------------------------------------------------------------

def journal(event, version, trace=None, **fields):
    """One ``{"kind": "wsync"}`` journal record (no-op with telemetry
    off — the off-by-default contract). Every record of one sync
    transaction shares the trace id minted at transaction start, so
    ``tools/telemetry_report.py``'s version timeline reconstructs
    staged → applied/rejected/rolled-back per transaction."""
    if not _tel.ENABLED:
        return
    from ..telemetry import export as _export

    rec = {"kind": "wsync", "event": event, "version": version,
           "t": time.time(), "trace": trace}
    rec.update(fields)
    _export.emit(rec)
