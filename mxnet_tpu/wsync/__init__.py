"""mxwsync: gated live trainer→serving weight sync (ISSUE 17).

The bridge between the framework's two running halves: a
:class:`~.publisher.WeightPublisher` owns versioned full-precision
weight sets (fed by a trainer's in-process ``publish()`` hook or a
checkpoint-directory watcher over ``model.find_latest_checkpoint``) and
serves **per-tensor versioned deltas** over the elastic RPC substrate
(``elastic/protocol.py``). A :class:`~.subscriber.WeightSubscriber`
rides inside each serving process: it long-polls for new versions,
fetches only the tensors whose content fingerprint changed, stages them
into a host-side double buffer, runs the gates (shape/dtype hard
reject, guardian-style finiteness, a pluggable acceptance probe), and
asks the Engine to swap the staged set in **atomically between
scheduled steps** — target and draft params in one transaction, no
drain, no jit recompile.

Every version transition is journaled (``wsync.*`` counters plus
``{"kind": "wsync"}`` records sharing one trace id per transaction),
the Engine keeps a bounded ring of last-good versions, and mxctl's
``rollback_weights`` actuator restores the previous version when the
windowed quality rules (``spec_accept_rate``) fire.

Off by default: with ``MXNET_WSYNC`` unset nothing here starts — no
thread, no socket, no journal records (docs/how_to/weight_sync.md).
"""
from __future__ import annotations

import os

__all__ = ["enabled", "publisher_addr"]


def enabled():
    """Master switch (read live, like the other MXNET_* knobs)."""
    return os.environ.get("MXNET_WSYNC", "0").strip().lower() not in (
        "", "0", "false", "off", "no")


def publisher_addr():
    """``MXNET_WSYNC_PUBLISHER`` (host:port of the publisher), or None.
    With :func:`enabled` on and this set, every constructed serving
    Engine auto-starts a subscriber against it."""
    return os.environ.get("MXNET_WSYNC_PUBLISHER", "").strip() or None
