"""The serving-side half of wsync: stage, gate, swap.

A :class:`WeightSubscriber` runs inside the serving process next to one
:class:`~..serving.engine.Engine`. Its loop long-polls the publisher,
and for each new version runs ONE transaction under one trace id:

1. **fetch** the version's manifest and every tensor whose content
   fingerprint differs from the subscriber's host cache (per-tensor
   deltas — unchanged tensors never cross the wire again);
2. **stage** the complete candidate set host-side (the double buffer:
   the engine's live params are untouched while the candidate
   assembles, so a torn fetch aborts without a trace on the device);
3. **gate** — the pluggable acceptance probe here, then the engine's
   own hard gates (shape/dtype reject, guardian finiteness) inside
   :meth:`Engine.install_weights`;
4. **swap** — the engine installs target + draft params atomically
   between scheduled steps and pushes the outgoing version onto its
   last-good ring;
5. **ack** the outcome back to the publisher.

Anything that fails mid-transaction (publisher SIGKILL, retry budget
exhausted, a gate) leaves the engine byte-identical on its previous
version: partial application is structurally impossible because the
engine only ever sees complete staged sets.

``maybe_autosync`` is the off-by-default entry: Engine construction
calls it only when ``MXNET_WSYNC=1``, and it starts a thread only when
``MXNET_WSYNC_PUBLISHER`` names an address.
"""
from __future__ import annotations

import threading
import time

import numpy as np

from .. import telemetry as _tel
from ..base import MXNetError
from . import common as _wc
from . import enabled as _enabled, publisher_addr as _pub_addr
from .client import WsyncClient

__all__ = ["WeightSubscriber", "maybe_autosync"]


class WeightSubscriber:
    """One engine's sync loop against one publisher.

    Parameters
    ----------
    engine : serving.engine.Engine
    addr : "host:port" or (host, port)
    rank : int
        Identity in the publisher's ack ledger.
    poll_wait : float, optional
        Long-poll budget per poll (``MXNET_WSYNC_POLL_WAIT``, default
        5.0 s; capped server-side at 25 s).
    accept : callable(version, params, draft_params) -> bool, optional
        The pluggable acceptance probe — an eval harness hook that
        refuses quality-regressed versions before they reach the
        engine. None accepts everything (the gates below still apply).
    """

    def __init__(self, engine, addr, rank=-1, poll_wait=None, accept=None):
        self.engine = engine
        self._client = WsyncClient(addr, rank=rank)
        if poll_wait is None:
            poll_wait = _wc.env_float("MXNET_WSYNC_POLL_WAIT", 5.0)
        self.poll_wait = max(0.0, float(poll_wait))
        self.accept = accept
        self._host = {}       # flat key -> host array of the applied set
        self._fps = {}        # flat key -> fingerprint of that array
        self._cursor = 0      # newest version attempted (applied OR not:
        self._stop = threading.Event()   # a rejected version must not
        self._thread = None              # re-fetch forever)

    @property
    def version(self):
        """Newest version applied to the engine by this subscriber."""
        return self.engine.weight_version()

    # -- one transaction -------------------------------------------------------
    def sync_once(self, wait=0.0):
        """One poll (+ transaction when a new version exists). Returns
        the applied version, or None."""
        resp = self._client.poll_version(self._cursor, wait=wait)
        v = int(resp.get("version", 0) or 0)
        if resp.get("status") != "ok" or v <= self._cursor:
            return None
        return self._transact(v)

    def _transact(self, version):
        trace = _tel.mint_trace() if _tel.ENABLED else None
        t0 = time.monotonic()
        candidate = {}
        fetched = fetched_bytes = 0
        try:
            manifest = self._client.fetch_manifest(version)["tensors"]
            for key in sorted(manifest):
                fp = manifest[key]["fp"]
                held = self._host.get(key)
                if held is not None and self._fps.get(key) == fp:
                    candidate[key] = held      # unchanged — delta skip
                    continue
                arr = np.asarray(
                    self._client.fetch_tensor(version, key)["value"])
                candidate[key] = arr
                fetched += 1
                fetched_bytes += int(arr.nbytes)
        except (MXNetError, ConnectionError, OSError) as e:
            # torn transaction: nothing staged reaches the engine
            self._cursor = max(self._cursor, version)
            if _tel.ENABLED:
                _tel.counter("wsync.aborted_total").inc()
            _wc.journal("aborted", version, trace=trace, reason=str(e),
                        fetched=fetched)
            self._ack(version, "aborted")
            return None
        self._cursor = max(self._cursor, version)
        if _tel.ENABLED:
            _tel.counter("wsync.tensors_fetched_total").inc(fetched)
            _tel.counter("wsync.bytes_fetched_total").inc(fetched_bytes)
        _wc.journal("staged", version, trace=trace, tensors=len(candidate),
                    fetched=fetched, bytes=fetched_bytes)
        target, draft = _wc.split_draft(candidate)
        params = _wc.unflatten_params(target)
        draft_params = _wc.unflatten_params(draft) if draft else None
        if self.accept is not None and not self.accept(version, params,
                                                       draft_params):
            if _tel.ENABLED:
                _tel.counter("wsync.rejected_total").inc()
            _wc.journal("rejected", version, trace=trace,
                        reason="acceptance-probe")
            self._ack(version, "rejected:acceptance-probe")
            return None
        try:
            self.engine.install_weights(version, params, draft_params,
                                        trace=trace)
        except MXNetError as e:
            # the engine's gates counted + journaled the reject already
            self._ack(version, "rejected:%s" % e)
            return None
        self._host = candidate
        self._fps = {k: manifest[k]["fp"] for k in manifest}
        if _tel.ENABLED:
            _tel.histogram("wsync.apply_secs").observe(
                time.monotonic() - t0)
        self._ack(version, "applied")
        return version

    def _ack(self, version, outcome):
        try:
            self._client.ack_version(version, outcome, check=False)
        except (MXNetError, ConnectionError, OSError):
            pass  # a dead publisher must not take the outcome path down

    # -- the loop --------------------------------------------------------------
    def run(self):
        while not self._stop.is_set():
            try:
                self.sync_once(wait=self.poll_wait)
            except (MXNetError, ConnectionError, OSError):
                # publisher down: back off, keep serving on the current
                # version — sync is strictly additive to availability
                self._stop.wait(min(1.0, self.poll_wait or 1.0))

    def start(self):
        if self._thread is None:
            self._thread = threading.Thread(target=self.run,
                                            name="mx-wsync-sub",
                                            daemon=True)
            self._thread.start()
        return self

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join()
            self._thread = None


def maybe_autosync(engine):
    """Start a subscriber for ``engine`` iff ``MXNET_WSYNC=1`` and
    ``MXNET_WSYNC_PUBLISHER`` is set; returns it (or None). The
    off-by-default contract lives here: unset env ⇒ no thread, no
    socket, no journal records."""
    if not _enabled():
        return None
    addr = _pub_addr()
    if not addr:
        return None
    return WeightSubscriber(engine, addr).start()
