"""Deprecated scheduler aliases (ref: python/mxnet/misc.py — the
pre-lr_scheduler module some 2016-era scripts still import)."""
from __future__ import annotations

import warnings

from .lr_scheduler import FactorScheduler as _FactorScheduler
from .lr_scheduler import LRScheduler as _LRScheduler

__all__ = ["LearningRateScheduler", "FactorScheduler"]


class LearningRateScheduler(_LRScheduler):
    """ref misc.py:7; superseded by lr_scheduler.LRScheduler."""

    def __init__(self, *args, **kwargs):
        warnings.warn("mxnet_tpu.misc is deprecated; use "
                      "mxnet_tpu.lr_scheduler", DeprecationWarning)
        super().__init__(*args, **kwargs)


class FactorScheduler(_FactorScheduler):
    """ref misc.py:24; superseded by lr_scheduler.FactorScheduler."""

    def __init__(self, *args, **kwargs):
        warnings.warn("mxnet_tpu.misc is deprecated; use "
                      "mxnet_tpu.lr_scheduler", DeprecationWarning)
        super().__init__(*args, **kwargs)
