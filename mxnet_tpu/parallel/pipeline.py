"""Pipeline parallelism: GPipe-style stage execution over a mesh axis.

Not in the 2016 reference (its model parallelism is ctx_group graph
partitioning with the engine overlapping stages implicitly — SURVEY
§2.7); this is the explicit TPU-era formulation: each device along the
'pipe' mesh axis owns one stage's weights, microbatches stream through
with `lax.ppermute` carrying activations to the next stage each tick,
and the schedule runs S + M - 1 ticks (the GPipe bubble). Differentiable
end-to-end: jax.grad through ppermute gives the reverse schedule for
free.

Constraints (the classic SPMD-pipeline ones): every stage must map
activations of one shape to the same shape, and stage weights must share
a common pytree structure (stacked on a leading stage axis).
"""
from __future__ import annotations



def pipeline_apply(stage_fn, stage_params, x, axis_name, n_microbatches):
    """Run a pipeline inside shard_map.

    stage_fn(params_slice, act) -> act; stage_params are THIS device's
    stage weights; x: [n_microbatches, mb, ...] microbatched input
    (identical on every device; stage 0 consumes it). Returns the
    pipeline output [n_microbatches, mb, ...] (valid on the LAST stage;
    other devices hold don't-care values)."""
    import jax.numpy as jnp
    from jax import lax

    from .mesh import axis_size

    stages = axis_size(axis_name)
    stage_id = lax.axis_index(axis_name)
    if x.shape[0] != n_microbatches:
        raise ValueError(
            "pipeline input has %d microbatches, schedule expects %d"
            % (x.shape[0], n_microbatches))
    mb_shape = x.shape[1:]
    total_ticks = stages + n_microbatches - 1
    perm = [(i, (i + 1) % stages) for i in range(stages)]

    state = jnp.zeros(mb_shape, x.dtype)      # activation held by stage
    outs = jnp.zeros((n_microbatches,) + mb_shape, x.dtype)
    # the carry becomes device-varying along the pipe axis after the
    # first ppermute; mark the initials so the loop carry types match
    # (same discipline as ring_attention's accumulators)
    from .mesh import mark_varying

    state, outs = mark_varying((state, outs), axis_name)

    def tick(t, carry):
        state, outs = carry
        # stage 0 ingests microbatch t (when in range), others take the
        # activation permuted from the previous stage
        feed = lax.dynamic_index_in_dim(
            x, jnp.clip(t, 0, n_microbatches - 1), keepdims=False)
        inp = jnp.where(stage_id == 0, feed, state)
        act = stage_fn(stage_params, inp)
        # last stage records its result for microbatch t - (stages - 1)
        out_slot = t - (stages - 1)
        valid = (out_slot >= 0) & (out_slot < n_microbatches)
        slot = jnp.clip(out_slot, 0, n_microbatches - 1)
        cur = lax.dynamic_index_in_dim(outs, slot, keepdims=False)
        upd = jnp.where(valid & (stage_id == stages - 1), act, cur)
        outs = lax.dynamic_update_index_in_dim(outs, upd, slot, axis=0)
        state = lax.ppermute(act, axis_name, perm)
        return state, outs

    _, outs = lax.fori_loop(0, total_ticks, tick, (state, outs))
    return outs


def make_pipeline(mesh, stage_fn, pipe_axis="pipe", n_microbatches=4):
    """shard_map wrapper: stacked stage params [S, ...] sharded on the
    pipe axis; input [n_microbatches, mb, ...] replicated; output taken
    from the last stage (psum-masked so every host sees it)."""
    import jax

    try:
        from jax import shard_map
    except ImportError:  # jax < 0.7 layout
        from jax.experimental.shard_map import shard_map
    from jax.sharding import NamedSharding, PartitionSpec as P

    stages = mesh.shape[pipe_axis]

    def inner(stacked_params, x):
        from jax import lax

        # each device's shard is [1, ...]: its own stage's weights
        my_params = jax.tree.map(lambda p: p[0], stacked_params)
        outs = pipeline_apply(
            stage_fn, my_params, x, pipe_axis, n_microbatches)
        # broadcast the last stage's result to every device
        mask = (lax.axis_index(pipe_axis) == stages - 1).astype(outs.dtype)
        return lax.psum(outs * mask, pipe_axis)

    mapped = shard_map(
        inner, mesh=mesh,
        in_specs=(P(pipe_axis), P()), out_specs=P())

    def apply(stacked_params, x):
        for leaf in jax.tree_util.tree_leaves(stacked_params):
            if leaf.shape[0] != stages:
                raise ValueError(
                    "stacked stage params have leading dim %d but the "
                    "'%s' mesh axis has %d stages — each device must hold "
                    "exactly one stage" % (leaf.shape[0], pipe_axis, stages))
        stacked_params = jax.tree.map(
            lambda p: jax.device_put(
                p, NamedSharding(mesh, P(pipe_axis))), stacked_params)
        x = jax.device_put(x, NamedSharding(mesh, P()))
        return mapped(stacked_params, x)

    return apply
