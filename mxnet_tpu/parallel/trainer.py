"""Sharded training steps: the performance path of the framework.

Where the reference's hot loop is Engine pushes of per-op kernels plus
KVStore reduce (SURVEY §3.1), the TPU-native hot loop is ONE jit-compiled
program per step: forward + backward + optimizer update, with buffer
donation for in-place weight updates and shardings that put gradients on
ICI all-reduces. This is what bench.py measures and what the Module/KVStore
facade ultimately delegates to on a mesh.

Sharding model: params/opt_state are committed to the mesh with
jax.device_put before training (ShardedTrainer does this); jit then infers
all program shardings from the committed inputs, and the mean-over-batch
loss makes XLA insert the gradient all-reduce (the KVStore 'device'
all-reduce of SURVEY §2.7, now riding ICI).
"""
from __future__ import annotations

from ..base import MXNetError


def data_parallel_spec(mesh, batch_axis="data"):
    """(replicated, batch-sharded) NamedShardings for pure data parallelism."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    return NamedSharding(mesh, P()), NamedSharding(mesh, P(batch_axis))


def _put_batch(batch, batch_spec):
    """Commit a host batch to the mesh. batch_spec: one sharding applied to
    every leaf, or a pytree of shardings matching the batch."""
    import jax

    if batch_spec is None:
        return batch
    if isinstance(batch_spec, dict) or isinstance(batch_spec, (list, tuple)):
        return jax.tree.map(
            lambda x, s: jax.device_put(x, s), batch, batch_spec,
            is_leaf=lambda x: hasattr(x, "shape") or hasattr(x, "__array__"),
        )
    return jax.tree.map(lambda x: jax.device_put(x, batch_spec), batch)


def make_train_step(loss_fn, optimizer=None, mesh=None, param_spec=None,
                    batch_spec=None, donate=True, has_aux=False):
    """Build a jitted fused train step (fwd+bwd+update in one XLA program).

    loss_fn(params, batch, rng) -> loss (or (loss, aux) when has_aux).
    optimizer: optax GradientTransformation (default optax.sgd(0.01)).
    With a mesh, the host batch is committed per batch_spec (default:
    sharded on dim 0 over the first mesh axis) and params should be
    committed by the caller (ShardedTrainer handles it); jit infers the
    rest. donate=True donates params+opt_state for in-place HBM updates.

    Returns (step_fn, init_state): step_fn(params, opt_state, batch, rng)
    -> (params, opt_state, loss[, aux]).
    """
    import jax
    import optax

    if optimizer is None:
        optimizer = optax.sgd(0.01)

    def step(params, opt_state, batch, rng):
        if has_aux:
            (loss, aux), grads = jax.value_and_grad(loss_fn, has_aux=True)(
                params, batch, rng
            )
        else:
            loss, grads = jax.value_and_grad(loss_fn)(params, batch, rng)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        if has_aux:
            return params, opt_state, loss, aux
        return params, opt_state, loss

    from ..analysis import compile_verify as _cv

    # fixed-shape sharded step: one compile (MXNET_JIT_VERIFY names the
    # offending arg if a varying value sneaks into the trace)
    jitted = _cv.wrap(
        "trainer.sharded_step",
        jax.jit(step, donate_argnums=(0, 1) if donate else ()),
        budget=1, group="train.sharded_step")

    if mesh is not None and batch_spec is None:
        from jax.sharding import NamedSharding, PartitionSpec as P

        batch_spec = NamedSharding(mesh, P(mesh.axis_names[0]))

    def step_fn(params, opt_state, batch, rng):
        return jitted(params, opt_state, _put_batch(batch, batch_spec), rng)

    def init_state(params):
        return optimizer.init(params)

    return step_fn, init_state


class ShardedTrainer:
    """Stateful convenience wrapper: commits params to the mesh, builds the
    fused step, tracks opt_state/rng.

    Example:
        trainer = ShardedTrainer(loss_fn, params, optax.adam(1e-3), mesh=mesh)
        for batch in data:
            loss = trainer.step(batch)
    """

    def __init__(self, loss_fn, params, optimizer=None, mesh=None,
                 param_spec=None, batch_spec=None, donate=True, seed=0, has_aux=False):
        import jax

        self.mesh = mesh
        self.has_aux = has_aux
        if mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec as P

            if param_spec is None:
                param_spec = NamedSharding(mesh, P())  # replicated
            if isinstance(param_spec, dict):
                params = jax.tree.map(
                    lambda x, s: jax.device_put(x, s), params, param_spec,
                    is_leaf=lambda x: hasattr(x, "shape"),
                )
            else:
                params = jax.device_put(params, param_spec)
        self.params = params
        self._step_fn, init_state = make_train_step(
            loss_fn, optimizer=optimizer, mesh=mesh, param_spec=param_spec,
            batch_spec=batch_spec, donate=donate, has_aux=has_aux,
        )
        self.opt_state = init_state(params)
        self._rng = jax.random.PRNGKey(seed)

    def step(self, batch):
        import jax

        self._rng, sub = jax.random.split(self._rng)
        out = self._step_fn(self.params, self.opt_state, batch, sub)
        if self.has_aux:
            self.params, self.opt_state, loss, aux = out
            return loss, aux
        self.params, self.opt_state, loss = out
        return loss
