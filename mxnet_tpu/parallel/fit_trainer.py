"""Scanned fast path for the public ``fit()`` training loops.

The reference's throughput numbers are ``fit()`` numbers (ref:
python/mxnet/model.py:117 _train_multi_device) — its engine pipelines the
per-batch pushes so the Python loop never blocks. On the tunneled TPU
backend every jitted dispatch costs ~20 ms of host round-trip when the
loop fences (metric updates fence every batch), so a per-batch loop is
structurally slower than the compiled trainer bench.py measures
(docs/perf_analysis.md). This module closes that gap for the public API:
K training steps run as ONE dispatched ``lax.scan`` program — forward,
backward, and the REAL ``mxnet_tpu.optimizer.Optimizer.update`` traced
into the program — so ``FeedForward.fit``/``Module.fit`` get the same
throughput as the internal trainer while preserving the reference
semantics (per-index lr/wd multipliers, gradient clipping, rescale,
schedulers, Adam step counts).

How the Python Optimizer is traced (not reimplemented): inside the scan
body each parameter/gradient/state leaf is wrapped in an NDArray facade
around the tracer and ``optimizer.update(index, w, g, state)`` runs with
two instance patches active:

- ``_get_lr`` returns a traced per-step base lr (host-precomputed from
  the real scheduler for each of the K steps) times the static
  lr_mult/idx2name lookup — schedulers stay host logic (see run_chunk
  for the one-update boundary nuance the per-batch loop itself has).
- ``_index_update_count`` reads as a traced step number (Adam's bias
  correction switches to jnp.sqrt on traced t, optimizer.py) and
  ``_update_count`` is a no-op during tracing; real counts advance on
  the host after each chunk.

Optimizers whose update is stateful on the host beyond counts (SGLD's
host-side PRNG draw) are not scan-safe and must use the per-batch path —
``supports_optimizer`` is the gate.
"""
from __future__ import annotations

import numpy as _np

from ..base import MXNetError

# exactly these classes (not subclasses: a subclass may override update
# with host logic the trace would freeze)
_SCANNABLE_OPTIMIZERS = ("SGD", "ccSGD", "NAG", "Adam", "AdaGrad",
                         "RMSProp", "AdaDelta", "Test")


def _resident_on(a, dev):
    """True iff ``a`` is a jax.Array wholly resident on ``dev``.

    Probes ``a.devices()`` (the stable jax.Array API — a set of devices)
    rather than ``a.device``, whose property-vs-method status has moved
    across jax versions; numpy arrays and anything else without
    ``devices()`` report False (host path)."""
    devices = getattr(a, "devices", None)
    if devices is None:
        return False
    try:
        return set(devices()) == {dev}
    except TypeError:  # .devices is data, not callable, on exotic types
        return False


def supports_optimizer(optimizer):
    from .. import optimizer as opt

    cls = type(optimizer)
    return any(
        cls is opt.Optimizer.opt_registry.get(n.lower()) for n in _SCANNABLE_OPTIMIZERS
    )


class _TracedCounts(dict):
    """Every index reads as the traced step count while update() traces."""

    def __init__(self, t):
        super().__init__()
        self._t = t

    def __getitem__(self, key):
        return self._t

    def __contains__(self, key):
        return True


def _static_lr_mult(optimizer, index):
    if index in optimizer.lr_mult:
        return optimizer.lr_mult[index]
    if index in optimizer.idx2name:
        return optimizer.lr_mult.get(optimizer.idx2name[index], 1.0)
    return 1.0


class FitTrainer:
    """Compiled K-step trainer driving a Symbol's fused fwd+bwd program
    and the user's real Optimizer object. Create via ``make_fit_trainer``."""

    def __init__(self, symbol, ctx, input_shapes, optimizer, arg_params,
                 aux_params, param_names, compute_dtype=None):
        import jax
        import jax.numpy as jnp

        self._jax = jax
        self._jnp = jnp
        self.optimizer = optimizer
        self.param_names = list(param_names)
        self.input_names = list(input_shapes)
        self.ctx = ctx
        self._cdt = jnp.dtype(compute_dtype) if compute_dtype else None

        if any((not n.is_variable) and n.op.is_host_op for n in symbol.nodes):
            # host ops run eagerly via the Executor's hybrid mode; inside
            # a lax.scan they would have to become pure_callback nodes —
            # the compiled-program host-callback path the hybrid engine
            # exists to avoid. Per-batch loop handles these graphs.
            raise MXNetError("scanned fit does not support host ops "
                             "(Custom/NumpyOp/torch bridge)")
        # persistent jit cache (docs/how_to/compilation.md): the K-step
        # scanned program this trainer builds is the single most
        # expensive compile in the framework — with
        # MXNET_COMPILE_CACHE_DIR set the next process loads it from
        # disk instead of rebuilding (the bind below also applies the
        # MXNET_COMPILE_OPT graph rewrites to the traced program)
        from .. import compile as _compile

        _compile.ensure_jit_cache()
        exe = symbol.simple_bind(ctx, grad_req="null", **input_shapes)
        if not all(exe._head_no_grad):
            raise MXNetError("scanned fit requires loss-op heads")
        self._run = exe._run
        # _run is a bound method and pins the executor; release its
        # freshly allocated device arg/grad/aux arrays (the trainer keeps
        # its own copies — without this the parameters sit in HBM twice)
        exe._release_device_arrays()
        self._arg_names = symbol.list_arguments()

        dev = ctx.jax_device
        self.params = {
            n: jax.device_put(jnp.asarray(arg_params[n].asnumpy(), jnp.float32), dev)
            for n in self.param_names
        }
        self.aux = [
            jax.device_put(jnp.asarray(a.asnumpy(), jnp.float32), dev)
            for a in (aux_params[n] for n in symbol.list_auxiliary_states())
        ]
        # real optimizer states (host-created NDArrays) -> jax leaf lists
        self._state_tree = []
        self.opt_states = []
        for i, n in enumerate(self.param_names):
            st = optimizer.create_state(i, arg_params[n])
            leaves, treedef = jax.tree_util.tree_flatten(
                st, is_leaf=lambda x: x is None)
            self._state_tree.append(treedef)
            self.opt_states.append([
                None if l is None else jax.device_put(
                    jnp.asarray(l.asnumpy(), jnp.float32), dev)
                for l in leaves
            ])
        self._jit_cache = {}
        # seed the per-step dropout keys from the package random chain so
        # mx.random.seed governs the scanned path exactly like the
        # per-batch path (both draw from the same stateful chain)
        from .. import random as _mxrandom

        self._key = _mxrandom.next_key()
        # guardian sentinel (docs/how_to/guardrails.md): when on, every
        # scanned step computes finiteness + grad norm and applies the
        # whole update (params, opt states, aux) through jnp.where — a
        # poisoned step is suppressed INSIDE the fused program, and the
        # per-step verdicts stack into the chunk's outputs (they ride
        # the existing per-chunk D2H with the metrics; zero extra host
        # syncs). Off (the default), none of the sentinel ops are even
        # traced. The grad.nan/loss.spike chaos points stage
        # one host-drawn multiplier per step (lax.scan bodies trace
        # once, so the per-step fire pattern must enter as data).
        from ..resilience import faults as _flt
        from ..resilience import guardian as _grd

        # mxprof (telemetry/prof.py): the scanned K-step loop is the
        # training hot program — keep what's needed to attribute it
        # (analytic DAG cost + the staged shapes that key the record)
        self._symbol = symbol
        self._input_shapes = dict(input_shapes)
        self._prof_analytic = None
        self._prof_keys = {}
        self.last_program_key = None

        self._aux_names = symbol.list_auxiliary_states()
        self._guard_on = _grd.enabled()
        self._guard_max_norm = (
            _grd._env_float("MXNET_GUARDIAN_GRADNORM_MAX", 0.0)
            if self._guard_on else 0.0)
        self._inject = _flt.armed("grad.nan") or _flt.armed("loss.spike")
        self._last_flags = None

    # -- tracing helpers -------------------------------------------------------
    def _traced_update(self, params, opt_states, grads, lr_t, t_t):
        """Run the REAL optimizer.update once per parameter with traced
        values, returning new (params, opt_states)."""
        import types

        from ..ndarray import NDArray

        opt = self.optimizer
        orig_get_lr = opt._get_lr
        orig_update_count = opt._update_count
        orig_counts = opt._index_update_count

        def patched_get_lr(self_o, index):
            return lr_t * _static_lr_mult(self_o, index)

        try:
            opt._get_lr = types.MethodType(patched_get_lr, opt)
            opt._update_count = types.MethodType(lambda s, i: None, opt)
            opt._index_update_count = _TracedCounts(t_t)
            new_params, new_states = {}, []
            for i, n in enumerate(self.param_names):
                w = NDArray(params[n], self.ctx)
                g = NDArray(grads[n], self.ctx)
                leaves = [
                    None if l is None else NDArray(l, self.ctx)
                    for l in opt_states[i]
                ]
                st = self._jax.tree_util.tree_unflatten(
                    self._state_tree[i], leaves)
                opt.update(i, w, g, st)
                new_params[n] = w._data
                new_states.append([
                    None if l is None else l._data for l in leaves
                ])
            return new_params, new_states
        finally:
            opt._get_lr = orig_get_lr
            opt._update_count = orig_update_count
            opt._index_update_count = orig_counts

    def _make_loop(self, K):
        import jax
        import jax.numpy as jnp

        cdt = self._cdt

        def cast_param(v):
            return v.astype(cdt) if (cdt is not None and v.ndim >= 2) else v

        def cast_data(v):
            return (
                v.astype(cdt)
                if (cdt is not None and v.ndim >= 2 and
                    jnp.issubdtype(v.dtype, jnp.floating))
                else v
            )

        guard_on = self._guard_on
        max_norm = self._guard_max_norm
        inject = self._inject

        def step(params, opt_states, aux, batch, lr_t, t_t, rng, mult):
            def f(p):
                vals = [
                    (cast_data(batch[n]) if n in batch else cast_param(p[n]))
                    for n in self._arg_names
                ]
                outs, new_aux = self._run(vals, aux, rng, is_train=True)
                # inexact heads only get cotangents; aux is state, not a
                # differentiable output (see symbol_trainer.step_impl)
                flt = [o for o in outs
                       if jnp.issubdtype(o.dtype, jnp.inexact)]
                return flt, (outs, new_aux)

            flt, vjp_fn, (outs, new_aux) = jax.vjp(f, params, has_aux=True)
            head_grads = [jnp.ones(o.shape, o.dtype) for o in flt]
            (grads,) = vjp_fn(head_grads)
            grads = {k: v.astype(jnp.float32) for k, v in grads.items()}
            if inject:  # chaos multiplier (1.0 when this step drew no fault)
                grads = {k: v * mult for k, v in grads.items()}
            flags = None
            if guard_on:
                gsq = sum(jnp.sum(jnp.square(g)) for g in grads.values())
                ok = jnp.array(True)
                for g in grads.values():
                    ok = ok & jnp.all(jnp.isfinite(g))
                if max_norm > 0.0:
                    ok = ok & (gsq <= jnp.float32(max_norm) ** 2)
            new_params, new_states = self._traced_update(
                params, opt_states, grads, lr_t, t_t)
            if guard_on:
                def sel(new, old):
                    return jnp.where(ok, new, old)

                new_params = {k: sel(v, params[k])
                              for k, v in new_params.items()}
                new_states = [
                    [None if l is None else sel(l, o)
                     for l, o in zip(ns, os_)]
                    for ns, os_ in zip(new_states, opt_states)
                ]
                new_aux = [sel(a, b) for a, b in zip(new_aux, aux)]
                flags = (ok, jnp.sqrt(gsq))
            return new_params, new_states, new_aux, outs, flags

        def loop(params, opt_states, aux, batches, lrs, ts, rngs, mults):
            def body(carry, xs):
                params, opt_states, aux = carry
                batch, lr_t, t_t, rng, mult = xs
                params, opt_states, aux, outs, flags = step(
                    params, opt_states, aux, batch, lr_t, t_t, rng, mult)
                return (params, opt_states, aux), (tuple(outs), flags)

            (params, opt_states, aux), (stacked, flags) = jax.lax.scan(
                body, (params, opt_states, aux),
                (batches, lrs, ts, rngs, mults))
            return params, opt_states, aux, stacked, flags

        from ..compile import jit_cache as _jc

        # donated buffers + a persistently-cached executable corrupt the
        # heap on the CPU backend (jit_cache.donation_unsafe) — keep the
        # buffers there; everywhere else donation updates params in place
        donate = () if _jc.donation_unsafe() else (0, 1, 2)
        return jax.jit(loop, donate_argnums=donate)

    # -- public API ------------------------------------------------------------
    def stage_chunk(self, batch_list):
        """Stack K batches (dict name -> numpy or NDArray) into device
        arrays with leading axis K; returns an opaque staged chunk.

        Arrays already resident on the target device stack ON device
        (jnp.stack — an HBM copy, no host round trip): a prefetching
        pipeline or device-cached dataset feeds the scan at HBM speed.
        Host arrays stack on host and ship once per chunk; with a bf16
        compute dtype the image tensor is cast before transfer, halving
        H2D bytes (the tunnel's H2D bandwidth is the scarce resource;
        docs/perf_analysis.md). Iterator contract: yielded DataBatch
        arrays must not be mutated afterwards (the reference's async
        engine imposes the same rule)."""
        import jax

        from ..ndarray import NDArray

        K = len(batch_list)
        dev = self.ctx.jax_device
        jnp = self._jnp
        bf16 = (self._cdt is not None and str(self._cdt) == "bfloat16")
        staged = {}
        for n in self.input_names:
            vals = [b[n] for b in batch_list]
            datas = [v._data if isinstance(v, NDArray) else v for v in vals]
            on_dev = all(_resident_on(a, dev) for a in datas)
            if on_dev:
                v = jnp.stack(datas)
                if bf16 and v.ndim >= 3 and v.dtype == jnp.float32:
                    v = v.astype(jnp.bfloat16)
                staged[n] = v
                continue
            v = _np.stack([_np.asarray(a) for a in datas])
            if bf16 and v.ndim >= 3 and v.dtype == _np.float32:
                v = v.astype(self._jnp.bfloat16)
            staged[n] = jax.device_put(v, dev)
        return K, staged

    def run_chunk(self, staged):
        """Run K fused train steps on a staged chunk. Returns the list of
        head outputs, each stacked with leading axis K (device arrays)."""
        import jax

        K, batches = staged
        opt = self.optimizer
        base = opt.num_update
        # lr for step k = scheduler(base+k+1), the count every parameter
        # AFTER the first sees in the per-batch loop (the reference calls
        # _get_lr before _update_count, so within one batch the first
        # parameter reads the pre-increment count and the rest read the
        # post-increment count — at a scheduler boundary the two differ
        # by one update for that first parameter; we pick the dominant
        # post-increment value uniformly)
        lrs = _np.asarray(
            [
                (opt.lr_scheduler(base + k + 1)
                 if opt.lr_scheduler is not None else opt.lr)
                for k in range(K)
            ], _np.float32)
        ts = _np.arange(base + 1, base + K + 1, dtype=_np.int32)
        self._key, sub = jax.random.split(self._key)
        rngs = jax.random.split(sub, K)
        if self._inject:
            # one host fire decision per step, staged into the program
            from ..resilience import guardian as _grd

            mults = _np.asarray(
                [_grd.grad_fault_multiplier() for _ in range(K)],
                _np.float32)
        else:
            mults = _np.ones((K,), _np.float32)

        if K not in self._jit_cache:
            from ..analysis import compile_verify as _cv

            # one compile per chunk length K (the memo key IS the
            # bucket) — MXNET_JIT_VERIFY names any arg that breaks it
            self._jit_cache[K] = _cv.wrap(
                "fit_trainer.loop|K=%d" % K, self._make_loop(K),
                budget=1, group="train.fit_loop")
            from .. import telemetry as _tel

            if _tel.ENABLED:
                # the scanned loop is a jit build like any executor
                # program — the compile layer's cache-hit counters say
                # whether it loaded from disk or compiled cold
                _tel.counter("executor.jit_builds_total").inc()
            from ..telemetry import prof as _prof

            if _prof.ENABLED:
                # mxprof: AOT-compile the loop through attribute_jit so
                # the cost/memory record IS this program's one compile
                # (docs/how_to/profiling.md); falls back to the plain
                # jitted fn on any analysis failure
                if self._prof_analytic is None:
                    try:
                        self._prof_analytic = _prof.graph_cost(
                            self._symbol, self._input_shapes)
                    except Exception:
                        self._prof_analytic = {}
                sig = ",".join(
                    "%s=%s" % (n, "x".join(str(d) for d in batches[n].shape))
                    for n in sorted(batches))
                pkey = "fit_trainer|K=%d|%s" % (K, sig)
                # graph identity for the attribution memo: the traced
                # program depends on the symbol, the optimizer's traced
                # update (class + static scalar config), and the
                # compute dtype — not just the staged shapes
                opt = self.optimizer
                # graph identity must cover EVERYTHING _make_loop traces
                # as a constant: the symbol, the optimizer's static
                # scalar config, the compute dtype, AND the guardian /
                # fault-injection switches — an unguarded trainer's
                # cached program handed to a guarded one would silently
                # disable the sentinel
                ghash = _prof.graph_hash("%s|%s|%s|%s|g=%d,%s,%d" % (
                    _prof.symbol_fingerprint(self._symbol),
                    type(opt).__name__,
                    sorted((k, v) for k, v in vars(opt).items()
                           if isinstance(v, (int, float, str, bool))),
                    self._cdt, self._guard_on, self._guard_max_norm,
                    self._inject))
                from ..analysis import compile_verify as _cv

                # attribution replaces the program with its AOT compile
                # — rebind through the verifier boundary so compile
                # counting survives the swap
                _prev = self._jit_cache[K]
                self._jit_cache[K] = _cv.rebind(_prev, _prof.attribute_jit(
                    pkey, _cv.unwrap(_prev),
                    (self.params, self.opt_states, self.aux, batches, lrs,
                     ts, rngs, mults),
                    site="fit_trainer.scan",
                    analytic=self._prof_analytic or None,
                    meta={"K": K, "steps_per_call": K},
                    graph_key=ghash))
                self._prof_keys[K] = _prof.program_key_for(
                    pkey, graph_key=ghash)
        self.last_program_key = self._prof_keys.get(K)
        (self.params, self.opt_states, self.aux, stacked,
         self._last_flags) = self._jit_cache[K](
            self.params, self.opt_states, self.aux, batches, lrs, ts, rngs,
            mults)

        # host-side optimizer bookkeeping advances by K applied steps
        for i in range(len(self.param_names)):
            opt._index_update_count[i] = (
                opt._index_update_count.get(i, opt.begin_num_update) + K)
        opt.num_update = max(opt.num_update, base + K)
        return list(stacked)

    def take_step_flags(self):
        """The newest chunk's per-step guardian verdicts —
        ``(ok[K], grad_norm[K])`` device arrays — or None when the
        trainer runs unguarded. Consumed once (cleared on read) so a
        drain can never double-account a chunk."""
        flags, self._last_flags = self._last_flags, None
        return flags

    # -- guardian snapshot/rollback -------------------------------------------
    def snapshot_state(self):
        """Full host copy of the trainer state (params, optimizer
        states, aux, host-side step bookkeeping) — the guardian's
        in-memory last-good ring payload."""
        opt = self.optimizer
        return {
            "params": {n: _np.asarray(v) for n, v in self.params.items()},
            "aux": [_np.asarray(a) for a in self.aux],
            "opt_states": [
                [None if l is None else _np.asarray(l) for l in st]
                for st in self.opt_states
            ],
            "num_update": opt.num_update,
            "counts": dict(opt._index_update_count),
        }

    def restore_state(self, snap):
        """Adopt a :meth:`snapshot_state` dump (guardian rollback)."""
        import jax

        jnp = self._jnp
        dev = self.ctx.jax_device
        self.params = {n: jax.device_put(jnp.asarray(v), dev)
                       for n, v in snap["params"].items()}
        self.aux = [jax.device_put(jnp.asarray(a), dev)
                    for a in snap["aux"]]
        self.opt_states = [
            [None if l is None else jax.device_put(jnp.asarray(l), dev)
             for l in st]
            for st in snap["opt_states"]
        ]
        opt = self.optimizer
        opt.num_update = snap["num_update"]
        opt._index_update_count = dict(snap["counts"])

    def load_params(self, arg_params, aux_params):
        """Adopt checkpoint params/aux (the guardian's DISK rollback
        fallback). Names missing from the checkpoint (a prefix reused
        across model variants, allow_missing saves) keep their current
        device values — a recoverable rollback must not become a
        KeyError crash. A .params checkpoint carries no optimizer
        state, so momenta/variances restart from fresh zeros — the same
        contract as resuming a run from a checkpoint without its
        .states file."""
        import jax

        from ..ndarray import NDArray

        jnp = self._jnp
        dev = self.ctx.jax_device
        self.params = {
            n: (jax.device_put(
                jnp.asarray(arg_params[n].asnumpy(), jnp.float32), dev)
                if n in arg_params else self.params[n])
            for n in self.param_names
        }
        self.aux = [
            (jax.device_put(
                jnp.asarray(aux_params[n].asnumpy(), jnp.float32), dev)
             if n in aux_params else a)
            for n, a in zip(self._aux_names, self.aux)
        ]
        self.opt_states = []
        for i, n in enumerate(self.param_names):
            # create_state wants an NDArray-shaped weight; the restored
            # device value covers names the checkpoint did not
            w = arg_params.get(n)
            if w is None:
                w = NDArray(self.params[n], self.ctx)
            st = self.optimizer.create_state(i, w)
            leaves, _treedef = jax.tree_util.tree_flatten(
                st, is_leaf=lambda x: x is None)
            self.opt_states.append([
                None if l is None else jax.device_put(
                    jnp.asarray(l.asnumpy(), jnp.float32), dev)
                for l in leaves
            ])

    def write_back(self, arg_params, aux_params, aux_names):
        """Copy the device state into the user-visible NDArray dicts
        (epoch boundaries, checkpoints, final params)."""
        for n in self.param_names:
            arg_params[n][:] = _np.asarray(self.params[n])
        for n, a in zip(aux_names, self.aux):
            aux_params[n][:] = _np.asarray(a)


def make_fit_trainer(symbol, ctx, input_shapes, optimizer, arg_params,
                     aux_params, param_names, compute_dtype=None):
    return FitTrainer(symbol, ctx, input_shapes, optimizer, arg_params,
                      aux_params, param_names, compute_dtype=compute_dtype)
