"""Ring attention: sequence/context parallelism over a mesh axis.

Not present in the 2016 reference (SURVEY §5.7 explicitly lists it as the
TPU-era extension to build): attention over sequences sharded across
devices, rotating K/V blocks around the ring with `lax.ppermute` while
accumulating softmax numerator/denominator in log-sum-exp form (flash/
blockwise accumulation), so each chip only ever holds its sequence shard.
Used inside shard_map with a mesh axis named e.g. 'seq'.
"""
from __future__ import annotations

import functools


def _block_attn(q, k, v, mask, scale):
    """One blockwise attention contribution with running-max bookkeeping.
    q: [B,H,Tq,D], k/v: [B,H,Tk,D]; mask: [Tq,Tk] boolean (True = keep)."""
    import jax.numpy as jnp

    scores = jnp.einsum("bhqd,bhkd->bhqk", q, k) * scale
    neg = jnp.asarray(-1e30, scores.dtype)
    scores = jnp.where(mask[None, None], scores, neg)
    m = jnp.max(scores, axis=-1)  # [B,H,Tq]
    p = jnp.exp(scores - m[..., None])
    # fully-masked rows: exp(neg - neg)=1 would pollute; zero them
    row_any = jnp.any(mask, axis=-1)  # [Tq]
    p = p * row_any[None, None, :, None].astype(p.dtype)
    l = jnp.sum(p, axis=-1)  # [B,H,Tq]
    o = jnp.einsum("bhqk,bhkd->bhqd", p, v)
    m = jnp.where(row_any[None, None], m, neg)
    return o, l, m


def _merge_block(o_acc, l_acc, m_acc, o, l, m):
    """Merge one block's (o, l, m) into running accumulators with
    log-sum-exp rescaling (the flash-attention combine step). Shared by
    ring_attention and ulysses."""
    import jax.numpy as jnp

    new_m = jnp.maximum(m_acc, m)
    alpha = jnp.exp(m_acc - new_m)
    beta = jnp.exp(m - new_m)
    return (o_acc * alpha[..., None] + o * beta[..., None],
            l_acc * alpha + l * beta,
            new_m)


def ring_attention(q, k, v, axis_name, causal=True, scale=None, q_offset=0):
    """Attention with K/V ring-rotated across `axis_name`.

    Shapes (inside shard_map, per-shard): q,k,v [batch, heads, t_local, d].
    Global sequence = ring_size * t_local, laid out contiguously by rank.
    Returns [batch, heads, t_local, d].

    ``q_offset`` places the global query block at that absolute position
    within the key sequence: query i (global) sits at key position
    ``q_offset + i`` for causal masking. This is the chunked-prefill
    geometry (serving/model.py cp_prefill_kv): queries are the last
    ``ring * t_local_q`` tokens of a longer key sequence, so a serving
    prefill chunk attends to the whole accumulated prefix without
    re-running it. ``q_offset=0`` is the training case (q and k cover
    the same sequence).
    """
    import jax
    import jax.numpy as jnp
    from jax import lax

    if scale is None:
        scale = 1.0 / (q.shape[-1] ** 0.5)
    from .mesh import axis_size

    ring = axis_size(axis_name)
    my_rank = lax.axis_index(axis_name)
    tq = q.shape[2]
    tk = k.shape[2]

    # accumulators in f32 for stability on bf16 inputs
    acc_dtype = jnp.float32
    o_acc = jnp.zeros(q.shape[:3] + (v.shape[-1],), acc_dtype)
    l_acc = jnp.zeros(q.shape[:3], acc_dtype)
    m_acc = jnp.full(q.shape[:3], -1e30, acc_dtype)
    # mark accumulators as device-varying along the ring axis so the scan
    # carry type matches under shard_map's varying-axis checking
    from .mesh import mark_varying

    o_acc, l_acc, m_acc = mark_varying((o_acc, l_acc, m_acc), axis_name)

    def body(step, carry):
        o_acc, l_acc, m_acc, k_cur, v_cur = carry
        kv_rank = (my_rank - step) % ring
        if causal:
            # absolute positions: q at q_offset + my_rank*tq + iq ;
            # k at kv_rank*tk + ik
            iq = jnp.arange(tq)[:, None] + my_rank * tq + q_offset
            ik = jnp.arange(tk)[None, :] + kv_rank * tk
            mask = ik <= iq
        else:
            mask = jnp.ones((tq, tk), bool)
        o, l, m = _block_attn(q, k_cur, v_cur, mask, scale)
        o_acc2, l_acc2, new_m = _merge_block(
            o_acc, l_acc, m_acc,
            o.astype(acc_dtype), l.astype(acc_dtype), m.astype(acc_dtype))
        perm = [(i, (i + 1) % ring) for i in range(ring)]
        k_next = lax.ppermute(k_cur, axis_name, perm)
        v_next = lax.ppermute(v_cur, axis_name, perm)
        return (o_acc2, l_acc2, new_m, k_next, v_next)

    o_acc, l_acc, m_acc, _, _ = lax.fori_loop(
        0, ring, body, (o_acc, l_acc, m_acc, k, v)
    )
    out = o_acc / jnp.maximum(l_acc, 1e-30)[..., None]
    return out.astype(q.dtype)


def make_ring_attention(mesh, seq_axis="seq", causal=True, q_offset=0):
    """Wrap ring_attention in shard_map over `seq_axis` of `mesh`.
    Takes/returns global arrays [B, H, T, D] with T sharded on seq_axis.
    Q and K/V lengths may differ; ``q_offset`` is the queries' absolute
    start position in the key sequence (chunked-prefill reuse)."""
    import jax
    from jax.sharding import PartitionSpec as P

    try:
        from jax import shard_map
    except ImportError:  # older jax
        from jax.experimental.shard_map import shard_map

    spec = P(None, None, seq_axis, None)

    @functools.partial(
        shard_map, mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
    )
    def f(q, k, v):
        return ring_attention(q, k, v, seq_axis, causal=causal,
                              q_offset=q_offset)

    return f
