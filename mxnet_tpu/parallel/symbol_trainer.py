"""Fused train step compiled from a Symbol graph.

This is the TPU-native answer to the reference's bulk-exec + kvstore loop
(SURVEY §2.6 InitOpSegs, §3.1): the WHOLE training step — forward, backward
(jax.vjp with loss-head cotangents, same semantics as Executor.backward),
optimizer update (optax) — is one XLA program with donated param/opt/aux
buffers, so weights update in-place in HBM and every elementwise op fuses
into the surrounding matmuls/convs.

Mixed precision: master params stay f32; tensors with ndim>=2 are cast to
``compute_dtype`` (bf16 on TPU → MXU) inside the step; FC accumulates f32
via preferred_element_type, convs ride XLA:TPU's f32 MXU accumulators
(see ops/nn.py dtype note).

Used by bench.py; Module users get the same semantics through the
Executor's fused fwd+bwd path.
"""
from __future__ import annotations

import numpy as _np

from ..base import MXNetError


def make_symbol_train_step(symbol, input_shapes, optimizer=None,
                           compute_dtype=None, ctx=None, mesh=None,
                           batch_axis="data", donate=True, seed=0):
    """Compile symbol into a fused train step.

    input_shapes: dict of data/label name -> shape (the non-parameter args).
    Returns (step, state) where state = dict(params, opt_state, aux) of
    jax arrays and step(state, batch_dict, rng) -> (state, outputs_list).
    With a mesh, batch leaves are committed sharded on `batch_axis` and
    params replicated (pure data parallelism; XLA emits the ICI psum).
    """
    import jax
    import jax.numpy as jnp
    import optax

    from ..context import cpu, tpu, num_devices
    from ..ndarray import NDArray

    if optimizer is None:
        optimizer = optax.sgd(0.05, momentum=0.9)
    if ctx is None:
        ctx = tpu(0) if num_devices("tpu") > 0 else cpu(0)

    arg_names = symbol.list_arguments()
    aux_names = symbol.list_auxiliary_states()
    arg_shapes, out_shapes, aux_shapes = symbol.infer_shape(**input_shapes)
    param_names = [n for n in arg_names if n not in input_shapes]

    if any((not n.is_variable) and n.op.is_host_op for n in symbol.nodes):
        # host ops would have to trace as pure_callback inside this jit —
        # the compiled-program host-callback path the hybrid executor
        # exists to avoid (see executor.py); Module/FeedForward handle
        # these graphs through the hybrid engine instead
        raise MXNetError("make_symbol_train_step does not support host "
                         "ops (Custom/NumpyOp/torch bridge)")
    # persistent jit cache: the fused train step (and bench.py's scanned
    # loop over it) caches across processes once MXNET_COMPILE_CACHE_DIR
    # is set; the bind below also applies the MXNET_COMPILE_OPT graph
    # rewrites to the traced program (docs/how_to/compilation.md)
    from .. import compile as _compile
    from ..compile import jit_cache as _jc

    _compile.ensure_jit_cache()
    if donate and _jc.donation_unsafe():
        # donated buffers + a persistently-cached executable corrupt the
        # heap on the CPU backend (see jit_cache.donation_unsafe)
        donate = False
    # one throwaway bind to reuse the Executor's traced program & plan;
    # release its device arrays — `run` is a bound method and would
    # otherwise pin a second full parameter set in HBM
    exe = symbol.simple_bind(ctx, grad_req="null", **input_shapes)
    run = exe._run
    no_head_grad = exe._head_no_grad
    exe._release_device_arrays()
    if not all(no_head_grad):
        raise MXNetError("make_symbol_train_step requires loss-op heads")

    rng0 = _np.random.RandomState(seed)
    params = {}
    for n, s in zip(arg_names, arg_shapes):
        if n in input_shapes:
            continue
        fan_in = float(_np.prod(s[1:])) if len(s) > 1 else float(s[0])
        scale = _np.sqrt(2.0 / max(fan_in, 1.0))
        if n.endswith("bias") or n.endswith("beta"):
            params[n] = jnp.zeros(s, jnp.float32)
        elif n.endswith("gamma"):
            params[n] = jnp.ones(s, jnp.float32)
        else:
            params[n] = jnp.asarray(rng0.normal(0, scale, s), jnp.float32)
    aux = [
        jnp.zeros(s, jnp.float32) if "mean" in n else jnp.ones(s, jnp.float32)
        for n, s in zip(aux_names, aux_shapes)
    ]

    cdt = jnp.dtype(compute_dtype) if compute_dtype else None

    def _cast(p):
        if cdt is None:
            return p
        return {
            k: (v.astype(cdt) if v.ndim >= 2 else v) for k, v in p.items()
        }

    def step_impl(params, opt_state, aux, batch, rng):
        def f(p):
            pc = _cast(p)
            vals = [
                (batch[n] if n in batch else pc[n]) for n in arg_names
            ]
            outs, new_aux = run(vals, aux, rng, is_train=True)
            # only inexact heads get cotangents (integer heads, e.g. a
            # BlockGrad'd id tensor, have none); moving stats are state,
            # not differentiable outputs — both ride through has_aux so
            # the vjp never builds a backward graph for them
            flt = [o for o in outs if jnp.issubdtype(o.dtype, jnp.inexact)]
            return flt, (outs, new_aux)

        flt, vjp_fn, (outs, new_aux) = jax.vjp(f, params, has_aux=True)
        head_grads = [jnp.ones(o.shape, o.dtype) for o in flt]
        (grads,) = vjp_fn(head_grads)
        grads = {k: v.astype(jnp.float32) for k, v in grads.items()}
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, new_aux, outs

    from ..analysis import compile_verify as _cv

    # fixed-shape bind: the per-batch step and the scanned loop each
    # compile exactly once (budget 1 — any second compile means a
    # caller leaked a varying value into the traced signature)
    jitted = _cv.wrap(
        "symbol_trainer.step",
        jax.jit(step_impl, donate_argnums=(0, 1, 2) if donate else ()),
        budget=1, group="train.symbol_step")

    batch_sharding = None
    if mesh is not None:
        from jax.sharding import NamedSharding, PartitionSpec as P

        batch_sharding = NamedSharding(mesh, P(batch_axis))
        rep = NamedSharding(mesh, P())
        params = jax.device_put(params, rep)
        aux = [jax.device_put(a, rep) for a in aux]
    else:
        dev = ctx.jax_device
        params = jax.device_put(params, dev)
        aux = [jax.device_put(a, dev) for a in aux]

    opt_state = optimizer.init(params)
    state = {"params": params, "opt_state": opt_state, "aux": aux}

    def step(state, batch, rng):
        batch = {
            k: jax.device_put(
                jnp.asarray(v), batch_sharding if batch_sharding else ctx.jax_device
            )
            for k, v in batch.items()
        }
        p, o, a, outs = jitted(state["params"], state["opt_state"], state["aux"], batch, rng)
        return {"params": p, "opt_state": o, "aux": a}, outs

    def loop_impl(params, opt_state, aux, batches, rngs):
        def body(carry, xs):
            params, opt_state, aux = carry
            batch, rng = xs
            params, opt_state, aux, outs = step_impl(
                params, opt_state, aux, batch, rng)
            return (params, opt_state, aux), tuple(outs)

        (params, opt_state, aux), stacked = jax.lax.scan(
            body, (params, opt_state, aux), (batches, rngs))
        return params, opt_state, aux, stacked

    # the scanned loop legitimately re-traces per distinct chunk length
    # (a tail chunk is a different K) — budget a small bucket set
    jitted_loop = _cv.wrap(
        "symbol_trainer.loop",
        jax.jit(loop_impl, donate_argnums=(0, 1, 2) if donate else ()),
        budget=4, group="train.symbol_step")

    def loop(state, batches, rng):
        """Run K train steps in ONE dispatch (jitted lax.scan).

        On the tunneled TPU backend each jitted call costs ~20 ms of host
        round-trip regardless of compute (measured: a 1-op program and an
        8-conv program both dispatch in ~22 ms) — a per-batch step()
        train loop pays that every batch. Scanning K steps amortizes the
        dispatch to ~0 (docs/perf_analysis.md).

        batches: dict name -> stacked array with leading axis K (one
        slice per step). rng: a single PRNGKey, split into K per-step
        keys. Returns (state, outs) where outs is a tuple with one entry
        per symbol head, each stacked over the K steps (leading axis K).
        """
        K = next(iter(batches.values())).shape[0]
        if batch_sharding is not None:
            from jax.sharding import NamedSharding, PartitionSpec as P

            # leading axis is the step index; the per-step batch axis
            # (now axis 1) carries the data-parallel sharding
            tgt = NamedSharding(mesh, P(None, batch_axis))
        else:
            tgt = ctx.jax_device
        batches = {k: jax.device_put(jnp.asarray(v), tgt)
                   for k, v in batches.items()}
        rngs = jax.random.split(rng, K)
        p, o, a, outs = jitted_loop(
            state["params"], state["opt_state"], state["aux"], batches, rngs)
        return {"params": p, "opt_state": o, "aux": a}, outs

    step.loop = loop
    return step, state
