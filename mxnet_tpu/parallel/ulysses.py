"""Ulysses (all-to-all) sequence parallelism.

The second canonical context-parallel scheme alongside ring attention
(SURVEY §5.7; neither exists in the 2016 reference — both are the TPU-era
long-context extensions this framework treats as first-class). Where ring
attention rotates K/V shards around the mesh axis, Ulysses re-shards with
two all-to-alls: inputs arrive sequence-sharded, an all-to-all trades the
sequence axis for the head axis so each device holds the FULL sequence
for heads/N attention heads, blockwise (flash-style) attention runs
locally, and a second all-to-all restores sequence sharding.

Cost model vs ring: both move O(seq·d) activation bytes per device, but
Ulysses does it in TWO dense all-to-all collectives (one latency hop
each on a torus) while ring takes N ppermute hops overlapped with
compute. Ulysses wins when heads >= axis size and the interconnect has
strong all-to-all bandwidth; ring wins when heads < axis size or K/V
transfer must hide entirely behind compute.

Used inside shard_map with a mesh axis named e.g. 'seq'; head count must
be divisible by the axis size.
"""
from __future__ import annotations

import functools


def ulysses_attention(q, k, v, axis_name, causal=True, scale=None,
                      q_offset=0):
    """All-to-all sequence-parallel attention.

    Per-shard shapes (inside shard_map): q,k,v [batch, heads, t_local, d]
    with the global sequence laid out contiguously by rank along
    `axis_name`. Returns [batch, heads, t_local, d].

    Q and K/V lengths may differ; ``q_offset`` is the queries' absolute
    start position in the key sequence for causal masking — the
    chunked-prefill geometry (serving/model.py cp_prefill_kv), same
    contract as ring_attention. A nonzero offset (or rectangular q/k)
    takes the blockwise fallback; the square Pallas-kernel path is the
    training case.
    """
    import jax.numpy as jnp
    from jax import lax

    from .mesh import axis_size

    n = axis_size(axis_name)
    b, h, t_local, d = q.shape
    tk_local = k.shape[2]
    if h % n != 0:
        raise ValueError(
            "ulysses: heads (%d) must divide by mesh axis size (%d)" % (h, n))
    if scale is None:
        scale = 1.0 / (d ** 0.5)

    def seq_to_heads(x):
        # [B, H, Tl, D] -> heads split across devices, full sequence local:
        # all_to_all splits the head axis and concatenates the seq axis
        return lax.all_to_all(
            x, axis_name, split_axis=1, concat_axis=2, tiled=True)

    def heads_to_seq(x):
        return lax.all_to_all(
            x, axis_name, split_axis=2, concat_axis=1, tiled=True)

    from .ring_attention import _block_attn, _merge_block

    ql, kl, vl = seq_to_heads(q), seq_to_heads(k), seq_to_heads(v)
    # ql: [B, H/n, T_global, D] — exactly the flash kernel's shape, and
    # unlike ring there is no cross-step LSE combine, so the local
    # attention can ride the tuned Pallas kernels (fwd AND custom-vjp
    # backward) whenever the local problem tiles and K/V fit the
    # kernel's per-cell VMEM budget. Gating on the same conditions
    # flash_attention checks guarantees the kernel path — never its
    # dense O(T^2) fallback, which would lose this loop's
    # O(T_global*chunk) memory bound.
    from ..ops import pallas_kernels as pk

    t_global = ql.shape[2]
    tk_global = kl.shape[2]
    if (q_offset == 0 and t_global == tk_global
            and pk.flash_kernel_usable(t_global, tk_global, d,
                                       vl.shape[-1])):
        out = pk.flash_attention(ql, kl, vl, causal=causal, scale=scale)
        return heads_to_seq(out.astype(q.dtype))
    # fallback: blockwise over key chunks with the shared flash-style
    # LSE accumulation — peak memory O(T_global*chunk) scores per
    # head-chunk, not O(T_global^2)
    chunk = tk_local
    acc = jnp.float32
    iq = jnp.arange(t_global)[:, None] + q_offset

    def body(c, carry):
        o_acc, l_acc, m_acc = carry
        kc = lax.dynamic_slice_in_dim(kl, c * chunk, chunk, axis=2)
        vc = lax.dynamic_slice_in_dim(vl, c * chunk, chunk, axis=2)
        if causal:
            ik = c * chunk + jnp.arange(chunk)[None, :]
            mask = ik <= iq
        else:
            mask = jnp.ones((t_global, chunk), bool)
        o, l, m = _block_attn(ql, kc, vc, mask, scale)
        return _merge_block(o_acc, l_acc, m_acc,
                            o.astype(acc), l.astype(acc), m.astype(acc))

    init = (jnp.zeros(ql.shape[:3] + (vl.shape[-1],), acc),
            jnp.zeros(ql.shape[:3], acc),
            jnp.full(ql.shape[:3], -1e30, acc))
    from .mesh import mark_varying

    # block results are device-varying (post-all_to_all operands);
    # mark the initial carry to match (same as ring's accumulators)
    init = mark_varying(init, axis_name)
    o_acc, l_acc, m_acc = lax.fori_loop(0, tk_global // chunk, body, init)
    out = o_acc / jnp.maximum(l_acc, 1e-30)[..., None]
    return heads_to_seq(out.astype(q.dtype))


def make_ulysses_attention(mesh, seq_axis="seq", causal=True, q_offset=0):
    """Wrap ulysses_attention in shard_map over `seq_axis` of `mesh` —
    same factory contract as make_ring_attention: takes/returns global
    arrays [batch, heads, seq, d] sharded on the sequence axis, with
    ``q_offset`` placing the query block inside the key sequence."""
    import jax

    try:
        from jax import shard_map
    except ImportError:  # jax < 0.7 layout
        from jax.experimental.shard_map import shard_map
    from jax.sharding import NamedSharding, PartitionSpec as P

    spec = P(None, None, seq_axis, None)
    fn = functools.partial(
        ulysses_attention, axis_name=seq_axis, causal=causal,
        q_offset=q_offset)
    # replication checking off: the Pallas flash kernel's out_shapes
    # carry no varying-axes annotation, which the checker rejects inside
    # shard_map (jax >= 0.7 spells the knob check_vma, 0.4.x spells it
    # check_rep and has no pallas replication rule at all); correctness
    # is pinned by the dense parity + ring cross-check tests instead
    kw = dict(mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec)
    try:
        mapped = shard_map(fn, check_vma=False, **kw)
    except TypeError:
        try:
            mapped = shard_map(fn, check_rep=False, **kw)
        except TypeError:  # neither knob: checker not present
            mapped = shard_map(fn, **kw)

    def apply(q, k, v):
        shard = NamedSharding(mesh, spec)
        q = jax.device_put(q, shard)
        k = jax.device_put(k, shard)
        v = jax.device_put(v, shard)
        return mapped(q, k, v)

    return apply
