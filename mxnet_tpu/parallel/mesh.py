"""Device mesh utilities.

The mesh is the TPU-native analog of the reference's device lists
(`ctx=[mx.gpu(i) ...]`) + comm topology (comm.h P2P rings): one
`jax.sharding.Mesh` whose axes name the parallelism dimensions
(data/model/seq/expert), with XLA inserting ICI/DCN collectives.
"""
from __future__ import annotations

from ..base import MXNetError


def local_devices(platform=None):
    import jax

    if platform:
        try:
            return jax.devices(platform)
        except RuntimeError:
            return []
    return jax.devices()


def create_mesh(shape, axis_names, devices=None):
    """Create a Mesh of the given logical shape, e.g.
    create_mesh((2, 4), ('data', 'model'))."""
    import numpy as np
    import jax
    from jax.sharding import Mesh

    if devices is None:
        devices = jax.devices()
    n = 1
    for s in shape:
        n *= s
    if len(devices) < n:
        raise MXNetError(
            "mesh shape %s needs %d devices, only %d available" % (shape, n, len(devices))
        )
    dev_array = np.array(devices[:n]).reshape(shape)
    return Mesh(dev_array, axis_names)


def default_mesh(axis_name="data", devices=None):
    """1-D all-devices mesh — pure data parallelism."""
    import jax

    if devices is None:
        devices = jax.devices()
    return create_mesh((len(devices),), (axis_name,), devices)
