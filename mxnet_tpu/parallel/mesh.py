"""Device mesh utilities.

The mesh is the TPU-native analog of the reference's device lists
(`ctx=[mx.gpu(i) ...]`) + comm topology (comm.h P2P rings): one
`jax.sharding.Mesh` whose axes name the parallelism dimensions
(data/model/seq/expert), with XLA inserting ICI/DCN collectives.
"""
from __future__ import annotations

from ..base import MXNetError

# Overridable device pool for mesh construction. The test harness (and any
# embedder that wants meshes on something other than jax.devices(), e.g. the
# virtual CPU devices from xla_force_host_platform_device_count) sets this
# via set_default_devices(); production code paths keep the real device set
# and fail loudly when a mesh doesn't fit.
_default_devices = None


def set_default_devices(devices):
    """Set the device pool used when create_mesh/default_mesh get no
    explicit devices. Pass None to restore jax.devices()."""
    global _default_devices
    _default_devices = list(devices) if devices is not None else None


def mark_varying(x, axis_name):
    """Mark a pytree of arrays device-varying along ``axis_name`` inside a
    shard_map body (loop-carry typing discipline for ppermute/all_to_all
    results). Prefers ``lax.pcast(..., to='varying')``; falls back to the
    deprecated ``lax.pvary`` on older jax; no-op when neither exists."""
    from jax import lax

    axes = (axis_name,) if isinstance(axis_name, str) else tuple(axis_name)
    if hasattr(lax, "pcast"):
        return lax.pcast(x, axes, to="varying")
    if hasattr(lax, "pvary"):
        return lax.pvary(x, axes)
    return x


def axis_size(axis_name):
    """Static size of a mapped mesh axis inside a shard_map/pmap body.
    ``lax.axis_size`` only exists on newer jax; on older releases
    ``lax.psum(1, axis)`` of a literal constant-folds to the same
    concrete int (the pre-axis_size idiom), so loop bounds built from it
    stay static."""
    from jax import lax

    if hasattr(lax, "axis_size"):
        return lax.axis_size(axis_name)
    return lax.psum(1, axis_name)


def local_devices(platform=None):
    import jax

    if platform:
        try:
            return jax.devices(platform)
        except RuntimeError:
            return []
    return jax.devices()


def _resolve_devices(devices):
    import jax

    if devices is not None:
        return list(devices)
    if _default_devices is not None:
        return list(_default_devices)
    return jax.devices()


def create_mesh(shape, axis_names, devices=None):
    """Create a Mesh of the given logical shape, e.g.
    create_mesh((2, 4), ('data', 'model'))."""
    import numpy as np
    from jax.sharding import Mesh

    devices = _resolve_devices(devices)
    n = 1
    for s in shape:
        n *= s
    if len(devices) < n:
        raise MXNetError(
            "mesh shape %s needs %d devices, only %d available" % (shape, n, len(devices))
        )
    dev_array = np.array(devices[:n]).reshape(shape)
    return Mesh(dev_array, axis_names)


def default_mesh(axis_name="data", devices=None):
    """1-D all-devices mesh — pure data parallelism."""
    devices = _resolve_devices(devices)
    return create_mesh((len(devices),), (axis_name,), devices)
