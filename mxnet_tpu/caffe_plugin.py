"""Caffe plugin: CaffeOp / CaffeLoss / CaffeDataIter.

The reference can embed Caffe layers/losses/data layers as operators
when built with the caffe plugin (ref: plugin/caffe/caffe_op-inl.h,
caffe_loss-inl.h, caffe_data_iter.cc; enabled by `CAFFE_PATH` in
make/config.mk) — each op instantiates a libcaffe layer from its
prototxt string and runs caffe's CPU/GPU kernels in-graph.

TPU-native redesign: there are no foreign kernels inside an XLA
program, so ``CaffeOp``/``CaffeLoss`` INTERPRET the layer prototxt —
the spec is parsed (self-contained text-format parser, no caffe, no
protobuf schema) and mapped onto the native op registry
(``mxnet_tpu/_caffe_proto.py``), where XLA runs the math. The user
surface is the reference's exactly (``data_0..data_k``, ``num_weight``,
``prototxt``, ``grad_scale``), so example/caffe scripts port verbatim;
unsupported layer types raise a clear error naming the type. Caffe's
ceil-mode pooling arithmetic is honored (pooling_convention='full').

``CaffeDataIter`` wraps caffe's LMDB data layer and genuinely needs the
caffe runtime, which is not installable here — it stays behind the
availability gate, like the reference compiled without CAFFE_PATH.
"""
from __future__ import annotations

from ._caffe_proto import _aslist, apply_layer, parse_prototxt
from .base import MXNetError

__all__ = ["caffe_available", "CaffeOp", "CaffeLoss", "CaffeDataIter"]


def caffe_available():
    """True when the real caffe python runtime is importable (only
    CaffeDataIter still requires it; CaffeOp/CaffeLoss do not)."""
    try:
        import caffe  # noqa: F401

        return True
    except ImportError:
        return False


def _check_counts(what, **counts):
    """Validate the reference's blob-count params (accepted for surface
    parity only; native ops declare their own parameters)."""
    for label_, v in counts.items():
        if v is None:
            continue
        try:
            n = int(v)
        except (TypeError, ValueError):
            raise MXNetError("%s: %s must be an integer, got %r"
                             % (what, label_, v))
        if n < 0:
            raise MXNetError("%s: %s must be >= 0" % (what, label_))


def _single_layer(prototxt, what):
    try:
        net = parse_prototxt(prototxt)
    except ValueError as exc:
        raise MXNetError("%s: bad prototxt: %s" % (what, exc))
    layers = _aslist(net.get("layer")) or _aslist(net.get("layers"))
    if len(layers) != 1:
        raise MXNetError(
            "%s expects exactly one layer{...} in prototxt, got %d"
            % (what, len(layers)))
    return layers[0]


def CaffeOp(*data, prototxt=None, name=None, num_weight=None,
            num_data=None, num_out=None, **kwargs):
    """Run one caffe layer spec as an operator
    (ref: plugin/caffe/caffe_op-inl.h; python surface
    mx.symbol.CaffeOp(data_0=..., num_weight=..., prototxt=...)).

    ``num_weight``/``num_data``/``num_out`` are accepted for surface
    parity — the reference needs them to size caffe blobs; the native
    ops declare their own parameters, so they are validated only for
    being non-negative when given.
    """
    if prototxt is None:
        raise MXNetError("CaffeOp requires prototxt=")
    _check_counts("CaffeOp", num_weight=num_weight, num_data=num_data,
                  num_out=num_out)
    # either positional data OR data_0/data_1/... keywords — mixing the
    # two would silently reorder (or drop) bottoms
    idx = 0
    keyed = []
    while "data_%d" % idx in kwargs:
        keyed.append(kwargs.pop("data_%d" % idx))
        idx += 1
    if kwargs:
        raise MXNetError("CaffeOp: unknown arguments %s" % sorted(kwargs))
    if data and keyed:
        raise MXNetError(
            "CaffeOp: pass inputs either positionally or as data_0..data_%d,"
            " not both" % (idx - 1))
    bottoms = list(data) or keyed
    if not bottoms:
        raise MXNetError("CaffeOp requires at least data_0")
    layer = _single_layer(prototxt, "CaffeOp")
    try:
        out = apply_layer(layer, bottoms, name=name)
    except NotImplementedError as exc:
        raise MXNetError("CaffeOp: %s" % exc)
    if out is None:
        raise MXNetError(
            "CaffeOp: layer type %r is a no-op" % layer.get("type"))
    return out


def CaffeLoss(data=None, label=None, grad_scale=1.0, prototxt=None,
              name=None, num_data=None, num_out=None, **kwargs):
    """Run a caffe criterion spec as a loss op
    (ref: plugin/caffe/caffe_loss-inl.h; python surface
    mx.symbol.CaffeLoss(data=..., label=..., grad_scale=...,
    prototxt='layer{type:"SoftmaxWithLoss"}'); num_data/num_out are
    blob-count parity params like CaffeOp's).

    Outputs: ``[softmax_probabilities, per_example_nll]`` for
    SoftmaxWithLoss specs — the reference CaffeLoss's output is the
    loss blob, so a verbatim-ported script's ``mx.metric.Caffe()``
    reports the loss (the metric reads the loss head); the NLL head is
    gradient-blocked, so training gradients are exactly SoftmaxOutput's.
    """
    if prototxt is None:
        prototxt = 'layer{type:"SoftmaxWithLoss"}'
    if data is None:
        raise MXNetError("CaffeLoss requires data=")
    _check_counts("CaffeLoss", num_data=num_data, num_out=num_out)
    if kwargs:
        raise MXNetError("CaffeLoss: unknown arguments %s" % sorted(kwargs))
    layer = _single_layer(prototxt, "CaffeLoss")
    try:
        out = apply_layer(layer, [data], name=name, label=label,
                          grad_scale=float(grad_scale), emit_loss=True)
    except NotImplementedError as exc:
        raise MXNetError("CaffeLoss: %s" % exc)
    if out is None:
        raise MXNetError(
            "CaffeLoss: layer type %r is a no-op" % layer.get("type"))
    return out


def CaffeDataIter(*args, **kwargs):
    """ref: plugin/caffe/caffe_data_iter.cc — caffe's LMDB data layer as
    a DataIter; needs the real caffe runtime."""
    raise MXNetError(
        "CaffeDataIter requires the caffe python package, which is not "
        "available in this build (ref: plugin/caffe, gated on "
        "CAFFE_PATH). Pack datasets with tools/im2rec.py and read them "
        "with mx.io.ImageRecordIter instead.")


def _install():
    """Expose the ops where the reference puts them: mx.symbol.CaffeOp /
    mx.symbol.CaffeLoss (the plugin registers them into the regular op
    namespace, plugin/caffe/caffe_op.cc MXNET_REGISTER_OP_PROPERTY)."""
    from . import symbol as _symbol

    _symbol.CaffeOp = CaffeOp
    _symbol.CaffeLoss = CaffeLoss


_install()
