"""Data iterators (ref: python/mxnet/io.py:1-722, src/io/ 2.2k LoC).

The reference pipeline is RecordIO read → decode → augment → batch →
prefetch on background threads (SURVEY §3.5). Here iterators produce host
numpy batches; the device copy is an async jax.device_put (the analog of
FnProperty::kCopyToGPU engine ops, ref: ndarray.cc:226-282). PrefetchingIter
reproduces dmlc::ThreadedIter's lookahead queue with a Python thread.
"""
from __future__ import annotations

import gzip
import os
import struct
import threading
import time as _time
import queue as _queue

import re as _re

import numpy as _np

from . import telemetry as _tel
from .base import MXNetError
from .context import cpu
from .ndarray import NDArray, array

__all__ = [
    "DataBatch", "DataIter", "NDArrayIter", "MNISTIter", "CSVIter",
    "ResizeIter", "PrefetchingIter", "ImageRecordIter", "DataDesc",
    "DataServiceIter",
]


def __getattr__(name):
    # DataServiceIter lives in the data_service package (it imports
    # this module's DataIter protocol classes); the lazy re-export
    # keeps the local-read path import-cycle-free AND zero-cost — with
    # no data service in play, nothing from that package ever loads
    if name == "DataServiceIter":
        from .data_service.client import DataServiceIter

        return DataServiceIter
    raise AttributeError("module %r has no attribute %r"
                         % (__name__, name))


class DataDesc:
    """Name+shape(+dtype,layout) of one input (io.py provides name/shape
    pairs; layout mapping ref: python/mxnet/io.py LayoutMapper:24)."""

    def __init__(self, name, shape, dtype=_np.float32, layout="NCHW"):
        self.name = name
        self.shape = tuple(shape)
        self.dtype = dtype
        self.layout = layout

    @staticmethod
    def get_list(shapes, types):
        """DataDesc list from (name, shape) and optional (name, type)
        attribute lists (ref: io.py:629)."""
        if types is not None:
            type_dict = dict(types)
            return [DataDesc(x[0], x[1], type_dict[x[0]]) for x in shapes]
        return [DataDesc(x[0], x[1]) for x in shapes]

    def __repr__(self):
        return "DataDesc[%s,%s,%s,%s]" % (self.name, self.shape, self.dtype, self.layout)

    def __iter__(self):  # unpack like a (name, shape) tuple
        yield self.name
        yield self.shape

    def __getitem__(self, i):  # index like a (name, shape) tuple
        return (self.name, self.shape)[i]

    def __len__(self):
        return 2


class LayoutMapper:
    """Decide which axis of a named tensor is the batch axis
    (ref: python/mxnet/io.py:24). Subclass to override."""

    def get_layout_string(self, name):
        """Layout string (e.g. "NCHW") for ``name``, or None if unknown."""
        raise NotImplementedError()

    def get_batch_axis(self, name):
        """Index of the batch dimension for ``name``."""
        raise NotImplementedError()


class DefaultLayoutMapper(LayoutMapper):
    """Layout from a ``:__layout_X__`` tag in the name, else a fixed
    default batch axis (ref: python/mxnet/io.py:59; the
    rnn-time-major example relies on this convention)."""

    # NB: the reference's pattern (io.py:70, `([^_*])`) matches exactly
    # ONE character, so its own documented multi-char tags (NCHW, TNC)
    # can never match and always fall back to the default axis — an
    # upstream bug, not a spec. Multi-char capture here.
    LAYOUT_PATTERN = _re.compile(r":__layout_([^_]+?)__")

    def __init__(self, default_batch_axis=0):
        self._default_batch_axis = default_batch_axis

    def get_layout_string(self, name):
        ret = self.LAYOUT_PATTERN.search(name)
        return None if ret is None else ret.group(1)

    def get_batch_axis(self, name):
        layout = self.get_layout_string(name)
        if layout is None:
            return self._default_batch_axis
        return layout.find("N")  # -1 when N absent, as the reference


class DataBatch:
    """ref: python/mxnet/io.py:48."""

    def __init__(self, data, label, pad=None, index=None,
                 bucket_key=None, provide_data=None, provide_label=None):
        self.data = data
        self.label = label
        self.pad = pad
        self.index = index
        self.bucket_key = bucket_key
        self.provide_data = provide_data
        self.provide_label = provide_label


class DataIter:
    """ref: python/mxnet/io.py:80."""

    def __init__(self):
        self.batch_size = 0

    def reset(self):
        pass

    def __iter__(self):
        return self

    def __next__(self):
        return self.next()

    next = __next__

    def next(self):  # noqa: F811
        if self.iter_next():
            return DataBatch(
                data=self.getdata(), label=self.getlabel(),
                pad=self.getpad(), index=self.getindex(),
            )
        raise StopIteration

    def iter_next(self):
        raise NotImplementedError()

    def getdata(self):
        raise NotImplementedError()

    def getlabel(self):
        raise NotImplementedError()

    def getindex(self):
        return None

    def getpad(self):
        raise NotImplementedError()


def _init_data(data, allow_empty, default_name):
    """Convert arbitrary data to list of (name, numpy) (ref: io.py:456)."""
    assert data is not None or allow_empty
    if data is None:
        data = []
    if isinstance(data, (_np.ndarray, NDArray)):
        data = [data]
    if isinstance(data, list):
        if not allow_empty:
            assert len(data) > 0
        if len(data) == 1:
            data = {default_name: data[0]}
        else:
            data = {("_%d_%s" % (i, default_name)): d for i, d in enumerate(data)}
    if not isinstance(data, dict):
        raise TypeError("Input must be NDArray, numpy.ndarray, a list of them or dict")
    out = []
    for k, v in data.items():
        if isinstance(v, NDArray):
            v = v.asnumpy()
        out.append((k, _np.asarray(v, dtype=v.dtype if hasattr(v, "dtype") else _np.float32)))
    return out


class NDArrayIter(DataIter):
    """Iterate over in-memory arrays (ref: python/mxnet/io.py:475)."""

    def __init__(self, data, label=None, batch_size=1, shuffle=False,
                 last_batch_handle="pad", data_name="data", label_name="softmax_label",
                 num_parts=1, part_index=0):
        super().__init__()
        self.data = _init_data(data, allow_empty=False, default_name=data_name)
        self.label = _init_data(label, allow_empty=True, default_name=label_name)
        if num_parts > 1:
            # distributed sharding (ref: src/io/iter_mnist.cc part_index /
            # kv.num_workers convention used by tests/nightly/dist_lenet.py).
            # Every worker gets exactly n//num_parts samples so sharded
            # iterators yield identical batch counts — unequal counts would
            # deadlock collective-backed dist training at epoch end. When
            # shuffling, a shared-seed permutation of the FULL set runs
            # before the split so class-ordered inputs don't bias shards.
            if not 0 <= part_index < num_parts:
                raise ValueError(
                    "part_index must be in [0, num_parts), got %d/%d"
                    % (part_index, num_parts))
            n = self.data[0][1].shape[0]
            per = n // num_parts
            if shuffle:
                perm = _np.random.RandomState(0).permutation(n)
                sel = perm[part_index * per:(part_index + 1) * per]
            else:
                sel = _np.arange(part_index * per, (part_index + 1) * per)
            self.data = [(k, v[sel]) for k, v in self.data]
            self.label = [(k, v[sel]) for k, v in self.label]
        self.num_data = self.data[0][1].shape[0]
        assert self.num_data >= batch_size, "batch_size needs to be smaller than data size."
        self.idx = _np.arange(self.num_data)
        if shuffle:
            _np.random.shuffle(self.idx)
        if last_batch_handle == "discard":
            new_n = self.num_data - self.num_data % batch_size
            self.idx = self.idx[:new_n]
            self.num_data = new_n
        self.data_list = [x[1] for x in self.data] + [x[1] for x in self.label]
        self.num_source = len(self.data_list)
        self.cursor = -batch_size
        self.batch_size = batch_size
        self.last_batch_handle = last_batch_handle
        self.shuffle = shuffle

    @property
    def provide_data(self):
        return [
            DataDesc(k, (self.batch_size,) + v.shape[1:], v.dtype)
            for k, v in self.data
        ]

    @property
    def provide_label(self):
        return [
            DataDesc(k, (self.batch_size,) + v.shape[1:], v.dtype)
            for k, v in self.label
        ]

    def hard_reset(self):
        self.cursor = -self.batch_size

    def reset(self):
        if self.shuffle:
            _np.random.shuffle(self.idx)
        if self.last_batch_handle == "roll_over" and self.cursor > self.num_data:
            self.cursor = -self.batch_size + (self.cursor - self.num_data)
        else:
            self.cursor = -self.batch_size

    def iter_next(self):
        self.cursor += self.batch_size
        return self.cursor < self.num_data

    def next(self):
        if not _tel.ENABLED:
            return self._next_impl()
        t0 = _time.monotonic()
        batch = self._next_impl()  # StopIteration is not a fetch
        _tel.histogram("io.batch_fetch_secs").observe(
            _time.monotonic() - t0)
        return batch

    def _next_impl(self):
        if self.iter_next():
            return DataBatch(
                data=self.getdata(), label=self.getlabel(),
                pad=self.getpad(), index=None,
            )
        raise StopIteration

    def _getdata(self, data_source):
        assert self.cursor < self.num_data, "DataIter needs reset."
        if self.cursor + self.batch_size <= self.num_data:
            sel = self.idx[self.cursor:self.cursor + self.batch_size]
        else:
            pad = self.batch_size - self.num_data + self.cursor
            sel = _np.concatenate([self.idx[self.cursor:], self.idx[:pad]])
        return [array(x[sel]) for _, x in data_source]

    def getdata(self):
        return self._getdata(self.data)

    def getlabel(self):
        return self._getdata(self.label)

    def getpad(self):
        if self.last_batch_handle == "pad" and self.cursor + self.batch_size > self.num_data:
            return self.cursor + self.batch_size - self.num_data
        return 0


def _read_idx_images(path):
    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "rb") as f:
        magic, num, rows, cols = struct.unpack(">IIII", f.read(16))
        assert magic == 2051, "not an MNIST image file: %s" % path
        data = _np.frombuffer(f.read(), dtype=_np.uint8)
        return data.reshape(num, rows, cols)


def _read_idx_labels(path):
    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "rb") as f:
        magic, num = struct.unpack(">II", f.read(8))
        assert magic == 2049, "not an MNIST label file: %s" % path
        return _np.frombuffer(f.read(), dtype=_np.uint8).astype(_np.float32)


class MNISTIter(NDArrayIter):
    """MNIST idx-format iterator (ref: src/io/iter_mnist.cc, registered as
    MNISTIter). Reads the same idx files the reference reads; if the files
    are absent and ``allow_synthetic``, generates a deterministic synthetic
    digit-like dataset so tests run hermetically."""

    def __init__(self, image="train-images-idx3-ubyte", label="train-labels-idx1-ubyte",
                 batch_size=128, shuffle=True, flat=False, silent=False, seed=0,
                 input_shape=None, allow_synthetic=True, num_synthetic=2048,
                 num_parts=1, part_index=0, **kwargs):
        if os.path.exists(image) and os.path.exists(label):
            images = _read_idx_images(image).astype(_np.float32) / 255.0
            labels = _read_idx_labels(label)
        elif allow_synthetic:
            rng = _np.random.RandomState(seed)
            n = num_synthetic
            labels = rng.randint(0, 10, size=n).astype(_np.float32)
            # deterministic class-dependent blobs: classifiable synthetic digits
            images = rng.rand(n, 28, 28).astype(_np.float32) * 0.1
            for i in range(n):
                c = int(labels[i])
                images[i, 2 + c * 2: 6 + c * 2, 4:24] += 0.9
            images = _np.clip(images, 0, 1)
        else:
            raise MXNetError("MNIST files not found: %s" % image)
        if flat:
            images = images.reshape(images.shape[0], -1)
        else:
            images = images.reshape(images.shape[0], 1, 28, 28)
        super().__init__(
            images, labels, batch_size=batch_size, shuffle=shuffle,
            last_batch_handle="discard", num_parts=num_parts,
            part_index=part_index,
            data_name=kwargs.pop("data_name", "data"),
            label_name=kwargs.pop("label_name", "softmax_label"),
        )


class CSVIter(NDArrayIter):
    """CSV iterator (ref: src/io/iter_csv.cc)."""

    def __init__(self, data_csv, data_shape, label_csv=None, label_shape=(1,),
                 batch_size=1, **kwargs):
        data = _np.loadtxt(data_csv, delimiter=",", dtype=_np.float32)
        data = data.reshape((-1,) + tuple(data_shape))
        label = None
        if label_csv is not None:
            label = _np.loadtxt(label_csv, delimiter=",", dtype=_np.float32)
            label = label.reshape((-1,) + tuple(label_shape))
            if label.shape[-1] == 1:
                label = label.reshape(label.shape[:-1])
        super().__init__(data, label, batch_size=batch_size, last_batch_handle="discard")


class ResizeIter(DataIter):
    """Resize (truncate/loop) another iterator to `size` batches
    (ref: python/mxnet/io.py:138)."""

    def __init__(self, data_iter, size, reset_internal=True):
        super().__init__()
        self.data_iter = data_iter
        self.size = size
        self.reset_internal = reset_internal
        self.cur = 0
        self.current_batch = None
        self.provide_data = data_iter.provide_data
        self.provide_label = data_iter.provide_label
        self.batch_size = data_iter.batch_size

    def reset(self):
        self.cur = 0
        if self.reset_internal:
            self.data_iter.reset()

    def iter_next(self):
        if self.cur == self.size:
            return False
        try:
            self.current_batch = self.data_iter.next()
        except StopIteration:
            self.data_iter.reset()
            self.current_batch = self.data_iter.next()
        self.cur += 1
        return True

    def next(self):
        if self.iter_next():
            return self.current_batch
        raise StopIteration

    def getdata(self):
        return self.current_batch.data

    def getlabel(self):
        return self.current_batch.label

    def getindex(self):
        return self.current_batch.index

    def getpad(self):
        return self.current_batch.pad


class PrefetchingIter(DataIter):
    """Threaded lookahead over one or more iters (ref: python/mxnet/io.py:170;
    C++ analog PrefetcherIter, src/io/iter_prefetcher.h:47)."""

    def __init__(self, iters, rename_data=None, rename_label=None, prefetch_depth=2):
        super().__init__()
        if not isinstance(iters, list):
            iters = [iters]
        self.n_iter = len(iters)
        assert self.n_iter > 0
        self.iters = iters
        self.rename_data = rename_data
        self.rename_label = rename_label
        self.batch_size = self.provide_data[0].shape[0]
        self._depth = prefetch_depth
        self._queue = _queue.Queue(maxsize=prefetch_depth)
        self._stop = threading.Event()
        self._thread = None
        self._peek = None
        self._start()

    @property
    def provide_data(self):
        if self.rename_data is None:
            return sum([i.provide_data for i in self.iters], [])
        return sum([
            [DataDesc(r[n], s, d.dtype) for (n, s), d in zip(i.provide_data, i.provide_data)]
            for r, i in zip(self.rename_data, self.iters)
        ], [])

    @property
    def provide_label(self):
        if self.rename_label is None:
            return sum([i.provide_label for i in self.iters], [])
        return sum([
            [DataDesc(r[n], s, d.dtype) for (n, s), d in zip(i.provide_label, i.provide_label)]
            for r, i in zip(self.rename_label, self.iters)
        ], [])

    def _producer(self):
        while not self._stop.is_set():
            try:
                batches = [i.next() for i in self.iters]
            except StopIteration:
                self._queue.put(None)
                return
            self._queue.put(batches)

    def _start(self):
        self._thread = threading.Thread(target=self._producer, daemon=True)
        self._thread.start()

    def reset(self):
        self._stop.set()
        try:
            while True:
                self._queue.get_nowait()
        except _queue.Empty:
            pass
        self._thread.join(timeout=5)
        for i in self.iters:
            i.reset()
        self._stop = threading.Event()
        self._queue = _queue.Queue(maxsize=self._depth)
        self._peek = None
        self._start()

    def _fetch(self):
        if _tel.ENABLED:
            # occupancy BEFORE the get: depth==0 means the consumer is
            # about to stall on the producer (the signal that matters)
            _tel.gauge("io.prefetch_queue_depth").set(self._queue.qsize())
            t0 = _time.monotonic()
            batches = self._queue.get()
            _tel.histogram("io.batch_fetch_secs").observe(
                _time.monotonic() - t0)
        else:
            batches = self._queue.get()
        if batches is None:
            return None
        if self.n_iter == 1:
            return batches[0]
        return DataBatch(
            data=sum([b.data for b in batches], []),
            label=sum([b.label for b in batches], []),
            pad=batches[0].pad, index=batches[0].index,
        )

    def iter_next(self):
        """Advance to the next batch (DataIter protocol: iter_next moves the
        cursor; getdata/getlabel read the current batch)."""
        self._peek = self._fetch()
        return self._peek is not None

    def next(self):
        if self.iter_next():
            return self._peek
        raise StopIteration

    def getdata(self):
        assert self._peek is not None, "call iter_next() first"
        return self._peek.data

    def getlabel(self):
        assert self._peek is not None, "call iter_next() first"
        return self._peek.label

    def getindex(self):
        assert self._peek is not None, "call iter_next() first"
        return self._peek.index

    def getpad(self):
        assert self._peek is not None, "call iter_next() first"
        return self._peek.pad


class ImageRecordIter(DataIter):
    """Image RecordIO iterator: read packed recordio, decode, augment,
    batch, prefetch (ref: src/io/iter_image_recordio.cc:356 +
    image_aug_default.cc + iter_batchloader.h). Decode uses PIL (OpenCV
    equivalent); augmentation: rand_crop, rand_mirror, mean subtract, scale.
    """

    def __init__(self, path_imgrec, data_shape, batch_size, label_width=1,
                 shuffle=False, rand_crop=False, rand_mirror=False,
                 mean_img=None, mean_r=0, mean_g=0, mean_b=0, scale=1.0,
                 round_batch=True, prefetch_depth=4, seed=0,
                 num_parts=1, part_index=0, preprocess_threads=4,
                 max_random_scale=1.0, min_random_scale=1.0,
                 max_aspect_ratio=0.0, random_h=0, random_s=0, random_l=0,
                 corrupt="raise", **kwargs):
        super().__init__()
        from . import recordio as _recordio

        # corrupt="skip": resync past damaged records instead of killing
        # the epoch (resilience subsystem; docs/how_to/fault_tolerance.md)
        self.rec = _recordio.MXRecordIO(path_imgrec, "r", corrupt=corrupt)
        self.data_shape = tuple(data_shape)
        if len(self.data_shape) != 3 or self.data_shape[0] not in (1, 3):
            raise MXNetError(
                "ImageRecordIter: data_shape must be (1|3, h, w), got %s"
                % (self.data_shape,))
        self.batch_size = batch_size
        self.label_width = label_width
        self.shuffle = shuffle
        self.rand_crop = rand_crop
        self.rand_mirror = rand_mirror
        self.scale = scale
        # scale/aspect/color jitter (ref: image_aug_default.cc params;
        # random_h in degrees [0,180], random_s/random_l as cv HLS byte
        # deltas [0,255] — converted to fractions for the HLS math)
        self.max_random_scale = float(max_random_scale)
        self.min_random_scale = float(min_random_scale)
        self.max_aspect_ratio = float(max_aspect_ratio)
        self.random_h = float(random_h)
        self.random_s = float(random_s) / 255.0
        self.random_l = float(random_l) / 255.0
        self.mean = None
        mean_from_img = False
        if mean_img is not None and os.path.exists(str(mean_img)):
            from .ndarray import load as _ndload

            self.mean = list(_ndload(mean_img).values())[0].asnumpy()
            mean_from_img = True
        elif mean_r or mean_g or mean_b:
            self.mean = _np.array([mean_r, mean_g, mean_b], _np.float32).reshape(3, 1, 1)
        if self.mean is not None and self.data_shape[0] == 1:
            # a 3-channel mean must not broadcast a (1,h,w) image into a
            # 3-channel batch behind provide_data's back: a mean_img
            # plane collapses to its channel average; scalar mean_r is
            # the gray mean as given (ref image_aug_default.cc subtracts
            # mean_r_ from channel 0)
            if mean_from_img and self.mean.ndim == 3 and self.mean.shape[0] == 3:
                self.mean = self.mean.mean(axis=0, keepdims=True)
            elif self.mean.shape == (3, 1, 1):
                self.mean = self.mean[:1]
            self.mean = self.mean.astype(_np.float32)
        self._rng = _np.random.RandomState(seed)
        # round-robin sharding during the scan: out-of-shard record bytes are
        # dropped immediately so per-worker memory is O(dataset/num_parts);
        # shards are then truncated to total//num_parts so every worker
        # yields the same batch count (collective-backed dist training
        # deadlocks on unequal counts)
        if not 0 <= part_index < num_parts:
            raise ValueError("part_index must be in [0, num_parts), got %d/%d"
                             % (part_index, num_parts))
        self._records = []
        i = 0
        while True:
            s = self.rec.read()
            if s is None:
                break
            if i % num_parts == part_index:
                self._records.append(s)
            i += 1
        if num_parts > 1:
            self._records = self._records[: i // num_parts]
        self._order = _np.arange(len(self._records))
        self.cursor = -batch_size
        # Native decode+augment pipeline (src/imagedec.cc), the
        # OMP-worker role of the reference's ImageRecordIOParser
        # (ref: src/io/iter_image_recordio.cc:150, `preprocess_threads`).
        # Falls back to a PIL thread pool when the native build is
        # unavailable (GIL-bound, ~8x slower — see docs/perf_analysis.md).
        self.preprocess_threads = max(1, int(preprocess_threads))
        self._nlib = None
        from . import _native

        lib = _native.load("imagedec")
        if lib is not None:
            import ctypes

            lib.ImgdecBatch.restype = ctypes.c_int
            self._nlib = lib
        self._pool = None
        # the pool backs every batch that routes through the PIL path —
        # either no native lib, or a channel count ImgdecBatch can't emit
        if ((self._nlib is None or self.data_shape[0] != 3)
                and self.preprocess_threads > 1):
            from concurrent.futures import ThreadPoolExecutor

            self._pool = ThreadPoolExecutor(max_workers=self.preprocess_threads)

    def __del__(self):
        pool = getattr(self, "_pool", None)
        if pool is not None:
            pool.shutdown(wait=False)

    @property
    def provide_data(self):
        return [DataDesc("data", (self.batch_size,) + self.data_shape)]

    @property
    def provide_label(self):
        shape = (self.batch_size,) if self.label_width == 1 else (self.batch_size, self.label_width)
        return [DataDesc("softmax_label", shape)]

    def reset(self):
        if self.shuffle:
            self._rng.shuffle(self._order)
        self.cursor = -self.batch_size

    def iter_next(self):
        self.cursor += self.batch_size
        return self.cursor + self.batch_size <= len(self._records)

    @staticmethod
    def _hls_jitter(arr, dh, ds, dl):
        """Vectorized RGB->HLS->RGB jitter on an HWC f32 [0,255] array
        (dh in turns, ds/dl as fractions) — numpy port of the native
        pipeline's per-pixel conversion (src/imagedec.cc)."""
        rgb = arr.reshape(-1, 3) / 255.0
        mx_ = rgb.max(axis=1)
        mn = rgb.min(axis=1)
        l = (mx_ + mn) / 2
        d = mx_ - mn
        nz = d > 1e-6
        s = _np.zeros_like(l)
        denom = _np.where(l > 0.5, 2.0 - mx_ - mn, mx_ + mn)
        s[nz] = d[nz] / _np.maximum(denom[nz], 1e-12)
        h = _np.zeros_like(l)
        r, g, b = rgb[:, 0], rgb[:, 1], rgb[:, 2]
        dd = _np.where(nz, d, 1.0)
        is_r = nz & (mx_ == r)
        is_g = nz & ~is_r & (mx_ == g)
        is_b = nz & ~is_r & ~is_g
        h[is_r] = _np.mod((g - b)[is_r] / dd[is_r], 6.0) / 6.0
        h[is_g] = ((b - r)[is_g] / dd[is_g] + 2.0) / 6.0
        h[is_b] = ((r - g)[is_b] / dd[is_b] + 4.0) / 6.0
        h = _np.mod(h + dh, 1.0)
        l = _np.clip(l + dl, 0.0, 1.0)
        s = _np.clip(s + ds, 0.0, 1.0)
        q = _np.where(l < 0.5, l * (1 + s), l + s - l * s)
        p = 2 * l - q

        def hue(t):
            t = _np.mod(t, 1.0)
            out = _np.where(t < 1 / 6, p + (q - p) * 6 * t, q)
            out = _np.where(t >= 1 / 2,
                            _np.where(t < 2 / 3,
                                      p + (q - p) * (2 / 3 - t) * 6, p), out)
            return out

        out = _np.stack([hue(h + 1 / 3), hue(h), hue(h - 1 / 3)], axis=1)
        out = _np.where(s[:, None] < 1e-6, l[:, None], out)
        return (out * 255.0).reshape(arr.shape).astype(_np.float32)

    def _decode(self, s, aug):
        """PIL fallback path; aug = 8 uniforms (crop_scale, crop_aspect,
        crop_x, crop_y, mirror, dh, ds, dl) drawn on the iterator thread
        so thread-pool decode stays deterministic. Mirrors
        src/imagedec.cc's augment order."""
        from . import recordio as _recordio

        header, img_bytes = _recordio.unpack(s)
        import io as _io

        try:
            from PIL import Image
        except ImportError as e:  # pragma: no cover
            raise MXNetError("ImageRecordIter requires PIL for decode") from e
        c, h, w = self.data_shape
        # c==1 decodes grayscale, like the reference's gray flag
        # (iter_image_recordio.cc flag-driven cv::imread mode)
        img = Image.open(_io.BytesIO(img_bytes)).convert("RGB" if c == 3 else "L")
        iw, ih = img.size
        rsc, rar, rx, ry, rm, rh, rs, rl = aug
        if self.rand_crop:
            s_ = self.min_random_scale + (
                self.max_random_scale - self.min_random_scale) * rsc
            ar = 1.0 + self.max_aspect_ratio * (2 * rar - 1)
            cw = min(iw, max(1, int(w * s_ * ar + 0.5)))
            ch = min(ih, max(1, int(h * s_ + 0.5)))
            x0 = int(rx * (iw - cw + 1))
            y0 = int(ry * (ih - ch + 1))
            img = img.crop((x0, y0, x0 + cw, y0 + ch))
        img = img.resize((w, h))
        arr = _np.asarray(img, _np.float32)  # HWC (HW when grayscale)
        if arr.ndim == 2:
            arr = arr[:, :, None]
            # hue/saturation are undefined on gray (cv HLS leaves them
            # no-op), but lightness jitter still applies
            if self.random_l:
                dl = self.random_l * (2 * rl - 1)
                arr = _np.clip(arr / 255.0 + dl, 0.0, 1.0) * 255.0
        if c == 3 and (self.random_h or self.random_s or self.random_l):
            arr = self._hls_jitter(
                arr,
                self.random_h * (2 * rh - 1) / 360.0,
                self.random_s * (2 * rs - 1),
                self.random_l * (2 * rl - 1))
        arr = arr.transpose(2, 0, 1)  # CHW, RGB
        if self.rand_mirror and rm < 0.5:
            arr = arr[:, :, ::-1]
        if self.mean is not None:
            arr = arr - self.mean
        arr = arr * self.scale
        label = header.label
        return arr, label

    def _decode_batch_native(self, recs, augs):
        """One C call decodes+augments the whole batch in parallel
        (src/imagedec.cc ImgdecBatch)."""
        import ctypes

        from . import recordio as _recordio

        c, h, w = self.data_shape
        n = len(recs)
        headers = []
        bufs = (ctypes.POINTER(ctypes.c_ubyte) * n)()
        sizes = (ctypes.c_size_t * n)()
        keepalive = []
        for i, s in enumerate(recs):
            header, img_bytes = _recordio.unpack(s)
            headers.append(header)
            keepalive.append(img_bytes)
            bufs[i] = ctypes.cast(ctypes.c_char_p(img_bytes),
                                  ctypes.POINTER(ctypes.c_ubyte))
            sizes[i] = len(img_bytes)
        flags = ((1 if self.rand_crop else 0)
                 | (2 if self.rand_mirror else 0)
                 | (4 if (self.random_h or self.random_s or self.random_l)
                    else 0))
        rands = _np.ascontiguousarray(augs, _np.float32)
        if self.mean is None:
            mean_p, mean_kind = None, 0
        elif self.mean.size == 3:
            mean_p = _np.ascontiguousarray(self.mean.ravel(), _np.float32)
            mean_kind = 1
        else:
            # ImgdecBatch indexes the mean as a dense (3, h, w) plane; any
            # other layout would read out of bounds natively (the PIL path
            # fails the same input with a broadcast error)
            if tuple(self.mean.shape) != (3, h, w):
                raise MXNetError(
                    "ImageRecordIter: mean_img shape %s does not match "
                    "data_shape-derived (3, %d, %d)"
                    % (tuple(self.mean.shape), h, w))
            mean_p = _np.ascontiguousarray(self.mean, _np.float32)
            mean_kind = 2
        out = _np.empty((n, c, h, w), _np.float32)
        rc = self._nlib.ImgdecBatch(
            bufs, sizes, n, h, w, self.preprocess_threads,
            ctypes.c_uint(flags),
            rands.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
            None if mean_p is None else
            mean_p.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
            mean_kind, ctypes.c_float(self.scale),
            ctypes.c_float(self.max_aspect_ratio),
            ctypes.c_float(self.min_random_scale),
            ctypes.c_float(self.max_random_scale),
            ctypes.c_float(self.random_h),
            ctypes.c_float(self.random_s),
            ctypes.c_float(self.random_l),
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_float)))
        if rc != 0:
            raise MXNetError(
                "ImageRecordIter: corrupt JPEG at batch index %d" % (-rc - 1))
        labels = [hd.label for hd in headers]
        return out, labels

    def next(self):
        if not _tel.ENABLED:
            return self._next_impl()
        t0 = _time.monotonic()
        batch = self._next_impl()
        _tel.histogram("io.batch_fetch_secs").observe(
            _time.monotonic() - t0)
        return batch

    def _next_impl(self):
        if not self.iter_next():
            raise StopIteration
        recs = [self._records[self._order[self.cursor + i]]
                for i in range(self.batch_size)]
        augs = [tuple(self._rng.rand(8)) for _ in recs]
        # ImgdecBatch always emits 3 channels (n*3*h*w floats); route
        # grayscale/other channel counts through the PIL path instead of
        # overflowing the (n, c, h, w) output allocation
        if self._nlib is not None and self.data_shape[0] == 3:
            stacked, labels = self._decode_batch_native(recs, augs)
            data = array(stacked)
        else:
            if self._pool is not None:
                results = list(self._pool.map(self._decode, recs, augs))
            else:
                results = [self._decode(s, a) for s, a in zip(recs, augs)]
            data = array(_np.stack([d for d, _ in results]))
            labels = [l for _, l in results]
        label = array(_np.asarray(labels, _np.float32).reshape(
            (self.batch_size,) if self.label_width == 1 else (self.batch_size, self.label_width)
        ))
        return DataBatch(data=[data], label=[label], pad=0, index=None)
