"""Standalone prediction API + compiled-model export.

Two deployment surfaces, mirroring the reference's prediction story:

1. ``Predictor`` — the c_predict_api equivalent (ref:
   include/mxnet/c_predict_api.h:60-170 ``MXPredCreate/SetInput/Forward/
   PartialForward/GetOutputShape/GetOutput``, impl
   src/c_api/c_predict_api.cc). Construct from the symbol JSON + raw
   ``.params`` bytes (the checkpoint files ``prefix-symbol.json`` /
   ``prefix-%04d.params``), feed inputs, run forward, read outputs.
   ``set_input``/``forward``/``get_output`` keep the reference's
   stateful call sequence so predict-only clients port 1:1.

2. ``export_compiled``/``load_compiled`` — the amalgamation equivalent
   (ref: amalgamation/, which concatenates the whole library into one
   translation unit so a prediction runs with zero framework deps). The
   TPU-native analog is ``jax.export``: the bound forward program is
   serialized as a StableHLO artifact with the weights baked in, and
   ``load_compiled`` runs it WITHOUT the model code, the Symbol graph, or
   the op registry — only jax is needed at the deployment site. The
   artifact is forward-compatible across jax releases per StableHLO
   versioning guarantees.
"""
from __future__ import annotations

import numpy as _np

from .base import MXNetError
from .context import cpu
from .ndarray import NDArray

__all__ = ["Predictor", "load_compiled"]


class Predictor:
    """Predict-only executor over a checkpointed model
    (ref: c_predict_api.h MXPredCreate:60).

    Parameters
    ----------
    symbol_json_str : str
        Symbol graph JSON (contents of ``prefix-symbol.json``).
    param_bytes : bytes or dict
        Raw contents of ``prefix-%04d.params`` (NDArray dict with
        ``arg:``/``aux:`` name prefixes), or an already-loaded dict.
    ctx : Context
        Device to run on.
    input_shapes : dict of name -> tuple
        Shapes of the input nodes (ref MXPredCreate input_keys/shapes).
    output_names : list of str, optional
        Restrict outputs to these heads — the MXPredCreatePartialOut
        variant (c_predict_api.h:93).
    """

    def __init__(self, symbol_json_str, param_bytes, ctx=None,
                 input_shapes=None, output_names=None):
        from . import ndarray as nd
        from .symbol import load_json

        if ctx is None:
            ctx = cpu()
        if input_shapes is None:
            raise MXNetError("Predictor requires input_shapes")
        sym = load_json(symbol_json_str)
        if output_names is not None:
            from .symbol import Group

            internals = sym.get_internals()
            heads = [internals[n if n.endswith("_output") else n + "_output"]
                     for n in output_names]
            sym = Group(heads) if len(heads) > 1 else heads[0]
        self._symbol = sym
        self._ctx = ctx

        if isinstance(param_bytes, dict):
            loaded = param_bytes
        else:
            loaded = nd.load_frombuffer(param_bytes)
        arg_params, aux_params = {}, {}
        for k, v in loaded.items():
            if k.startswith("arg:"):
                arg_params[k[4:]] = v
            elif k.startswith("aux:"):
                aux_params[k[4:]] = v
            else:  # unprefixed dicts accepted like FeedForward.load does
                arg_params[k] = v

        self._input_names = list(input_shapes.keys())
        self._bind(dict(input_shapes), arg_params, aux_params)

    def _bind(self, input_shapes, arg_params, aux_params):
        arg_names = self._symbol.list_arguments()
        aux_names = self._symbol.list_auxiliary_states()
        # seed shape inference with the checkpoint's own parameter
        # shapes as well as the declared inputs: a graph whose weights
        # feed through a transformation (w * scale into FullyConnected)
        # has no inferable leaf shape from the data side alone — the
        # loaded arrays are the authority
        known = dict(input_shapes)
        for name in arg_names:
            if name not in known and name in arg_params:
                known[name] = tuple(arg_params[name].shape)
        arg_shapes, out_shapes, aux_shapes = self._symbol.infer_shape(**known)
        # output shapes are fixed for the life of a binding — cache them
        # here instead of re-running full graph shape inference on every
        # get_output_shape() call; reshape() re-binds, refreshing them
        self._out_shapes = [tuple(s) for s in out_shapes]
        args = {}
        for name, shape in zip(arg_names, arg_shapes):
            if name in input_shapes:
                args[name] = NDArray(_np.zeros(shape, _np.float32), ctx=self._ctx)
            elif name in arg_params:
                if tuple(arg_params[name].shape) != tuple(shape):
                    raise MXNetError(
                        "param %s shape %s != expected %s"
                        % (name, tuple(arg_params[name].shape), tuple(shape)))
                args[name] = arg_params[name].as_in_context(self._ctx)
            else:
                # label arguments of loss heads are not in predict-time
                # param files; bind zeros (inference never reads them)
                args[name] = NDArray(_np.zeros(shape, _np.float32), ctx=self._ctx)
        aux = [aux_params[n].as_in_context(self._ctx) if n in aux_params
               else NDArray(_np.zeros(s, _np.float32), ctx=self._ctx)
               for n, s in zip(aux_names, aux_shapes)]
        self._args = args
        self._arg_params = arg_params
        self._aux_params = aux_params
        # compile layer: predict-time weights never change after bind,
        # so the fold pass may bake parameter-only subexpressions into
        # constants (compile/fold.py frozen mode — the training
        # executors never get this). The persistent jit cache turns the
        # predict program's cold-start compile into a disk load — the
        # serving latency-floor fix (docs/how_to/compilation.md).
        from . import compile as _compile

        _compile.ensure_jit_cache()
        frozen = {n: args[n] for n in arg_names
                  if n not in input_shapes and n in arg_params}
        self._exe = self._symbol.bind(
            self._ctx, args, aux_states=aux, grad_req="null",
            _compile_opts={"frozen_params": frozen} if frozen else None)
        self._outputs = None

    @classmethod
    def from_checkpoint(cls, prefix, epoch, ctx=None, input_shapes=None,
                        output_names=None):
        """Build from ``prefix-symbol.json`` + ``prefix-%04d.params``
        (the files written by save_checkpoint, ref: model.py:311)."""
        from .model import fence_checkpoint

        fence_checkpoint(prefix)  # in-flight async checkpoint writes
        with open("%s-symbol.json" % prefix) as f:
            sym_json = f.read()
        with open("%s-%04d.params" % (prefix, epoch), "rb") as f:
            params = f.read()
        return cls(sym_json, params, ctx=ctx, input_shapes=input_shapes,
                   output_names=output_names)

    # -- the c_predict_api call sequence --------------------------------------
    def set_input(self, key, value):
        """ref: MXPredSetInput (c_predict_api.h:126)."""
        if key not in self._args or key not in self._input_names:
            raise MXNetError("unknown input %r; inputs are %s"
                             % (key, self._input_names))
        v = value.asnumpy() if hasattr(value, "asnumpy") else _np.asarray(value)
        if tuple(v.shape) != tuple(self._args[key].shape):
            raise MXNetError("input %s shape %s != declared %s"
                             % (key, v.shape, self._args[key].shape))
        self._args[key][:] = v

    def forward(self, **kwargs):
        """Run forward; kwargs are a convenience for set_input
        (ref: MXPredForward c_predict_api.h:135)."""
        for k, v in kwargs.items():
            self.set_input(k, v)
        self._outputs = self._exe.forward(is_train=False)
        return self._outputs

    def get_output_shape(self, index=0):
        """ref: MXPredGetOutputShape (c_predict_api.h:113). Served from
        the shapes cached at bind time (``_bind``) — shape inference is
        a full graph walk, far too heavy for a per-call query on a hot
        serving path."""
        return self._out_shapes[index]

    def get_output(self, index=0):
        """ref: MXPredGetOutput (c_predict_api.h:161)."""
        if self._outputs is None:
            raise MXNetError("call forward() before get_output()")
        return self._outputs[index].asnumpy()

    def reshape(self, new_input_shapes):
        """Rebind for new input shapes sharing weights
        (ref: MXPredReshape c_predict_api.h:178)."""
        self._bind(dict(new_input_shapes), self._arg_params, self._aux_params)

    # -- compiled export (amalgamation equivalent) ----------------------------
    def export_compiled(self):
        """Serialize the forward program (weights baked in) to bytes via
        jax.export; see module docstring."""
        import jax
        import jax.numpy as jnp
        from jax import export as jexport

        exe = self._exe
        arg_names = self._symbol.list_arguments()
        aux_vals = [a._data for a in exe.aux_arrays] if exe.aux_arrays else []
        const_args = {
            n: self._args[n]._data for n in arg_names
            if n not in self._input_names
        }

        def fn(*inputs):
            vals = []
            it = iter(inputs)
            for n in arg_names:
                vals.append(next(it) if n in self._input_names else const_args[n])
            outs, _ = exe._run(vals, aux_vals, None, is_train=False)
            return tuple(outs)

        in_avals = [
            jax.ShapeDtypeStruct(self._args[n].shape,
                                 _np.dtype(self._args[n].dtype))
            for n in self._input_names
        ]
        # cross-platform artifact: deployable on cpu hosts and tpu alike
        exported = jexport.export(jax.jit(fn), platforms=("cpu", "tpu"))(*in_avals)
        blob = exported.serialize()
        # envelope: input names so load_compiled can accept kwargs
        import json
        header = json.dumps({"inputs": self._input_names}).encode()
        return b"MXTC" + len(header).to_bytes(4, "little") + header + blob


class _CompiledPredictor:
    """Deserialized compiled model: runs without symbol/op machinery."""

    def __init__(self, input_names, exported):
        self.input_names = list(input_names)
        self._exported = exported
        self._outputs = None

    def forward(self, **kwargs):
        vals = [kwargs[n] if not hasattr(kwargs[n], "asnumpy")
                else kwargs[n].asnumpy() for n in self.input_names]
        self._outputs = self._exported.call(*[_np.asarray(v) for v in vals])
        return self._outputs

    def get_output(self, index=0):
        if self._outputs is None:
            raise MXNetError("call forward() before get_output()")
        return _np.asarray(self._outputs[index])


def load_compiled(blob):
    """Load an export_compiled() artifact; needs only jax at runtime."""
    import json

    from jax import export as jexport

    if blob[:4] != b"MXTC":
        raise MXNetError("not a compiled-model artifact")
    hlen = int.from_bytes(blob[4:8], "little")
    header = json.loads(blob[8:8 + hlen].decode())
    exported = jexport.deserialize(blob[8 + hlen:])
    return _CompiledPredictor(header["inputs"], exported)
