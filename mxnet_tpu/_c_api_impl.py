"""Python side of the flat C API (ref: src/c_api/c_api.cc, SURVEY §2.10).

The reference exposes ~110 flat C functions over its C++ core; every
language binding (Python/R/Scala/MATLAB/amalgamation) sits on that ABI.
In this framework the core is the Python/JAX layer, so the C ABI
(src/c_api.cc) embeds CPython and marshals into the plain functions here.
Each function takes/returns only simple types (ints, strings, bytes,
tuples, handles-as-objects) so the C side stays a dumb marshaller.

Device-type codes follow the reference (include/mxnet/base.h:85-118):
1 = cpu, 2 = gpu (alias of tpu here), 3 = cpu_pinned, 6 = tpu.
"""
from __future__ import annotations

import numpy as _np

_DEV = {}


def _ctx(dev_type, dev_id):
    from . import context

    if not _DEV:
        _DEV.update({1: context.cpu, 2: context.tpu, 3: context.cpu_pinned,
                     6: context.tpu})
    return _DEV[int(dev_type)](int(dev_id))


def _dev_code(ctx):
    return {"cpu": 1, "tpu": 6, "gpu": 6, "cpu_pinned": 3}[ctx.device_type], ctx.device_id


# -- NDArray ------------------------------------------------------------------

def ndarray_create(shape, dev_type, dev_id):
    from . import ndarray as nd

    return nd.empty(tuple(int(s) for s in shape), ctx=_ctx(dev_type, dev_id))


def ndarray_create_none():
    from . import ndarray as nd

    return nd.empty((0,))


def ndarray_sync_copy_from(arr, data):
    """data: bytes of float32, length must equal arr.size*4."""
    src = _np.frombuffer(data, dtype=_np.float32).reshape(arr.shape)
    arr[:] = src.astype(arr.dtype, copy=False)
    return 0


def ndarray_sync_copy_to(arr):
    return _np.ascontiguousarray(arr.asnumpy().astype(_np.float32)).tobytes()


def ndarray_shape(arr):
    return tuple(int(s) for s in arr.shape)


def ndarray_dtype_code(arr):
    from .base import _DTYPE_NP_TO_MX

    return int(_DTYPE_NP_TO_MX[_np.dtype(arr.dtype)])


def ndarray_context(arr):
    return _dev_code(arr.context)


def ndarray_slice(arr, start, stop):
    return arr[int(start):int(stop)]

def ndarray_at(arr, idx):
    return arr[int(idx)]


def ndarray_save(fname, handles, keys):
    from . import ndarray as nd

    if keys:
        nd.save(fname, dict(zip(keys, handles)))
    else:
        nd.save(fname, list(handles))
    return 0


def ndarray_load(fname):
    """Returns (list_of_arrays, list_of_names) — names empty for a list."""
    from . import ndarray as nd

    data = nd.load(fname)
    if isinstance(data, dict):
        names = list(data.keys())
        return [data[k] for k in names], names
    return list(data), []


def ndarray_wait_to_read(arr):
    arr.wait_to_read()
    return 0


def wait_all():
    from . import ndarray as nd

    nd.waitall()
    return 0


def random_seed(seed):
    from . import random

    random.seed(int(seed))
    return 0


# -- imperative function registry --------------------------------------------

def list_all_op_names():
    """Registered operators only — the set a binding generator should wrap
    (ref: MXListFunctions lists the op registry, not module helpers)."""
    from .ops.registry import REGISTRY

    return sorted(n for n, op in REGISTRY.items() if op.imperative)


def _parse_literal(s):
    """Best-effort string→value for kwargs crossing the C ABI, mirroring
    the reference's dmlc::Parameter string protocol (registry Field.convert
    handles op params; this covers plain jnp-wrapper functions)."""
    import ast

    try:
        return ast.literal_eval(s)
    except (ValueError, SyntaxError):
        return s


def func_invoke(name, inputs, keys, vals):
    """Generic imperative invoke (ref: MXFuncInvoke, c_api.h:447).
    kwargs arrive as strings, as in the reference C API."""
    from . import ndarray as nd
    from .ops.registry import REGISTRY

    op = REGISTRY.get(name)
    if op is None or not op.imperative:
        raise ValueError("unknown NDArray function: %s" % name)
    fn = getattr(nd, name)
    kwargs = {k: _parse_literal(v) for k, v in zip(keys, vals)}
    out = fn(*inputs, **kwargs)
    return list(out) if isinstance(out, (list, tuple)) else [out]


# -- Symbol -------------------------------------------------------------------

def symbol_create_from_json(json_str):
    from . import symbol

    return symbol.load_json(json_str)


def symbol_to_json(sym):
    return sym.tojson()


def symbol_create_variable(name):
    from . import symbol

    return symbol.Variable(name)


def symbol_create_atomic(op_name, keys, vals):
    """Create an un-composed op symbol; compose() wires its inputs
    (ref: MXSymbolCreateAtomicSymbol + MXSymbolCompose, c_api.h:600-668)."""
    from . import symbol

    op = getattr(symbol, op_name, None)
    if op is None:
        raise ValueError("unknown operator: %s" % op_name)
    # registry ops convert string params themselves (Field.convert — the
    # dmlc::Parameter protocol), so kwargs stay as strings here
    return ("_atomic", op, dict(zip(keys, vals)))


def symbol_compose(atom, name, keys, args):
    if not (isinstance(atom, tuple) and atom and atom[0] == "_atomic"):
        raise ValueError("handle is not an atomic symbol")
    _, op, base_kwargs = atom
    kwargs = dict(base_kwargs)  # the atomic handle may be composed repeatedly
    if name:
        kwargs.setdefault("name", name)
    if keys:
        kwargs.update(dict(zip(keys, args)))
        return op(**kwargs)
    return op(*args, **kwargs)


def symbol_list_arguments(sym):
    return list(sym.list_arguments())


def symbol_list_outputs(sym):
    return list(sym.list_outputs())


def symbol_list_aux(sym):
    return list(sym.list_auxiliary_states())


def symbol_infer_shape(sym, keys, shapes):
    """shapes: list of int tuples aligned with keys. Returns
    (arg_shapes, out_shapes, aux_shapes) or None on incomplete info."""
    kwargs = {k: tuple(int(d) for d in s) for k, s in zip(keys, shapes)}
    arg, out, aux = sym.infer_shape(**kwargs)
    if arg is None:
        return None
    return ([tuple(map(int, s)) for s in arg],
            [tuple(map(int, s)) for s in out],
            [tuple(map(int, s)) for s in aux])


# -- Predict API (ref: include/mxnet/c_predict_api.h) -------------------------

def pred_create(symbol_json, param_bytes, dev_type, dev_id, input_keys,
                input_shapes):
    from .predictor import Predictor

    shapes = {k: tuple(int(d) for d in s)
              for k, s in zip(input_keys, input_shapes)}
    return Predictor(symbol_json, param_bytes, ctx=_ctx(dev_type, dev_id),
                     input_shapes=shapes)


def pred_set_input(pred, key, data):
    if key not in pred._args:
        raise ValueError("unknown input %r" % key)
    shape = pred._args[key].shape
    arr = _np.frombuffer(data, dtype=_np.float32).reshape(shape)
    pred.set_input(key, arr)
    return 0


def pred_forward(pred):
    pred.forward()
    return 0


def pred_get_output_shape(pred, index):
    return tuple(int(s) for s in pred.get_output_shape(int(index)))


def pred_get_output(pred, index):
    out = pred.get_output(int(index))
    return _np.ascontiguousarray(
        _np.asarray(out, dtype=_np.float32)).tobytes()


def pred_reshape(pred, input_keys, input_shapes):
    """Returns a NEW predictor at the new shapes; the original handle
    stays valid at its old shapes (ref: MXPredReshape contract)."""
    import copy

    shapes = {k: tuple(int(d) for d in s)
              for k, s in zip(input_keys, input_shapes)}
    newp = copy.copy(pred)
    newp.reshape(shapes)
    return newp
