"""mxtel exporters: JSONL run journal, Prometheus text, console summary.

The journal is the queryable record of what the runtime did: one JSON
object per line, either a finished span or a metrics snapshot::

    {"kind": "span", "name": "epoch", "id": 7, "parent": null,
     "t": 1722700000.1, "dur": 12.03, "thread": "MainThread"}
    {"kind": "metrics", "t": ..., "mark": "periodic",
     "counters": {...}, "gauges": {...}, "histograms": {...}}

Activated by ``MXNET_TELEMETRY=1`` + ``MXNET_TELEMETRY_JOURNAL=<path>``
(telemetry.reload() reads both). Spans buffer in memory and hit disk on
the periodic flusher (``MXNET_TELEMETRY_FLUSH_SECS``, default 10 — each
flush also appends a ``mark="periodic"`` metrics snapshot, which is what
gives the report tool its throughput timeline), on explicit
``telemetry.flush()``, and finally at interpreter exit: the engine's
exit drain calls :func:`flush_at_exit` after pending host tasks land,
and an atexit hook (registered before the engine's, so it runs after —
atexit is LIFO) closes the journal either way.

``tools/telemetry_report.py`` renders a journal; :func:`prometheus_text`
and :func:`console_summary` serve scrape endpoints and humans.
"""
from __future__ import annotations

import atexit
import json
import os
import threading
import time

from . import registry as _registry
from . import tracing as _tracing

__all__ = [
    "configure", "emit", "flush", "flush_at_exit", "close",
    "journal_path", "prometheus_text", "console_summary",
]

DEFAULT_FLUSH_SECS = 10.0

_lock = threading.Lock()
_path = None
_file = None
_buffer = []
_flush_secs = DEFAULT_FLUSH_SECS
_flusher = None
_flusher_stop = None
_exit_snapshot_done = False


def journal_path():
    """The configured journal path, or None when journaling is off."""
    return _path


def configure(path, flush_secs=None):
    """(Re)configure the journal target. Same path is a no-op so
    ``telemetry.reload()`` is idempotent; a changed path (including
    None) flushes and closes the previous journal first."""
    global _path, _flush_secs, _exit_snapshot_done
    if flush_secs is None or flush_secs <= 0:
        flush_secs = DEFAULT_FLUSH_SECS
    with _lock:
        same = (path == _path)
        _flush_secs = float(flush_secs)
    if same:
        return
    close()
    with _lock:
        _path = path
        _exit_snapshot_done = False


def emit(record):
    """Queue one journal record (no-op when no journal is configured).
    Called from span exits and instrumentation; must never raise. The
    first record opens the journal and starts the periodic flusher —
    a run that never emits never touches the filesystem."""
    if _path is None:
        return
    with _lock:
        if _path is None:
            return
        _open_locked()
        if _path is None:  # open failed: journaling disabled itself
            return
        _buffer.append(record)


def _open_locked():
    """Open the journal file + start the periodic flusher. Caller holds
    the lock."""
    global _file, _flusher, _flusher_stop, _path
    if _file is not None or _path is None:
        return
    d = os.path.dirname(os.path.abspath(_path))
    try:
        os.makedirs(d, exist_ok=True)
        _file = open(_path, "a", encoding="utf-8")
    except OSError:
        # an unwritable journal must not take training down — disable
        # journaling entirely (metrics/spans stay queryable in-process).
        # Buffering on would grow without bound: no file means no
        # flusher thread ever drains the buffer.
        import logging

        logging.warning(
            "mxtel: journal %r is unwritable; journaling disabled "
            "(metrics remain available in-process)", _path)
        _file = None
        _path = None
        _buffer[:] = []
        return
    # identity header: trace_merge (tools/trace_merge.py) reads the
    # rank from the journal itself instead of trusting file names
    _buffer.insert(0, {
        "kind": "meta", "t": time.time(), "pid": os.getpid(),
        "rank": int(os.environ.get("MXNET_PROC_ID", "0") or 0),
        "world": int(os.environ.get("MXNET_NUM_PROCS", "1") or 1),
    })
    stop = _flusher_stop = threading.Event()
    # a zero/negative cadence would busy-loop the flusher thread
    secs = _flush_secs if _flush_secs > 0 else DEFAULT_FLUSH_SECS

    def _run():
        while not stop.wait(secs):
            try:
                flush(mark="periodic")
            except Exception:
                pass

    _flusher = threading.Thread(
        target=_run, name="mxtel-journal-flush", daemon=True)
    _flusher.start()


def _metrics_record(mark):
    snap = _registry.default_registry().snapshot()
    snap.update({"kind": "metrics", "t": time.time(), "mark": mark})
    return snap


def flush(mark=None):
    """Write buffered records to the journal; with ``mark`` also append
    a metrics snapshot record tagged with it (``periodic`` from the
    flusher, ``test_end`` from the suite fixture, ``exit`` at
    shutdown). No-op without a configured journal."""
    global _exit_snapshot_done
    if _path is None:
        return
    with _lock:
        if _path is None:
            return
        _open_locked()
        recs, _buffer[:] = list(_buffer), []
        if mark is not None:
            recs.append(_metrics_record(mark))
        if mark == "exit":
            # an explicit exit flush (controller/replica teardown,
            # chaos workloads) must suppress the atexit hook's own exit
            # snapshot: counter-folding harnesses SUM exit records, and
            # a doubled snapshot doubles every total
            _exit_snapshot_done = True
        if _file is None or not recs:
            return
        for r in recs:
            _file.write(json.dumps(r) + "\n")
        _file.flush()


def flush_at_exit():
    """Final flush: buffered spans + one ``mark="exit"`` metrics
    snapshot (written at most once — the engine drain hook and the
    atexit hook both funnel here)."""
    global _exit_snapshot_done
    if _path is None:
        return
    with _lock:
        done, _exit_snapshot_done = _exit_snapshot_done, True
    try:
        flush(mark=None if done else "exit")
    except Exception:
        pass


def close():
    """Final flush, then stop the flusher and release the file."""
    global _file, _flusher, _flusher_stop, _path
    flush_at_exit()
    with _lock:
        stop, _flusher_stop, _flusher = _flusher_stop, None, None
        f, _file = _file, None
        _path = None
        _buffer[:] = []
    if stop is not None:
        stop.set()
    if f is not None:
        try:
            f.close()
        except OSError:
            pass


# Registered at import: telemetry is imported before the engine module
# in package init, so this atexit hook runs AFTER the engine's exit
# drain (atexit is LIFO) — metrics from host tasks completing during the
# drain still make the journal.
atexit.register(flush_at_exit)


# -- human/scrape renderers ----------------------------------------------------
def _prom_name(name):
    out = []
    for ch in name:
        out.append(ch if ch.isalnum() or ch == "_" else "_")
    return "mxtpu_" + "".join(out)


def prometheus_text():
    """Prometheus exposition-format dump of the live registry.

    Histograms render as REAL histogram families — cumulative
    ``_bucket{le="..."}`` series over the registry's fixed bounds plus
    ``_sum``/``_count`` — so server-side aggregation (rate, quantile
    estimation across ranks) works the way Prometheus intends. The
    pre-PR-13 quantile-labelled lines (reservoir-exact p50/p95/p99)
    ride along under the same metric name for dashboard backward
    compatibility; scrapers that only understand the histogram family
    ignore them."""
    lines = []
    for m in _registry.default_registry().metrics():
        pn = _prom_name(m.name)
        if m.kind == "counter":
            lines.append("# TYPE %s counter" % pn)
            lines.append("%s %d" % (pn, m.value))
        elif m.kind == "gauge":
            lines.append("# TYPE %s gauge" % pn)
            lines.append("%s %g" % (pn, m.value))
        else:
            s = m.summary()
            lines.append("# TYPE %s histogram" % pn)
            for le, cum in m.bucket_counts():
                lines.append('%s_bucket{le="%s"} %d'
                             % (pn, "+Inf" if le == float("inf")
                                else ("%g" % le), cum))
            lines.append("%s_sum %g" % (pn, s["sum"]))
            lines.append("%s_count %d" % (pn, s["count"]))
            # backward-compat: the reservoir-exact percentile gauges
            for q, key in ((0.5, "p50"), (0.95, "p95"), (0.99, "p99")):
                if s[key] is not None:
                    lines.append('%s{quantile="%g"} %g' % (pn, q, s[key]))
    return "\n".join(lines) + ("\n" if lines else "")


def console_summary(top=10):
    """One readable block: counters, gauges, histogram percentiles, and
    the top spans by total time. The quick look when you don't want the
    journal + report tool round trip."""
    reg = _registry.default_registry()
    lines = ["=== mxtel summary ==="]
    snap = reg.snapshot()
    if snap["counters"]:
        lines.append("counters:")
        for k, v in sorted(snap["counters"].items()):
            lines.append("  %-42s %d" % (k, v))
    if snap["gauges"]:
        lines.append("gauges:")
        for k, v in sorted(snap["gauges"].items()):
            lines.append("  %-42s %g" % (k, v))
    if snap["histograms"]:
        lines.append("histograms (secs unless noted):")
        lines.append("  %-42s %8s %10s %10s %10s %10s" % (
            "name", "count", "p50", "p95", "p99", "max"))
        for k, s in sorted(snap["histograms"].items()):
            lines.append("  %-42s %8d %10.6g %10.6g %10.6g %10.6g" % (
                k, s["count"], s["p50"] or 0, s["p95"] or 0,
                s["p99"] or 0, s["max"] or 0))
    aggs = _tracing.span_aggregates()
    if aggs:
        lines.append("top spans by total time:")
        lines.append("  %-30s %8s %12s %12s" % (
            "span", "count", "total_s", "max_s"))
        ranked = sorted(aggs.items(), key=lambda kv: -kv[1]["total"])[:top]
        for name, a in ranked:
            lines.append("  %-30s %8d %12.6g %12.6g" % (
                name, a["count"], a["total"], a["max"]))
    return "\n".join(lines)
