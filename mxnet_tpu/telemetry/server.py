"""mxdash: live introspection HTTP server over the mxtel registry.

Production dataflow systems treat live inspection of a *running* job as
first-class (TensorFlow couples its runtime with servable status/trace
pages, arXiv:1605.08695); until now the only way to see inside a live
trainer or serving engine was to kill it and read the journal. This
module serves the in-process mxtel state over plain HTTP:

====================  =========================================================
``/healthz``          liveness probe (200 ``ok``)
``/readyz``           readiness probe: alive AND accepting work — 503 while
                      the process is marked starting/stopping
                      (:func:`mark_ready`) or any serving engine is draining
``/metrics``          Prometheus exposition text (export.prometheus_text)
``/statusz``          uptime, rank/world, MXNET_* env config, jit-cache +
                      compile counters (JSON)
``/tracez``           currently-open spans + the recent finished-span ring
                      (``?n=`` bounds the tail; JSON)
``/enginez``          dependency-engine pending count, queued + in-flight
                      task dump (the PR 2 wait-watchdog introspection, live)
``/servingz``         live serving-request table, KV-pool utilization,
                      scheduler event tail for every serving Engine
``/profilez``         mxprof attribution (prof.py, ``MXNET_PROF=1``): top
                      programs by device time with XLA flops/bytes/memory,
                      step-time decomposition, derived MFU/roofline%, HBM
                      live/peak
====================  =========================================================

Enablement: ``MXNET_TELEMETRY=1`` plus ``MXNET_TELEMETRY_HTTP=<port>``
(``host:port`` to pick an interface; bare ports bind loopback — the
same trusted-network posture as the elastic coordinator; port ``0``
binds an ephemeral port, read back via :func:`port`). Off by default:
without both variables no thread starts and no socket is opened —
:func:`configure` with None is a pure no-op on a never-started server.

The server is read-only (GET only) and deliberately stdlib-only: one
daemon ``ThreadingHTTPServer`` whose handlers read the registry/tracer
snapshots under their own locks. Handlers never take a lock of this
module while calling into other subsystems — the module lock guards
only the start/stop hand-off.
"""
from __future__ import annotations

import json
import logging
import os
import sys
import threading
import time

from . import registry as _registry
from . import tracing as _tracing

__all__ = ["configure", "port", "running", "mark_ready", "is_ready"]

_lock = threading.Lock()
_server = None
_thread = None
_bound = None        # (host, port) actually bound
_started_t = None

# process-level readiness: /readyz (alive AND accepting work) vs
# /healthz (alive). Defaults ready so plain jobs need no opt-in; a
# replica that warms up before taking traffic calls
# mark_ready(False, "starting") first — but user code only runs AFTER
# package import, and the server answers DURING it, so a supervisor
# that must never see a booting replica as ready exports
# MXNET_TELEMETRY_READY=0 (mxctl does this for supervised replicas):
# the process then starts not-ready until its own mark_ready(True).
# Serving engines additionally gate readiness on their drain state
# (Engine.accepting()).
_ready = os.environ.get("MXNET_TELEMETRY_READY", "").strip().lower() \
    not in ("0", "false", "off", "no")
_ready_reason = "" if _ready else "starting (MXNET_TELEMETRY_READY=0)"


def mark_ready(flag, reason=""):
    """Set the process-level readiness flag (the starting/stopping
    states a liveness probe must not see as dead)."""
    global _ready, _ready_reason
    _ready = bool(flag)
    _ready_reason = reason if not flag else ""


def is_ready():
    """(ready, reasons): the /readyz verdict — the process flag AND
    every live serving engine accepting admissions. Importable for
    in-process checks; never CREATES anything."""
    reasons = []
    if not _ready:
        reasons.append(_ready_reason or "marked not ready")
    srv_mod = sys.modules.get("mxnet_tpu.serving.engine")
    # getattr guard: a scrape can land DURING package import, when the
    # module is in sys.modules but not yet initialized
    live = getattr(srv_mod, "live_engines", None) if srv_mod else None
    if live is not None:
        for e in live():
            if not e.accepting():
                reasons.append("serving engine %#x draining" % id(e))
    return not reasons, reasons


def running():
    """True while the HTTP server thread is serving."""
    return _thread is not None and _thread.is_alive()


def port():
    """The bound TCP port, or None when the server is off (the useful
    accessor under ``MXNET_TELEMETRY_HTTP=0`` ephemeral-port tests)."""
    b = _bound
    return b[1] if b else None


def parse_spec(raw):
    """``MXNET_TELEMETRY_HTTP`` value -> (host, port) or None (off).
    Accepts ``<port>`` (loopback) or ``<host>:<port>``."""
    raw = (raw or "").strip()
    if not raw:
        return None
    host, sep, p = raw.rpartition(":")
    if not sep:
        host, p = "127.0.0.1", raw
    try:
        p = int(p)
    except ValueError:
        logging.warning("mxdash: MXNET_TELEMETRY_HTTP=%r is not a port "
                        "(or host:port); introspection server disabled", raw)
        return None
    if p < 0:
        return None
    return host or "127.0.0.1", p


def configure(spec):
    """Apply an endpoint spec ((host, port) tuple or None). Idempotent:
    the same spec keeps the running server (and its ephemeral port);
    a changed spec (including None) stops it first. Called from
    ``telemetry.reload()`` — never starts anything unless telemetry is
    enabled AND a spec is given."""
    global _server, _thread, _bound, _started_t
    with _lock:
        srv, thread = _server, _thread
        same = srv is not None and getattr(srv, "_mxdash_spec", None) == spec
    if same:
        return
    # stop outside the module lock: shutdown() blocks on the serve loop
    if srv is not None:
        srv.shutdown()
        srv.server_close()
        if thread is not None:
            thread.join()
        with _lock:
            _server = _thread = _bound = _started_t = None
    if spec is None:
        return
    new_srv = _build(spec)
    if new_srv is None:
        return
    t = threading.Thread(target=new_srv.serve_forever, name="mxtel-http",
                         daemon=True)
    with _lock:
        _server, _thread = new_srv, t
        _bound = new_srv.server_address[:2]
        _started_t = time.time()
    t.start()


def _build(spec):
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    class _Handler(BaseHTTPRequestHandler):
        # a scrape loop must not spam the job's stderr
        def log_message(self, fmt, *args):
            pass

        def do_GET(self):
            path, _, query = self.path.partition("?")
            fn = _ROUTES.get(path.rstrip("/") or "/")
            if fn is None:
                self._send(404, "text/plain; charset=utf-8",
                           "unknown endpoint %r\nknown: %s\n"
                           % (path, " ".join(sorted(_ROUTES))))
                return
            try:
                out = fn(_params(query))
            except Exception as e:  # introspection must never kill the job
                logging.exception("mxdash: %s handler failed", path)
                self._send(500, "text/plain; charset=utf-8",
                           "%s: %s\n" % (type(e).__name__, e))
                return
            # handlers return (ctype, body) for 200, or
            # (code, ctype, body) — /readyz answers 503 when draining
            if len(out) == 3:
                code, ctype, body = out
            else:
                code, (ctype, body) = 200, out
            self._send(code, ctype, body)

        def _send(self, code, ctype, body):
            data = body.encode("utf-8")
            self.send_response(code)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(data)))
            self.end_headers()
            try:
                self.wfile.write(data)
            except OSError:
                pass  # scraper hung up mid-reply

    try:
        srv = ThreadingHTTPServer(spec, _Handler)
    except OSError as e:
        logging.warning("mxdash: cannot bind %s:%d (%s); introspection "
                        "server disabled", spec[0], spec[1], e)
        return None
    srv.daemon_threads = True
    srv._mxdash_spec = spec
    return srv


def _params(query):
    out = {}
    for part in query.split("&"):
        if "=" in part:
            k, _, v = part.partition("=")
            out[k] = v
    return out


# -- endpoint bodies -----------------------------------------------------------
def _json(obj):
    return ("application/json", json.dumps(obj, indent=1, default=str) + "\n")


def _healthz(params):
    return ("text/plain; charset=utf-8", "ok\n")


def _readyz(params):
    """Readiness split from liveness (docs/how_to/control_plane.md): a
    draining or still-starting replica is alive (200 /healthz) but not
    accepting work (503 here) — external probes and the mxctl
    controller must not conflate the two."""
    ready, reasons = is_ready()
    if ready:
        return ("text/plain; charset=utf-8", "ready\n")
    return (503, "text/plain; charset=utf-8",
            "not ready: %s\n" % "; ".join(reasons))


def _metrics(params):
    from . import export as _export

    return ("text/plain; version=0.0.4; charset=utf-8",
            _export.prometheus_text())


def _statusz(params):
    from . import _T0 as _proc_t0  # telemetry subsystem import time

    snap = _registry.default_registry().snapshot()
    compile_counters = {k: v for k, v in snap["counters"].items()
                        if k.startswith("compile.")}
    jc = sys.modules.get("mxnet_tpu.compile.jit_cache")
    if jc is not None:
        # plain-int mirrors: live even across registry resets and in
        # telemetry-off subprocesses (jit_cache.HITS/MISSES/CORRUPT)
        for name in ("HITS", "MISSES", "CORRUPT"):
            compile_counters["compile.jit_cache_%s" % name.lower()] = \
                int(getattr(jc, name, 0))
    # mxjit verifier snapshot (per-boundary compile counts vs budgets,
    # D2H ledger) — only when the module is live and armed, never an
    # import from here
    cv = sys.modules.get("mxnet_tpu.analysis.compile_verify")
    jit_verify = (cv.summary() if cv is not None
                  and getattr(cv, "ENABLED", False) else None)
    return _json({
        "pid": os.getpid(),
        "rank": int(os.environ.get("MXNET_PROC_ID", "0") or 0),
        "world": int(os.environ.get("MXNET_NUM_PROCS", "1") or 1),
        "uptime_s": time.time() - _proc_t0,
        "server_uptime_s": (time.time() - _started_t
                            if _started_t is not None else None),
        "journal": _journal_path(),
        "jit_cache_dir": os.environ.get("MXNET_COMPILE_CACHE_DIR") or None,
        "compile": compile_counters,
        "jit_verify": jit_verify,
        "env": {k: v for k, v in sorted(os.environ.items())
                if k.startswith(("MXNET_", "MXRACE_", "JAX_PLATFORMS"))},
    })


def _journal_path():
    from . import export as _export

    return _export.journal_path()


def _tracez(params):
    try:
        n = max(1, int(params.get("n", "64")))
    except ValueError:
        n = 64
    return _json({
        "open": _tracing.open_spans(),
        "recent": _tracing.span_tail(n),
        "aggregates": _tracing.span_aggregates(),
    })


def _enginez(params):
    eng_mod = sys.modules.get("mxnet_tpu.engine")
    eng = getattr(eng_mod, "Engine", None) if eng_mod else None
    inst = getattr(eng, "_instance", None) if eng else None
    if inst is None:
        # introspection must never CREATE the engine singleton: a scrape
        # of a process that never pushed host work reports exactly that
        return _json({"engine": None})
    snap = inst.pending_snapshot()
    snap.update({
        "engine": inst.engine_type,
        "native": inst.is_native,
    })
    counters = _registry.default_registry().snapshot()["counters"]
    snap["counters"] = {k: v for k, v in counters.items()
                       if k.startswith("engine.")}
    return _json(snap)


def _profilez(params):
    """mxprof live attribution (docs/how_to/profiling.md). Answers with
    ``enabled: false`` (not an error) when MXNET_PROF is unset — a
    scraper can always tell "off" from "down"."""
    from . import prof as _prof

    try:
        n = max(1, int(params.get("n", "20")))
    except ValueError:
        n = 20
    return _json(_prof.snapshot(top=n))


def _servingz(params):
    srv_mod = sys.modules.get("mxnet_tpu.serving.engine")
    if srv_mod is None:
        return _json({"engines": []})
    return _json({"engines": [e.introspect()
                              for e in srv_mod.live_engines()]})


_ROUTES = {
    "/": lambda p: ("text/plain; charset=utf-8",
                    "mxdash endpoints: %s\n" % " ".join(
                        sorted(k for k in _ROUTES if k != "/"))),
    "/healthz": _healthz,
    "/readyz": _readyz,
    "/metrics": _metrics,
    "/statusz": _statusz,
    "/tracez": _tracez,
    "/enginez": _enginez,
    "/servingz": _servingz,
    "/profilez": _profilez,
}
