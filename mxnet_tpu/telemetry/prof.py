"""mxprof: continuous performance & memory attribution (``MXNET_PROF=1``).

mxtel/mxdash record *that* time passed — spans, counters, merged rank
timelines — but nothing attributes *where* a training or serving step's
time and HBM actually go. mxprof is that attribution layer, and like the
rest of the telemetry subsystem it is always available and **off by
default**: with ``MXNET_PROF`` unset every instrumented site reduces to
one module-bool check (the same contract as ``telemetry.ENABLED``).

Three views, all keyed consistently:

1. **Per-program cost records.** Call sites that hold a jitted program
   and its example arguments (the Executor's fused fwd+bwd, the scanned
   fit trainer's K-step loop, the serving model's bucketed ragged step)
   hand them to :func:`attribute_jit`, which AOT-lowers and compiles
   ONCE, folds in XLA's ``compiled.cost_analysis()`` (flops, bytes
   accessed) and ``compiled.memory_analysis()`` (argument/output/temp
   bytes — the program's static HBM footprint), and returns the
   compiled callable so the attribution compile IS the program's one
   compile (no double build). Records are keyed
   ``<compile.config_key()>|<site signature>`` — the same configuration
   key the PR 6 persistent jit cache dirs hash, so a program's cost
   record and its cache entry describe the same executable.

2. **Analytic graph cost.** :func:`graph_cost` walks a Symbol DAG with
   the jax-free IR utilities (``compile/ir.py``: shape/dtype sweeps)
   and computes per-node FLOPs/bytes from the op metadata alone — no
   device, no jax import. The per-op table is what `/profilez` and the
   report tool render; the totals cross-check XLA's numbers (the
   analytic-vs-XLA agreement gate in tests/unittest/test_mxprof.py).

3. **Step-time decomposition.** The train and serving step paths feed
   :func:`note_step` fenced sub-phase durations — ``host`` (input
   prep/staging), ``dispatch`` (submitting the compiled program),
   ``device`` (block-until-ready delta: time truly blocked on the
   accelerator), ``d2h`` (result pull + metric fence), ``update``
   (optimizer/kvstore, per-batch path only). Each call lands a
   ``{"kind": "prof", "event": "step_breakdown"}`` journal record plus
   ``prof.step.<phase>_secs`` histograms, and classifies the step as
   input-/compute-/host-bound — a first-class per-rank signal
   ``tools/trace_merge.py`` merges (``prof_rows``).

Derived headline metrics — MFU against the chip's bf16 peak and
roofline% against the HBM-bandwidth bound, the derivations bench.py and
bench_lm.py previously hard-coded — live here (:func:`peak_flops`,
:func:`hbm_gbps`, :func:`derived`) so `/profilez`, the bench legs and
``tools/perf_gate.py`` all share one definition.

Enablement::

    MXNET_PROF=1                    # master switch (off by default)
    MXNET_PROF_PEAK_FLOPS=1.97e14   # optional: chip peak override
    MXNET_PROF_HBM_GBPS=819         # optional: HBM bandwidth override

With ``MXNET_TELEMETRY=1`` as well, prof metrics land in the registry /
journal / ``/profilez``; prof alone still accumulates its in-process
program and step tables (``snapshot()``).
"""
from __future__ import annotations

import logging
import os
import threading
import time

__all__ = [
    "ENABLED", "reload", "reset",
    "graph_cost", "attribute_jit", "program_records",
    "note_step", "step_summary",
    "peak_flops", "hbm_gbps", "hbm_stats", "derived", "snapshot",
    "DEFAULT_PEAK_BF16", "DEFAULT_HBM_GBPS", "ROOFLINE_IMG_S",
    "PHASES",
]

log = logging.getLogger("mxnet_tpu.prof")

#: v5e chip bf16 peak (docs/perf_analysis.md) — the MFU denominator
#: bench_lm.py has always used, promoted here so every consumer shares
#: one number.
DEFAULT_PEAK_BF16 = 197e12
#: v5e HBM bandwidth (GB/s) — the roofline denominator.
DEFAULT_HBM_GBPS = 819.0
#: ResNet-50 bs=128 bf16 HBM roofline on one v5e chip: ~190 MB of
#: activation traffic per image at 819 GB/s ≈ 3,400 img/s at perfect
#: overlap (docs/perf_analysis.md "Roofline") — bench.py's derivation.
ROOFLINE_IMG_S = 3400.0

#: the fenced sub-phases a step decomposes into (note_step keys)
PHASES = ("host", "dispatch", "device", "d2h", "update")

#: phase -> boundedness verdict when it dominates the step
_BOUND_BY_PHASE = {
    "host": "input",      # staging/input prep dominates: input-bound
    "dispatch": "host",   # python dispatch overhead dominates
    "device": "compute",  # blocked on the accelerator: compute-bound
    "d2h": "host",        # result pull / metric fence dominates
    "update": "host",
}

ENABLED = False

_lock = threading.Lock()
#: key -> program record dict (attribute_jit)
_programs = {}
#: key -> compiled callable (attribute_jit memo; separate from the
#: json-able record so snapshot() never trips over an executable)
_compiled = {}
#: path -> {"count", "batches", "phases": {p: total}, "total": s,
#:          "bound": {verdict: count}}
_steps = {}
#: monotonic stamp of the last derived-gauge refresh (note_step
#: throttles the derived()/hbm_stats() recomputation — a per-decode-
#: step program-table scan + device memory_stats query would tax
#: ms-scale steps for a gauge nobody reads faster than ~1 Hz)
_GAUGE_REFRESH_SECS = 1.0
_last_gauge_t = 0.0
#: fresh attribute_jit compiles performed (NOT memo hits). Step
#: instrumentation snapshots this around a step and skips the
#: breakdown record when it advanced: a first-dispatch XLA compile
#: (seconds) inside the timed window would otherwise dominate the
#: phase shares and misclassify short runs as input/host-bound.
_attr_compiles = 0


def attribution_count():
    """Number of fresh AOT compiles attribute_jit has performed —
    call sites bracket a step with it to drop compile-polluted
    breakdown records."""
    return _attr_compiles


def _env_on(name):
    return os.environ.get(name, "").strip().lower() not in (
        "", "0", "false", "off", "no")


def reload():
    """Re-read ``MXNET_PROF``; called from ``telemetry.reload()`` so
    tests toggle via monkeypatch.setenv + telemetry.reload()."""
    global ENABLED
    ENABLED = _env_on("MXNET_PROF")
    return ENABLED


def reset():
    """Drop program/step state (test isolation; rides
    ``telemetry.reset()``)."""
    global _last_gauge_t
    with _lock:
        _programs.clear()
        _compiled.clear()
        _steps.clear()
        _last_gauge_t = 0.0  # next note_step refreshes the gauges


# -- derived-metric constants -------------------------------------------------
def peak_flops():
    """The chip's peak FLOP/s for MFU derivation:
    ``MXNET_PROF_PEAK_FLOPS`` override, else the v5e bf16 peak. On a
    CPU container the default is aspirational — the derived MFU is then
    a consistency signal (did it regress), not an absolute one."""
    raw = os.environ.get("MXNET_PROF_PEAK_FLOPS", "").strip()
    if raw:
        try:
            return float(raw)
        except ValueError:
            pass
    return DEFAULT_PEAK_BF16


def hbm_gbps():
    """HBM bandwidth (GB/s) for roofline%: ``MXNET_PROF_HBM_GBPS``
    override, else the v5e figure."""
    raw = os.environ.get("MXNET_PROF_HBM_GBPS", "").strip()
    if raw:
        try:
            return float(raw)
        except ValueError:
            pass
    return DEFAULT_HBM_GBPS


# -- analytic graph cost ------------------------------------------------------
def _prod(shape):
    n = 1
    for d in shape:
        n *= int(d)
    return n


def _itemsize(dt):
    try:
        import numpy as np

        return int(np.dtype(dt).itemsize)
    except Exception:
        return 4


def _node_flops(n, out_shape, in_shapes):
    """Forward FLOPs for one node from its op metadata + shapes (the
    standard conventions: 2·M·N·K for matmuls/convs, a few ops per
    element for normalization/softmax, one per element otherwise)."""
    op = n.op.name
    p = n.params
    if out_shape is None:
        return 0
    size = _prod(out_shape)
    if op in ("Convolution", "Deconvolution"):
        kernel = p.get("kernel") or ()
        group = int(p.get("num_group") or 1)
        # in channels from the data input's shape (NCHW)
        cin = None
        if in_shapes and in_shapes[0] is not None and len(in_shapes[0]) >= 2:
            cin = int(in_shapes[0][1])
        if cin is None or not kernel:
            return 2 * size  # underdetermined: be cheap, not wrong-sign
        return 2 * size * (cin // max(group, 1)) * _prod(kernel)
    if op == "FullyConnected":
        if in_shapes and in_shapes[0] is not None:
            d_in = _prod(in_shapes[0][1:])
            return 2 * size * d_in
        return 2 * size
    if op == "BatchNorm":
        return 8 * size
    if op == "Pooling":
        kernel = p.get("kernel") or ()
        if p.get("global_pool") and in_shapes and in_shapes[0] is not None:
            return _prod(in_shapes[0])
        return size * max(1, _prod(kernel))
    if op in ("SoftmaxOutput", "Softmax", "SoftmaxActivation",
              "LogisticRegressionOutput", "LinearRegressionOutput",
              "MAERegressionOutput", "log_softmax", "softmax"):
        return 5 * size
    if op in ("Concat", "Reshape", "Flatten", "transpose", "SliceChannel",
              "expand_dims", "BlockGrad", "Cast", "_copy"):
        return 0  # pure data movement: bytes, not flops
    return size


def graph_cost(symbol, input_shapes, input_types=None):
    """Analytic per-node FLOPs/bytes for a Symbol graph — jax-free.

    ``input_shapes``: {arg name: shape} seeding the bidirectional shape
    sweep (``compile/ir.py``). Returns::

        {"nodes": [{"name", "op", "flops", "bytes", "out_shape"}...],
         "flops": <forward total>, "flops_train": <~3x forward>,
         "bytes": <total moved>, "params_bytes": <weight footprint>,
         "unresolved": <nodes whose shapes stayed unknown>}

    Nodes whose shapes cannot be recovered contribute zero (and are
    counted in ``unresolved``) — the walk must work on whatever the
    sweep can infer, same contract as graph_lint's shape pass.
    """
    from ..compile import ir

    nodes = symbol.nodes
    name_to_var = {n.name: n for n in nodes if n.is_variable}
    seed = {}
    for name, shape in (input_shapes or {}).items():
        v = name_to_var.get(name)
        if v is not None and shape is not None:
            seed[(id(v), 0)] = tuple(shape)
    shapes = ir.propagate_shapes(nodes, seed)
    tseed = {}
    if input_types:
        import numpy as np

        for name, t in input_types.items():
            v = name_to_var.get(name)
            if v is not None and t is not None:
                tseed[(id(v), 0)] = np.dtype(t)
    dtypes = ir.propagate_dtypes(nodes, tseed)

    out = []
    total_flops = 0
    total_bytes = 0
    unresolved = 0
    params_bytes = 0
    input_names = set(input_shapes or ())
    for n in nodes:
        if n.is_variable:
            s = shapes.get((id(n), 0))
            if s is not None and n.name not in input_names:
                params_bytes += _prod(s) * _itemsize(
                    dtypes.get((id(n), 0), "float32"))
            continue
        out_shape = shapes.get((id(n), 0))
        in_shapes = [shapes.get((id(s), i)) for s, i in n.inputs]
        if out_shape is None:
            unresolved += 1
        flops = _node_flops(n, out_shape, in_shapes)
        nbytes = 0
        for (s, i), sh in zip(n.inputs, in_shapes):
            if sh is not None:
                nbytes += _prod(sh) * _itemsize(
                    dtypes.get((id(s), i), "float32"))
        n_out = len(n.op.list_outputs(n.params))
        for i in range(n_out):
            sh = shapes.get((id(n), i))
            if sh is not None:
                nbytes += _prod(sh) * _itemsize(
                    dtypes.get((id(n), i), "float32"))
        total_flops += flops
        total_bytes += nbytes
        out.append({
            "name": n.name, "op": n.op.name, "flops": int(flops),
            "bytes": int(nbytes),
            "out_shape": list(out_shape) if out_shape is not None else None,
        })
    out.sort(key=lambda r: -r["flops"])
    return {
        "nodes": out,
        "flops": int(total_flops),
        # fwd+bwd ≈ 3x fwd for matmul-dominated graphs (the standard
        # training-FLOPs convention bench_lm.py also counts by)
        "flops_train": int(3 * total_flops),
        "bytes": int(total_bytes),
        "params_bytes": int(params_bytes),
        "unresolved": unresolved,
    }


# -- XLA program attribution --------------------------------------------------
def config_key_prefix():
    """The PR 6 jit-cache configuration key — program records carry it
    so a record and the persistent-cache entry of the same executable
    share a key root."""
    try:
        from .. import compile as _compile

        return _compile.config_key()
    except Exception:
        return "v1|opt=?"


def _cost_dict(compiled):
    """Normalize ``compiled.cost_analysis()`` across jax versions
    (dict, or a 1-list of dicts) to {"flops", "bytes_accessed"}."""
    ca = compiled.cost_analysis()
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else {}
    if not isinstance(ca, dict):
        return {}
    out = {}
    if "flops" in ca:
        out["flops"] = float(ca["flops"])
    if "bytes accessed" in ca:
        out["bytes_accessed"] = float(ca["bytes accessed"])
    return out


def _memory_dict(compiled):
    ma = compiled.memory_analysis()
    out = {}
    for field in ("argument_size_in_bytes", "output_size_in_bytes",
                  "temp_size_in_bytes", "alias_size_in_bytes",
                  "generated_code_size_in_bytes"):
        v = getattr(ma, field, None)
        if v is not None:
            out[field[:-len("_in_bytes")]] = int(v)
    if out:
        # static footprint while the program runs: args + outputs +
        # scratch (aliased/donated buffers counted once, on the
        # argument side)
        out["static_peak"] = (out.get("argument_size", 0)
                              + out.get("output_size", 0)
                              + out.get("temp_size", 0)
                              - out.get("alias_size", 0))
    return out


def graph_hash(text):
    """Short stable hash of a graph-identity string (a
    :func:`symbol_fingerprint`, a config repr) — the component of a
    program key that distinguishes two programs whose shape signatures
    coincide."""
    import hashlib

    return hashlib.sha1(str(text).encode("utf-8", "replace")) \
        .hexdigest()[:12]


def symbol_fingerprint(sym):
    """Graph-identity hash of a Symbol: op names, node names, FULL op
    params and wiring. ``debug_str`` deliberately omits params — but
    two graphs differing only in a param (``act_type=relu`` vs
    ``tanh``) are different programs, and the attribute_jit memo must
    never alias them."""
    lines = []
    for n in sym.nodes:
        ins = ",".join("%s[%d]" % (s.name, i) for s, i in n.inputs)
        if n.is_variable:
            lines.append("var %s %r" % (n.name, sorted(n.attrs.items())))
        else:
            lines.append("%s %s %r %r (%s)" % (
                n.op.name, n.name, sorted(n.params.items()),
                sorted(n.attrs.items()), ins))
    return graph_hash("\n".join(lines))


def attribute_jit(key, jitted, args=(), kwargs=None, site="",
                  analytic=None, meta=None, graph_key=None):
    """AOT-compile ``jitted`` for ``args`` once, record its XLA cost and
    memory analysis under ``<config_key>|<key>[|g=<graph_key>]``, and
    return the compiled callable — so attribution reuses the program's
    one compile instead of adding a second. Any failure (backend
    without the AOT API, analysis unimplemented) falls back to
    returning ``jitted`` unchanged with whatever partial record could
    be built; this function never raises into a training or serving
    step.

    ``graph_key`` is REQUIRED for correctness whenever two different
    programs could share a shape signature: the memo returns the cached
    compiled executable for a repeated key, so the key must capture the
    program's identity (graph structure / config), not just its
    argument shapes — callers pass :func:`graph_hash` of the symbol's
    ``debug_str`` or the model config. ``analytic``: an optional
    :func:`graph_cost` result to fold into the record (the per-op table
    `/profilez` renders). ``meta``: free-form json-able context
    (shapes, bucket, K).
    """
    full_key = "%s|%s" % (config_key_prefix(), key)
    if graph_key:
        full_key += "|g=%s" % graph_key
    with _lock:
        cached = _compiled.get(full_key)
    if cached is not None:
        return cached
    global _attr_compiles
    _attr_compiles += 1
    rec = {
        "key": full_key, "site": site or key, "t": time.time(),
        "calls": 0, "device_secs": 0.0,
        "meta": dict(meta or {}),
    }
    fn = jitted
    try:
        lowered = jitted.lower(*args, **(kwargs or {}))
        compiled = lowered.compile()
        fn = compiled
        try:
            rec.update(_cost_dict(compiled))
        except Exception as e:
            rec["cost_error"] = "%s: %s" % (type(e).__name__, e)
        try:
            rec["memory"] = _memory_dict(compiled)
        except Exception as e:
            rec["memory_error"] = "%s: %s" % (type(e).__name__, e)
    except Exception as e:
        # no AOT path (or tracing rejected the args): keep the jitted
        # callable, record what we know
        rec["lower_error"] = "%s: %s" % (type(e).__name__, e)
        log.debug("mxprof: attribute_jit(%s) fell back to the jitted "
                  "callable: %s", key, e)
    if analytic is not None:
        rec["analytic"] = {
            "flops": analytic.get("flops"),
            "flops_train": analytic.get("flops_train"),
            "bytes": analytic.get("bytes"),
            "params_bytes": analytic.get("params_bytes"),
            "top_ops": analytic.get("nodes", [])[:12],
        }
    with _lock:
        _programs[full_key] = rec
        _compiled[full_key] = fn
    _emit(dict(rec, kind="prof", event="program"))
    return fn


def program_records(top=None):
    """Program records sorted by accumulated device seconds (then
    flops) — the `/profilez` "top programs" table."""
    with _lock:
        recs = [dict(r) for r in _programs.values()]
    recs.sort(key=lambda r: (-r.get("device_secs", 0.0),
                             -(r.get("flops") or 0)))
    return recs if top is None else recs[:top]


def program_key_for(key, graph_key=None):
    """The full (config-prefixed) key attribute_jit stored ``key``
    under (same ``graph_key`` as the attribute_jit call) — call sites
    pass it back to :func:`note_step`."""
    full_key = "%s|%s" % (config_key_prefix(), key)
    if graph_key:
        full_key += "|g=%s" % graph_key
    return full_key


# -- step-time decomposition --------------------------------------------------
def _emit(record):
    from . import export as _export

    _export.emit(record)


def note_step(path, phases, key=None, batches=1, samples=None,
              tokens=None, d2h_bytes=None):
    """Record one decomposed step (or K-batch chunk).

    ``phases``: {phase: seconds} with phases from :data:`PHASES` —
    absent phases simply don't apply to this path. Accumulates the
    per-path aggregate, attributes the ``device`` phase to the program
    record under ``key``, observes ``prof.step.<phase>_secs`` +
    ``prof.step_secs`` histograms and refreshes the derived gauges
    (``prof.mfu`` etc.) when telemetry is on, and emits one
    ``step_breakdown`` journal record. ``d2h_bytes`` (optional) is the
    number of result bytes the step actually pulled device->host — the
    serving decode path journals it so the "logits never leave the
    device" contract is mechanically checkable (ISSUE 15: a decode
    step's pull is the token vector, not a [B, V] logits array).
    Callers guard on :data:`ENABLED`; calling this with prof off is a
    no-op."""
    if not ENABLED:
        return None
    total = sum(phases.values())
    dominant = max(phases, key=lambda p: phases[p]) if phases else None
    bound = _BOUND_BY_PHASE.get(dominant, "unknown")
    with _lock:
        st = _steps.get(path)
        if st is None:
            st = _steps[path] = {
                "count": 0, "batches": 0, "total": 0.0,
                "phases": {}, "bound": {},
            }
        st["count"] += 1
        st["batches"] += int(batches)
        st["total"] += total
        for p, v in phases.items():
            st["phases"][p] = st["phases"].get(p, 0.0) + float(v)
        st["bound"][bound] = st["bound"].get(bound, 0) + 1
        if key is not None:
            prog = _programs.get(key)
            if prog is not None:
                prog["calls"] += 1
                prog["device_secs"] += float(phases.get("device", 0.0))
    from .. import telemetry as _tel

    if _tel.ENABLED:
        _tel.histogram("prof.step_secs").observe(total)
        for p, v in phases.items():
            _tel.histogram("prof.step.%s_secs" % p).observe(v)
        global _last_gauge_t
        now = time.monotonic()
        with _lock:
            # the throttle stamp is written under the module lock
            # everywhere (reset() holds it too); the derived()/
            # memory_stats work below stays outside the critical
            # section — only the claim of this refresh window is locked
            refresh = now - _last_gauge_t >= _GAUGE_REFRESH_SECS
            if refresh:
                _last_gauge_t = now
        if refresh:
            d = derived()
            if d.get("mfu") is not None:
                _tel.gauge("prof.mfu").set(d["mfu"])
            if d.get("roofline_pct") is not None:
                _tel.gauge("prof.roofline_pct").set(d["roofline_pct"])
            hbm = hbm_stats()
            if hbm.get("live_bytes") is not None:
                _tel.gauge("prof.hbm_live_bytes").set(hbm["live_bytes"])
            if hbm.get("peak_bytes") is not None:
                _tel.gauge("prof.hbm_peak_bytes").set(hbm["peak_bytes"])
    rec = {
        "kind": "prof", "event": "step_breakdown", "t": time.time(),
        "path": path, "batches": int(batches), "total_s": total,
        "phases": {p: float(v) for p, v in phases.items()},
        "bound": bound,
    }
    if key is not None:
        rec["key"] = key
    if samples is not None and total > 0:
        rec["samples_per_s"] = samples / total
    if tokens is not None and total > 0:
        rec["tokens_per_s"] = tokens / total
    if d2h_bytes is not None:
        rec["d2h_bytes"] = int(d2h_bytes)
    _emit(rec)
    return rec


def step_summary():
    """{path: aggregate} — per-path phase totals, mean shares, and the
    majority boundedness verdict."""
    with _lock:
        out = {}
        for path, st in _steps.items():
            total = st["total"] or 1e-12
            shares = {p: v / total for p, v in st["phases"].items()}
            verdict = max(st["bound"], key=lambda b: st["bound"][b]) \
                if st["bound"] else None
            out[path] = {
                "count": st["count"], "batches": st["batches"],
                "total_s": st["total"],
                "phases_s": dict(st["phases"]),
                "phase_share": shares,
                "bound": verdict,
                "bound_votes": dict(st["bound"]),
            }
        return out


# -- derived metrics + HBM ----------------------------------------------------
def hbm_stats():
    """{"live_bytes", "peak_bytes", "source"} — the device allocator's
    view when the backend exposes ``memory_stats()`` (TPU/GPU), else a
    static estimate from the attributed programs' memory analyses
    (args+outputs+temp of the largest program)."""
    try:
        import jax

        dev = jax.local_devices()[0]
        ms = dev.memory_stats()
        if ms and "bytes_in_use" in ms:
            return {
                "live_bytes": int(ms.get("bytes_in_use", 0)),
                "peak_bytes": int(ms.get("peak_bytes_in_use",
                                         ms.get("bytes_in_use", 0))),
                "source": "device",
            }
    except Exception:
        pass
    with _lock:
        peaks = [r.get("memory", {}).get("static_peak")
                 for r in _programs.values()]
    peaks = [p for p in peaks if p]
    if peaks:
        return {"live_bytes": None, "peak_bytes": max(peaks),
                "source": "static_estimate"}
    return {"live_bytes": None, "peak_bytes": None, "source": "none"}


def derived():
    """Headline derivations over the attributed programs:

    - ``mfu``: executed FLOPs / device seconds / chip peak, over every
      program with measured device time (the bench_lm derivation,
      continuous);
    - ``roofline_pct``: achieved bytes/s as % of HBM bandwidth — the
      bench.py ResNet roofline generalized to whatever ran;
    - per-program ``mfu`` on the top entry.
    """
    with _lock:
        recs = [dict(r) for r in _programs.values()]
    flops_done = 0.0
    bytes_done = 0.0
    dev_secs = 0.0
    for r in recs:
        calls, ds = r.get("calls", 0), r.get("device_secs", 0.0)
        if not calls or ds <= 0:
            continue
        if r.get("flops"):
            flops_done += r["flops"] * calls
        if r.get("bytes_accessed"):
            bytes_done += r["bytes_accessed"] * calls
        dev_secs += ds
    out = {
        "peak_flops": peak_flops(),
        "hbm_gbps": hbm_gbps(),
        "roofline_img_s": ROOFLINE_IMG_S,
        "device_secs": dev_secs,
        "mfu": None,
        "roofline_pct": None,
    }
    if dev_secs > 0 and flops_done > 0:
        out["mfu"] = flops_done / dev_secs / peak_flops()
        out["tflops"] = flops_done / dev_secs / 1e12
    if dev_secs > 0 and bytes_done > 0:
        out["roofline_pct"] = (100.0 * bytes_done / dev_secs
                               / (hbm_gbps() * 1e9))
    return out


def snapshot(top=20):
    """The `/profilez` body: program table, step decomposition, derived
    MFU/roofline, HBM view. Valid (``enabled: false``) when prof is
    off — introspection never errors."""
    return {
        "enabled": ENABLED,
        "config_key": config_key_prefix(),
        "programs": program_records(top=top),
        "steps": step_summary(),
        "derived": derived(),
        "hbm": hbm_stats(),
    }


reload()
