"""mxtel: runtime observability — metrics registry, span tracer, journal.

The reference framework's observability story is host-side EvalMetric
updates plus LOG(INFO) lines (SURVEY §5.5); its only timeline hook is
the profiler's xplane capture. mxtel adds the third leg production
runtimes rely on: an always-available, *off-by-default* structured
record of what the runtime actually did — counters/gauges/histograms
per layer (registry.py), nested spans (tracing.py), and a JSONL run
journal + Prometheus/console exporters (export.py). The engine, kvstore,
executor, IO pipeline, and the training loops all report in; the
resilience layer's retries, fault fires, and watchdogs do too, so a
chaos run can *prove* which recovery paths exercised
(tools/chaos.py).

Enablement contract::

    MXNET_TELEMETRY=1                 # master switch (off by default)
    MXNET_TELEMETRY_JOURNAL=run.jsonl # optional JSONL run journal
    MXNET_TELEMETRY_FLUSH_SECS=10     # journal flush cadence
    MXNET_TELEMETRY_HTTP=8321         # optional live introspection
                                      # server (mxdash, server.py):
                                      # /metrics /healthz /statusz
                                      # /tracez /enginez /servingz
                                      # /profilez
    MXNET_PROF=1                      # mxprof attribution layer
                                      # (prof.py, its own off-by-default
                                      # switch; docs/how_to/profiling.md)

Instrumented hot paths guard on the module attribute ``ENABLED``::

    from . import telemetry as _tel
    ...
    if _tel.ENABLED:
        _tel.counter("engine.push_total").inc()

so the disabled cost is one attribute read + truth test per site and
``span()`` returns a shared null context. ``reload()`` re-reads the
environment (tests toggle via monkeypatch.setenv + reload()).

Render a journal with ``tools/telemetry_report.py``; the metrics
catalog lives in docs/how_to/observability.md.
"""
from __future__ import annotations

import os
import time as _time

from . import registry as _registry_mod
from . import tracing
from . import export
from . import server
from . import prof
from .registry import Counter, Gauge, Histogram, Registry, default_registry
from .tracing import (
    span, current_span, span_aggregates, span_tail,
    wire_context, mint_trace, open_spans, event,
)
from .export import (
    console_summary, flush_at_exit, journal_path, prometheus_text,
)

__all__ = [
    "ENABLED", "enabled", "reload", "reset", "flush",
    "counter", "gauge", "histogram", "span", "current_span",
    "span_aggregates", "span_tail", "snapshot",
    "wire_context", "mint_trace", "open_spans", "event",
    "Counter", "Gauge", "Histogram", "Registry", "default_registry",
    "console_summary", "prometheus_text", "journal_path", "flush_at_exit",
    "prof",
]

#: subsystem import time — /statusz uptime (telemetry is imported at
#: package init, so this is ~process start)
_T0 = _time.time()

#: Master switch. Instrumentation reads this ONE attribute; everything
#: else in the subsystem sits behind it.
ENABLED = False


def enabled():
    return ENABLED


def _env_on(name):
    return os.environ.get(name, "").strip().lower() not in (
        "", "0", "false", "off", "no")


def reload():
    """Re-read MXNET_TELEMETRY / MXNET_TELEMETRY_JOURNAL /
    MXNET_TELEMETRY_FLUSH_SECS / MXNET_TELEMETRY_HTTP and apply them.
    Called once at import; tests call it after mutating the
    environment."""
    global ENABLED
    ENABLED = _env_on("MXNET_TELEMETRY")
    path = os.environ.get("MXNET_TELEMETRY_JOURNAL", "").strip() or None
    if not ENABLED:
        path = None
    raw = os.environ.get("MXNET_TELEMETRY_FLUSH_SECS", "").strip()
    try:
        flush_secs = float(raw) if raw else None
    except ValueError:
        flush_secs = None
    export.configure(path, flush_secs)
    # live introspection server (mxdash): gated on BOTH the master
    # switch and the endpoint var — off means no thread and no socket
    http_spec = server.parse_spec(
        os.environ.get("MXNET_TELEMETRY_HTTP")) if ENABLED else None
    server.configure(http_spec)
    # mxprof (prof.py) has its own master switch (MXNET_PROF) but rides
    # the same reload cycle so one env round-trip configures both
    prof.reload()
    return ENABLED


def counter(name):
    """Process-wide named Counter (created on first use)."""
    return _registry_mod.default_registry().counter(name)


def gauge(name):
    """Process-wide named Gauge."""
    return _registry_mod.default_registry().gauge(name)


def histogram(name, capacity=Histogram.DEFAULT_CAPACITY):
    """Process-wide named Histogram (ring-buffer reservoir)."""
    return _registry_mod.default_registry().histogram(name, capacity)


def snapshot():
    """Plain-data snapshot of every registered metric."""
    return _registry_mod.default_registry().snapshot()


def flush(mark=None):
    """Flush buffered journal records (plus a metrics snapshot when
    ``mark`` is given). No-op without an active journal."""
    export.flush(mark=mark)


def reset():
    """Drop all metric and finished-span state (test isolation — the
    suite fixture calls this between tests). Does not touch the
    enable flag or the journal file."""
    _registry_mod.default_registry().reset()
    tracing.reset()
    prof.reset()


reload()
