"""Process-wide metric registry: Counters, Gauges, streaming Histograms.

The reference's entire always-on observability is EvalMetric updates plus
LOG(INFO) lines (SURVEY §5.5); production dataflow runtimes pair trace
capture with structured counters (TensorFlow couples its runtime with
counters/timelines for the same reason, arXiv:1605.08695). This registry
is the structured half of mxtel: named metrics any runtime layer can
update cheaply, snapshotted by the exporters (export.py).

Design constraints, in priority order:

1. The *disabled* fast path in instrumented code is a single module-bool
   check (``telemetry.ENABLED``) — nothing here is ever reached.
2. The *enabled* path is a dict lookup + a locked integer/float update;
   Histogram keeps a fixed ring-buffer reservoir (no allocation per
   observe) and computes exact p50/p95/p99 over the reservoir on read.
3. Everything is thread-safe: engine worker threads, the prefetch
   producer, and the kvstore heartbeat thread all report concurrently.
"""
from __future__ import annotations

import bisect
import threading

import numpy as _np

__all__ = ["Counter", "Gauge", "Histogram", "Registry", "default_registry"]


class Counter:
    """Monotonically increasing count (events, bytes, retries)."""

    __slots__ = ("name", "_value", "_lock")

    kind = "counter"

    def __init__(self, name):
        self.name = name
        self._value = 0
        self._lock = threading.Lock()

    def inc(self, n=1):
        # += on an int is read-modify-write, not atomic across threads
        with self._lock:
            self._value += n

    @property
    def value(self):
        return self._value

    def summary(self):
        return self._value


class Gauge:
    """Last-write-wins instantaneous value (queue depth, samples/sec)."""

    __slots__ = ("name", "_value", "_lock")

    kind = "gauge"

    def __init__(self, name):
        self.name = name
        self._value = 0.0
        self._lock = threading.Lock()

    def set(self, v):
        with self._lock:
            self._value = float(v)

    def inc(self, n=1):
        with self._lock:
            self._value += n

    def dec(self, n=1):
        with self._lock:
            self._value -= n

    @property
    def value(self):
        return self._value

    def summary(self):
        return self._value


class Histogram:
    """Streaming distribution over a fixed ring-buffer reservoir.

    ``observe()`` is O(1) and allocation-free: the newest ``capacity``
    observations live in a preallocated float64 ring; count/sum/min/max
    run over the full stream. Percentiles are computed on read by
    sorting the reservoir — *exact* over the window (the last
    ``capacity`` observations), which is the useful answer for runtime
    latencies: recent behavior, not epoch-0 compile spikes forever.
    """

    __slots__ = ("name", "capacity", "_buf", "_n", "_sum", "_min", "_max",
                 "_bucket_counts", "_lock")

    kind = "histogram"

    DEFAULT_CAPACITY = 2048
    QUANTILES = (50.0, 95.0, 99.0)
    #: fixed Prometheus bucket upper bounds (seconds-oriented, covering
    #: sub-ms engine tasks through multi-minute epochs); the terminal
    #: +Inf bucket is implicit (``bucket_counts`` appends it). Fixed
    #: bounds — unlike the reservoir percentiles — aggregate correctly
    #: across scrapes and ranks, which is what makes the ``_bucket``
    #: exposition families on /metrics real histograms.
    BOUNDS = (0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
              0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0, 300.0)

    def __init__(self, name, capacity=DEFAULT_CAPACITY):
        if capacity < 1:
            raise ValueError("histogram capacity must be >= 1, got %r"
                             % (capacity,))
        self.name = name
        self.capacity = int(capacity)
        self._buf = _np.empty(self.capacity, dtype=_np.float64)
        self._n = 0
        self._sum = 0.0
        self._min = None
        self._max = None
        # per-bound observation counts (non-cumulative; +Inf overflow
        # bucket last) — cumulated on read, O(1) per observe
        self._bucket_counts = [0] * (len(self.BOUNDS) + 1)
        self._lock = threading.Lock()

    def observe(self, v):
        v = float(v)
        idx = bisect.bisect_left(self.BOUNDS, v)
        with self._lock:
            self._buf[self._n % self.capacity] = v
            self._n += 1
            self._sum += v
            self._bucket_counts[idx] += 1
            if self._min is None or v < self._min:
                self._min = v
            if self._max is None or v > self._max:
                self._max = v

    def bucket_counts(self):
        """[(upper_bound, cumulative_count)] over the FULL stream (not
        the reservoir window), Prometheus ``le`` semantics — the last
        entry is ``(inf, total count)``."""
        with self._lock:
            counts = list(self._bucket_counts)
        out = []
        cum = 0
        for le, c in zip(self.BOUNDS + (float("inf"),), counts):
            cum += c
            out.append((le, cum))
        return out

    @property
    def count(self):
        return self._n

    @property
    def sum(self):
        return self._sum

    def _window(self):
        """Sorted copy of the reservoir contents (under the lock)."""
        with self._lock:
            filled = min(self._n, self.capacity)
            win = self._buf[:filled].copy()
        win.sort()
        return win

    def percentile(self, q):
        """Exact q-th percentile of the reservoir window, linearly
        interpolated between order statistics (numpy's default method,
        so tests can diff against ``np.percentile`` directly)."""
        win = self._window()
        n = win.shape[0]
        if n == 0:
            return None
        pos = (q / 100.0) * (n - 1)
        lo = int(pos)
        frac = pos - lo
        if lo + 1 >= n:
            return float(win[-1])
        return float(win[lo] * (1.0 - frac) + win[lo + 1] * frac)

    def percentiles(self, qs=QUANTILES):
        win = self._window()
        n = win.shape[0]
        out = {}
        for q in qs:
            if n == 0:
                out[q] = None
                continue
            pos = (q / 100.0) * (n - 1)
            lo = int(pos)
            frac = pos - lo
            if lo + 1 >= n:
                out[q] = float(win[-1])
            else:
                out[q] = float(win[lo] * (1.0 - frac) + win[lo + 1] * frac)
        return out

    def summary(self):
        with self._lock:
            count, total = self._n, self._sum
            mn, mx = self._min, self._max
        ps = self.percentiles()
        return {
            "count": count, "sum": total, "min": mn, "max": mx,
            "p50": ps[50.0], "p95": ps[95.0], "p99": ps[99.0],
        }


class Registry:
    """Named metric table. ``counter/gauge/histogram`` get-or-create;
    asking for an existing name with a different kind is a bug and
    raises (two layers silently sharing one metric under different
    semantics would corrupt both)."""

    def __init__(self):
        self._metrics = {}
        self._lock = threading.Lock()

    def _get(self, name, cls, **kwargs):
        m = self._metrics.get(name)
        if m is not None:
            if not isinstance(m, cls):
                raise TypeError(
                    "metric %r already registered as %s, not %s"
                    % (name, m.kind, cls.kind))
            return m
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = self._metrics[name] = cls(name, **kwargs)
            elif not isinstance(m, cls):
                raise TypeError(
                    "metric %r already registered as %s, not %s"
                    % (name, m.kind, cls.kind))
            return m

    def counter(self, name):
        return self._get(name, Counter)

    def gauge(self, name):
        return self._get(name, Gauge)

    def histogram(self, name, capacity=Histogram.DEFAULT_CAPACITY):
        return self._get(name, Histogram, capacity=capacity)

    def metrics(self):
        """Stable-order snapshot of the live metric objects."""
        with self._lock:
            return [self._metrics[k] for k in sorted(self._metrics)]

    def snapshot(self):
        """{"counters": {...}, "gauges": {...}, "histograms": {...}} —
        plain data, safe to json-dump (the journal's metrics record)."""
        out = {"counters": {}, "gauges": {}, "histograms": {}}
        for m in self.metrics():
            out[m.kind + "s"][m.name] = m.summary()
        return out

    def reset(self):
        """Drop every metric (test isolation; conftest fixture)."""
        with self._lock:
            self._metrics.clear()


_default = Registry()


def default_registry():
    return _default
