"""Nested span tracer: monotonic-clock durations, thread-local nesting.

``span("name")`` opens a scope; on exit the finished span (name, start,
duration, id, parent id, thread) is recorded into a bounded in-memory
tail, folded into a per-name aggregate (count / total / max — the
"top spans by total time" table), and appended to the run journal when
one is active (export.py).

Parent ids propagate through a thread-local stack: spans opened on the
same thread nest naturally. Work handed to another thread (the prefetch
producer, an engine worker) inherits by *explicit* capture — the
dispatching side reads :func:`current_span` and passes it as
``span(name, parent=...)`` on the worker; an implicit ambient-context
hand-off would misattribute unrelated threads' work the moment two jobs
share a pool.

Spans also forward into :func:`mxnet_tpu.profiler.scope` while the
profiler is capturing, so the same names land in the xplane timeline —
mxtel is the always-on record, xplane stays the deep-dive view.

Every span belongs to a **trace**: root spans mint a process-unique
``trace`` id, children inherit it through the nesting chain, and
:func:`wire_context` / ``span(name, wire=...)`` carry it across an RPC
boundary (the elastic coordinator protocol attaches it to its request
envelope) so a server-side handler's spans land in the *caller's*
trace. ``tools/trace_merge.py`` stitches per-rank journals back into one
timeline on these ids.

When telemetry is disabled ``span()`` hands back one shared
null context: a single flag check, no allocation.
"""
from __future__ import annotations

import collections
import itertools
import os
import sys
import threading
import time
from contextlib import nullcontext as _nullcontext

__all__ = ["span", "current_span", "span_aggregates", "span_tail", "reset",
           "wire_context", "mint_trace", "open_spans", "event"]

_NULL = _nullcontext()
_ids = itertools.count(1)
_trace_ids = itertools.count(1)
_tls = threading.local()

# finished spans, newest last (bounded: tooling reads the journal for the
# full stream; this tail serves console summaries and tests)
_TAIL_MAX = 4096
_tail = collections.deque(maxlen=_TAIL_MAX)
# name -> [count, total_secs, max_secs]
_agg = {}
# id -> record of every span currently OPEN (entered, not yet exited) —
# the /tracez introspection endpoint's live view
_open = {}
_lock = threading.Lock()


def mint_trace():
    """A new process-unique trace id. The pid prefix keeps ids from
    different ranks of one job distinct, so merged timelines never
    alias two ranks' traces."""
    return "%x-%x" % (os.getpid(), next(_trace_ids))


def _stack():
    s = getattr(_tls, "stack", None)
    if s is None:
        s = _tls.stack = []
    return s


def current_span():
    """Id of the innermost open span on this thread, or None. Capture
    this before dispatching work to another thread and pass it as
    ``span(..., parent=...)`` there to keep the nesting chain."""
    s = getattr(_tls, "stack", None)
    return s[-1] if s else None


def wire_context():
    """Trace context of the innermost open span on this thread as a
    plain picklable dict (``{"trace": str, "span": int}``), or None
    when no span is open. Attach it to an RPC request so the server
    side can open child spans with ``span(name, wire=ctx)`` — the
    cross-*process* analog of ``parent=``."""
    s = getattr(_tls, "stack", None)
    if not s:
        return None
    sid = s[-1]
    with _lock:
        rec = _open.get(sid)
    if rec is None:
        return None
    return {"trace": rec["trace"], "span": sid}


class _Span:
    __slots__ = ("name", "id", "parent", "trace", "remote_parent",
                 "_t0", "_wall", "_prof")

    def __init__(self, name, parent, wire=None):
        self.name = name
        self.id = next(_ids)
        self.parent = parent
        self.trace = None
        self.remote_parent = None
        if wire:
            self.trace = wire.get("trace")
            self.remote_parent = wire.get("span")
        self._t0 = 0.0
        self._wall = 0.0
        self._prof = None

    def __enter__(self):
        stack = _stack()
        if self.parent is None and stack:
            self.parent = stack[-1]
        # trace inheritance: explicit wire context wins, else the
        # parent's trace (parent may live on another thread — the
        # open-span table is the lookup), else mint a fresh root trace
        if self.trace is None and self.parent is not None:
            with _lock:
                prec = _open.get(self.parent)
            if prec is not None:
                self.trace = prec["trace"]
        if self.trace is None:
            self.trace = mint_trace()
        stack.append(self.id)
        # forward into the xplane timeline only while a capture runs —
        # TraceAnnotation costs a jax call per span otherwise. The
        # sys.modules probe (not an import) keeps light processes — the
        # standalone elastic coordinator — from paying the full package
        # import just because telemetry is on.
        _profiler = sys.modules.get("mxnet_tpu.profiler")
        if _profiler is not None and _profiler.state() == "run":
            self._prof = _profiler.scope(self.name)
            self._prof.__enter__()
        self._wall = time.time()
        self._t0 = time.monotonic()
        with _lock:
            _open[self.id] = {
                "name": self.name, "id": self.id, "parent": self.parent,
                "trace": self.trace, "t": self._wall,
                "thread": threading.current_thread().name,
            }
        return self

    def __exit__(self, exc_type, exc, tb):
        dur = time.monotonic() - self._t0
        if self._prof is not None:
            self._prof.__exit__(exc_type, exc, tb)
            self._prof = None
        stack = _stack()
        if stack and stack[-1] == self.id:
            stack.pop()
        rec = {
            "kind": "span", "name": self.name, "id": self.id,
            "parent": self.parent, "trace": self.trace,
            "t": self._wall, "dur": dur,
            "thread": threading.current_thread().name,
        }
        if self.remote_parent is not None:
            rec["remote_parent"] = self.remote_parent
        with _lock:
            _open.pop(self.id, None)
            _tail.append(rec)
            a = _agg.get(self.name)
            if a is None:
                _agg[self.name] = [1, dur, dur]
            else:
                a[0] += 1
                a[1] += dur
                if dur > a[2]:
                    a[2] = dur
        from . import export as _export

        _export.emit(rec)
        return False


def span(name, parent=None, wire=None):
    """Open a named span. A context manager; cheap no-op when telemetry
    is off. ``parent`` overrides the thread-local nesting (cross-thread
    propagation); ``wire`` adopts a remote caller's trace context (a
    :func:`wire_context` dict that crossed an RPC boundary) — the
    span's trace id and remote parent come from the caller's process,
    so merged timelines keep the causal chain."""
    from . import ENABLED

    if not ENABLED:
        return _NULL
    return _Span(name, parent, wire=wire)


def event(name, t=None, dur=0.0, trace=None, parent=None, **fields):
    """Record one span with *explicit* timestamps (epoch seconds) —
    lifecycle events reconstructed after the fact, like a serving
    request's submit/prefill/decode/complete phases, where the phases
    are known only once the request finishes. Lands in the tail, the
    per-name aggregates, and the journal exactly like a context-manager
    span. No-op when telemetry is off."""
    from . import ENABLED

    if not ENABLED:
        return None
    rec = {
        "kind": "span", "name": name, "id": next(_ids), "parent": parent,
        "trace": trace if trace is not None else mint_trace(),
        "t": time.time() if t is None else float(t), "dur": float(dur),
        "thread": threading.current_thread().name,
    }
    rec.update(fields)
    with _lock:
        _tail.append(rec)
        a = _agg.get(name)
        if a is None:
            _agg[name] = [1, rec["dur"], rec["dur"]]
        else:
            a[0] += 1
            a[1] += rec["dur"]
            if rec["dur"] > a[2]:
                a[2] = rec["dur"]
    from . import export as _export

    _export.emit(rec)
    return rec


def open_spans():
    """Snapshot of every currently open span (entered, not yet exited),
    each with an ``age_s`` field — the /tracez live view."""
    now = time.time()
    with _lock:
        recs = [dict(r) for r in _open.values()]
    for r in recs:
        r["age_s"] = now - r["t"]
    return sorted(recs, key=lambda r: r["id"])


def span_aggregates():
    """{name: {"count": n, "total": secs, "max": secs}} over every
    finished span since the last reset — the top-spans table's data."""
    with _lock:
        return {k: {"count": v[0], "total": v[1], "max": v[2]}
                for k, v in _agg.items()}


def span_tail(n=None):
    """The newest ``n`` finished span records (all retained if None)."""
    with _lock:
        recs = list(_tail)
    return recs if n is None else recs[-n:]


def reset():
    """Drop finished-span state (test isolation). Open spans on live
    threads are untouched — they complete into the fresh tables."""
    with _lock:
        _tail.clear()
        _agg.clear()
