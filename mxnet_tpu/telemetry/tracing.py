"""Nested span tracer: monotonic-clock durations, thread-local nesting.

``span("name")`` opens a scope; on exit the finished span (name, start,
duration, id, parent id, thread) is recorded into a bounded in-memory
tail, folded into a per-name aggregate (count / total / max — the
"top spans by total time" table), and appended to the run journal when
one is active (export.py).

Parent ids propagate through a thread-local stack: spans opened on the
same thread nest naturally. Work handed to another thread (the prefetch
producer, an engine worker) inherits by *explicit* capture — the
dispatching side reads :func:`current_span` and passes it as
``span(name, parent=...)`` on the worker; an implicit ambient-context
hand-off would misattribute unrelated threads' work the moment two jobs
share a pool.

Spans also forward into :func:`mxnet_tpu.profiler.scope` while the
profiler is capturing, so the same names land in the xplane timeline —
mxtel is the always-on record, xplane stays the deep-dive view.

When telemetry is disabled ``span()`` hands back one shared
null context: a single flag check, no allocation.
"""
from __future__ import annotations

import collections
import itertools
import threading
import time
from contextlib import nullcontext as _nullcontext

__all__ = ["span", "current_span", "span_aggregates", "span_tail", "reset"]

_NULL = _nullcontext()
_ids = itertools.count(1)
_tls = threading.local()

# finished spans, newest last (bounded: tooling reads the journal for the
# full stream; this tail serves console summaries and tests)
_TAIL_MAX = 4096
_tail = collections.deque(maxlen=_TAIL_MAX)
# name -> [count, total_secs, max_secs]
_agg = {}
_lock = threading.Lock()


def _stack():
    s = getattr(_tls, "stack", None)
    if s is None:
        s = _tls.stack = []
    return s


def current_span():
    """Id of the innermost open span on this thread, or None. Capture
    this before dispatching work to another thread and pass it as
    ``span(..., parent=...)`` there to keep the nesting chain."""
    s = getattr(_tls, "stack", None)
    return s[-1] if s else None


class _Span:
    __slots__ = ("name", "id", "parent", "_t0", "_wall", "_prof")

    def __init__(self, name, parent):
        self.name = name
        self.id = next(_ids)
        self.parent = parent
        self._t0 = 0.0
        self._wall = 0.0
        self._prof = None

    def __enter__(self):
        stack = _stack()
        if self.parent is None and stack:
            self.parent = stack[-1]
        stack.append(self.id)
        # forward into the xplane timeline only while a capture runs —
        # TraceAnnotation costs a jax call per span otherwise
        from .. import profiler as _profiler

        if _profiler.state() == "run":
            self._prof = _profiler.scope(self.name)
            self._prof.__enter__()
        self._wall = time.time()
        self._t0 = time.monotonic()
        return self

    def __exit__(self, exc_type, exc, tb):
        dur = time.monotonic() - self._t0
        if self._prof is not None:
            self._prof.__exit__(exc_type, exc, tb)
            self._prof = None
        stack = _stack()
        if stack and stack[-1] == self.id:
            stack.pop()
        rec = {
            "kind": "span", "name": self.name, "id": self.id,
            "parent": self.parent, "t": self._wall, "dur": dur,
            "thread": threading.current_thread().name,
        }
        with _lock:
            _tail.append(rec)
            a = _agg.get(self.name)
            if a is None:
                _agg[self.name] = [1, dur, dur]
            else:
                a[0] += 1
                a[1] += dur
                if dur > a[2]:
                    a[2] = dur
        from . import export as _export

        _export.emit(rec)
        return False


def span(name, parent=None):
    """Open a named span. A context manager; cheap no-op when telemetry
    is off. ``parent`` overrides the thread-local nesting (cross-thread
    propagation — see module docstring)."""
    from . import ENABLED

    if not ENABLED:
        return _NULL
    return _Span(name, parent)


def span_aggregates():
    """{name: {"count": n, "total": secs, "max": secs}} over every
    finished span since the last reset — the top-spans table's data."""
    with _lock:
        return {k: {"count": v[0], "total": v[1], "max": v[2]}
                for k, v in _agg.items()}


def span_tail(n=None):
    """The newest ``n`` finished span records (all retained if None)."""
    with _lock:
        recs = list(_tail)
    return recs if n is None else recs[-n:]


def reset():
    """Drop finished-span state (test isolation). Open spans on live
    threads are untouched — they complete into the fresh tables."""
    with _lock:
        _tail.clear()
        _agg.clear()
