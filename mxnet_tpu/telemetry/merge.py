"""Multi-rank journal merging: clock alignment, straggler attribution,
Chrome trace export.

Each rank of an elastic job writes its own JSONL journal (per-rank
``{rank}`` templating in tools/launch.py); this module stitches N of
them into ONE timeline:

1. **Clock alignment.** Every rank's wall clock drifts independently;
   naively overlaying journals misorders events across ranks. The
   elastic client journals ``clock`` records for fast coordinator RPCs
   — ``(t0, t1, srv_t)`` where t0/t1 bracket the round trip on the
   caller's clock and srv_t is the coordinator's clock at reply time.
   Each sample bounds the offset to ``srv_t - (t0+t1)/2`` within half
   the RTT (the classic NTP estimate); the per-rank offset is the
   median over all samples, and every rank maps onto the
   *coordinator's* clock: ``t_aligned = t + offset``.

2. **Barrier-wait vs compute attribution.** The elastic kvstore wraps
   its blocked-on-peers time in ``kvstore.round_wait`` /
   ``kvstore.barrier_wait`` spans (WAIT_SPANS). Summing those inside
   each rank's ``epoch`` span splits the epoch into wait and compute —
   the rank everyone else waits ON shows the *least* wait (it is the
   straggler); a killed rank's journal simply stops (truncation is the
   strongest straggler signal of all).

3. **Chrome trace-event export.** ``chrome_trace()`` renders the merged
   timeline as Chrome trace-event JSON (one "process" per rank, one
   "thread" per journal thread), loadable directly in Perfetto
   (https://ui.perfetto.dev) — the workflow documented in
   docs/how_to/observability.md.

Pure stdlib (json/math) so tools/trace_merge.py and
tools/telemetry_report.py can import it without the jax stack.
"""
from __future__ import annotations

import json
import os
import re

__all__ = ["WAIT_SPANS", "load_journal", "clock_offset", "merge",
           "epoch_rows", "straggler_report", "cross_rank_rows",
           "prof_rows", "chrome_trace", "render_summary"]

#: span names that mean "blocked waiting on peers" (not computing)
WAIT_SPANS = ("kvstore.round_wait", "kvstore.barrier_wait")

_RANK_RE = re.compile(r"(\d+)\.jsonl$")


def load_journal(path):
    """One journal -> {"path", "rank", "records"}. Bad lines (a rank
    SIGKILLed mid-write leaves a torn tail) are skipped, not fatal; a
    missing file is an empty journal (the killed-before-first-flush
    case). Rank comes from the journal's own ``meta`` record, falling
    back to a trailing ``<digits>.jsonl`` in the file name."""
    records = []
    if os.path.exists(path):
        with open(path, "r", encoding="utf-8") as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    records.append(json.loads(line))
                except ValueError:
                    pass
    rank = None
    for r in records:
        if r.get("kind") == "meta" and "rank" in r:
            rank = int(r["rank"])
            break
    if rank is None:
        m = _RANK_RE.search(os.path.basename(path))
        if m:
            rank = int(m.group(1))
    return {"path": path, "rank": rank, "records": records}


def clock_offset(records):
    """(median offset to the coordinator clock, sample count). Offset
    0.0 with no samples — single-host runs share a clock anyway."""
    offs = sorted(
        r["srv_t"] - (r["t0"] + r["t1"]) / 2.0
        for r in records
        if r.get("kind") == "clock" and "srv_t" in r)
    if not offs:
        return 0.0, 0
    n = len(offs)
    mid = n // 2
    med = offs[mid] if n % 2 else (offs[mid - 1] + offs[mid]) / 2.0
    return med, n


def merge(paths):
    """Merge journals into one clock-aligned timeline.

    Returns ``{"ranks": {rank: info}, "spans": [...]}`` where each span
    record gains ``rank`` and ``t_aligned`` (coordinator-clock start)
    and the merged list is sorted by aligned start time. ``info`` per
    rank: path, offset, clock_samples, spans, records, last_t (aligned
    time of the journal's final record — the truncation signal)."""
    ranks = {}
    for i, path in enumerate(paths):
        j = load_journal(path)
        rank = j["rank"] if j["rank"] is not None else i
        while rank in ranks:  # duplicate/unknown ranks never clobber
            rank += len(paths)
        off, n = clock_offset(j["records"])
        spans = []
        last_t = None
        for r in j["records"]:
            t = r.get("t") or r.get("t1")
            if t is not None:
                at = t + off
                last_t = at if last_t is None else max(last_t, at)
            if r.get("kind") == "span":
                s = dict(r)
                s["rank"] = rank
                s["t_aligned"] = r["t"] + off
                spans.append(s)
        ranks[rank] = {
            "path": path, "offset": off, "clock_samples": n,
            "spans": spans, "records": j["records"], "last_t": last_t,
        }
    merged = sorted((s for info in ranks.values() for s in info["spans"]),
                    key=lambda s: s["t_aligned"])
    return {"ranks": ranks, "spans": merged}


def epoch_rows(merged):
    """Per (rank, epoch-index) attribution rows: each rank's n-th
    ``epoch`` span split into barrier-wait (WAIT_SPANS inside the epoch
    window) and compute."""
    rows = []
    for rank in sorted(merged["ranks"]):
        spans = merged["ranks"][rank]["spans"]
        epochs = sorted((s for s in spans if s["name"] == "epoch"),
                        key=lambda s: s["t_aligned"])
        waits = [s for s in spans if s["name"] in WAIT_SPANS]
        batches = [s for s in spans if s["name"] in ("batch", "chunk")]
        for i, ep in enumerate(epochs):
            lo, hi = ep["t_aligned"], ep["t_aligned"] + ep["dur"]
            wait = sum(s["dur"] for s in waits
                       if lo <= s["t_aligned"] <= hi)
            nb = sum(1 for s in batches if lo <= s["t_aligned"] <= hi)
            rows.append({
                "rank": rank, "epoch": i, "start": lo, "dur": ep["dur"],
                "wait_s": wait, "compute_s": max(0.0, ep["dur"] - wait),
                "wait_frac": (wait / ep["dur"]) if ep["dur"] > 0 else 0.0,
                "batches": nb,
            })
    return rows


def straggler_report(merged, rows=None):
    """Who was everyone waiting on?

    Three signals, strongest first:

    - **truncation** — a rank whose journal stops well before the
      merged horizon was killed (or wedged): the ultimate straggler;
    - **incomplete epochs** — a rank that closed fewer ``epoch`` spans
      than its peers dropped out mid-run (an epoch span only lands on
      exit, so a killed rank's final epoch never closes);
    - **least wait** — per epoch, the rank with the smallest
      barrier-wait total is the one its peers rendezvoused on.

    Returns {"straggler": rank|None, "truncated": [...],
    "incomplete": [...],
    "per_epoch": [{"epoch", "straggler", "waits": {rank: s}}]}.
    """
    rows = epoch_rows(merged) if rows is None else rows
    last = {r: info["last_t"] for r, info in merged["ranks"].items()
            if info["last_t"] is not None}
    truncated = []
    if last:
        horizon = max(last.values())
        starts = [s["t_aligned"] for s in merged["spans"]]
        length = (horizon - min(starts)) if starts else 0.0
        gate = max(2.0, 0.2 * length)
        truncated = sorted(r for r, t in last.items()
                           if horizon - t > gate)
    epochs_per_rank = {r: 0 for r in merged["ranks"]}
    for row in rows:
        epochs_per_rank[row["rank"]] = max(
            epochs_per_rank.get(row["rank"], 0), row["epoch"] + 1)
    incomplete = []
    if epochs_per_rank and len(set(epochs_per_rank.values())) > 1:
        most = max(epochs_per_rank.values())
        incomplete = sorted(r for r, n in epochs_per_rank.items()
                            if n < most)
    per_epoch = []
    by_epoch = {}
    for row in rows:
        by_epoch.setdefault(row["epoch"], {})[row["rank"]] = row
    for ep in sorted(by_epoch):
        waits = {r: row["wait_s"] for r, row in by_epoch[ep].items()}
        if len(waits) < 2:
            continue
        straggler = min(waits, key=lambda r: (waits[r], r))
        per_epoch.append({"epoch": ep, "straggler": straggler,
                          "waits": waits})
    overall = None
    if truncated:
        overall = truncated[0]
    elif incomplete:
        overall = incomplete[0]
    elif per_epoch:
        votes = {}
        for e in per_epoch:
            votes[e["straggler"]] = votes.get(e["straggler"], 0) + 1
        overall = max(sorted(votes), key=lambda r: votes[r])
    # boundedness labels from the mxprof step-breakdown rows
    # (MXNET_PROF=1): an *input*-bound "straggler" is input starvation
    # — the fix is the data plane (shards, credits, prefetch), not
    # evict-replace — so the attribution carries the distinction
    # instead of letting a stalled input pipeline read as a slow rank.
    # Verdicts are weighted by each path's total seconds: a rank's few
    # host-bound eval steps must not outvote its dominant training path.
    votes = {}
    for row in prof_rows(merged):
        b = row.get("bound")
        if b:
            w = votes.setdefault(row["rank"], {})
            w[b] = w.get(b, 0.0) + float(row.get("total_s") or 0.0) \
                + 1e-12
    bounds = {rank: max(sorted(w), key=lambda b: w[b])
              for rank, w in votes.items()}
    return {"straggler": overall, "truncated": truncated,
            "incomplete": incomplete, "per_epoch": per_epoch,
            "bounds": bounds,
            "straggler_bound": bounds.get(overall)}


def cross_rank_rows(merged):
    """Per-rank summary for telemetry_report's cross-rank section:
    span/batch counts, epoch count, total barrier wait, and the final
    snapshot's ``train.step_secs`` p50."""
    out = []
    for rank in sorted(merged["ranks"]):
        info = merged["ranks"][rank]
        spans = info["spans"]
        final = None
        for r in info["records"]:
            if r.get("kind") == "metrics":
                final = r
        step_p50 = None
        if final:
            h = final.get("histograms", {}).get("train.step_secs")
            if h:
                step_p50 = h.get("p50")
        out.append({
            "rank": rank, "path": info["path"],
            "offset_s": info["offset"],
            "clock_samples": info["clock_samples"],
            "spans": len(spans),
            "batches": sum(1 for s in spans
                           if s["name"] in ("batch", "chunk")),
            "epochs": sum(1 for s in spans if s["name"] == "epoch"),
            "wait_s": sum(s["dur"] for s in spans
                          if s["name"] in WAIT_SPANS),
            "step_p50_s": step_p50,
            "last_t": info["last_t"],
        })
    return out


def fold_breakdowns(records):
    """Fold ``prof.step_breakdown`` journal records (MXNET_PROF=1,
    docs/how_to/profiling.md) into per-path aggregates:
    ``{path: {count, batches, total, phases: {p: secs},
    bound: {verdict: votes}}}``. THE one implementation of this fold —
    telemetry_report's profiling section and the cross-rank
    :func:`prof_rows` both consume it, so the single-journal and merged
    reports can never disagree about the same records."""
    per_path = {}
    for r in records:
        if r.get("kind") != "prof" or r.get("event") != "step_breakdown":
            continue
        st = per_path.setdefault(r.get("path", "?"), {
            "count": 0, "batches": 0, "total": 0.0, "phases": {},
            "bound": {}})
        st["count"] += 1
        st["batches"] += r.get("batches", 1)
        st["total"] += r.get("total_s", 0.0)
        for p, v in (r.get("phases") or {}).items():
            st["phases"][p] = st["phases"].get(p, 0.0) + v
        b = r.get("bound", "?")
        st["bound"][b] = st["bound"].get(b, 0) + 1
    return per_path


def prof_rows(merged):
    """Per-(rank, path) mxprof step-breakdown attribution rows — the
    cross-rank form of the ``prof.step_breakdown`` journal records.
    Each row: phase-share percentages plus the majority
    input/compute/host-bound verdict, so a merged timeline says not
    just WHO straggled but what kind of bound each rank ran at."""
    rows = []
    for rank in sorted(merged["ranks"]):
        per_path = fold_breakdowns(merged["ranks"][rank]["records"])
        for path in sorted(per_path):
            st = per_path[path]
            tot = st["total"] or 1e-12
            rows.append({
                "rank": rank, "path": path, "steps": st["count"],
                "batches": st["batches"], "total_s": st["total"],
                "phase_share": {p: v / tot
                                for p, v in st["phases"].items()},
                "bound": max(st["bound"], key=lambda b: st["bound"][b])
                if st["bound"] else None,
            })
    return rows


def chrome_trace(merged):
    """Chrome trace-event JSON (Perfetto-loadable): one process per
    rank, one thread per journal thread, one complete ("X") event per
    span with the trace id in args."""
    spans = merged["spans"]
    t0 = min((s["t_aligned"] for s in spans), default=0.0)
    events = []
    tids = {}
    for rank in sorted(merged["ranks"]):
        events.append({"ph": "M", "name": "process_name", "pid": rank,
                       "tid": 0, "args": {"name": "rank %d" % rank}})
    for s in spans:
        key = (s["rank"], s.get("thread", "MainThread"))
        tid = tids.get(key)
        if tid is None:
            tid = tids[key] = sum(1 for k in tids if k[0] == s["rank"])
            events.append({"ph": "M", "name": "thread_name",
                           "pid": s["rank"], "tid": tid,
                           "args": {"name": key[1]}})
        args = {"trace": s.get("trace"), "id": s.get("id")}
        if s.get("parent") is not None:
            args["parent"] = s["parent"]
        if s.get("remote_parent") is not None:
            args["remote_parent"] = s["remote_parent"]
        events.append({
            "ph": "X", "name": s["name"], "pid": s["rank"], "tid": tid,
            "ts": (s["t_aligned"] - t0) * 1e6,
            "dur": max(0.0, s.get("dur", 0.0)) * 1e6,
            "cat": s["name"].split(".")[0],
            "args": args,
        })
    return {"traceEvents": events, "displayTimeUnit": "ms"}


def render_summary(merged, top_traces=5):
    """Human-readable merged-timeline summary lines (the trace_merge
    CLI's stdout; chaos.py greps the straggler line)."""
    rows = epoch_rows(merged)
    rep = straggler_report(merged, rows)
    lines = ["=== merged timeline (%d ranks, %d spans) ==="
             % (len(merged["ranks"]), len(merged["spans"]))]
    for r in cross_rank_rows(merged):
        lines.append(
            "rank %-3d offset %+8.3fs (%d clock samples)  spans %-6d "
            "batches %-5d epochs %-2d wait %8.3fs"
            % (r["rank"], r["offset_s"], r["clock_samples"], r["spans"],
               r["batches"], r["epochs"], r["wait_s"]))
    if rows:
        lines.append("")
        lines.append("-- per-epoch barrier-wait vs compute --")
        lines.append("  %-5s %-6s %10s %10s %10s %6s %8s" % (
            "rank", "epoch", "dur_s", "wait_s", "compute_s", "wait%",
            "batches"))
        for row in rows:
            lines.append("  %-5d %-6d %10.3f %10.3f %10.3f %5.1f%% %8d" % (
                row["rank"], row["epoch"], row["dur"], row["wait_s"],
                row["compute_s"], 100.0 * row["wait_frac"],
                row["batches"]))
    profs = prof_rows(merged)
    if profs:
        lines.append("")
        lines.append("-- per-rank step decomposition (mxprof) --")
        lines.append("  %-5s %-14s %6s %9s %9s %9s %9s %9s  %s" % (
            "rank", "path", "steps", "host%", "disp%", "dev%", "d2h%",
            "upd%", "bound"))
        for row in profs:
            sh = row["phase_share"]
            lines.append(
                "  %-5d %-14s %6d %8.1f%% %8.1f%% %8.1f%% %8.1f%% "
                "%8.1f%%  %s-bound"
                % (row["rank"], row["path"], row["steps"],
                   100 * sh.get("host", 0.0), 100 * sh.get("dispatch", 0.0),
                   100 * sh.get("device", 0.0), 100 * sh.get("d2h", 0.0),
                   100 * sh.get("update", 0.0), row["bound"]))
    lines.append("")
    if rep["truncated"]:
        lines.append("truncated journals (killed/wedged rank?): %s"
                     % rep["truncated"])
    if rep["incomplete"]:
        lines.append("incomplete-epoch ranks (dropped out mid-run): %s"
                     % rep["incomplete"])
    for e in rep["per_epoch"]:
        lines.append("epoch %d straggler: rank %d (waits: %s)"
                     % (e["epoch"], e["straggler"],
                        {r: round(w, 3)
                         for r, w in sorted(e["waits"].items())}))
    if rep["straggler"] is not None:
        bound = rep.get("straggler_bound")
        note = ""
        if rep["straggler"] in rep["truncated"]:
            note = " (journal truncated — killed?)"
        elif bound == "input":
            # input stall != straggler: the rank is starved by the data
            # plane, not slow — evicting it would fix nothing
            note = (" [input-bound — input starvation, not a compute "
                    "straggler: check the data service (mxdata.* "
                    "stalls), not the rank]")
        elif bound is not None:
            note = " [%s-bound]" % bound
        lines.append("straggler: rank %d%s" % (rep["straggler"], note))
    else:
        lines.append("straggler: none identified")
    return lines
