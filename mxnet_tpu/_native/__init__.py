"""Loader for the framework's native C++ runtime components.

The reference's runtime around the compute path is C++ (engine, recordio
IO, storage — SURVEY §2.1-2.2, §2.9, §2.14); ours keeps the IO/prefetch
layer native too. Components are compiled from ``src/*.cc`` with g++ on
first use into this package directory and loaded via ctypes (no pybind11
in this environment). Set MXNET_NATIVE=0 to force the pure-Python
fallbacks; builds that fail (no compiler) degrade silently the same way.
"""
from __future__ import annotations

import ctypes
import os
import subprocess
import threading

_lock = threading.Lock()
_cache = {}

_PKG_DIR = os.path.dirname(os.path.abspath(__file__))
# repo layout first (editable install / source tree); wheel installs
# ship the sources INSIDE the package (setup.py stages them)
_SRC_DIR = os.path.join(os.path.dirname(os.path.dirname(_PKG_DIR)), "src")
if not os.path.isdir(_SRC_DIR):
    _SRC_DIR = os.path.join(_PKG_DIR, "src")  # wheel: staged by setup.py


def native_disabled():
    return os.environ.get("MXNET_NATIVE", "").strip().lower() in ("0", "false", "off")


def _extra_flags(name):
    """Per-component compile/link flags. c_api embeds CPython
    (src/c_api.cc) and needs the interpreter headers + libpython."""
    if name == "c_api":
        import sysconfig

        inc = sysconfig.get_paths()["include"]
        libdir = sysconfig.get_config_var("LIBDIR") or "/usr/local/lib"
        # LDVERSION carries ABI suffixes (e.g. 3.13t, 3.12d)
        ldver = (sysconfig.get_config_var("LDVERSION")
                 or "%d.%d" % tuple(__import__("sys").version_info[:2]))
        return ["-I" + inc, "-L" + libdir, "-lpython" + ldver,
                "-Wl,-rpath," + libdir]
    if name == "imagedec":
        # the per-pixel augment loop is the single-core bottleneck of the
        # data pipeline (docs/perf_analysis.md); -O3 + unrolling buys real
        # throughput there (-march is deliberately NOT set: the cached .so
        # must stay portable across the fleet's cpu steppings)
        return ["-ljpeg", "-O3", "-funroll-loops"]
    return []


def _build(name):
    src = os.path.join(_SRC_DIR, name + ".cc")
    out = os.path.join(_PKG_DIR, "lib%s.so" % name)
    if not os.path.isfile(src):
        return None
    # cache key = source mtime AND the compile flags: flags are
    # performance-load-bearing (-O3 for imagedec), and a restored tree
    # with preserved timestamps must not keep serving a stale binary
    # built under different flags
    stamp = out + ".flags"
    flags_sig = " ".join(_extra_flags(name))
    stamp_ok = (os.path.isfile(stamp)
                and open(stamp).read() == flags_sig)
    if (os.path.isfile(out) and stamp_ok
            and os.path.getmtime(out) >= os.path.getmtime(src)):
        return out
    # build to a per-pid temp and atomically rename: concurrent launcher
    # workers may race to build, and a half-written .so must never be
    # dlopen-able nor poison future sessions via a fresh mtime
    tmp = "%s.%d.tmp" % (out, os.getpid())
    cmd = [
        "g++", "-O2", "-std=c++17", "-shared", "-fPIC", "-pthread",
        src, "-o", tmp,
    ] + _extra_flags(name)
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
        os.replace(tmp, out)
        with open(stamp, "w") as f:
            f.write(flags_sig)
    except Exception:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        return None
    return out


def load(name):
    """Return the ctypes CDLL for src/<name>.cc, or None if unavailable."""
    if native_disabled():
        return None
    with _lock:
        if name in _cache:
            return _cache[name]
        lib = None
        # the g++ compile runs UNDER the lock on purpose: two threads
        # racing the first use of a component must not race the same
        # .so build (one compiles, everyone else waits for the cache) —
        # vetted blocking-under-lock
        path = _build(name)  # mxlint: disable
        if path is not None:
            try:
                lib = ctypes.CDLL(path)
            except OSError:
                # a wheel may ship a prebuilt .so that doesn't dlopen on
                # this target (glibc/arch mismatch); the staged sources
                # and local toolchain are the designed fallback — force
                # one rebuild before giving up on native
                try:
                    os.unlink(path)
                except OSError:
                    pass
                path = _build(name)  # mxlint: disable (same: serialized rebuild)
                if path is not None:
                    try:
                        lib = ctypes.CDLL(path)
                    except OSError:
                        lib = None
        _cache[name] = lib
        return lib


def recordio_lib():
    """librecordio with argtypes configured; None when native is off."""
    lib = load("recordio")
    if lib is None or getattr(lib, "_rio_configured", False):
        return lib
    c = ctypes
    lib.rio_writer_open.restype = c.c_void_p
    lib.rio_writer_open.argtypes = [c.c_char_p]
    lib.rio_writer_write.restype = c.c_int64
    lib.rio_writer_write.argtypes = [c.c_void_p, c.c_char_p, c.c_uint64]
    lib.rio_writer_tell.restype = c.c_int64
    lib.rio_writer_tell.argtypes = [c.c_void_p]
    lib.rio_writer_close.argtypes = [c.c_void_p]
    lib.rio_reader_open.restype = c.c_void_p
    lib.rio_reader_open.argtypes = [c.c_char_p, c.c_int]
    lib.rio_reader_next.restype = c.c_int
    lib.rio_reader_next.argtypes = [
        c.c_void_p, c.POINTER(c.POINTER(c.c_char)), c.POINTER(c.c_uint64)]
    lib.rio_reader_tell.restype = c.c_uint64
    lib.rio_reader_tell.argtypes = [c.c_void_p]
    lib.rio_reader_seek.argtypes = [c.c_void_p, c.c_uint64]
    lib.rio_reader_reset.argtypes = [c.c_void_p]
    lib.rio_reader_close.argtypes = [c.c_void_p]
    lib._rio_configured = True
    return lib
