"""Measure-and-cache autotuner for contested compilation choices.

Some rewrite decisions have no safe static answer — per-conv layout
(tiny spatial dims or odd channel counts can favor NCHW on some
backends), elementwise segment boundaries, and the matmul accumulation
flag all depend on the actual device. The TVM recipe (PAPERS.md) is to
*measure* the candidates once on the real hardware and remember the
winner: each contested choice is timed as a small jitted program
(compiled, warmed up, best-of-N wall clock with a hard D2H fence — the
same fencing discipline as bench.py), and the winner is persisted in an
on-disk tuning database keyed by ``(choice-kind, op, shapes, dtype,
backend)``.

Database format (``tuning.json`` under ``MXNET_COMPILE_CACHE_DIR``)::

    {"version": 1,
     "entries": {"<key>": {"choice": "...", "timings": {...},
                           "backend": "...", "ts": ...}}}

Reads are cheap and happen on every optimize(); measurement only runs
under ``MXNET_COMPILE_TUNE=1`` (a tuning run is a deliberate,
device-occupying act). A corrupt database never crashes a run: it is
quarantined to ``tuning.json.corrupt`` and counted via
``compile.cache_corrupt_total`` (same fallback contract as the jit
cache, docs/how_to/compilation.md).
"""
from __future__ import annotations

import json
import os
import time

import numpy as _np

from .. import telemetry as _tel

__all__ = ["TuningDB", "Tuner", "make_tuner"]

DB_VERSION = 1

#: process-lifetime counters (exact mirrors of the mxtel counters, kept
#: as plain ints so subprocess probes can report without telemetry on)
TRIALS = 0
CORRUPT = 0


def _count_corrupt():
    global CORRUPT
    CORRUPT += 1
    if _tel.ENABLED:
        _tel.counter("compile.cache_corrupt_total").inc()


def _count_trial():
    global TRIALS
    TRIALS += 1
    if _tel.ENABLED:
        _tel.counter("compile.tuning_trials_total").inc()


class TuningDB:
    """On-disk choice database with crash/corruption-safe semantics:
    atomic replace on write, quarantine + empty-start on unreadable or
    malformed content."""

    def __init__(self, path):
        self.path = path
        self._entries = None

    def _load(self):
        if self._entries is not None:
            return self._entries
        self._entries = {}
        if not os.path.exists(self.path):
            return self._entries
        try:
            with open(self.path, "r") as f:
                data = json.load(f)
            if (not isinstance(data, dict)
                    or data.get("version") != DB_VERSION
                    or not isinstance(data.get("entries"), dict)):
                raise ValueError("malformed tuning db")
            self._entries = dict(data["entries"])
        except (OSError, ValueError) as e:
            # truncated write, bit-flip, wrong version: recompute-able
            # state, so quarantine and start empty — never crash the run
            _count_corrupt()
            try:
                os.replace(self.path, self.path + ".corrupt")
            except OSError:
                pass
            import logging

            logging.getLogger("mxnet_tpu.compile").warning(
                "tuning db %s unreadable (%s); starting empty "
                "(quarantined to .corrupt)", self.path, e)
            self._entries = {}
        return self._entries

    def get(self, key):
        return self._load().get(key)

    def put(self, key, record):
        entries = self._load()
        entries[key] = record
        tmp = "%s.tmp.%d" % (self.path, os.getpid())
        os.makedirs(os.path.dirname(self.path) or ".", exist_ok=True)
        with open(tmp, "w") as f:
            json.dump({"version": DB_VERSION, "entries": entries}, f,
                      indent=1, sort_keys=True)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self.path)

    def __len__(self):
        return len(self._load())


def _fence(value):
    """Hard D2H sync: read 4 bytes of the result. block_until_ready can
    return before compute finishes on the tunneled axon backend — a
    value read cannot (bench.py's fence, same reasoning)."""
    import jax

    leaf = jax.tree_util.tree_leaves(value)[0]
    return _np.asarray(leaf).ravel()[:1]


def measure(fn, args, warmup=2, iters=5):
    """Best-of-N wall time of ``jit(fn)(*args)`` with hard fencing.
    One call = one tuning trial (counted)."""
    import jax

    return measure_calls(jax.jit(fn), args, warmup=warmup, iters=iters)


def measure_calls(f, args, warmup=2, iters=5):
    """Time an already-prepared callable (jitted program or a chain of
    dispatches) best-of-N with warmup and hard fencing. One call = one
    tuning trial (counted)."""
    _count_trial()
    r = None
    for _ in range(max(1, warmup)):
        r = f(*args)
    _fence(r)
    best = float("inf")
    for _ in range(max(1, iters)):
        t0 = time.perf_counter()
        r = f(*args)
        _fence(r)
        best = min(best, time.perf_counter() - t0)
    return best


class Tuner:
    """Decision point used by the rewrite passes.

    ``measure_enabled=False`` (the default outside MXNET_COMPILE_TUNE=1)
    makes the tuner read-only: recorded winners are honored, unknown
    keys fall back to ``default`` without touching the device."""

    def __init__(self, db, measure_enabled=False, backend=None):
        self.db = db
        self.measure_enabled = measure_enabled
        self._backend = backend

    @property
    def backend(self):
        if self._backend is None:
            import jax

            self._backend = jax.default_backend()
        return self._backend

    def pick(self, key, candidates, default):
        """``candidates``: dict choice-name -> zero-arg thunk returning
        measured seconds. Returns the winning choice name."""
        rec = self.db.get(key) if self.db is not None else None
        if rec is not None and rec.get("choice") in candidates:
            return rec["choice"]
        if not self.measure_enabled:
            return default
        timings = {}
        for name, thunk in candidates.items():
            try:
                timings[name] = thunk()
            except Exception as e:
                import logging

                logging.getLogger("mxnet_tpu.compile").warning(
                    "tuning candidate %s for %s failed (%s: %s); skipped",
                    name, key, type(e).__name__, e)
        if not timings:
            return default
        choice = min(timings, key=timings.get)
        if self.db is not None:
            self.db.put(key, {
                "choice": choice,
                "timings": {k: round(v, 6) for k, v in timings.items()},
                "backend": self.backend,
                "ts": time.time(),
            })
        return choice

    # -- the contested choices -------------------------------------------------
    def pick_conv_layout(self, params, dshape, dtype=None):
        """'nhwc' or 'nchw' for one Convolution, keyed by its full
        problem statement. Measures fwd+bwd (training is the dominant
        consumer) of the bare conv in each layout."""
        if dshape is None:
            return "nchw"
        dt = str(_np.dtype(dtype)) if dtype is not None else "float32"
        k = tuple(params.get("kernel") or ())
        key = "conv_layout|d=%s|k=%s|s=%s|p=%s|dl=%s|f=%s|g=%s|dt=%s|b=%s" % (
            tuple(dshape), k, tuple(params.get("stride") or ()),
            tuple(params.get("pad") or ()),
            tuple(params.get("dilate") or ()), params.get("num_filter"),
            params.get("num_group", 1), dt, self.backend)

        def _variant(nhwc):
            def run():
                import jax
                import jax.numpy as jnp

                from ..ops import nn as _nn

                rng = _np.random.RandomState(0)
                nsp = len(dshape) - 2
                kk = _nn._pair(k, nsp)
                cin = dshape[1]
                nf = int(params.get("num_filter"))
                g = int(params.get("num_group", 1) or 1)
                w = jnp.asarray(
                    rng.rand(nf, cin // g, *kk), _np.dtype(dt))
                x_nchw = rng.rand(*dshape).astype(_np.dtype(dt))
                stride = _nn._pair(params.get("stride") or (1,) * nsp, nsp)
                pad = _nn._pair(params.get("pad") or (0,) * nsp, nsp)
                dil = _nn._pair(params.get("dilate") or (1,) * nsp, nsp)
                if nhwc:
                    x = jnp.asarray(x_nchw.transpose(0, 2, 3, 1))
                    wt = jnp.transpose(w, (2, 3, 1, 0))
                    dn = ("NHWC", "HWIO", "NHWC")
                else:
                    x = jnp.asarray(x_nchw)
                    wt = w
                    dn = ("NCHW", "OIHW", "NCHW")

                def loss(wt_):
                    import jax.lax as lax

                    o = lax.conv_general_dilated(
                        x, wt_, stride, [(p, p) for p in pad],
                        rhs_dilation=dil, dimension_numbers=dn,
                        feature_group_count=g)
                    return jnp.sum(o * o)

                def step(wt_):
                    import jax

                    return jax.value_and_grad(loss)(wt_)

                return measure(step, (wt,))
            return run

        return self.pick(key, {"nchw": _variant(False),
                               "nhwc": _variant(True)}, default="nhwc")

    def pick_segment_boundary(self, op_names, shape):
        """'whole' or 'split' for an elementwise chain: fuse the chain
        into one segment or split it at the midpoint. Keyed by the op
        signature and shape."""
        key = "seg_boundary|ops=%s|d=%s|b=%s" % (
            "+".join(op_names), tuple(shape), self.backend)

        def _variant(split):
            def run():
                import jax
                import jax.numpy as jnp

                x = jnp.asarray(
                    _np.random.RandomState(0).rand(*shape), _np.float32)
                n = len(op_names)

                def chain(v, count):
                    for i in range(count):
                        v = jnp.tanh(v) if i % 2 else jnp.maximum(v, 0) * 1.01
                    return v

                if split:
                    # two separate dispatches — the segment-boundary cost
                    # being contested; an outer jit would fuse them away
                    f1 = jax.jit(lambda v: chain(v, n // 2))
                    f2 = jax.jit(lambda v: chain(v, n - n // 2))
                    return measure_calls(lambda v: f2(f1(v)), (x,))
                return measure(lambda v: chain(v, n), (x,))
            return run

        return self.pick(key, {"whole": _variant(False),
                               "split": _variant(True)}, default="whole")

    def pick_matmul_precision(self, dshape, num_hidden, dtype=None):
        """'f32' (preferred_element_type=float32, the framework default)
        or 'fast' (backend-default accumulation) for one FullyConnected
        problem."""
        dt = str(_np.dtype(dtype)) if dtype is not None else "float32"
        key = "matmul_prec|d=%s|h=%s|dt=%s|b=%s" % (
            tuple(dshape), num_hidden, dt, self.backend)

        def _variant(f32):
            def run():
                import jax.numpy as jnp

                rng = _np.random.RandomState(0)
                flat = int(_np.prod(dshape[1:]))
                x = jnp.asarray(rng.rand(dshape[0], flat), _np.dtype(dt))
                w = jnp.asarray(rng.rand(num_hidden, flat), _np.dtype(dt))

                def f(x_, w_):
                    if f32:
                        return jnp.dot(x_, w_.T,
                                       preferred_element_type=jnp.float32)
                    return jnp.dot(x_, w_.T)

                return measure(f, (x, w))
            return run

        return self.pick(key, {"f32": _variant(True),
                               "fast": _variant(False)}, default="f32")


def make_tuner(cache_dir, measure_enabled):
    """Build the pipeline's tuner, or None when there is nowhere to
    persist decisions and measurement is off (a memory-only tuner that
    re-times every process would violate the measure-ONCE contract)."""
    if cache_dir:
        db = TuningDB(os.path.join(cache_dir, "tuning.json"))
        return Tuner(db, measure_enabled=measure_enabled)
    if measure_enabled:
        return Tuner(TuningDB(os.path.join(
            os.path.expanduser("~"), ".cache", "mxnet_tpu", "tuning.json")),
            measure_enabled=True)
    return None
