"""Persistent compilation cache: jit builds survive process restarts.

Every process today pays every XLA compile from scratch — mxtel's
``executor.jit_builds_total`` counts them, and for a serving cold start
they ARE the latency floor. This module wires jax's persistent
compilation-cache machinery (``jax_compilation_cache_dir``) through the
framework's compile entry points (Executor, the scanned trainers,
Predictor): with ``MXNET_COMPILE_CACHE_DIR`` set, compiled executables
land on disk keyed by their HLO + compile options, and the next process
that builds the same program LOADS instead of compiling.

Keying: entries live under ``<dir>/jit-<config-hash>/`` where the hash
covers the rewrite-pass configuration (pass set, layout/precision
modes, cache format version). The HLO itself already differs when a
pass rewrites the graph, but the subdir keying also isolates
configurations whose effect is not visible in the HLO (and makes
``rm -r`` per-config cleanup trivial).

Robustness: a truncated or bit-flipped cache entry must cost a
recompile, never a crash. jax's own read path already demotes
undecodable entries to a miss (``_cache_read`` catches and warns);
``verify_cache_dir`` goes further and sweeps the directory at ensure()
time, deleting entries whose compressed payload no longer decodes and
counting them via ``compile.cache_corrupt_total`` — so one poisoned
entry costs exactly one recompile and disappears.

Hit/miss accounting rides jax's monitoring events
(``/jax/compilation_cache/cache_hits`` / ``cache_misses``) into both
mxtel counters (``compile.cache_hits_total`` / ``misses_total``) and
module-level plain ints readable without telemetry (bench.py's
cold-start leg reports them from a bare subprocess).
"""
from __future__ import annotations

import hashlib
import os
import zlib

from .. import telemetry as _tel

__all__ = ["ensure", "verify_cache_dir", "cache_dir", "stats"]

#: process-lifetime counters (mirrors of the mxtel counters; plain ints
#: so subprocesses can report them without enabling telemetry)
HITS = 0
MISSES = 0
CORRUPT = 0

_configured_dir = None
_listener_on = False


def cache_dir():
    """MXNET_COMPILE_CACHE_DIR, or None (cache off)."""
    return os.environ.get("MXNET_COMPILE_CACHE_DIR", "").strip() or None


def donation_unsafe():
    """True when donated executables may load from the persistent cache
    on the CPU backend. jaxlib 0.4.3x CPU executables deserialized from
    the cache corrupt the heap when run with donated buffers (verified
    in this container: the warm-process scanned-fit loop segfaults with
    `malloc_consolidate(): invalid chunk size`; with donation stripped
    the same cached executable runs clean — and the bug reproduces with
    jax's own JAX_COMPILATION_CACHE_DIR env wiring, so it is not this
    module's doing). Donating entry points (parallel/fit_trainer.py,
    parallel/symbol_trainer.py) consult this and keep their buffers;
    TPU backends keep donation (different serialization path, and the
    HBM headroom matters there)."""
    if cache_dir() is None:
        return False
    try:
        import jax

        return jax.default_backend() == "cpu"
    except Exception:
        return False


def stats():
    return {"hits": HITS, "misses": MISSES, "corrupt": CORRUPT}


def _on_event(event, **kwargs):
    global HITS, MISSES
    if event == "/jax/compilation_cache/cache_hits":
        HITS += 1
        if _tel.ENABLED:
            _tel.counter("compile.cache_hits_total").inc()
    elif event == "/jax/compilation_cache/cache_misses":
        MISSES += 1
        if _tel.ENABLED:
            _tel.counter("compile.cache_misses_total").inc()


def _register_listener():
    global _listener_on
    if _listener_on:
        return
    try:
        from jax._src import monitoring

        monitoring.register_event_listener(_on_event)
        _listener_on = True
    except Exception:  # monitoring API moved: counters stay at 0, cache
        pass           # itself still works


def _decompress_ok(payload):
    """True iff a cache entry's payload decodes with the compressor jax
    writes with (zstandard when installed, zlib otherwise — mirror of
    compilation_cache.compress_executable)."""
    try:
        import zstandard
    except ImportError:
        zstandard = None
    try:
        if zstandard is not None:
            zstandard.ZstdDecompressor().decompress(
                payload, max_output_size=1 << 31)
        else:
            zlib.decompress(payload)
        return True
    except Exception:
        return False


def verify_cache_dir(path):
    """Sweep ``path`` for undecodable ``*-cache`` entries; delete them
    (recompile beats crash-or-warn-forever) and count each via
    ``compile.cache_corrupt_total``. Returns (n_checked, n_removed)."""
    global CORRUPT
    checked = removed = 0
    try:
        names = os.listdir(path)
    except OSError:
        return 0, 0
    for name in names:
        if not name.endswith("-cache"):
            continue
        fpath = os.path.join(path, name)
        checked += 1
        try:
            with open(fpath, "rb") as f:
                payload = f.read()
            ok = _decompress_ok(payload)
        except OSError:
            ok = False
        if not ok:
            removed += 1
            CORRUPT += 1
            if _tel.ENABLED:
                _tel.counter("compile.cache_corrupt_total").inc()
            try:
                os.remove(fpath)
                # the atime sidecar of a removed entry is dead weight
                sidecar = fpath[:-len("-cache")] + "-atime"
                if os.path.exists(sidecar):
                    os.remove(sidecar)
            except OSError:
                pass
    return checked, removed


def keyed_dir(base, config_key):
    h = hashlib.sha256(config_key.encode()).hexdigest()[:16]
    return os.path.join(base, "jit-%s" % h)


def ensure(config_key=""):
    """Idempotently enable the persistent jit cache when
    MXNET_COMPILE_CACHE_DIR is set. Returns the active entry directory
    or None. Called from every compile entry point (Executor bind, the
    scanned trainers, Predictor) — the first caller configures jax,
    later calls are one string compare."""
    global _configured_dir
    base = cache_dir()
    if base is None:
        return None
    target = keyed_dir(base, config_key)
    if _configured_dir == target:
        return target
    os.makedirs(target, exist_ok=True)
    verify_cache_dir(target)
    import jax

    jax.config.update("jax_compilation_cache_dir", target)
    # default thresholds skip exactly the small fast-to-build programs
    # a cold start is made of; cache everything (each knob guarded: the
    # spelling differs across jax versions and a missing threshold knob
    # must degrade to default gating, not crash every bind)
    try:
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0)
    except Exception:
        pass
    try:
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
    except Exception:
        pass
    # jax memoizes cache-usability at the FIRST compile of the process
    # (_cache_checked in compilation_cache.py): any jit dispatched
    # before this ensure() — an autotuning trial, a warmup program —
    # would otherwise freeze the cache off for the process lifetime
    try:
        from jax._src import compilation_cache as _cc

        _cc.reset_cache()
    except Exception:
        pass  # private API moved: configuring before first jit still works
    _register_listener()
    _configured_dir = target
    return target
