"""Shared Symbol-graph IR walk utilities.

The compile passes (fuse/layout/fold), the executor's segment planner and
the mxlint graph pass all need the same handful of graph facts: consumer
maps, head keys, best-effort shape propagation, elementwise-op
classification and fusible-chain discovery. This module is the one place
those walks live — ``analysis/graph_lint.py`` imports it for the
``fusible-chain`` finding and its shape sweep, and the rewrite passes in
this package build on it for their pattern matching.

Deliberately jax-free: everything here is host-side metadata walking
(the graph lint must stay importable before any device is touched, see
analysis/graph_lint.py). Rewrites that *evaluate* ops (constant folding)
import jax inside the pass module, not here.

Node duck type: ``analysis`` consumes ``symbol._Node`` objects —
``op`` (OpDef or None), ``name``, ``params``, ``inputs``
(list of ``(node, out_idx)``), ``attrs``.
"""
from __future__ import annotations

__all__ = [
    "head_keys", "consumers_map", "propagate_shapes", "propagate_dtypes",
    "is_elementwise", "find_fusible_chains", "rebuild",
]


def head_keys(sym):
    """The set of ``(id(node), out_idx)`` entries that are graph heads."""
    return {(id(n), i) for n, i in sym._outputs}


def consumers_map(nodes):
    """Map ``(id(src), out_idx)`` -> set of consumer serials (indices
    into ``nodes``). The executor's segment planner and the fusion
    pass both key liveness off this."""
    consumers = {}
    for serial, n in enumerate(nodes):
        if n.is_variable:
            continue
        for s, i in n.inputs:
            consumers.setdefault((id(s), i), set()).add(serial)
    return consumers


def propagate_shapes(nodes, seed, sweeps=3):
    """Best-effort forward/backward shape sweep over the DAG.

    ``seed`` maps ``(id(node), out_idx)`` -> shape. Unknown stays
    absent; op infer errors are skipped (callers must tolerate a
    partially-specified graph — the lint and the layout pass both run
    on whatever shapes are recoverable)."""
    shapes = dict(seed)
    for _ in range(sweeps):  # bidirectional infer needs a couple of sweeps
        changed = False
        for n in nodes:
            if n.is_variable:
                continue
            in_shapes = [shapes.get((id(s), i)) for s, i in n.inputs]
            try:
                ins, outs, _aux = n.op.infer_shape(n.params, in_shapes)
            except Exception:
                continue
            for (src, i), s in zip(n.inputs, ins):
                if s is not None and shapes.get((id(src), i)) != tuple(s):
                    shapes[(id(src), i)] = tuple(s)
                    changed = True
            for i, s in enumerate(outs):
                if s is not None and shapes.get((id(n), i)) != tuple(s):
                    shapes[(id(n), i)] = tuple(s)
                    changed = True
        if not changed:
            break
    return shapes


def propagate_dtypes(nodes, seed, sweeps=3):
    """Best-effort dtype sweep (the type analog of propagate_shapes).
    ``seed`` maps ``(id(node), out_idx)`` -> numpy dtype. The autotuner
    keys its decisions by the dtype an op ACTUALLY computes in, which
    for every layer past the first is an interior edge — only a
    propagation from the bound-argument dtypes can answer that."""
    dtypes = dict(seed)
    for _ in range(sweeps):
        changed = False
        for n in nodes:
            if n.is_variable:
                continue
            in_types = [dtypes.get((id(s), i)) for s, i in n.inputs]
            try:
                _ins, outs, _aux = n.op.infer_type(n.params, in_types)
            except Exception:
                continue
            for i, t in enumerate(outs):
                if t is not None and dtypes.get((id(n), i)) != t:
                    dtypes[(id(n), i)] = t
                    changed = True
        if not changed:
            break
    return dtypes


def is_elementwise(node):
    """True iff ``node`` is a plain elementwise op the fusion pass may
    place inside a fused segment: default (elementwise) shape
    inference, one output, no aux state, no RNG, no host kernel, no
    loss-head semantics. The default-infer_shape test is the load-
    bearing one — every op registered without a custom ``infer_shape``
    promises all inputs and outputs share one shape (registry.py)."""
    if node.is_variable:
        return False
    op = node.op
    if getattr(op, "_infer_shape", None) is not None:
        return False
    if op.is_host_op or op.need_rng:
        return False
    if op.head_no_grad(node.params):
        return False
    if len(op.list_outputs(node.params)) != 1:
        return False
    if op.list_auxiliary_states(node.params):
        return False
    return True


def find_fusible_chains(sym, min_len=2):
    """Maximal linear chains of elementwise ops.

    A chain is a node sequence ``n1 -> n2 -> ... -> nk`` where every
    node ``is_elementwise``, each interior link is the ONLY consumer of
    its producer's output, and no interior output is a graph head
    (interior values must be free to disappear into the fused
    segment). Non-chain inputs of interior nodes (the other operand of
    a binary op) become external inputs of the fused segment.

    Returns a list of chains, each a list of nodes in topo order.
    Shared by the fusion pass (which rewrites them) and the graph lint
    (which reports them as ``fusible-chain`` opportunities)."""
    nodes = sym.nodes
    cons = consumers_map(nodes)
    heads = head_keys(sym)

    def sole_consumer(n):
        """The unique elementwise consumer of n's single output, when
        the output is not a head and feeds exactly one input slot."""
        k = (id(n), 0)
        if k in heads:
            return None
        c = cons.get(k, set())
        if len(c) != 1:
            return None
        nxt = nodes[next(iter(c))]
        if not is_elementwise(nxt):
            return None
        # the producer must feed exactly one input slot of the consumer
        # (x * x would otherwise drop one operand in the rewrite)
        if sum(1 for s, i in nxt.inputs if s is n and i == 0) != 1:
            return None
        return nxt

    chains, in_chain = [], set()
    for n in nodes:
        if id(n) in in_chain or not is_elementwise(n):
            continue
        # only start a chain at a node whose producer link does NOT
        # continue a chain (maximality)
        chain = [n]
        cur = n
        while True:
            nxt = sole_consumer(cur)
            if nxt is None or id(nxt) in in_chain:
                break
            chain.append(nxt)
            cur = nxt
        if len(chain) >= min_len:
            chains.append(chain)
            in_chain.update(id(c) for c in chain)
    # chains come out in topo order of their first node: seeds walk the
    # topo list, and a seed that would continue an earlier chain was
    # already consumed by that chain's sole_consumer walk
    return chains


def rebuild(sym, replace):
    """Clone the graph under a node-level rewrite.

    ``replace(node, new_inputs, memo)`` returns either a replacement
    node or None to keep the node (with its inputs rewired to the
    cloned producers). ``memo`` maps ``id(original)`` -> clone for
    every already-lowered node, so a pass replacing a multi-node
    pattern can reach the clones of non-immediate producers (the
    fusion pass needs the external inputs of interior chain nodes).
    Variables are NEVER cloned — the executor maps bound arrays to
    variable nodes by identity, so passes must preserve variable
    objects. Returns a new Symbol over the rewritten heads.

    The walk is iterative (explicit stack): model-zoo graphs (unrolled
    RNNs) exceed Python's default recursion depth.
    """
    from ..symbol import Symbol

    memo = {}

    def lower(node):
        stack = [node]
        while stack:
            cur = stack[-1]
            if id(cur) in memo:
                stack.pop()
                continue
            pending = [s for s, _ in cur.inputs if id(s) not in memo]
            if pending:
                stack.extend(pending)
                continue
            stack.pop()
            if cur.is_variable:
                memo[id(cur)] = cur
                continue
            new_inputs = [(memo[id(s)], i) for s, i in cur.inputs]
            out = replace(cur, new_inputs, memo)
            if out is None:
                if all(a is b for (a, _), (b, _) in zip(new_inputs, cur.inputs)):
                    out = cur  # untouched subtree: share, don't clone
                else:
                    from ..symbol import _Node

                    out = _Node(cur.op, cur.name, cur.params, new_inputs,
                                cur.attrs)
            memo[id(cur)] = out
        return memo[id(node)]

    return Symbol([(lower(n), i) for n, i in sym._outputs])
