"""Constant-folding pass: evaluate parameter-only subexpressions once.

Two folding modes, both replacing a maximal foldable subgraph with a
single ``_mxc_const`` node whose forward returns the baked value:

- **pure constants** (always safe): subexpressions with no variable
  leaves at all — graphs built from constant-producing ops and scalar
  chains. These re-evaluated on every traced step for no reason.
- **frozen parameters** (opt-in via ``frozen_params``): subexpressions
  whose variable leaves are ALL in the caller-supplied frozen set.
  ``Predictor`` passes its checkpoint weights here — predict-time
  weights never change after bind, so weight-transformation chains
  (reshapes/transposes/scalar math on parameters) collapse into baked
  constants and disappear from the per-request program. Training
  executors must NOT pass ``frozen_params`` (the optimizer mutates
  weights in place every step); the pipeline only enables this mode on
  the predict path.

Safety envelope: a node folds only when it has no aux state, no RNG, no
host kernel, no ``is_train`` sensitivity risk (evaluation runs with
``is_train=False`` — predict-path semantics), and the baked output is
not larger than its inputs (``GROWTH_LIMIT``; folding a broadcast would
trade a few FLOPs for resident HBM).
"""
from __future__ import annotations

import numpy as _np

from . import ir

__all__ = ["apply", "CONST_OP"]

CONST_OP = "_mxc_const"

#: Refuse to bake a constant larger than this multiple of its inputs'
#: total size (a folded broadcast/tile would pin the expanded tensor).
GROWTH_LIMIT = 4.0


def _make_const_op(value, name):
    from ..ops.registry import OpDef

    def forward(params, inputs, aux, is_train, rng):
        return [value], []

    def infer_shape(params, in_shapes):
        return [], [tuple(value.shape)], []

    def infer_type(params, in_types):
        return [], [_np.dtype(value.dtype)], []

    return OpDef(CONST_OP, forward, arguments=(),
                 infer_shape=infer_shape, infer_type=infer_type,
                 doc="compile-time folded constant (compile/fold.py)")


def _foldable_op(node):
    if node.is_variable:
        return False
    op = node.op
    if op.is_host_op or op.need_rng:
        return False
    if op.head_no_grad(node.params):
        return False
    if op.list_auxiliary_states(node.params):
        return False
    return True


def apply(sym, frozen_params=None):
    """Fold constant subexpressions in ``sym``.

    ``frozen_params``: optional dict name -> array-like for variables
    the caller guarantees will never change after bind (predict path).
    Returns ``(new_sym, n_folded_nodes)``.
    """
    frozen = dict(frozen_params or {})
    nodes = sym.nodes
    heads = ir.head_keys(sym)

    # mark every node whose transitive leaves are foldable
    constish = {}  # id(node) -> True/False
    for n in nodes:
        if n.is_variable:
            constish[id(n)] = n.name in frozen
        else:
            constish[id(n)] = (_foldable_op(n)
                               and all(constish[id(s)] for s, _ in n.inputs))
    if not any(constish[id(n)] and not n.is_variable for n in nodes):
        return sym, 0

    # fold only MAXIMAL const subgraphs: a const node whose every
    # consumer is also const evaluates inside its consumer's fold —
    # baking it separately would duplicate the value
    cons = ir.consumers_map(nodes)
    fold_roots = []
    for serial, n in enumerate(nodes):
        if n.is_variable or not constish[id(n)]:
            continue
        out_keys = [(id(n), i)
                    for i in range(len(n.op.list_outputs(n.params)))]
        is_root = any(k in heads for k in out_keys) or any(
            not constish[id(nodes[c])]
            for k in out_keys for c in cons.get(k, ())
        )
        # only single-output roots bake cleanly into one const node;
        # a multi-output root stays (const consumers of it still fold
        # THROUGH it — the evaluator walks originals, not the rewrite)
        if is_root and len(out_keys) == 1:
            fold_roots.append(serial)
    if not fold_roots:
        return sym, 0

    # evaluate the const region once, bottom-up, on host
    env = {}

    def value_of(node, oidx):
        key = (id(node), oidx)
        if key in env:
            return env[key]
        if node.is_variable:
            v = _np.asarray(
                frozen[node.name].asnumpy()
                if hasattr(frozen[node.name], "asnumpy")
                else frozen[node.name])
            env[key] = v
            return v
        ins = [value_of(s, i) for s, i in node.inputs]
        outs, _aux = node.op.apply(node.params, ins, [], False, None)
        for i, o in enumerate(outs):
            env[(id(node), i)] = _np.asarray(o)
        return env[key]

    folded = {}  # id(node) -> const node (or None when growth-gated)
    n_folded = 0
    from ..symbol import _Node

    for serial in fold_roots:
        n = nodes[serial]
        try:
            val = value_of(n, 0)
        except Exception:
            folded[id(n)] = None  # evaluation failed: leave the subgraph
            continue
        in_bytes = sum(
            v.nbytes for k, v in env.items()
            if k[0] in {id(s) for s, _ in n.inputs}
        ) or val.nbytes
        if val.nbytes > GROWTH_LIMIT * max(1, in_bytes):
            folded[id(n)] = None
            continue
        import jax.numpy as jnp

        baked = jnp.asarray(val)
        folded[id(n)] = _Node(
            _make_const_op(baked, n.name), n.name, {}, [],
            dict(n.attrs, __mxc_opt__="fold"))
        n_folded += 1

    if not n_folded:
        return sym, 0

    def replace(node, new_inputs, memo):
        return folded.get(id(node))

    return ir.rebuild(sym, replace), n_folded
