"""Matmul-precision pass: tune the FullyConnected accumulation flag.

``ops/nn.py`` hardcodes ``preferred_element_type=float32`` on the
FullyConnected dot — the safe default (bf16 operands, f32 accumulate).
Whether that flag costs anything is backend-dependent: on the MXU f32
accumulation is free, on other backends the widened output can force an
extra materialization. This pass makes the flag a *measured* per-shape
choice: with a tuner, each FC problem is timed under both flags and the
winner is keyed into the tuning DB; the rewrite swaps in an
``_mxc_fc_prec`` node carrying the explicit choice.

The pass is deliberately inert without a tuner decision or an explicit
``MXNET_COMPILE_MATMUL_PREC`` override — 'fast' accumulation changes
numerics (tolerance-bounded in the golden-equivalence tests), so it
must be asked for, never defaulted in.
"""
from __future__ import annotations

from . import ir

__all__ = ["apply", "FC_PREC"]

FC_PREC = "_mxc_fc_prec"


def _make_fc_op(base_params, choice):
    from ..ops.registry import Field, OpDef
    from ..ops import nn as _nn

    # only the 'fast' choice ever builds a node — 'f32' IS the stock
    # FullyConnected, so apply() leaves those untouched
    assert choice == "fast", choice

    def forward(params, inputs, aux, is_train, rng):
        import jax.numpy as jnp

        data, w = inputs[0], inputs[1]
        x = data.reshape(data.shape[0], -1)
        out = jnp.dot(x, w.T)  # backend-default accumulation
        if not params["no_bias"]:
            out = out + inputs[2].astype(out.dtype)
        return [out], []

    return OpDef(
        FC_PREC + "[%s]" % choice, forward,
        params={
            "num_hidden": Field("int", required=True),
            "no_bias": Field("bool", default=False),
        },
        arguments=_nn._fc_args,
        infer_shape=_nn._fc_shape,
        doc="compile-time FullyConnected with tuned accumulation flag")


def apply(sym, input_shapes=None, input_types=None, tuner=None, mode="auto"):
    """Rewrite FullyConnected nodes to the tuned accumulation flag.

    ``mode``: 'auto' (consult the tuner; inert without one), 'f32' or
    'fast' (explicit override for every FC). Returns
    ``(new_sym, n_rewritten)``."""
    if mode == "auto" and tuner is None:
        return sym, 0
    import numpy as _np

    nodes = sym.nodes
    seed = {}
    for n in nodes:
        if n.is_variable and input_shapes and n.name in input_shapes:
            seed[(id(n), 0)] = tuple(input_shapes[n.name])
    shapes = ir.propagate_shapes(nodes, seed) if seed else {}
    tseed = {(id(n), 0): _np.dtype(input_types[n.name])
             for n in nodes
             if n.is_variable and input_types and n.name in input_types}
    # dtype of the FC's ACTUAL input edge (interior past the first
    # layer) — propagated, not looked up by bound-argument name
    dtype_map = ir.propagate_dtypes(nodes, tseed) if tseed else {}

    choices = {}
    for n in nodes:
        if n.is_variable or n.op.name != "FullyConnected":
            continue
        if mode in ("f32", "fast"):
            choice = mode
        else:
            dshape = shapes.get((id(n.inputs[0][0]), n.inputs[0][1]))
            if dshape is None:
                continue
            dtype = dtype_map.get((id(n.inputs[0][0]), n.inputs[0][1]))
            choice = tuner.pick_matmul_precision(
                dshape, n.params["num_hidden"], dtype)
        if choice != "f32":  # f32 IS the stock op; no rewrite needed
            choices[id(n)] = choice
    if not choices:
        return sym, 0

    from ..symbol import _Node

    def replace(node, new_inputs, memo):
        choice = choices.get(id(node))
        if choice is None:
            return None
        return _Node(_make_fc_op(node.params, choice), node.name,
                     node.params, new_inputs,
                     dict(node.attrs, __mxc_opt__="precision"))

    return ir.rebuild(sym, replace), len(choices)
