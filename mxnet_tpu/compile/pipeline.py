"""Pass pipeline: ordered application + pass-level correctness check.

Order matters and is fixed: ``fold`` first (a folded subgraph is fewer
nodes for everyone downstream), ``layout`` second (NHWC regions are
established before fusion so chains *inside* a region fuse), ``fuse``
third (the boundary transposes and converted ops carry custom
infer_shape and never enter a chain), ``precision`` last (it rewrites
FC nodes wherever they ended up). Each pass is individually
disableable via ``MXNET_COMPILE_PASSES`` (see compile/__init__).

``MXNET_COMPILE_VERIFY=1`` adds a pass-level golden check at
optimize() time: both graphs run eagerly on small random inputs and the
heads must agree within tolerance — the unrewritten graph is the
reference. A mismatch raises (a wrong rewrite must never train
silently); the golden-equivalence tests in
tests/unittest/test_compile.py apply the same check suite-style across
the model zoo.
"""
from __future__ import annotations

import time as _time

import numpy as _np

from ..base import MXNetError
from .. import telemetry as _tel
from . import CompileVerifyError

__all__ = ["run"]

#: most recent optimize() report: pass -> rewrite count (test hook and
#: tools surface; one optimize at a time — binds are host-serial)
LAST_REPORT = {}


def run(sym, passes, input_shapes=None, input_types=None,
        frozen_params=None, tuner=None, matmul_prec="auto", verify=False):
    """Apply ``passes`` (iterable of names) to ``sym``; returns the
    rewritten Symbol (``sym`` itself when nothing applied)."""
    global LAST_REPORT
    report = {}
    new = sym
    t0 = _time.monotonic()
    for name in passes:
        with _tel.span("compile.pass.%s" % name):
            if name == "fold":
                from . import fold

                new, n = fold.apply(new, frozen_params=frozen_params)
            elif name == "layout":
                from . import layout

                new, n = layout.apply(new, input_shapes=input_shapes,
                                      input_types=input_types, tuner=tuner)
            elif name == "fuse":
                from . import fuse

                new, n = fuse.apply(new, input_shapes=input_shapes,
                                    tuner=tuner)
            elif name == "precision":
                from . import precision

                new, n = precision.apply(
                    new, input_shapes=input_shapes, input_types=input_types,
                    tuner=tuner, mode=matmul_prec)
            else:
                raise MXNetError("unknown compile pass %r" % (name,))
        report[name] = n
        if n and _tel.ENABLED:
            _tel.counter("compile.passes_applied_total").inc()
            _tel.counter("compile.pass.%s_rewrites_total" % name).inc(n)
    report["secs"] = round(_time.monotonic() - t0, 4)
    LAST_REPORT = report
    if verify and new is not sym:
        check_equivalence(sym, new, input_shapes or {},
                          frozen_params=frozen_params,
                          loose=bool(report.get("layout")
                                     or report.get("precision")))
    return new


# -- pass-level golden check ---------------------------------------------------

def _eval_graph(sym, arg_vals, seed=0):
    """Eager reference interpreter: run every node with op.apply
    (is_train=False, no RNG) and return the head values. aux states get
    their op-declared init (init_aux) or the zeros/ones-by-name default
    simple_bind uses."""
    env = {}
    nodes = sym.nodes
    for n in nodes:
        if n.is_variable:
            env[(id(n), 0)] = arg_vals[n.name]
            continue
        ins = [env[(id(s), i)] for s, i in n.inputs]
        aux_names = n.op.list_auxiliary_states(n.params)
        aux = []
        if aux_names:
            aux_shapes = None
            if n.op.init_aux is not None:
                try:
                    _i, _o, aux_shapes = n.op.infer_shape(
                        n.params, [getattr(x, "shape", None) for x in ins])
                except MXNetError:
                    aux_shapes = None
            if n.op.init_aux is not None and aux_shapes is not None:
                aux = [_np.asarray(a)
                       for a in n.op.init_aux(n.params, aux_shapes)]
            else:
                _i, _o, aux_shapes = n.op.infer_shape(
                    n.params, [getattr(x, "shape", None) for x in ins])
                aux = [(_np.ones(s, _np.float32) if "var" in an
                        else _np.zeros(s, _np.float32))
                       for an, s in zip(aux_names, aux_shapes)]
        outs, _new_aux = n.op.apply(n.params, ins, aux, False, None)
        for i, o in enumerate(outs):
            env[(id(n), i)] = o
    return [env[(id(n), i)] for n, i in sym._outputs]


def check_equivalence(ref_sym, opt_sym, input_shapes, frozen_params=None,
                      loose=False, rtol=None, atol=None, seed=0):
    """Run both graphs on shared random inputs; raise MXNetError when a
    head diverges. ``loose`` applies the layout/precision tolerance
    (reduction-order and accumulation-dtype changes are legitimate);
    fuse/fold rewrites must match bit-exactly. ``frozen_params`` must
    be the same values the fold pass baked — the reference graph reads
    them as arguments, the rewritten graph carries them as constants,
    so random stand-ins would diverge by construction."""
    import jax.numpy as jnp

    rng = _np.random.RandomState(seed)
    frozen = dict(frozen_params or {})
    arg_names = ref_sym.list_arguments()
    shapes = {k: tuple(v) for k, v in input_shapes.items()
              if k in set(arg_names)}
    if any(n not in shapes and n not in frozen for n in arg_names):
        # data/label-only callers (Symbol.optimize with just the input
        # shapes): weight shapes are fully inferable from those
        try:
            arg_shapes, _, _ = ref_sym.infer_shape(**shapes)
            for n, s in zip(arg_names, arg_shapes):
                if s is not None:
                    shapes.setdefault(n, tuple(s))
        except MXNetError:
            pass  # underdetermined: the explicit check below reports it
    arg_vals = {}
    for name in arg_names:
        if name in frozen:
            v = frozen[name]
            v = v.asnumpy() if hasattr(v, "asnumpy") else _np.asarray(v)
        elif name not in shapes:
            raise MXNetError(
                "compile verify: no shape for argument %s" % name)
        elif name.endswith("label"):
            v = rng.randint(0, 2, shapes[name]).astype(_np.float32)
        else:
            v = rng.rand(*shapes[name]).astype(_np.float32) - 0.5
        arg_vals[name] = jnp.asarray(v)
    ref = _eval_graph(ref_sym, arg_vals, seed)
    opt = _eval_graph(opt_sym, arg_vals, seed)
    if rtol is None:
        rtol = 2e-3 if loose else 0.0
    if atol is None:
        atol = 2e-3 if loose else 0.0
    for i, (a, b) in enumerate(zip(ref, opt)):
        a = _np.asarray(a)
        b = _np.asarray(b)
        if a.shape != b.shape:
            raise CompileVerifyError(
                "compile verify: head %d shape %s != reference %s"
                % (i, b.shape, a.shape))
        if not _np.allclose(a, b, rtol=rtol, atol=atol):
            err = float(_np.max(_np.abs(a - b))) if a.size else 0.0
            raise CompileVerifyError(
                "compile verify: head %d diverges from the unrewritten "
                "graph (max abs err %.3g, rtol=%g atol=%g)"
                % (i, err, rtol, atol))
