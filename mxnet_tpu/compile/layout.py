"""Layout-selection pass: rewrite NCHW conv subgraphs to NHWC with
transpose hoisting.

``tools/probe_layout.py`` measured the three candidate policies on the
real chip (VERDICT r1 weak #2): logical-NHWC end-to-end beats logical
NCHW, and a naive per-conv transpose sandwich gives most of the win.
This pass promotes that experiment into the production path: every
eligible ``Convolution`` is rewritten to compute channels-last, and the
transposes are HOISTED — a layout region grows forward through every
layout-capable consumer (BatchNorm, Pooling, Activation and all plain
elementwise ops), so ``conv -> bn -> relu -> conv`` chains carry NO
interior transposes; conversions happen only at region borders (the
data input, shortcut joins from NCHW producers, and graph heads /
layout-incapable consumers such as Flatten, whose element order depends
on the layout).

Weights stay in their reference OIHW layout (the bound parameter arrays,
checkpoints and the optimizer never see the rewrite); the NHWC conv op
transposes its weight operand inside the program, where XLA folds the
tiny permute into the conv's operand layout assignment.

Per-conv layout is a *contested* choice (small spatial dims or odd
channel counts can favor NCHW on some backends): with an autotuner the
decision is measured once on the real device and persisted in the
tuning DB keyed by (op, shapes, dtype, backend); without one, every
eligible conv converts (the measured default from the probe).

Numerics: convolution and BN reductions in NHWC sum in a different
order, so rewritten graphs are tolerance-equivalent, not bit-identical
(the golden-equivalence tests bound the drift; see
docs/how_to/compilation.md).
"""
from __future__ import annotations

import numpy as _np

from ..base import MXNetError
from . import ir

__all__ = ["apply", "TO_NHWC", "TO_NCHW", "CONV_NHWC", "BN_NHWC",
           "POOL_NHWC"]

TO_NHWC = "_mxc_to_nhwc"
TO_NCHW = "_mxc_to_nchw"
CONV_NHWC = "_mxc_conv_nhwc"
BN_NHWC = "_mxc_bn_nhwc"
POOL_NHWC = "_mxc_pool_nhwc"


def _nchw_of(s):
    return (s[0], s[3], s[1], s[2])


def _nhwc_of(s):
    return (s[0], s[2], s[3], s[1])


# -- internal OpDefs (built lazily: this module only loads when the
#    pipeline runs, but keep jax imports inside forwards to match the
#    executor's import discipline) ---------------------------------------------
_OPS = {}


def _op(name):
    if not _OPS:
        _build_ops()
    return _OPS[name]


def _build_ops():
    from ..ops.registry import Field, OpDef
    from ..ops import nn as _nn

    def _t_nhwc_fwd(params, inputs, aux, is_train, rng):
        import jax.numpy as jnp

        return [jnp.transpose(inputs[0], (0, 2, 3, 1))], []

    def _t_nchw_fwd(params, inputs, aux, is_train, rng):
        import jax.numpy as jnp

        return [jnp.transpose(inputs[0], (0, 3, 1, 2))], []

    def _t_shape(perm_in, perm_out):
        def infer(params, in_shapes):
            s = in_shapes[0]
            if s is None:
                raise MXNetError("transpose: input shape unknown")
            if len(s) != 4:
                raise MXNetError("transpose: rank-4 input required")
            return [s], [perm_out(s)], []
        return infer

    _OPS[TO_NHWC] = OpDef(TO_NHWC, _t_nhwc_fwd,
                          infer_shape=_t_shape(_nchw_of, _nhwc_of),
                          doc="layout-pass NCHW->NHWC boundary transpose")
    _OPS[TO_NCHW] = OpDef(TO_NCHW, _t_nchw_fwd,
                          infer_shape=_t_shape(_nhwc_of, _nchw_of),
                          doc="layout-pass NHWC->NCHW boundary transpose")

    # -- NHWC convolution: data NHWC, weight kept OIHW --------------------------
    def _conv_nhwc_fwd(params, inputs, aux, is_train, rng):
        import jax
        import jax.numpy as jnp

        data, weight = inputs[0], inputs[1]
        if weight.dtype != data.dtype:
            weight = weight.astype(data.dtype)
        stride = _nn._pair(params["stride"] or (1, 1), 2)
        pad = _nn._pair(params["pad"] or (0, 0), 2)
        dilate = _nn._pair(params["dilate"] or (1, 1), 2)
        w = jnp.transpose(weight, (2, 3, 1, 0))  # OIHW -> HWIO
        out = jax.lax.conv_general_dilated(
            data, w,
            window_strides=stride,
            padding=[(p, p) for p in pad],
            rhs_dilation=dilate,
            dimension_numbers=("NHWC", "HWIO", "NHWC"),
            feature_group_count=params["num_group"],
            # same accumulation contract as ops/nn.py _conv_fwd: no
            # preferred_element_type (jax conv transpose AD constraint);
            # XLA:TPU accumulates bf16 convs in f32 MXU accumulators
        )
        if not params["no_bias"]:
            out = out + inputs[2].astype(out.dtype).reshape((1, 1, 1, -1))
        return [out], []

    def _conv_nhwc_shape(params, in_shapes):
        if in_shapes[0] is None:
            raise MXNetError("conv_nhwc: data shape unknown")
        ins, outs, aux = _nn._conv_shape(
            params, [_nchw_of(in_shapes[0])] + list(in_shapes[1:]))
        return [_nhwc_of(ins[0])] + ins[1:], [_nhwc_of(outs[0])], aux

    from ..ops.nn import _CONV_PARAMS

    _OPS[CONV_NHWC] = OpDef(
        CONV_NHWC, _conv_nhwc_fwd, params=dict(_CONV_PARAMS),
        arguments=_nn._fc_args, infer_shape=_conv_nhwc_shape,
        doc="layout-pass channels-last Convolution (weights stay OIHW)")

    # -- NHWC BatchNorm: channel axis -1, same custom-vjp kernel ----------------
    def _bn_nhwc_fwd(params, inputs, aux, is_train, rng):
        import os

        import jax
        import jax.numpy as jnp

        data, gamma, beta = inputs
        moving_mean, moving_var = aux
        eps, momentum = params["eps"], params["momentum"]
        if params["fix_gamma"]:
            gamma = jnp.ones_like(jax.lax.stop_gradient(gamma))
        axes = (0, 1, 2)
        bshape = (1, 1, 1, -1)
        if is_train and not params["use_global_stats"]:
            try:
                sample = max(1, int(os.environ.get("MXNET_BN_STATS_SAMPLE", "1")))
            except ValueError:
                sample = 1
            if sample > 1 or os.environ.get("MXNET_BN_AUTODIFF", "") == "1":
                out, mean, var, _ = _nn._bn_norm_fwd_impl(
                    data, gamma.astype(jnp.float32), beta.astype(jnp.float32),
                    eps, axes, bshape, sample=sample)
            else:
                out, mean, var = _nn._bn_train_norm(
                    data, gamma.astype(jnp.float32), beta.astype(jnp.float32),
                    eps, axes, bshape)
            new_mm = moving_mean * momentum + jax.lax.stop_gradient(mean) * (1 - momentum)
            new_mv = moving_var * momentum + jax.lax.stop_gradient(var) * (1 - momentum)
            return [out], [new_mm, new_mv]
        mean = jax.lax.stop_gradient(moving_mean).astype(jnp.float32)
        var = jax.lax.stop_gradient(moving_var).astype(jnp.float32)
        inv = jax.lax.rsqrt(var.reshape(bshape) + eps)
        out = (data.astype(jnp.float32) - mean.reshape(bshape)) * inv
        out = (out * gamma.astype(jnp.float32).reshape(bshape)
               + beta.astype(jnp.float32).reshape(bshape))
        return [out.astype(data.dtype)], [moving_mean, moving_var]

    def _bn_nhwc_shape(params, in_shapes):
        if in_shapes[0] is None:
            raise MXNetError("bn_nhwc: data shape unknown")
        c = (in_shapes[0][3],)
        return [in_shapes[0], c, c], [in_shapes[0]], [c, c]

    from ..ops.nn import _bn_init_aux

    _OPS[BN_NHWC] = OpDef(
        BN_NHWC, _bn_nhwc_fwd,
        params={
            "eps": Field("float", default=1e-3),
            "momentum": Field("float", default=0.9),
            "fix_gamma": Field("bool", default=True),
            "use_global_stats": Field("bool", default=False),
        },
        arguments=("data", "gamma", "beta"),
        aux=("moving_mean", "moving_var"),
        infer_shape=_bn_nhwc_shape,
        init_aux=_bn_init_aux,
        doc="layout-pass channels-last BatchNorm")

    # -- NHWC Pooling -----------------------------------------------------------
    def _pool_nhwc_fwd(params, inputs, aux, is_train, rng):
        import jax
        import jax.numpy as jnp

        x = inputs[0]
        if params["global_pool"]:
            k = x.shape[1:3]
            stride = (1, 1)
            pad = (0, 0)
        else:
            k = _nn._pair(params["kernel"], 2)
            stride = _nn._pair(params["stride"] or (1, 1), 2)
            pad = _nn._pair(params["pad"] or (0, 0), 2)
        dims = (1,) + k + (1,)
        strides = (1,) + stride + (1,)
        hi_pad = list(pad)
        if not params["global_pool"] and params["pooling_convention"] == "full":
            for i in range(2):
                out_d = _nn._pool_out_dim(
                    x.shape[1 + i], pad[i], k[i], stride[i], "full")
                need = (out_d - 1) * stride[i] + k[i] - (x.shape[1 + i] + 2 * pad[i])
                hi_pad[i] = pad[i] + max(0, need)
        padding = ((0, 0),) + tuple(
            (p, hp) for p, hp in zip(pad, hi_pad)) + ((0, 0),)
        pt = params["pool_type"]
        if pt == "max":
            init = (-_np.inf if jnp.issubdtype(x.dtype, jnp.floating)
                    else _np.iinfo(x.dtype).min)
            out = jax.lax.reduce_window(x, init, jax.lax.max, dims, strides,
                                        padding)
        else:
            out = jax.lax.reduce_window(
                x, 0.0 if jnp.issubdtype(x.dtype, jnp.floating) else 0,
                jax.lax.add, dims, strides, padding)
            if pt == "avg":
                out = out / float(_np.prod(k))
        return [out], []

    def _pool_nhwc_shape(params, in_shapes):
        if in_shapes[0] is None:
            raise MXNetError("pool_nhwc: data shape unknown")
        ins, outs, aux = _nn._pool_shape(params, [_nchw_of(in_shapes[0])])
        return [_nhwc_of(ins[0])], [_nhwc_of(outs[0])], aux

    _OPS[POOL_NHWC] = OpDef(
        POOL_NHWC, _pool_nhwc_fwd,
        params={
            "kernel": Field("shape", required=True),
            "pool_type": Field("str", required=True,
                               enum=["max", "avg", "sum"]),
            "global_pool": Field("bool", default=False),
            "pooling_convention": Field("str", default="valid",
                                        enum=["valid", "full"]),
            "stride": Field("shape", default=None),
            "pad": Field("shape", default=None),
        },
        infer_shape=_pool_nhwc_shape,
        doc="layout-pass channels-last Pooling")


# -- capability + region growth ------------------------------------------------

def _capability(node, shapes):
    """How this node can participate in an NHWC region:
    'conv' (region seed), 'bn'/'pool' (converted in place),
    'eltwise' (layout-agnostic passthrough) or None (region border)."""
    if node.is_variable:
        return None
    out_shape = shapes.get((id(node), 0))
    if out_shape is None or len(out_shape) != 4:
        return None
    name = node.op.name
    if name == "Convolution":
        dshape = shapes.get((id(node.inputs[0][0]), node.inputs[0][1]))
        if dshape is not None and len(dshape) == 4:
            return "conv"
        return None
    if name == "BatchNorm":
        return "bn"
    if name == "Pooling":
        return "pool"
    if ir.is_elementwise(node):
        return "eltwise"
    return None


def apply(sym, input_shapes=None, input_types=None, tuner=None):
    """Rewrite eligible NCHW conv subgraphs to NHWC.

    Returns ``(new_sym, n_converted_convs)``; ``new_sym is sym`` when
    nothing converted. ``input_shapes`` seeds the shape sweep that
    gates eligibility (the executor passes its bound arg shapes)."""
    nodes = sym.nodes
    seed = {}
    for n in nodes:
        if not n.is_variable:
            continue
        s = None
        if input_shapes and n.name in input_shapes:
            s = tuple(input_shapes[n.name])
        else:
            raw = n.attrs.get("__shape__")
            if raw:
                import ast

                try:
                    s = tuple(int(d) for d in ast.literal_eval(str(raw)))
                except (ValueError, SyntaxError, TypeError):
                    s = None
        if s is not None:
            seed[(id(n), 0)] = s
    shapes = ir.propagate_shapes(nodes, seed) if seed else {}
    if not shapes:
        return sym, 0
    dtype_map = {}
    if tuner is not None and input_types:
        tseed = {(id(n), 0): _np.dtype(input_types[n.name])
                 for n in nodes
                 if n.is_variable and n.name in input_types}
        # tuning decisions key by the dtype each conv ACTUALLY computes
        # in — an interior edge for every layer past the first, so the
        # bound-argument dtypes must propagate through the graph
        dtype_map = ir.propagate_dtypes(nodes, tseed) if tseed else {}

    nhwc, n_convs = set(), 0
    for n in nodes:
        kind = _capability(n, shapes)
        if kind == "conv":
            if tuner is not None:
                dshape = shapes.get((id(n.inputs[0][0]), n.inputs[0][1]))
                dtype = dtype_map.get((id(n.inputs[0][0]), n.inputs[0][1]))
                choice = tuner.pick_conv_layout(n.params, dshape, dtype)
            else:
                choice = "nhwc"
            if choice == "nhwc":
                nhwc.add(id(n))
                n_convs += 1
        elif kind in ("bn", "pool", "eltwise"):
            if any(id(s) in nhwc for s, _ in n.inputs):
                nhwc.add(id(n))
    if not nhwc:
        return sym, 0

    from ..symbol import _Node, Symbol

    t_cache = {}  # (id(clone), oidx, target) -> transpose node

    def _wrap(entry, target):
        """Insert a boundary transpose around a cloned entry (cached so
        one conversion serves every consumer — the hoisting)."""
        node, oidx = entry
        key = (id(node), oidx, target)
        if key not in t_cache:
            t_cache[key] = _Node(
                _op(target), "%s_%s" % (node.name, target.strip("_")),
                {}, [entry], {"__mxc_opt__": "layout"})
        return (t_cache[key], 0)

    _CONVERT = {"conv": CONV_NHWC, "bn": BN_NHWC, "pool": POOL_NHWC}

    def replace(node, new_inputs, memo):
        in_region = id(node) in nhwc
        kind = _capability(node, shapes) if in_region else None
        if not in_region:
            # NCHW consumer: any input produced inside a region needs a
            # conversion back to NCHW at the border
            ins = [
                _wrap(e, TO_NCHW) if id(src) in nhwc else e
                for e, (src, _i) in zip(new_inputs, node.inputs)
            ]
            if all(a is b for (a, _), (b, _) in zip(ins, new_inputs)):
                return None  # default clone/share path
            return _Node(node.op, node.name, node.params, ins, node.attrs)

        def act(pos):
            """Activation operand at input slot pos, converted to NHWC."""
            src, _i = node.inputs[pos]
            e = new_inputs[pos]
            return e if id(src) in nhwc else _wrap(e, TO_NHWC)

        if kind == "conv":
            ins = [act(0)] + list(new_inputs[1:])  # weight/bias stay put
            return _Node(_op(CONV_NHWC), node.name, node.params, ins,
                         dict(node.attrs, __mxc_opt__="layout"))
        if kind == "bn":
            ins = [act(0)] + list(new_inputs[1:])
            return _Node(_op(BN_NHWC), node.name, node.params, ins,
                         dict(node.attrs, __mxc_opt__="layout"))
        if kind == "pool":
            return _Node(_op(POOL_NHWC), node.name, node.params, [act(0)],
                         dict(node.attrs, __mxc_opt__="layout"))
        # eltwise passthrough: every operand becomes NHWC
        ins = [act(p) for p in range(len(node.inputs))]
        return _Node(node.op, node.name, node.params, ins, node.attrs)

    new_sym = ir.rebuild(sym, replace)
    # heads produced inside a region leave the graph in NCHW (the
    # public output contract is layout-invariant)
    outs = []
    for (orig, i), entry in zip(sym._outputs, new_sym._outputs):
        outs.append(_wrap(entry, TO_NCHW) if id(orig) in nhwc else entry)
    return Symbol(outs), n_convs
