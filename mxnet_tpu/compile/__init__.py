"""mxc: the compilation layer — graph rewrites, autotuning, jit cache.

Three cooperating pieces close the compiler-shaped half of the roofline
gap (ROADMAP "Compilation layer"; ground: PAPERS.md TVM):

1. **Graph-rewrite passes** over the Symbol graph before executor
   lowering — constant folding (fold.py), NCHW→NHWC layout selection
   with transpose hoisting (layout.py, the production promotion of
   tools/probe_layout.py), elementwise-chain fusion (fuse.py) and the
   tuned matmul-accumulation flag (precision.py). Each pass is a
   separate module sharing the ir.py walk utilities with
   ``analysis/graph_lint.py``, individually disableable, and checked
   against the unrewritten graph (pipeline.check_equivalence).
2. **A measure-and-cache autotuner** (autotune.py) for contested
   choices — per-conv layout, segment boundaries, matmul precision —
   timed once on the real device, winner persisted on disk keyed by
   (op, shapes, dtype, backend).
3. **A persistent compilation cache** (jit_cache.py): traced/lowered
   executables survive process restarts via jax's compilation cache,
   keyed to include the rewrite-pass configuration.

Enablement contract (off by default, the repo's established style)::

    MXNET_COMPILE_OPT=1               # master switch for the passes
    MXNET_COMPILE_PASSES=...          # subset of fold,layout,fuse,precision
    MXNET_COMPILE_CACHE_DIR=/path     # persistent jit cache + tuning db
    MXNET_COMPILE_TUNE=1              # allow on-device tuning trials
    MXNET_COMPILE_VERIFY=1            # golden-check every optimize()
    MXNET_COMPILE_MATMUL_PREC=auto    # auto | f32 | fast

The cache is independent of the passes: ``MXNET_COMPILE_CACHE_DIR``
alone turns cold-start jit builds into loads with zero graph changes.
Off, the only cost at bind time is one module attribute test.
mxtel counters: ``compile.passes_applied_total``,
``compile.cache_hits_total``/``misses_total``/``corrupt_total``,
``compile.tuning_trials_total``; spans: ``compile.optimize``,
``compile.pass.<name>``. Docs: docs/how_to/compilation.md.
"""
from __future__ import annotations

import os

from .. import telemetry as _tel
from ..base import MXNetError

__all__ = [
    "ENABLED", "enabled", "reload", "optimize", "ensure_jit_cache",
    "active_passes", "config_key", "last_report", "CompileVerifyError",
]


class CompileVerifyError(MXNetError):
    """A rewritten graph diverged from the unrewritten reference under
    ``MXNET_COMPILE_VERIFY=1``. Never swallowed by the bind-time
    fallback — a wrong rewrite must not train silently."""

#: Master switch for the rewrite passes. The executor reads this ONE
#: attribute on every bind; everything else loads lazily behind it.
ENABLED = False

PASS_ORDER = ("fold", "layout", "fuse", "precision")

_passes = PASS_ORDER
_verify = False
_tune = False
_matmul_prec = "auto"


def _env_on(name):
    return os.environ.get(name, "").strip().lower() not in (
        "", "0", "false", "off", "no")


def reload():
    """Re-read the MXNET_COMPILE_* environment (import-time default;
    tests call it after monkeypatching)."""
    global ENABLED, _passes, _verify, _tune, _matmul_prec
    ENABLED = _env_on("MXNET_COMPILE_OPT")
    raw = os.environ.get("MXNET_COMPILE_PASSES", "").strip()
    if raw:
        wanted = {p.strip() for p in raw.split(",") if p.strip()}
        unknown = wanted - set(PASS_ORDER)
        if unknown:
            raise ValueError(
                "MXNET_COMPILE_PASSES: unknown pass(es) %s (know: %s)"
                % (sorted(unknown), list(PASS_ORDER)))
        _passes = tuple(p for p in PASS_ORDER if p in wanted)
    else:
        _passes = PASS_ORDER
    _verify = _env_on("MXNET_COMPILE_VERIFY")
    _tune = _env_on("MXNET_COMPILE_TUNE")
    _matmul_prec = (os.environ.get("MXNET_COMPILE_MATMUL_PREC", "auto")
                    .strip().lower() or "auto")
    if _matmul_prec not in ("auto", "f32", "fast"):
        raise ValueError(
            "MXNET_COMPILE_MATMUL_PREC=%r (know: auto, f32, fast)"
            % (_matmul_prec,))


def enabled():
    return ENABLED


def active_passes():
    return _passes


def config_key():
    """Stable string describing the rewrite configuration — folded into
    the jit-cache directory key so executables compiled under different
    pass configurations never share entries."""
    return "v1|opt=%d|passes=%s|prec=%s" % (
        int(ENABLED), ",".join(_passes) if ENABLED else "-", _matmul_prec)


def optimize(sym, input_shapes=None, input_types=None, frozen_params=None):
    """Run the active passes over ``sym``; returns the rewritten Symbol
    (``sym`` unchanged when nothing applies). Callers treat the result
    as an executor-internal artifact: it shares variable nodes with the
    original by identity and its fused/layout ops are not registry ops,
    so it must never be serialized."""
    if not ENABLED:
        return sym
    from . import autotune, pipeline
    from .jit_cache import cache_dir

    tuner = autotune.make_tuner(cache_dir(), measure_enabled=_tune)
    with _tel.span("compile.optimize"):
        return pipeline.run(
            sym, _passes, input_shapes=input_shapes,
            input_types=input_types, frozen_params=frozen_params,
            tuner=tuner, matmul_prec=_matmul_prec, verify=_verify)


def ensure_jit_cache():
    """Enable the persistent jit cache when configured; safe no-op
    otherwise. Every compile entry point calls this before building
    programs."""
    if os.environ.get("MXNET_COMPILE_CACHE_DIR", "").strip():
        from . import jit_cache

        return jit_cache.ensure(config_key())
    return None


def last_report():
    """The most recent optimize() pass report (test/tools hook)."""
    from . import pipeline

    return dict(pipeline.LAST_REPORT)


try:
    reload()
except ValueError as _e:  # a typo'd env var must not break import;
    import logging as _logging  # explicit reload() still raises for tests

    _logging.getLogger("mxnet_tpu.compile").warning(
        "MXNET_COMPILE_* misconfigured (%s); compile layer disabled", _e)
    ENABLED = False
