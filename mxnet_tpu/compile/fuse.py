"""Op-fusion pass: collapse elementwise chains into single fused nodes.

XLA fuses elementwise ops inside one compiled program regardless; what
this pass removes is everything the framework pays *per node* before and
beside XLA: trace-time Python dispatch (one ``op.apply`` + env
bookkeeping per node in ``Executor._run``), per-node eager dispatches on
the multi-device and monitor-replay paths, per-node plan items in the
hybrid/mirror segment planners (``_build_hybrid_plan`` segments whatever
nodes it is given — fewer nodes, coarser segments), and per-node entries
in every graph walk after this one. A ResNet-style shortcut tail
(``add -> relu``) or a hand-built normalization chain of 5 scalar ops
becomes ONE node whose forward applies the composed closure (conv and
BatchNorm themselves stay out — see the envelope below).

The chain discovery lives in ``ir.find_fusible_chains`` and is shared
with ``analysis/graph_lint.py``'s ``fusible-chain`` informational
finding, so ``mxlint`` reports exactly what this pass would do even when
``MXNET_COMPILE_OPT`` is off.

Correctness envelope: only ops with the default elementwise shape
contract, one output, no aux, no RNG, no host kernel and no loss-head
semantics enter a chain (``ir.is_elementwise``), and interior values
must have exactly one consumer and not be graph heads. The fused forward
applies the member ops in original topo order — same jnp calls, same
order, so the jitted program is the same computation (golden-equivalence
tests assert bit-identical outputs and gradients, test_compile.py).
"""
from __future__ import annotations

from . import ir

__all__ = ["apply", "make_fused_op", "FUSED_OP_PREFIX"]

FUSED_OP_PREFIX = "_mxc_fused"


def make_fused_op(chain, ext_keys_per_node):
    """Build a one-off OpDef whose forward runs ``chain`` composed.

    ``ext_keys_per_node``: for each chain node, the list of input slots
    ``(pos_in_node_inputs, ext_index_or_None)`` — None marks the slot
    fed by the previous chain node's output. The OpDef is NOT put in
    the registry (the rewritten graph is an executor-internal artifact,
    never serialized; the user's symbol is untouched)."""
    from ..ops.registry import OpDef

    ops = [(n.op, dict(n.params or {})) for n in chain]
    slot_plans = list(ext_keys_per_node)

    def forward(params, inputs, aux, is_train, rng):
        cur = None
        for (op, p), slots in zip(ops, slot_plans):
            ins = [cur if ext is None else inputs[ext]
                   for _pos, ext in slots]
            outs, _aux = op.apply(p, ins, [], is_train, None)
            cur = outs[0]
        return [cur], []

    n_ext = 1 + max(
        (ext for slots in slot_plans for _p, ext in slots
         if ext is not None), default=-1)
    return OpDef(
        FUSED_OP_PREFIX + "[%s]" % "+".join(op.name for op, _ in ops),
        forward,
        arguments=tuple("in%d" % i for i in range(n_ext)),
        doc="compile-time fused elementwise chain (compile/fuse.py)",
    )


def apply(sym, input_shapes=None, tuner=None):
    """Rewrite ``sym``: every fusible chain becomes one fused node.

    ``tuner``: optional autotuner (compile/autotune.py). When present,
    each chain's segment boundary is a measured choice — the chain is
    fused whole or split at the tuned boundary — keyed by the chain's
    op signature and shape. Without a tuner, chains fuse whole.

    Returns ``(new_sym, n_chains_fused)``; ``new_sym is sym`` when
    nothing fused.
    """
    chains = ir.find_fusible_chains(sym)
    if tuner is not None and input_shapes:
        chains = _split_tuned(sym, chains, input_shapes, tuner)
    if not chains:
        return sym, 0

    last_of_chain = {}   # id(last node) -> chain
    interior = set()
    for chain in chains:
        last_of_chain[id(chain[-1])] = chain
        interior.update(id(n) for n in chain[:-1])

    def replace(node, new_inputs, memo):
        chain = last_of_chain.get(id(node))
        if chain is None:
            return None  # interior nodes clone through and go dead
        chain_ids = {id(n) for n in chain}
        ext_entries, ext_index = [], {}
        slot_plans = []
        for ci, n in enumerate(chain):
            slots = []
            for pos, (src, oidx) in enumerate(n.inputs):
                if ci > 0 and id(src) in chain_ids and oidx == 0 \
                        and src is chain[ci - 1]:
                    slots.append((pos, None))
                    continue
                key = (id(src), oidx)
                if key not in ext_index:
                    ext_index[key] = len(ext_entries)
                    ext_entries.append((memo[id(src)], oidx))
                slots.append((pos, ext_index[key]))
            slot_plans.append(slots)
        op = make_fused_op(chain, slot_plans)
        from ..symbol import _Node

        fused = _Node(op, chain[-1].name, {}, ext_entries,
                      {"__mxc_opt__": "fuse",
                       "__mxc_members__": ",".join(n.name for n in chain)})
        return fused

    new_sym = ir.rebuild(sym, replace)
    return new_sym, len(chains)


def _split_tuned(sym, chains, input_shapes, tuner):
    """Consult the autotuner for each chain's segment boundary.

    The contested choice: fuse the whole chain into one segment, or
    split it in half (two fused segments — the boundary re-exposes one
    intermediate to the planner). Measured once per (op signature,
    shape, dtype, backend) and persisted in the tuning DB."""
    seed = {}
    nodes = sym.nodes
    for n in nodes:
        if n.is_variable and input_shapes and n.name in input_shapes:
            seed[(id(n), 0)] = tuple(input_shapes[n.name])
    shapes = ir.propagate_shapes(nodes, seed) if seed else {}
    out = []
    for chain in chains:
        shape = shapes.get((id(chain[0]), 0))
        if shape is None or len(chain) < 4:
            out.append(chain)  # nothing to contest below 2+2
            continue
        choice = tuner.pick_segment_boundary(
            [n.op.name for n in chain], shape)
        if choice == "split":
            mid = len(chain) // 2
            for part in (chain[:mid], chain[mid:]):
                if len(part) >= 2:
                    out.append(part)
        else:
            out.append(chain)
    return out
