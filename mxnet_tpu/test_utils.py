"""Test assertion library shipped inside the package
(ref: python/mxnet/test_utils.py:1-747). Provides the reference's numeric
gradient checker and cross-context consistency checker — the template for
TPU-vs-CPU parity tests (SURVEY §4.2, §4.4)."""
from __future__ import annotations

import numpy as _np

from .base import MXNetError
from .context import cpu, Context
from .ndarray import array, zeros, NDArray
from .symbol import Symbol

def default_context():
    from .context import current_context

    return current_context()


def reldiff(a, b):
    """ref: test_utils.py:92."""
    diff = _np.sum(_np.abs(a - b))
    norm = _np.sum(_np.abs(a)) + _np.sum(_np.abs(b))
    if diff == 0:
        return 0
    return diff / norm


def same(a, b):
    return _np.array_equal(a, b)


def assert_almost_equal(a, b, threshold=None):
    threshold = threshold or 1e-5
    rel = reldiff(a, b)
    if rel > threshold:
        raise AssertionError("reldiff %g > threshold %g\n%s\nvs\n%s" % (rel, threshold, a, b))


def random_arrays(*shapes):
    arrays = [_np.random.randn(*s).astype(default_dtype()) for s in shapes]
    if len(arrays) == 1:
        return arrays[0]
    return arrays


def _parse_location(sym, location, ctx):
    assert isinstance(location, (dict, list, tuple))
    if isinstance(location, dict):
        if set(location.keys()) != set(sym.list_arguments()):
            raise ValueError(
                "Symbol arguments and keys of the given location do not match: %s vs %s"
                % (str(set(sym.list_arguments())), str(set(location.keys())))
            )
    else:
        location = {k: v for k, v in zip(sym.list_arguments(), location)}
    location = {
        k: (array(v, ctx=ctx) if isinstance(v, _np.ndarray) else v)
        for k, v in location.items()
    }
    return location


def _parse_aux_states(sym, aux_states, ctx):
    if aux_states is not None:
        if isinstance(aux_states, dict):
            if set(aux_states.keys()) != set(sym.list_auxiliary_states()):
                raise ValueError("Symbol aux_states names and given aux_states do not match")
        elif isinstance(aux_states, (list, tuple)):
            aux_names = sym.list_auxiliary_states()
            aux_states = {k: v for k, v in zip(aux_names, aux_states)}
        aux_states = {k: array(v, ctx=ctx) for k, v in aux_states.items()}
    return aux_states


def numeric_grad(executor, location, aux_states=None, eps=1e-4, use_forward_train=True):
    """Finite-difference gradients (ref: test_utils.py:169)."""
    approx_grads = {k: _np.zeros(v.shape, dtype=_np.float32) for k, v in location.items()}
    for k, v in location.items():
        executor.arg_dict[k][:] = v
    for k in location:
        old_value = location[k].copy()
        for i in range(int(_np.prod(old_value.shape))):
            # inplace update
            loc = old_value.ravel().copy()
            loc[i] += eps / 2.0
            executor.arg_dict[k][:] = loc.reshape(old_value.shape)
            executor.forward(is_train=use_forward_train)
            f_peps = executor.outputs[0].asnumpy().sum()
            loc[i] -= eps
            executor.arg_dict[k][:] = loc.reshape(old_value.shape)
            executor.forward(is_train=use_forward_train)
            f_neps = executor.outputs[0].asnumpy().sum()
            approx_grads[k].ravel()[i] = (f_peps - f_neps) / eps
        executor.arg_dict[k][:] = old_value
    return approx_grads


def check_numeric_gradient(sym, location, aux_states=None, numeric_eps=1e-3,
                           check_eps=1e-2, grad_nodes=None, use_forward_train=True,
                           ctx=None):
    """Verify jax.vjp gradients against finite differences
    (ref: test_utils.py:219 check_numeric_gradient)."""
    if ctx is None:
        ctx = default_context()
    location = _parse_location(sym=sym, location=location, ctx=ctx)
    location_npy = {k: v.asnumpy() for k, v in location.items()}
    aux_states = _parse_aux_states(sym=sym, aux_states=aux_states, ctx=ctx)

    if grad_nodes is None:
        grad_nodes = sym.list_arguments()
        grad_req = {k: "write" for k in grad_nodes}
    elif isinstance(grad_nodes, (list, tuple)):
        grad_nodes = list(grad_nodes)
        grad_req = {k: "write" for k in grad_nodes}
    elif isinstance(grad_nodes, dict):
        grad_req = grad_nodes.copy()
        grad_nodes = grad_nodes.keys()
    else:
        raise ValueError

    input_shape = {k: v.shape for k, v in location.items()}
    _, out_shape, _ = sym.infer_shape(**input_shape)
    proj = Variable_like("__random_proj")
    out = _flat_sum(sym * proj)
    args = {
        k: zeros(v.shape, ctx) for k, v in location.items()
    }
    args["__random_proj"] = array(_np.random.normal(0, 0.01, size=out_shape[0]), ctx=ctx)
    args_grad = {k: zeros(v.shape, ctx) for k, v in args.items()}
    executor = out.bind(
        ctx, args=args, args_grad=args_grad,
        grad_req={k: grad_req.get(k, "write") for k in args}, aux_states=aux_states
    )
    inps = executor.arg_dict
    for k, v in location.items():
        inps[k][:] = v.asnumpy()
    executor.forward(is_train=True)
    executor.backward()
    symbolic_grads = {k: executor.grad_dict[k].asnumpy() for k in grad_nodes}

    # finite differences over the same projected scalar output
    numeric_gradients = numeric_grad(
        executor,
        {k: v for k, v in location_npy.items()},
        aux_states, eps=numeric_eps, use_forward_train=use_forward_train,
    )
    for name in grad_nodes:
        fd_grad = numeric_gradients[name]
        sym_grad = symbolic_grads[name]
        rel = reldiff(fd_grad, sym_grad)
        if rel > check_eps:
            raise AssertionError(
                "numeric check failed for %s: reldiff %g > %g\nnumeric:\n%s\nsymbolic:\n%s"
                % (name, rel, check_eps, fd_grad, sym_grad)
            )


def Variable_like(name):
    from .symbol import Variable

    return Variable(name)


def _flat_sum(sym):
    from . import symbol as S

    # MakeLoss head so backward() needs no out_grads (the reference checker
    # relies on the same loss-head semantics)
    return S.MakeLoss(S.sum(S.Flatten(sym)))


def check_symbolic_forward(sym, location, expected, check_eps=1e-5,
                           aux_states=None, ctx=None):
    """ref: test_utils.py:305."""
    if ctx is None:
        ctx = default_context()
    location = _parse_location(sym=sym, location=location, ctx=ctx)
    aux_states = _parse_aux_states(sym=sym, aux_states=aux_states, ctx=ctx)
    if isinstance(expected, dict):
        expected = [expected[k] for k in sym.list_outputs()]
    args = {k: v for k, v in location.items()}
    executor = sym.bind(ctx, args=args, aux_states=aux_states, grad_req="null")
    outputs = [x.asnumpy() for x in executor.forward(is_train=False)]
    for out, exp in zip(outputs, expected):
        assert_almost_equal(out, exp, check_eps)
    return outputs


def check_symbolic_backward(sym, location, out_grads, expected, check_eps=1e-5,
                            aux_states=None, grad_req="write", ctx=None):
    """ref: test_utils.py:353."""
    if ctx is None:
        ctx = default_context()
    location = _parse_location(sym=sym, location=location, ctx=ctx)
    aux_states = _parse_aux_states(sym=sym, aux_states=aux_states, ctx=ctx)
    if isinstance(expected, (list, tuple)):
        expected = {k: v for k, v in zip(sym.list_arguments(), expected)}
    args = {k: v for k, v in location.items()}
    args_grad = {k: zeros(v.shape, ctx) for k, v in expected.items()}
    executor = sym.bind(
        ctx, args=args, args_grad=args_grad, aux_states=aux_states, grad_req=grad_req
    )
    executor.forward(is_train=True)
    if isinstance(out_grads, (tuple, list)):
        out_grads = [array(v, ctx=ctx) if isinstance(v, _np.ndarray) else v for v in out_grads]
    executor.backward(out_grads)
    grads = {k: v.asnumpy() for k, v in executor.grad_dict.items() if k in expected}
    for name in expected:
        assert_almost_equal(grads[name], expected[name], check_eps)
    return grads


def check_consistency(sym, ctx_list, scale=1.0, type_dict=None, grad_req="write",
                      arg_params=None, aux_params=None, tol=None):
    """Bind the same symbol under several contexts/dtypes and require
    outputs & grads to agree within per-dtype tolerance — the reference's
    GPU↔CPU parity harness, reused for TPU↔CPU
    (ref: test_utils.py:615 check_consistency)."""
    if tol is None:
        tol = {
            _np.dtype(_np.float16): 1e-1,
            _np.dtype(_np.float32): 1e-3,
            _np.dtype(_np.float64): 1e-5,
            _np.dtype(_np.uint8): 0,
            _np.dtype(_np.int32): 0,
        }
    assert len(ctx_list) > 1
    if isinstance(sym, Symbol):
        sym = [sym] * len(ctx_list)
    else:
        assert len(sym) == len(ctx_list)

    output_points = None
    exe_list = []
    for s, ctx in zip(sym, ctx_list):
        ctx = dict(ctx)
        the_ctx = ctx.pop("ctx")
        exe = s.simple_bind(the_ctx, grad_req=grad_req, **ctx)
        exe_list.append(exe)

    arg_names = sym[0].list_arguments()
    # identical random init across contexts
    init_vals = {}
    for name, arr in exe_list[0].arg_dict.items():
        init_vals[name] = _np.random.normal(size=arr.shape, scale=scale)
    for exe in exe_list:
        for name, arr in exe.arg_dict.items():
            arr[:] = init_vals[name].astype(arr.dtype)
        if arg_params:
            for name, v in arg_params.items():
                exe.arg_dict[name][:] = v
        if aux_params:
            for name, v in aux_params.items():
                exe.aux_dict[name][:] = v

    outputs = []
    for exe in exe_list:
        exe.forward(is_train=(grad_req != "null"))
        if grad_req != "null":
            exe.backward(exe.outputs)
        outputs.append([o.asnumpy() for o in exe.outputs])

    # compare all against the highest-precision executor (last one)
    ref = outputs[-1]
    for i, out in enumerate(outputs[:-1]):
        dtype = out[0].dtype
        t = tol.get(_np.dtype(dtype), 1e-3)
        for o, r in zip(out, ref):
            assert_almost_equal(o.astype(_np.float64), r.astype(_np.float64), t)
    if grad_req != "null":
        ref_grads = {k: v.asnumpy() for k, v in exe_list[-1].grad_dict.items() if v is not None}
        for exe in exe_list[:-1]:
            for k, v in exe.grad_dict.items():
                if v is None or k not in ref_grads:
                    continue
                t = tol.get(v.dtype, 1e-3)
                assert_almost_equal(
                    v.asnumpy().astype(_np.float64),
                    ref_grads[k].astype(_np.float64), t,
                )
    return outputs


def default_dtype():
    """Default dtype for regression tests (ref: test_utils.py:27)."""
    return _np.float32


def default_numerical_threshold():
    """Default comparison threshold (ref: test_utils.py:33)."""
    return 1e-6


def set_default_context(ctx):
    """Make ``ctx`` the process default (ref: test_utils.py:23 sets
    Context.default_ctx): the bottom of the with-scope stack, consulted
    by current_context() whenever no `with ctx:` scope is active."""
    from .context import Context

    Context._default_bottom = ctx


def almost_equal(a, b, threshold=None):
    """True iff reldiff(a, b) <= threshold (ref: test_utils.py:110)."""
    rel = reldiff(a, b)
    return not _np.isnan(rel) and rel <= (threshold or
                                          default_numerical_threshold())


def np_reduce(dat, axis, keepdims, numpy_reduce_func):
    """Reduce ``dat`` over ``axis`` with numpy semantics — the oracle the
    reduction-op tests compare against (ref: test_utils.py:49)."""
    if isinstance(axis, int):
        axis = [axis]
    else:
        axis = list(axis) if axis is not None else range(len(dat.shape))
    ret = dat
    for i in reversed(sorted(axis)):
        ret = numpy_reduce_func(ret, axis=i)
    if keepdims:
        keepdims_shape = list(dat.shape)
        for i in axis:
            keepdims_shape[i] = 1
        ret = ret.reshape(tuple(keepdims_shape))
    return ret


def simple_forward(sym, ctx=None, is_train=False, **inputs):
    """Forward a symbol on numpy inputs, returning numpy outputs —
    the doctest convenience (ref: test_utils.py:138)."""
    ctx = ctx or default_context()
    args = {k: array(v, ctx=ctx) for k, v in inputs.items()}
    exe = sym.bind(ctx, args=args)
    exe.forward(is_train=is_train)
    outputs = [x.asnumpy() for x in exe.outputs]
    return outputs[0] if len(outputs) == 1 else outputs


def check_speed(sym, location=None, ctx=None, N=20, grad_req=None,
                typ="whole", **kwargs):
    """Average seconds per forward(+backward) over N runs
    (ref: test_utils.py:537). typ='whole' times fwd+bwd, 'forward' only
    the inference pass."""
    import time as _time

    from .ndarray import waitall

    ctx = ctx or default_context()
    grad_req = grad_req or "write"
    if location is None:
        exe = sym.simple_bind(grad_req=grad_req, ctx=ctx, **kwargs)
        rng = _np.random.RandomState(17)
        location = {k: rng.normal(size=arr.shape, scale=1.0)
                    for k, arr in exe.arg_dict.items()}
    else:
        if not isinstance(location, dict):
            raise TypeError("location must be a dict of name->np.ndarray")
        exe = sym.simple_bind(grad_req=grad_req, ctx=ctx,
                              **{k: v.shape for k, v in location.items()})
    for name, iarr in location.items():
        exe.arg_dict[name][:] = iarr.astype(exe.arg_dict[name].dtype)

    def run_once(train):
        exe.forward(is_train=train)
        if train:
            exe.backward(out_grads=exe.outputs)
        for output in exe.outputs:
            output.wait_to_read()

    if typ not in ("whole", "forward"):
        raise ValueError("typ can only be whole or forward")
    train = typ == "whole"
    run_once(train)  # warm up / compile
    tic = _time.time()
    for _ in range(N):
        run_once(train)
    waitall()
    return (_time.time() - tic) / N
