"""Caffe prototxt interpretation on native ops.

Shared by the caffe plugin facade (``mxnet_tpu.caffe_plugin.CaffeOp``
runs a single caffe layer spec as an op, ref: plugin/caffe/
caffe_op-inl.h) and the network converter (``tools/caffe_converter.py``,
ref: tools/caffe_converter/convert_symbol.py). The reference parses
prototxt through caffe's generated protobuf classes and executes layers
with libcaffe kernels; here a small self-contained text-format parser
reads the spec directly and each layer type maps onto the native op
registry — the TPU-native equivalent (XLA runs the math, no caffe
runtime required).

Supported layers: Input/Data, Convolution, Pooling (MAX/AVE),
InnerProduct, ReLU, TanH, Sigmoid, Dropout, LRN, Concat, Eltwise
(SUM/PROD/MAX), Flatten, Softmax / SoftmaxWithLoss, Accuracy (skipped).

Fidelity note: Pooling maps with ``pooling_convention="full"`` (caffe
sizes pooled maps with ceil), so spatial arithmetic matches caffe's.
"""
from __future__ import annotations

import re

from .base import MXNetError

__all__ = ["ProtoParseError", "parse_prototxt", "apply_layer",
           "convert_symbol"]


class ProtoParseError(MXNetError, ValueError):
    """Malformed prototxt (truncation, stray braces, bad tokens, missing
    required fields). Subclasses both MXNetError (the framework error
    contract) and ValueError (the historical parse-error type), so either
    catch handles every malformed-spec path uniformly."""

# -- minimal protobuf text-format parser --------------------------------------

_TOKEN = re.compile(r"""
    (?P<brace>[{}])
  | (?P<name>[A-Za-z_][A-Za-z0-9_]*)\s*(?P<colon>:)?
  | (?P<string>"(?:[^"\\]|\\.)*")
  | (?P<number>-?\d+(?:\.\d*)?(?:[eE][+-]?\d+)?)
""", re.VERBOSE)


def _tokenize(text):
    text = re.sub(r"#[^\n]*", "", text)  # comments
    pos = 0
    while pos < len(text):
        if text[pos].isspace():
            pos += 1
            continue
        m = _TOKEN.match(text, pos)
        if m is None:
            raise ProtoParseError("prototxt parse error at %r" % text[pos:pos + 30])
        pos = m.end()
        yield m


def _parse_block(tokens, top=False):
    """Parse `key: value` / `key { ... }` pairs until '}' (or, for the
    top-level block only, EOF) into a dict; repeated keys accumulate into
    lists. A nested block running out of tokens is a truncated prototxt."""
    out = {}

    def add(key, val):
        if key in out:
            if not isinstance(out[key], list):
                out[key] = [out[key]]
            out[key].append(val)
        else:
            out[key] = val

    for m in tokens:
        if m.group("brace") == "}":
            if top:
                # an unmatched top-level '}' would otherwise silently
                # drop every layer after it
                raise ProtoParseError("unmatched '}' at top level of prototxt")
            return out
        key = m.group("name")
        if key is None:
            raise ProtoParseError("expected field name, got %r" % m.group(0))
        try:
            nxt = next(tokens)
        except StopIteration:
            # a truncated prototxt must fail loudly, not leak a bare
            # StopIteration out of the generator protocol (ADVICE r5)
            raise ProtoParseError(
                "unexpected end of prototxt after field %r" % key) from None
        if nxt.group("brace") == "{":
            add(key, _parse_block(tokens))
        elif nxt.group("string") is not None:
            add(key, nxt.group("string")[1:-1])
        elif nxt.group("number") is not None:
            n = nxt.group("number")
            add(key, float(n) if ("." in n or "e" in n.lower()) else int(n))
        elif nxt.group("name") is not None:  # enum / bool literal
            v = nxt.group("name")
            add(key, {"true": True, "false": False}.get(v, v))
        else:
            raise ProtoParseError("unexpected token %r after %s" % (nxt.group(0), key))
    if not top:
        raise ProtoParseError("unexpected end of prototxt: unclosed block")
    return out


def parse_prototxt(text):
    return _parse_block(_tokenize(text), top=True)


# -- layer mapping ------------------------------------------------------------

def _aslist(v):
    if v is None:
        return []
    return v if isinstance(v, list) else [v]


def _first(v, default):
    lst = _aslist(v)
    return lst[0] if lst else default


def _dilate(p, name):
    """dilation is a repeated field: one value applies to both axes,
    two distinct values are anisotropic (unsupported)."""
    vals = [int(v) for v in _aslist(p.get("dilation"))]
    if not vals:
        return (1, 1)
    if len(set(vals)) > 1:
        raise NotImplementedError(
            "anisotropic dilation %s (%s) not supported" % (vals, name))
    return (vals[0], vals[0])


def _hw(p, field, default=None, required=False):
    """Resolve caffe's square (`kernel_size`) or per-axis
    (`kernel_h`/`kernel_w`) spatial params to an (h, w) tuple."""
    square = "%s_size" % field if field == "kernel" else field
    if p.get(square) is not None:
        k = int(_first(p[square], default))
        return (k, k)
    h, w = p.get(field + "_h"), p.get(field + "_w")
    if h is not None or w is not None:
        if h is None or w is None:
            raise ProtoParseError("%s_h/%s_w must be given together" % (field, field))
        return (int(h), int(w))
    if required:
        raise ProtoParseError("missing %s in %r" % (square, sorted(p)))
    return (int(default), int(default))


def apply_layer(layer, bottoms, name=None, label=None, grad_scale=1.0,
                emit_loss=False):
    """Apply ONE computational caffe layer spec to bottom symbol(s).

    Returns the output symbol, or None for no-op layers (Accuracy,
    Silence). `label` and `grad_scale` feed loss layers
    (SoftmaxWithLoss) — the CaffeLoss surface. ``emit_loss`` makes
    SoftmaxWithLoss also emit the per-example NLL loss blob (the
    reference CaffeLoss's output) as a second, gradient-blocked head —
    see CaffeLoss. Raises NotImplementedError for unsupported types."""
    import mxnet_tpu as mx

    ltype = str(layer.get("type", ""))
    if name is None:
        # keep the spec's own name when present; otherwise leave None so
        # the NameManager generates a unique one (two anonymous
        # `layer{type:"Convolution"}` CaffeOps must not collide)
        name = layer.get("name")
        name = str(name).replace("/", "_") if name is not None else None
    data = bottoms[0] if bottoms else None

    if ltype == "Convolution":
        p = layer.get("convolution_param", {})
        return mx.sym.Convolution(
            data=data, name=name, num_filter=int(p["num_output"]),
            kernel=_hw(p, "kernel", required=True),
            stride=_hw(p, "stride", default=1),
            pad=_hw(p, "pad", default=0),
            dilate=_dilate(p, name),
            no_bias=not p.get("bias_term", True),
            num_group=int(p.get("group", 1)))
    if ltype == "Pooling":
        p = layer.get("pooling_param", {})
        global_pool = bool(p.get("global_pooling", False))
        pool_modes = {"MAX": "max", "AVE": "avg", 0: "max", 1: "avg"}
        mode = p.get("pool", "MAX")
        if mode not in pool_modes:
            raise NotImplementedError(
                "Pooling mode %r (%s) not supported" % (mode, name))
        return mx.sym.Pooling(
            data=data, name=name,
            pool_type=pool_modes[mode],
            # non-global pooling with no kernel spec is a broken prototxt:
            # caffe requires kernel_size/kernel_h+w, and silently pooling
            # with a (1, 1) kernel is a no-op that trains wrong (ADVICE r5)
            kernel=(_hw(p, "kernel", required=True)
                    if not global_pool else (1, 1)),
            stride=_hw(p, "stride", default=1),
            pad=_hw(p, "pad", default=0),
            # caffe sizes pooled maps with ceil(): 'full' convention
            pooling_convention="full",
            global_pool=global_pool)
    if ltype == "InnerProduct":
        p = layer.get("inner_product_param", {})
        return mx.sym.FullyConnected(
            data=mx.sym.Flatten(data), name=name,
            num_hidden=int(p["num_output"]),
            no_bias=not p.get("bias_term", True))
    if ltype == "ReLU":
        return mx.sym.Activation(data=data, act_type="relu", name=name)
    if ltype == "TanH":
        return mx.sym.Activation(data=data, act_type="tanh", name=name)
    if ltype == "Sigmoid":
        return mx.sym.Activation(data=data, act_type="sigmoid", name=name)
    if ltype == "Dropout":
        p = layer.get("dropout_param", {})
        return mx.sym.Dropout(data=data, name=name,
                              p=float(p.get("dropout_ratio", 0.5)))
    if ltype == "LRN":
        p = layer.get("lrn_param", {})
        return mx.sym.LRN(
            data=data, name=name,
            alpha=float(p.get("alpha", 1e-4)),
            beta=float(p.get("beta", 0.75)),
            knorm=float(p.get("k", 1.0)),
            nsize=int(p.get("local_size", 5)))
    if ltype == "Concat":
        return mx.sym.Concat(*bottoms, num_args=len(bottoms), name=name)
    if ltype == "Eltwise":
        ep = layer.get("eltwise_param", {})
        op = str(ep.get("operation", "SUM"))
        coeffs = [float(c) for c in _aslist(ep.get("coeff"))]
        if coeffs and op in ("SUM", "1"):
            if len(coeffs) != len(bottoms):
                raise ProtoParseError(
                    "Eltwise %s: %d coeffs for %d bottoms"
                    % (name, len(coeffs), len(bottoms)))
            terms = [b * c for b, c in zip(bottoms, coeffs)]
        else:
            if coeffs:
                raise NotImplementedError(
                    "Eltwise coeff only defined for SUM")
            terms = bottoms
        out = terms[0]
        for b in terms[1:]:
            if op in ("SUM", "1"):
                out = out + b
            elif op in ("PROD", "0"):
                out = out * b
            elif op in ("MAX", "2"):
                out = mx.sym.maximum(out, b)
            else:
                raise NotImplementedError(
                    "Eltwise operation %r not supported" % op)
        return out
    if ltype == "Flatten":
        return mx.sym.Flatten(data=data, name=name)
    if ltype in ("Softmax", "SoftmaxWithLoss"):
        kwargs = {}
        if emit_loss and ltype == "SoftmaxWithLoss" and label is None:
            # the NLL head below must read the SAME label the softmax
            # grad uses, so materialize the variable SoftmaxOutput would
            # have auto-created
            label = mx.sym.Variable(
                "%s_label" % (name if name is not None else "softmax"))
        if label is not None:
            kwargs["label"] = label
        if grad_scale != 1.0:
            kwargs["grad_scale"] = float(grad_scale)
        prob = mx.sym.SoftmaxOutput(data=data, name=name, **kwargs)
        if not (emit_loss and ltype == "SoftmaxWithLoss"):
            return prob
        # Reference CaffeLoss outputs the loss blob (caffe_loss-inl.h);
        # emit it alongside the softmax head as per-example NLL with the
        # gradient blocked — mx.metric.Caffe() then reports the loss
        # while the training gradient stays exactly SoftmaxOutput's
        # (ADVICE r5 item 1). The tiny floor keeps an underflowed
        # probability from turning the METRIC into inf; it is orders of
        # magnitude below f32 resolution for any trainable loss value.
        picked = mx.sym.choose_element_0index(prob, label)
        nll = 0.0 - mx.sym.log(picked + 1e-30)
        loss_name = "%s_loss" % (name if name is not None else "softmax")
        loss = mx.sym.BlockGrad(nll, name=loss_name)
        return mx.sym.Group([prob, loss])
    if ltype in ("Accuracy", "Silence"):
        return None
    raise NotImplementedError(
        "caffe layer type %r (%s) not supported" % (ltype, name))


def convert_symbol(prototxt_text):
    """Whole-network prototxt -> (symbol, input_name, input_dim or None)
    (ref: convert_symbol.py proto2symbol)."""
    import mxnet_tpu as mx

    net = parse_prototxt(prototxt_text)
    layers = _aslist(net.get("layer")) or _aslist(net.get("layers"))
    outputs = {}  # caffe top name -> symbol
    input_name, input_dim = None, None

    if "input" in net:
        input_name = _first(net["input"], "data")
        dims = net.get("input_dim")
        if dims is None and "input_shape" in net:
            dims = _first(net["input_shape"], {}).get("dim")
        input_dim = tuple(_aslist(dims)) if dims else None
        outputs[input_name] = mx.sym.Variable(input_name)

    sym = outputs.get(input_name)
    for layer in layers:
        ltype = str(layer.get("type", ""))
        name = str(layer.get("name", ltype)).replace("/", "_")
        bottom_names = _aslist(layer.get("bottom"))
        if ltype not in ("Input", "Data", "MemoryData", "HDF5Data",
                         "Accuracy", "Silence"):
            missing = [b for b in bottom_names if b not in outputs]
            if missing:
                raise ProtoParseError(
                    "layer %r: unknown bottom blob(s) %s — not produced by "
                    "any earlier layer or input" % (name, missing))
        bottoms = [outputs[b] for b in bottom_names if b in outputs]
        tops = _aslist(layer.get("top")) or [name]

        if ltype in ("Input", "Data", "MemoryData", "HDF5Data"):
            input_name = tops[0]
            shape = layer.get("input_param", {}).get("shape")
            if shape:
                input_dim = tuple(_aslist(_first(_aslist(shape), {}).get("dim")))
            sym = mx.sym.Variable(input_name)
        else:
            out = apply_layer(layer, bottoms, name=name)
            if out is None:  # Accuracy / Silence
                continue
            sym = out
        for t in tops:
            outputs[t] = sym

    if sym is None:
        raise ProtoParseError("prototxt contains no layers and no input")
    return sym, input_name, input_dim
