"""Fused on-device sampling for the serving engine (ISSUE 15).

Before this module the decode hot path was greedy: the jitted step
returned a full ``[B, V]`` logits array that crossed device->host every
step just so the engine could argmax it. Sampling now happens INSIDE
the jitted step — temperature scaling, top-k, top-p (nucleus) filtering
and the categorical draw — so the only per-step D2H is the ``[B]``
int32 token vector. The same sampler drives plain decode, the final
prefill chunk's first-token emission, the draft model's proposals, and
the speculative verify step's accept/reject + rejection-resampling.

Determinism contract (the parity suite's foundation):

- every random draw is keyed by ``(request seed, global token
  position, salt)`` via ``fold_in`` chains — NOT by step count — so a
  request replayed after eviction/recompute, or re-chunked differently,
  draws identical samples at identical positions;
- ``temperature == 0`` is exact greedy argmax over the RAW logits (no
  filtering applied), byte-identical to the pre-sampling decode path;
- the device sampler and :func:`host_sample` (the numpy reference, used
  by tests and the context-parallel prefill path) share ONE filtering
  implementation, parameterized by the array namespace, and both take
  their Gumbel/uniform bits from the same jax PRNG chain.

Salt layout (one stream per random purpose at each position)::

    SALT_TARGET   the token draw a plain decode at this position makes
                  (also the speculative bonus draw — full acceptance
                  lands exactly the sample non-spec decode would);
    SALT_ACCEPT   the accept/reject uniform judging a draft token;
    SALT_DRAFT    the draft model's proposal draw;
    SALT_RESIDUAL the rejection-resampling draw from max(p - q, 0).
"""
from __future__ import annotations

import numpy as np

__all__ = [
    "SALT_TARGET", "SALT_ACCEPT", "SALT_DRAFT", "SALT_RESIDUAL",
    "filter_dist", "fold_keys", "sample_tokens", "host_key",
    "host_sample",
]

SALT_TARGET = 0
SALT_ACCEPT = 1
SALT_DRAFT = 2
SALT_RESIDUAL = 3

_NEG = np.float32(-1e30)  # effective -inf that survives arithmetic


def _filter_full(xp, scaled, top_k, top_p):
    """The sort-based top-k/top-p masking (see filter_dist)."""
    V = scaled.shape[-1]
    # top-k: threshold at the kth largest (k <= 0 -> keep all V)
    k = xp.asarray(top_k, np.int32)
    k = xp.where(k <= 0, np.int32(V), k)
    desc = -xp.sort(-scaled, axis=-1)
    kth = xp.take_along_axis(
        desc, xp.clip(k[..., None] - 1, 0, V - 1).astype(np.int32), axis=-1)
    masked = xp.where(scaled < kth, _NEG, scaled)
    # top-p over the top-k-filtered softmax: keep the smallest prefix of
    # descending-prob tokens reaching top_p mass (a token is kept while
    # the mass BEFORE it is under the cut)
    m = xp.max(masked, axis=-1, keepdims=True)
    e = xp.exp(masked - m) * (masked > _NEG)
    probs = e / xp.sum(e, axis=-1, keepdims=True)
    order = xp.argsort(-probs, axis=-1, kind="stable") \
        if xp is np else xp.argsort(-probs, axis=-1)
    sp = xp.take_along_axis(probs, order, axis=-1)
    before = xp.cumsum(sp, axis=-1) - sp
    keep_sorted = before < xp.asarray(top_p, np.float32)[..., None]
    inv = xp.argsort(order, axis=-1, kind="stable") \
        if xp is np else xp.argsort(order, axis=-1)
    keep = xp.take_along_axis(keep_sorted, inv, axis=-1)
    masked = xp.where(keep, masked, _NEG)
    e2 = xp.exp(masked - xp.max(masked, axis=-1, keepdims=True)) \
        * (masked > _NEG)
    probs = e2 / xp.sum(e2, axis=-1, keepdims=True)
    return masked, probs


def _filter_fast(xp, scaled):
    """The no-filtering path: plain softmax (identical arithmetic to
    the full path when every token is kept — XLA sorts are the hot-path
    cost this branch avoids)."""
    m = xp.max(scaled, axis=-1, keepdims=True)
    e = xp.exp(scaled - m) * (scaled > _NEG)
    probs = e / xp.sum(e, axis=-1, keepdims=True)
    return scaled, probs


def filter_dist(xp, logits, temp, top_k, top_p):
    """Temperature/top-k/top-p filtering, shared device/host.

    ``xp`` is ``jax.numpy`` (traced) or ``numpy`` (host reference) —
    the op sequence is identical so the two paths agree bit-for-bit up
    to backend ulps. ``logits`` is ``[..., V]`` float32; ``temp`` /
    ``top_k`` / ``top_p`` broadcast over the leading axes (``top_k <=
    0`` disables top-k, ``top_p >= 1`` keeps everything).

    When NO row filters (the greedy/plain-temperature hot path), a
    ``lax.cond`` skips the sort machinery — XLA CPU sorts were the
    dominant per-step sampler cost. The two branches are arithmetic-
    identical for the keep-everything case, so a mixed batch sending a
    no-filter row down the full path samples the same token the host
    reference (which branches per request) draws.

    Returns ``(masked, probs)``: filtered scaled logits (disallowed
    entries at a large negative) and the renormalized distribution.
    Callers handle ``temp == 0`` rows themselves (greedy argmax); the
    scale here clamps to a tiny epsilon only so traced math stays
    finite on those rows.
    """
    logits = logits.astype(np.float32)
    t = xp.asarray(temp, np.float32)[..., None]
    scaled = logits / xp.maximum(t, np.float32(1e-6))
    if xp is np:
        if np.any((np.asarray(top_k) > 0) | (np.asarray(top_p) < 1.0)):
            return _filter_full(np, scaled, top_k, top_p)
        return _filter_fast(np, scaled)
    import jax

    pred = xp.any((xp.asarray(top_k, np.int32) > 0)
                  | (xp.asarray(top_p, np.float32) < 1.0))
    return jax.lax.cond(
        pred,
        lambda s: _filter_full(xp, s, top_k, top_p),
        lambda s: _filter_fast(xp, s),
        scaled)


def fold_keys(seed, pos, salt):
    """Traced per-row PRNG keys: ``fold_in(fold_in(PRNGKey(seed), pos),
    salt)`` vmapped over matching ``[N]`` seed/pos arrays."""
    import jax

    def one(s, p):
        k = jax.random.PRNGKey(s)
        return jax.random.fold_in(jax.random.fold_in(k, p), salt)

    return jax.vmap(one)(seed.astype(np.uint32), pos.astype(np.int32))


def sample_tokens(logits, temp, top_k, top_p, seed, pos, salt):
    """In-jit fused sampler over ``[N, V]`` logits rows.

    Returns ``(tokens [N] int32, probs [N, V] f32)`` where ``probs`` is
    the distribution the token was drawn from (one-hot at the argmax
    for ``temp == 0`` rows — exactly the greedy "distribution", which
    is what speculative rejection accounting needs for ``q``).
    """
    import jax
    import jax.numpy as jnp

    V = logits.shape[-1]
    masked, probs = filter_dist(jnp, logits, temp, top_k, top_p)
    greedy = jnp.argmax(logits.astype(jnp.float32), axis=-1)
    is_sampled = jnp.asarray(temp, jnp.float32) > 0
    # all-greedy batches (the common serving default) skip the threefry
    # key derivation + Gumbel draw entirely — this runs once per draft
    # proposal inside the scanned chain, so it's hot
    sampled = jax.lax.cond(
        jnp.any(is_sampled),
        lambda m: jnp.argmax(
            m + jax.vmap(lambda k: jax.random.gumbel(
                k, (V,), jnp.float32))(fold_keys(seed, pos, salt)),
            axis=-1),
        lambda m: greedy,
        masked)
    tok = jnp.where(is_sampled, sampled, greedy).astype(jnp.int32)
    probs = jnp.where(is_sampled[..., None], probs,
                      jax.nn.one_hot(greedy, V, dtype=jnp.float32))
    return tok, probs


# -- host reference ------------------------------------------------------------
def host_key(seed, pos, salt):
    """Eager-mode key for one (seed, position, salt) — the same chain
    :func:`fold_keys` builds inside the jitted programs."""
    import jax

    k = jax.random.PRNGKey(np.uint32(seed))
    return jax.random.fold_in(jax.random.fold_in(k, int(pos)), int(salt))


def host_sample(logits, temperature, top_k, top_p, seed, pos,
                salt=SALT_TARGET):
    """Numpy reference sampler for ONE logits row — the independent
    implementation the device sampler is pinned against (and what the
    context-parallel prefill path, whose logits are already on host,
    uses so cp-prefilled requests sample identically)."""
    import jax

    logits = np.asarray(logits, np.float32).reshape(-1)
    if temperature <= 0:
        return int(np.argmax(logits))
    masked, _ = filter_dist(
        np, logits[None], np.asarray([temperature], np.float32),
        np.asarray([top_k], np.int32), np.asarray([top_p], np.float32))
    g = np.asarray(jax.random.gumbel(host_key(seed, pos, salt),
                                     (logits.shape[0],), np.float32))
    return int(np.argmax(masked[0] + g))
