"""Fleet replica: one serving Engine behind ``fleet_*`` RPC arms.

A :class:`ReplicaServer` wraps an :class:`~..engine.Engine` in the
elastic RPC substrate (connection-per-request ``elastic/protocol.py``
framing, linted by ``mxlint --proto``):

=================  ====================================================
``fleet_submit``   admit one request (optionally with a redelivery
                   ``prefix`` — tokens the client already streamed on a
                   dead replica, folded into the recompute prefill)
``fleet_stream``   short-long-poll new tokens past ``have``
``fleet_cancel``   cancel one request
``fleet_drain``    close admissions; in-flight work runs to completion
``fleet_stats``    engine stats + accepting flag — the router's health
                   scrape (a transport failure here IS the death signal)
=================  ====================================================

The ``python -m mxnet_tpu.serving.fleet.replica`` entry point is the
supervised-process shape (control/supervisor.py): build a seeded demo
model (every replica in a fleet seeds identically, so any survivor can
continue any stream byte-identically), warm it, mark mxdash ready,
register with the router, and on SIGTERM drain gracefully, send
``fleet_leave``, and exit 0 — the scale_down/drain contract. Real
deployments embed :class:`ReplicaServer` around their own Engine the
same way.
"""
from __future__ import annotations

import argparse
import os
import signal
import socketserver
import sys
import threading
import time

import numpy as np

from ... import telemetry as _tel
from ...base import MXNetError
from ...elastic import protocol
from ..engine import Engine, QueueFullError, ServingConfig

__all__ = ["ReplicaServer", "main"]

#: server-side cap on one fleet_stream long-poll (seconds) — well under
#: the client's 30 s RPC timeout (the wsync publisher discipline)
_STREAM_WAIT_CAP = 5.0


class _Handler(socketserver.BaseRequestHandler):
    def handle(self):
        try:
            req = protocol.recv_msg(self.request, what="fleet request")
            if req is None:
                return
            wire = req.pop("_trace", None)
            try:
                with _tel.span("fleet.serve.%s" % req.get("op"),
                               wire=wire):
                    resp = self.server.replica._dispatch(req)
            except MXNetError as e:
                resp = {"status": "error", "message": str(e)}
            if _tel.ENABLED:
                resp.setdefault("_srv_t", time.time())
            protocol.send_msg(self.request, resp)
        except (OSError, protocol.ProtocolError):
            pass  # client went away mid-request — its retry policy heals


class _Server(socketserver.ThreadingTCPServer):
    allow_reuse_address = True
    daemon_threads = True


class ReplicaServer:
    """One Engine served over ``fleet_*`` RPC.

    Parameters
    ----------
    engine : Engine
        The wrapped engine; the caller owns its step drive
        (``engine.start()`` for a live process, direct ``step()`` for
        deterministic tests).
    name : str
        Fleet-wide replica name (the supervisor/router key).
    bind : (host, port) or None
        RPC endpoint (port 0 ephemeral). ``None`` builds a socketless
        replica whose ``_dispatch`` the router drives in-process (the
        bench/mxrace shape — no sockets, same code path).
    """

    def __init__(self, engine, name="replica0", bind=("127.0.0.1", 0)):
        self.engine = engine
        self.name = str(name)
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._streams = {}       # rid -> {"buf": [...], "done", "status"}
        self._server = None
        self._thread = None
        if bind is not None:
            self._server = _Server(tuple(bind), _Handler)
            self._server.replica = self

    # -- lifecycle -----------------------------------------------------------
    @property
    def addr(self):
        if self._server is None:
            raise MXNetError("replica was built socketless (bind=None)")
        return self._server.server_address

    def start(self):
        """Serve in a daemon thread; returns the bound (host, port)."""
        if self._server is None:
            raise MXNetError("replica was built socketless (bind=None)")
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._server.serve_forever, name="mx-fleet-rep",
                daemon=True)
            self._thread.start()
        return self.addr

    def close(self):
        if self._server is not None and self._thread is not None:
            self._server.shutdown()
            self._server.server_close()
            self._thread = None

    # -- the per-request pump ------------------------------------------------
    def _pump(self, rid, handle):
        """Drain one StreamHandle into its wire buffer (daemon thread
        per request — the replica is the stream's consumer, so the
        engine's idle reaper never fires on fleet traffic; an abandoned
        ROUTER is handled by fleet_cancel / the router's own journal)."""
        try:
            for tok in handle.tokens():
                with self._cond:
                    self._streams[rid]["buf"].append(int(tok))
                    self._cond.notify_all()
        finally:
            with self._cond:
                rec = self._streams[rid]
                rec["done"] = True
                rec["status"] = handle.status
                self._cond.notify_all()

    # -- RPC dispatch --------------------------------------------------------
    def _dispatch(self, req):
        op = req.get("op")
        if op == "fleet_submit":
            try:
                handle = self.engine.submit(
                    np.asarray(req["prompt"], np.int32),
                    max_new_tokens=int(req["max_new"]),
                    eos_id=req.get("eos_id"),
                    temperature=float(req.get("temperature") or 0.0),
                    top_k=int(req.get("top_k") or 0),
                    top_p=float(req.get("top_p") or 1.0),
                    seed=int(req.get("seed") or 0),
                    prefix_tokens=req.get("prefix"))
            except QueueFullError as e:
                # backpressure is a protocol answer, not an error: the
                # router backs off for retry_after_s and sheds elsewhere
                return {"status": "full",
                        "queue_depth": e.queue_depth,
                        "retry_after_s": e.retry_after_s}
            rid = handle.request_id
            with self._cond:
                self._streams[rid] = {"buf": [], "done": False,
                                      "status": None, "handle": handle}
            threading.Thread(target=self._pump, args=(rid, handle),
                             name="mx-fleet-pump-%d" % rid,
                             daemon=True).start()
            return {"status": "ok", "rid": rid, "name": self.name}
        if op == "fleet_stream":
            rid = req["rid"]
            have = int(req.get("have") or 0)
            wait = min(float(req.get("wait") or 0.0), _STREAM_WAIT_CAP)
            deadline = time.monotonic() + wait
            with self._cond:
                rec = self._streams.get(rid)
                if rec is None:
                    return {"status": "error",
                            "message": "unknown rid %r" % (rid,)}
                while (len(rec["buf"]) <= have and not rec["done"]):
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        break
                    self._cond.wait(min(remaining, 0.5))
                toks = list(rec["buf"][have:])
                done = rec["done"] and have + len(toks) >= len(rec["buf"])
                out = {"status": "ok", "tokens": toks, "done": done,
                       "final_status": rec["status"]}
                if done:
                    del self._streams[rid]
                return out
        if op == "fleet_cancel":
            rid = req["rid"]
            with self._cond:
                rec = self._streams.get(rid)
            if rec is not None:
                rec["handle"].cancel()
            return {"status": "ok", "known": rec is not None}
        if op == "fleet_drain":
            drained = self.engine.drain(
                wait=bool(req.get("wait")),
                timeout=req.get("drain_timeout"))
            return {"status": "ok", "drained": bool(drained)}
        if op == "fleet_stats":
            return {"status": "ok", "name": self.name,
                    "accepting": self.engine.accepting(),
                    "stats": self.engine.stats()}
        return {"status": "error", "message": "unknown op %r" % (op,)}


# -- the supervised-process entry point --------------------------------------
def _build_demo_engine(seed):
    """A small, deterministic engine for the chaos/bench fleet: every
    replica seeded identically serves byte-identical streams, which is
    what makes redelivery provable end to end."""
    import jax

    from ...models.transformer import TransformerConfig, init_params

    cfg = TransformerConfig(
        vocab_size=int(os.environ.get("MXNET_FLEET_VOCAB", "61")),
        num_layers=2, d_model=32, num_heads=2, d_ff=64,
        max_seq_len=96, dtype="float32")
    params = init_params(cfg, jax.random.PRNGKey(int(seed)))
    scfg = ServingConfig(block_size=8, num_blocks=97, max_batch=4,
                         max_active=8, prefill_chunk=16,
                         max_queue_depth=int(
                             os.environ.get("MXNET_FLEET_QUEUE", "16")))
    return Engine(params, cfg, scfg)


def main(argv=None):
    ap = argparse.ArgumentParser(
        prog="python -m mxnet_tpu.serving.fleet.replica",
        description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("--router", default=os.environ.get(
        "MXNET_FLEET_ROUTER", ""), help="router host:port to register "
        "with (MXNET_FLEET_ROUTER)")
    ap.add_argument("--name", default=os.environ.get(
        "MXNET_FLEET_NAME", "") or os.environ.get(
        "MXCTL_REPLICA_NAME", "replica0"))
    ap.add_argument("--bind", default=os.environ.get(
        "MXNET_FLEET_BIND", "127.0.0.1:0"), metavar="HOST:PORT")
    ap.add_argument("--seed", type=int, default=int(
        os.environ.get("MXNET_FLEET_SEED", "0") or 0),
        help="model init seed — identical across the fleet")
    args = ap.parse_args(argv)

    _tel.server.mark_ready(False, "starting")
    host, _, port = args.bind.rpartition(":")
    eng = _build_demo_engine(args.seed)
    # warm the jit programs BEFORE advertising ready: with a shared
    # MXNET_COMPILE_CACHE_DIR a respawned replica comes back warm, the
    # property the scale-up chaos leg measures
    eng.generate([np.arange(5, dtype=np.int32),
                  np.arange(23, dtype=np.int32)], max_new_tokens=3)
    eng.start()
    rep = ReplicaServer(eng, name=args.name,
                        bind=(host or "127.0.0.1", int(port or 0)))
    bound = rep.start()
    print("fleet replica %s listening on %s:%d pid %d"
          % (args.name, bound[0], bound[1], os.getpid()), flush=True)

    stop = threading.Event()
    signal.signal(signal.SIGTERM, lambda *_a: stop.set())
    signal.signal(signal.SIGINT, lambda *_a: stop.set())

    client = None
    if args.router:
        from .router import FleetClient

        client = FleetClient(args.router)
        client.register(name=args.name,
                        addr="%s:%d" % (bound[0], bound[1]))
    _tel.server.mark_ready(True)

    while not stop.is_set():
        stop.wait(0.2)

    # SIGTERM -> drain contract: admissions close, in-flight requests
    # finish, THEN we leave the fleet and exit 0 (zero dropped streams)
    _tel.server.mark_ready(False, "stopping")
    eng.drain(wait=True, timeout=float(
        os.environ.get("MXNET_FLEET_DRAIN_TIMEOUT", "30") or 30))
    if client is not None:
        try:
            client.leave(name=args.name)
        except Exception:  # noqa: BLE001 - router may already be gone
            pass
    eng.stop()
    rep.close()
    if _tel.ENABLED:
        _tel.flush(mark="exit")
    return 0


if __name__ == "__main__":
    sys.exit(main())
