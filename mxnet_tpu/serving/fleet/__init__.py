"""mxfleet: a fault-isolated serving fleet (ISSUE 20).

N replicas — each one :class:`~..engine.Engine` in its own supervised
process (``replica.py``) — behind a health-routed front-end
(``router.py``). Any replica is a disposable fault domain: a SIGKILL
costs redelivered requests (already-streamed tokens folded into a
recompute prefill on a survivor), never lost streams. The router's
aggregate view feeds mxctl's ``scale_up``/``scale_down`` actuators
(control/actuators.py); ``tools/chaos.py --fleet`` proves the whole
loop. Architecture notes: docs/how_to/serving.md (fleet section).
"""
from __future__ import annotations

from .replica import ReplicaServer
from .router import FleetClient, FleetStream, Router

__all__ = ["ReplicaServer", "Router", "FleetClient", "FleetStream"]
