"""Fleet router: the health-routed serving front-end.

One :class:`Router` owns the live replica set and places every request
(least-loaded with session affinity) over the ``fleet_*`` RPC arms a
:class:`~.replica.ReplicaServer` serves. The robustness core is the
per-request **redelivery journal**: the router remembers each request's
prompt, sampling params, and every token already streamed — so when a
replica dies mid-decode (health-scrape failure or a torn stream RPC),
the request is re-placed on a survivor with the streamed tokens folded
into a recompute prefill (``Engine.submit(prefix_tokens=...)``, the
PR 8 eviction-recompute trick lifted one tier up). The client's stream
never tears and, because sampling is keyed by (seed, global position),
the continuation is byte-identical (exact at temperature 0).

Discipline notes:

- **scrape-failure = dead** (the mxctl liveness rule): an evicted
  replica stays in the table with ``alive=0`` so the
  :class:`~...control.probes.FleetProbe` keeps emitting its sample and
  the ``restart_replica`` rule can respawn it; re-registration under
  the same name revives the entry. A graceful ``fleet_leave`` (the
  drain contract) removes the entry instead — retirement, not death.
- **admission backpressure**: past ``MXNET_FLEET_PENDING_MAX`` queued
  placements, ``submit`` raises :class:`~..engine.QueueFullError`
  carrying queue depth + a retry-after hint; a replica answering
  ``full`` is backed off for ITS hinted interval rather than hammered.
- **deterministic drive**: ``step()`` runs one pump iteration
  (scrape -> place -> poll) under one lock — tests and the mxrace
  schedule explorer drive it directly; ``start()`` wraps it in a
  thread for live processes.
"""
from __future__ import annotations

import collections
import itertools
import os
import queue as _queue
import socketserver
import threading
import time

import numpy as np

from ... import telemetry as _tel
from ...base import MXNetError
from ...elastic import protocol
from ...elastic.client import parse_addr
from ...resilience import faults as _faults
from ...resilience.retry import RetryPolicy
from ..engine import QueueFullError

__all__ = ["FleetClient", "FleetStream", "Router"]

_END = object()


def _env_float(name, default):
    try:
        return float(os.environ.get(name, "") or default)
    except ValueError:
        return default


def _env_int(name, default):
    try:
        return int(os.environ.get(name, "") or default)
    except ValueError:
        return default


class FleetClient:
    """One handle on a fleet peer (replica or router). Stateless
    between calls; transport errors retry under the kv.coord policy
    (``MXNET_KV_RETRIES``). ``direct=`` wires the client straight to an
    in-process peer's ``_dispatch`` — the bench/mxrace shape: no
    sockets, same protocol dicts, same status handling."""

    def __init__(self, addr=None, direct=None, timeout=30.0):
        if addr is None and direct is None:
            raise MXNetError("FleetClient needs addr or direct")
        self.direct = direct
        self.addr = (parse_addr(addr) if isinstance(addr, str)
                     else tuple(addr) if addr is not None else None)
        self.timeout = float(timeout)
        attempts = max(1, _env_int("MXNET_KV_RETRIES", 4))
        self._policy = RetryPolicy(max_attempts=attempts, base_delay=0.05,
                                   max_delay=1.0, jitter=0.25)

    def call(self, op, check=True, **fields):
        """One RPC. ``error`` status raises MXNetError (when
        ``check``); ``full`` and other statuses are protocol answers
        the caller dispatches on."""
        req = dict(fields)
        req["op"] = op
        if self.direct is not None:
            try:
                resp = self.direct._dispatch(dict(req))
            except MXNetError as e:
                resp = {"status": "error", "message": str(e)}
        else:
            def _rpc():
                _faults.point("kv.coord")
                return protocol.call(self.addr, req, timeout=self.timeout)

            _rpc.__name__ = "fleet %s" % op
            if not _tel.ENABLED:
                resp = self._policy.call(_rpc)
            else:
                with _tel.span("fleet.rpc.%s" % op):
                    req["_trace"] = _tel.wire_context()
                    resp = self._policy.call(_rpc)
        if check and resp.get("status") == "error":
            raise MXNetError("fleet peer rejected %s: %s"
                             % (op, resp.get("message", "(no message)")))
        return resp

    # -- one wrapper per protocol op (mxlint --proto reads these) ------------
    def submit(self, prompt, max_new, eos_id=None, temperature=0.0,
               top_k=0, top_p=1.0, seed=0, prefix=None):
        return self.call("fleet_submit", check=False, prompt=prompt,
                         max_new=max_new, eos_id=eos_id,
                         temperature=temperature, top_k=top_k, top_p=top_p,
                         seed=seed, prefix=prefix)

    def stream(self, rid, have=0, wait=0.0):
        return self.call("fleet_stream", rid=rid, have=have, wait=wait)

    def cancel_req(self, rid):
        return self.call("fleet_cancel", rid=rid)

    def drain(self, wait=False, drain_timeout=None):
        return self.call("fleet_drain", wait=wait,
                         drain_timeout=drain_timeout)

    def stats(self):
        return self.call("fleet_stats")

    def register(self, name, addr):
        return self.call("fleet_register", name=name, addr=addr)

    def leave(self, name):
        return self.call("fleet_leave", name=name)


class FleetStream:
    """Router-side token stream: the same surface as the engine's
    :class:`~..engine.StreamHandle`, fed by the router's poll pump —
    redelivery is invisible here (tokens arrive exactly once, in
    order)."""

    def __init__(self, router, rid):
        self._router = router
        self._q = _queue.Queue()
        self.rid = rid
        self.status = "running"

    def _emit(self, token):
        self._q.put(int(token))

    def _end(self, status):
        self.status = status
        self._q.put(_END)

    def cancel(self):
        self._router.cancel(self.rid)

    def tokens(self, timeout=None):
        while True:
            item = self._q.get(timeout=timeout)
            if item is _END:
                return
            yield item

    def result(self, timeout=None):
        return list(self.tokens(timeout=timeout))


class _Replica:
    """Router-side view of one replica."""

    __slots__ = ("name", "addr", "client", "alive", "accepting",
                 "inflight", "stats", "full_until", "last_scrape_t")

    def __init__(self, name, addr, client):
        self.name = name
        self.addr = addr
        self.client = client
        self.alive = True
        self.accepting = True
        self.inflight = set()        # router rids placed here
        self.stats = {}              # last scraped engine stats
        self.full_until = 0.0        # backoff deadline from a "full"
        self.last_scrape_t = 0.0


class _FleetRequest:
    """The redelivery journal entry: everything needed to re-place the
    request on a survivor with nothing the client saw lost."""

    __slots__ = ("rid", "prompt", "max_new", "eos_id", "temperature",
                 "top_k", "top_p", "seed", "session", "tokens", "stream",
                 "replica", "rrid", "placed_tokens", "trace",
                 "pending_trace", "redeliveries", "submit_t",
                 "first_token_t")

    def __init__(self, rid, prompt, max_new, eos_id, temperature, top_k,
                 top_p, seed, session):
        self.rid = rid
        self.prompt = [int(t) for t in prompt]
        self.max_new = int(max_new)
        self.eos_id = eos_id
        self.temperature = float(temperature)
        self.top_k = int(top_k)
        self.top_p = float(top_p)
        self.seed = int(seed)
        self.session = session
        self.tokens = []             # every token streamed so far
        self.stream = None
        self.replica = None          # current placement (name)
        self.rrid = None             # replica-side request id
        self.placed_tokens = 0       # len(tokens) at current placement
        self.trace = None            # request-lifetime trace id
        self.pending_trace = None    # redelivery-transaction trace id
        self.redeliveries = 0
        self.submit_t = None
        self.first_token_t = None


class _Handler(socketserver.BaseRequestHandler):
    def handle(self):
        try:
            req = protocol.recv_msg(self.request, what="fleet request")
            if req is None:
                return
            wire = req.pop("_trace", None)
            try:
                with _tel.span("fleet.router.%s" % req.get("op"),
                               wire=wire):
                    resp = self.server.router._dispatch(req)
            except MXNetError as e:
                resp = {"status": "error", "message": str(e)}
            if _tel.ENABLED:
                resp.setdefault("_srv_t", time.time())
            protocol.send_msg(self.request, resp)
        except (OSError, protocol.ProtocolError):
            pass  # client went away mid-request — its retry policy heals


class _Server(socketserver.ThreadingTCPServer):
    allow_reuse_address = True
    daemon_threads = True


class Router:
    """Health-routed front-end over the live replica set.

    Parameters
    ----------
    bind : (host, port) or None
        Registration RPC endpoint (``fleet_register``/``fleet_leave``;
        port 0 ephemeral). ``None`` builds a socketless router for
        tests/bench that register replicas in-process.
    inflight_cap : int, optional
        Per-replica concurrent placements (``MXNET_FLEET_INFLIGHT``,
        default 8).
    pending_max : int, optional
        Router-level admission cap on unplaced requests
        (``MXNET_FLEET_PENDING_MAX``, default 64); past it ``submit``
        raises :class:`QueueFullError` with a retry-after hint.
    health_interval : float, optional
        Seconds between ``fleet_stats`` scrapes per replica
        (``MXNET_FLEET_HEALTH_INTERVAL``, default 2.0).
    """

    def __init__(self, bind=("127.0.0.1", 0), inflight_cap=None,
                 pending_max=None, health_interval=None):
        self.inflight_cap = (inflight_cap if inflight_cap is not None
                             else _env_int("MXNET_FLEET_INFLIGHT", 8))
        self.pending_max = (pending_max if pending_max is not None
                            else _env_int("MXNET_FLEET_PENDING_MAX", 64))
        self.health_interval = (
            health_interval if health_interval is not None
            else _env_float("MXNET_FLEET_HEALTH_INTERVAL", 2.0))
        self._lock = threading.RLock()
        self._replicas = {}          # name -> _Replica
        self._requests = {}          # rid -> _FleetRequest
        self._pending = collections.deque()
        self._affinity = {}          # session -> replica name
        self._rids = itertools.count()
        self._ttfts = []
        self._rate_window = []       # (t, cumulative tokens)
        self._tokens_total = 0
        self._last_rate = 0.0
        self._counts = {"submitted": 0, "completed": 0, "cancelled": 0,
                        "rejected": 0, "redelivered": 0, "evictions": 0,
                        "registered": 0, "left": 0}
        self._thread = None
        self._stop = False
        self._server = None
        self._srv_thread = None
        if bind is not None:
            self._server = _Server(tuple(bind), _Handler)
            self._server.router = self

    # -- lifecycle -----------------------------------------------------------
    @property
    def addr(self):
        if self._server is None:
            raise MXNetError("router was built socketless (bind=None)")
        return self._server.server_address

    def serve(self):
        """Answer registration RPCs from a daemon thread; returns the
        bound (host, port)."""
        if self._server is None:
            raise MXNetError("router was built socketless (bind=None)")
        if self._srv_thread is None:
            self._srv_thread = threading.Thread(
                target=self._server.serve_forever, name="mx-fleet-router",
                daemon=True)
            self._srv_thread.start()
        return self.addr

    def start(self, interval=0.02):
        """Drive ``step()`` from a background thread (live mode)."""

        def loop():
            while True:
                with self._lock:
                    if self._stop:
                        return
                if not self.step():
                    time.sleep(interval)

        with self._lock:
            if self._thread is not None:
                return
            self._stop = False
            self._thread = threading.Thread(target=loop,
                                            name="mx-fleet-pump",
                                            daemon=True)
            self._thread.start()

    def stop(self):
        with self._lock:
            thread = self._thread
            self._stop = True
        if thread is not None:
            thread.join()
            with self._lock:
                if self._thread is thread:
                    self._thread = None

    def close(self):
        self.stop()
        if self._server is not None and self._srv_thread is not None:
            self._server.shutdown()
            self._server.server_close()
            self._srv_thread = None

    # -- membership ----------------------------------------------------------
    def register(self, name, addr=None, client=None):
        """Add (or revive) a replica. Called by the ``fleet_register``
        arm when a replica finishes warmup (the /readyz-gated
        registration), and directly by tests/bench with ``client=``."""
        if client is None:
            if addr is None:
                raise MXNetError("register needs addr or client")
            client = FleetClient(addr)
        with self._lock:
            self._replicas[str(name)] = _Replica(str(name), addr, client)
            self._counts["registered"] += 1
            if _tel.ENABLED:
                _tel.counter("fleet.replicas_registered_total").inc()
                _tel.event("fleet.replica.register", replica=str(name),
                           addr=str(addr))

    def register_local(self, name, replica):
        """Register an in-process ReplicaServer (no sockets)."""
        self.register(name, addr=None, client=FleetClient(direct=replica))

    def leave(self, name):
        """Graceful departure (the drain-retire contract): the entry is
        REMOVED — unlike a crash eviction, nothing keeps reporting it
        dead, so no liveness rule respawns it."""
        with self._lock:
            rep = self._replicas.pop(str(name), None)
            if rep is None:
                return False
            self._counts["left"] += 1
            if _tel.ENABLED:
                _tel.counter("fleet.replicas_left_total").inc()
                _tel.event("fleet.replica.leave", replica=str(name),
                           inflight=len(rep.inflight))
            # a clean leave should have drained first; anything still
            # in flight is redelivered like a death (belt & braces)
            self._redeliver_locked(rep, "leave")
            self._affinity = {s: n for s, n in self._affinity.items()
                              if n != str(name)}
            return True

    def _dispatch(self, req):
        op = req.get("op")
        if op == "fleet_register":
            self.register(req["name"], addr=req["addr"])
            with self._lock:
                n = len(self._replicas)
            return {"status": "ok", "replicas": n}
        if op == "fleet_leave":
            known = self.leave(req["name"])
            return {"status": "ok", "known": bool(known)}
        return {"status": "error", "message": "unknown op %r" % (op,)}

    # -- intake --------------------------------------------------------------
    def submit(self, prompt, max_new_tokens=16, eos_id=None,
               temperature=0.0, top_k=0, top_p=1.0, seed=0, session=None):
        """Queue one request for placement; returns a FleetStream.
        Raises :class:`QueueFullError` past ``pending_max`` with the
        soonest replica-hinted retry-after."""
        with self._lock:
            depth = len(self._pending)
            if depth >= self.pending_max:
                self._counts["rejected"] += 1
                now = time.monotonic()
                hints = [r.full_until - now
                         for r in self._replicas.values()
                         if r.alive and r.full_until > now]
                if _tel.ENABLED:
                    _tel.counter("fleet.requests_rejected").inc()
                raise QueueFullError(
                    "router admission queue full (%d)" % self.pending_max,
                    queue_depth=depth,
                    retry_after_s=min(hints) if hints else 1.0)
            rid = next(self._rids)
            self._counts["submitted"] += 1
            entry = _FleetRequest(rid, prompt, max_new_tokens, eos_id,
                                  temperature, top_k, top_p, seed, session)
            entry.submit_t = time.monotonic()
            entry.stream = FleetStream(self, rid)
            if _tel.ENABLED:
                entry.trace = _tel.mint_trace()
                _tel.counter("fleet.requests_total").inc()
                _tel.event("fleet.request.submit", trace=entry.trace,
                           rid=rid, prompt_len=len(entry.prompt),
                           max_new_tokens=entry.max_new, session=session)
            self._requests[rid] = entry
            self._pending.append(rid)
            return entry.stream

    def cancel(self, rid):
        with self._lock:
            entry = self._requests.get(rid)
            if entry is None:
                return False
            rep = (self._replicas.get(entry.replica)
                   if entry.replica is not None else None)
            if rep is not None:
                rep.inflight.discard(rid)
                try:
                    rep.client.cancel_req(rid=entry.rrid)
                except Exception:  # noqa: BLE001 - dying replica: moot
                    pass
            if rid in self._pending:
                self._pending.remove(rid)
            self._counts["cancelled"] += 1
            if _tel.ENABLED:
                _tel.counter("fleet.requests_cancelled").inc()
            entry.stream._end("cancelled")
            del self._requests[rid]
            return True

    # -- the pump ------------------------------------------------------------
    def step(self, now=None):
        """One deterministic pump iteration: health scrape, placement,
        stream poll. Returns True when anything happened."""
        now = time.monotonic() if now is None else now
        with self._lock:
            worked = self._scrape_locked(now)
            worked = self._place_locked(now) or worked
            worked = self._poll_locked(now) or worked
            self._update_gauges_locked(now)
            return worked

    def _scrape_locked(self, now):
        worked = False
        for name in sorted(self._replicas):
            rep = self._replicas[name]
            if not rep.alive:
                continue
            if now - rep.last_scrape_t < self.health_interval:
                continue
            rep.last_scrape_t = now
            try:
                resp = rep.client.stats()
            except Exception as e:  # noqa: BLE001 - scrape failure = dead
                self._evict_locked(rep, "scrape_failed: %s"
                                   % type(e).__name__)
                worked = True
                continue
            rep.stats = dict(resp.get("stats") or {})
            rep.accepting = bool(resp.get("accepting", True))
        return worked

    def _candidates_locked(self, now):
        return [r for _, r in sorted(self._replicas.items())
                if r.alive and r.accepting and now >= r.full_until
                and len(r.inflight) < self.inflight_cap]

    def _place_locked(self, now):
        placed = False
        while self._pending:
            cands = self._candidates_locked(now)
            if not cands:
                break
            rid = self._pending[0]
            entry = self._requests[rid]
            rep = None
            if entry.session is not None:
                sticky = self._affinity.get(entry.session)
                rep = next((r for r in cands if r.name == sticky), None)
            if rep is None:
                # least-loaded: router-side in-flight count first, then
                # the scraped engine queue depth, name as tiebreak
                rep = min(cands, key=lambda r: (
                    len(r.inflight), r.stats.get("queue_depth", 0),
                    r.name))
            self._pending.popleft()
            prefix = entry.tokens if entry.tokens else None
            try:
                resp = rep.client.submit(
                    prompt=entry.prompt, max_new=entry.max_new,
                    eos_id=entry.eos_id, temperature=entry.temperature,
                    top_k=entry.top_k, top_p=entry.top_p,
                    seed=entry.seed, prefix=prefix)
            except Exception as e:  # noqa: BLE001 - transport = death
                self._pending.appendleft(rid)
                self._evict_locked(rep, "submit_failed: %s"
                                   % type(e).__name__)
                placed = True
                continue
            if resp.get("status") == "full":
                rep.full_until = now + float(
                    resp.get("retry_after_s") or 1.0)
                self._pending.appendleft(rid)
                continue
            if resp.get("status") != "ok":
                # a rejected placement (e.g. geometry) is terminal for
                # the REQUEST, not the replica
                entry.stream._end("error")
                del self._requests[rid]
                placed = True
                continue
            entry.replica = rep.name
            entry.rrid = resp["rid"]
            entry.placed_tokens = len(entry.tokens)
            rep.inflight.add(rid)
            if entry.session is not None:
                self._affinity[entry.session] = rep.name
            if _tel.ENABLED:
                _tel.event("fleet.request.place",
                           trace=entry.pending_trace or entry.trace,
                           rid=rid, replica=rep.name,
                           redeliveries=entry.redeliveries,
                           prefix_len=entry.placed_tokens)
            entry.pending_trace = None
            placed = True
        return placed

    def _poll_locked(self, now):
        worked = False
        for name in sorted(self._replicas):
            rep = self._replicas[name]
            if not rep.alive:
                continue
            for rid in sorted(rep.inflight):
                entry = self._requests[rid]
                have = len(entry.tokens) - entry.placed_tokens
                try:
                    resp = rep.client.stream(rid=entry.rrid, have=have)
                except Exception as e:  # noqa: BLE001 - transport = death
                    self._evict_locked(rep, "stream_failed: %s"
                                       % type(e).__name__)
                    worked = True
                    break
                toks = resp.get("tokens") or []
                for t in toks:
                    entry.tokens.append(int(t))
                    entry.stream._emit(t)
                    self._tokens_total += 1
                    self._rate_window.append((now, self._tokens_total))
                    if entry.first_token_t is None:
                        entry.first_token_t = now
                        self._ttfts.append(now - entry.submit_t)
                        if _tel.ENABLED:
                            _tel.histogram("fleet.ttft_s").observe(
                                now - entry.submit_t)
                if toks:
                    worked = True
                if resp.get("done"):
                    status = resp.get("final_status") or "finished"
                    rep.inflight.discard(rid)
                    del self._requests[rid]
                    self._counts["completed"] += 1
                    if _tel.ENABLED:
                        _tel.counter("fleet.requests_completed").inc()
                        _tel.event("fleet.request.complete",
                                   trace=entry.trace, rid=rid,
                                   status=status,
                                   tokens=len(entry.tokens),
                                   redeliveries=entry.redeliveries)
                    entry.stream._end(status)
                    worked = True
        return worked

    def _evict_locked(self, rep, reason):
        """Crash eviction: mark dead (the entry STAYS, reporting
        alive=0 to the FleetProbe) and redeliver its in-flight
        requests."""
        if not rep.alive:
            return
        rep.alive = False
        rep.accepting = False
        self._counts["evictions"] += 1
        if _tel.ENABLED:
            _tel.counter("fleet.replica_evictions_total").inc()
            _tel.event("fleet.replica.evict", replica=rep.name,
                       reason=reason, inflight=len(rep.inflight))
        self._redeliver_locked(rep, reason)
        self._affinity = {s: n for s, n in self._affinity.items()
                          if n != rep.name}

    def _redeliver_locked(self, rep, reason):
        """Re-queue everything in flight on ``rep`` at the FRONT of the
        pending queue (original submit order preserved — rids are
        monotonic). Each redelivery is one journal transaction: a fresh
        trace id shared by its ``fleet.redeliver`` event and the
        ``fleet.request.place`` that lands it on a survivor."""
        rids = sorted(rep.inflight)
        rep.inflight.clear()
        for rid in reversed(rids):
            entry = self._requests[rid]
            entry.replica = None
            entry.rrid = None
            entry.redeliveries += 1
            self._counts["redelivered"] += 1
            if _tel.ENABLED:
                entry.pending_trace = _tel.mint_trace()
                _tel.counter("fleet.redeliveries_total").inc()
                _tel.event("fleet.redeliver", trace=entry.pending_trace,
                           rid=rid, from_replica=rep.name, reason=reason,
                           tokens_streamed=len(entry.tokens),
                           redeliveries=entry.redeliveries)
            self._pending.appendleft(rid)

    # -- reporting -----------------------------------------------------------
    def _update_gauges_locked(self, now):
        win = [x for x in self._rate_window if now - x[0] <= 2.0]
        self._rate_window = win
        rate = 0.0
        if len(win) >= 2 and win[-1][0] > win[0][0]:
            rate = (win[-1][1] - win[0][1]) / (win[-1][0] - win[0][0])
        self._last_rate = rate
        if _tel.ENABLED:
            _tel.gauge("fleet.replicas_alive").set(
                sum(1 for r in self._replicas.values() if r.alive))
            _tel.gauge("fleet.queue_depth").set(len(self._pending))
            _tel.gauge("fleet.tokens_per_s").set(rate)

    def stats(self):
        """Aggregate + per-replica view (plain numbers — what the
        FleetProbe turns into mxctl TargetSamples)."""
        def pct(xs, q):
            if not xs:
                return None
            return float(np.percentile(np.asarray(xs), q))

        with self._lock:
            now = time.monotonic()
            self._update_gauges_locked(now)
            reps = {}
            for name, r in sorted(self._replicas.items()):
                reps[name] = {
                    "alive": r.alive,
                    "accepting": r.accepting,
                    "inflight": len(r.inflight),
                    "queue_depth": r.stats.get("queue_depth", 0),
                    "tokens_per_s": r.stats.get("tokens_per_s_window",
                                                0.0),
                    "addr": r.addr,
                }
            out = dict(self._counts)
            out.update({
                "replicas": reps,
                "replicas_alive": sum(
                    1 for r in self._replicas.values() if r.alive),
                "replicas_accepting": sum(
                    1 for r in self._replicas.values()
                    if r.alive and r.accepting),
                "pending": len(self._pending),
                "inflight": sum(len(r.inflight)
                                for r in self._replicas.values()),
                "queue_depth": len(self._pending) + sum(
                    r.stats.get("queue_depth", 0)
                    for r in self._replicas.values() if r.alive),
                "tokens_per_s": self._last_rate,
                "ttft_p50_s": pct(self._ttfts, 50),
                "ttft_p99_s": pct(self._ttfts, 99),
            })
            return out
