"""Request front-end: ``Engine.submit(prompt) -> stream of tokens``.

The serving subsystem's public surface. An Engine owns one model's
params, a paged KV pool sized by :class:`ServingConfig`, a
:class:`~.scheduler.Scheduler`, and the bucketed jitted step functions
(:class:`~.model.ServingModel`). Each ``step()`` runs at most one
decode batch and one prefill batch (scheduler.py module docstring);
``start()`` drives steps from a background thread so ``submit`` is a
non-blocking producer API, while tests and the bench drive ``step()``
directly for determinism.

Admission control: ``submit`` raises :class:`QueueFullError` past
``max_queue_depth`` (counted as a rejection — the caller sheds load),
and rejects outright any request whose worst-case footprint can never
fit the pool or the model's position table.

Telemetry (docs/how_to/serving.md catalog): counters
``serving.requests_{admitted,completed,evicted,rejected,cancelled}``,
gauges ``serving.kv_pool_utilization`` / ``serving.tokens_per_s`` /
``serving.queue_depth``, histograms ``serving.ttft_s`` (submit -> first
generated token) and ``serving.token_latency_s`` (gap between
consecutive tokens of one request). Mirrored as plain numbers in
``Engine.stats()`` so telemetry-off processes (bench subprocesses)
still get the record.
"""
from __future__ import annotations

import dataclasses
import queue as _queue
import threading
import time
import weakref

import numpy as np

from .. import telemetry as _tel
from ..analysis.engine_verify import maybe_trace_lock as _maybe_trace_lock
from ..base import MXNetError, env_int as _env_int
from .kv_cache import PagedKVPool, blocks_for_tokens
from .model import ServingModel, cp_prefill_kv
from .scheduler import (CANCELLED, DECODE, FINISHED, PREFILL, Request,
                        Scheduler)

__all__ = ["Engine", "ServingConfig", "StreamHandle", "QueueFullError",
           "live_engines"]

_END = object()

# every constructed Engine, weakly held — the /servingz introspection
# endpoint (telemetry/server.py) iterates this to render live request
# tables without the serving layer ever knowing about HTTP
_live_engines = weakref.WeakSet()


def live_engines():
    """The Engines currently alive in this process (weakly tracked)."""
    return sorted(_live_engines, key=id)


class QueueFullError(MXNetError):
    """submit() past max_queue_depth — shed load upstream."""


@dataclasses.dataclass
class ServingConfig:
    """Engine knobs. Every field defaults from an ``MXNET_SERVE_*``
    env var (docs/env_vars.md) so deployments tune without code."""

    block_size: int = None
    num_blocks: int = None
    max_batch: int = None
    max_active: int = None
    prefill_chunk: int = None
    token_budget: int = None
    max_queue_depth: int = None
    policy: str = "continuous"
    eos_id: int = None
    max_seq_tokens: int = None   # per-request cap; default model max_seq_len
    # context-parallel long-prompt prefill (model.cp_prefill_kv):
    mesh: object = None
    cp_kind: str = "ring"
    cp_seq_axis: str = "seq"
    cp_min_tokens: int = None
    cp_chunk: int = None

    def __post_init__(self):
        if self.block_size is None:
            self.block_size = _env_int("MXNET_SERVE_BLOCK_SIZE", 16)
        if self.num_blocks is None:
            self.num_blocks = _env_int("MXNET_SERVE_KV_BLOCKS", 256)
        if self.max_batch is None:
            self.max_batch = _env_int("MXNET_SERVE_MAX_BATCH", 8)
        if self.max_active is None:
            self.max_active = _env_int("MXNET_SERVE_MAX_ACTIVE",
                                       2 * self.max_batch)
        if self.prefill_chunk is None:
            self.prefill_chunk = _env_int("MXNET_SERVE_PREFILL_CHUNK", 64)
        if self.token_budget is None:
            self.token_budget = _env_int(
                "MXNET_SERVE_TOKEN_BUDGET",
                self.max_batch + self.prefill_chunk)
        if self.max_queue_depth is None:
            self.max_queue_depth = _env_int("MXNET_SERVE_MAX_QUEUE", 64)
        if self.cp_min_tokens is None:
            self.cp_min_tokens = _env_int("MXNET_SERVE_CP_MIN_TOKENS", 2048)


class StreamHandle:
    """Per-request token stream + control surface."""

    def __init__(self, engine, req):
        self._engine = engine
        self._req = req
        self._q = _queue.Queue()
        self.status = "running"
        req.stream = self

    @property
    def request_id(self):
        return self._req.rid

    def _emit(self, token):
        self._q.put(int(token))

    def _end(self, status):
        self.status = status
        self._q.put(_END)

    def cancel(self):
        """Request cancellation; takes effect at the next scheduler
        sweep (mid-decode safe: blocks are freed, stream ends with
        status "cancelled")."""
        self._engine.cancel(self._req)

    def tokens(self, timeout=None):
        """Iterate generated tokens as they land; ends when the request
        finishes, is cancelled, or errors."""
        while True:
            item = self._q.get(timeout=timeout)
            if item is _END:
                return
            yield item

    def result(self, timeout=None):
        """Block until the stream ends; returns the full token list."""
        return list(self.tokens(timeout=timeout))


class Engine:
    """Continuous-batching serving engine over a transformer LM.

    Parameters
    ----------
    params : pytree
        ``models/transformer.py`` params (what bench_lm.py trains).
    model_cfg : TransformerConfig
    cfg : ServingConfig, optional
    """

    def __init__(self, params, model_cfg, cfg=None):
        from ..compile import ensure_jit_cache

        ensure_jit_cache()  # serving cold starts ride the PR 6 cache
        self.params = params
        self.model_cfg = model_cfg
        self.cfg = cfg or ServingConfig()
        bs = self.cfg.block_size
        max_seq = min(self.cfg.max_seq_tokens or model_cfg.max_seq_len,
                      model_cfg.max_seq_len)
        self.max_seq_tokens = max_seq
        self.pool = PagedKVPool(
            model_cfg.num_layers, model_cfg.num_heads, model_cfg.head_dim,
            self.cfg.num_blocks, bs, dtype=model_cfg.dtype)
        w = blocks_for_tokens(max_seq, bs)
        # buckets must cover the PREFILL batch too, which can span the
        # whole admission depth (max_active), not just the decode width
        top = max(self.cfg.max_batch, self.cfg.max_active)
        batch_buckets = sorted({1, 2, 4, 8, 16, 32, 64, self.cfg.max_batch,
                                top})
        batch_buckets = [b for b in batch_buckets if b <= top]
        chunk_buckets = sorted({8, 16, 32, 64, 128, 256,
                                self.cfg.prefill_chunk})
        chunk_buckets = [c for c in chunk_buckets
                         if c <= self.cfg.prefill_chunk]
        self.model = ServingModel(model_cfg, bs, w,
                                  batch_buckets=batch_buckets,
                                  chunk_buckets=chunk_buckets)
        self.sched = Scheduler(
            self.pool, max_batch=self.cfg.max_batch,
            prefill_chunk=self.cfg.prefill_chunk,
            token_budget=self.cfg.token_budget, policy=self.cfg.policy,
            max_active=self.cfg.max_active)
        # under MXNET_ENGINE_VERIFY=1 the locks are TracedLock-wrapped:
        # every acquire/release lands in the ambient lock trace
        # (analysis/engine_verify.py) for observed-order verification
        self._lock = _maybe_trace_lock(threading.RLock(),
                                       "serving.Engine._lock")
        # serializes whole steps: model execution + pool swap run
        # outside _lock (submit must not block on a dispatch), so two
        # concurrent drivers (generate() from two client threads, or
        # generate() racing start()'s loop) would otherwise each donate
        # and swap the same pool buffers, losing each other's KV writes
        self._step_lock = _maybe_trace_lock(threading.Lock(),
                                            "serving.Engine._step_lock")
        self._work = threading.Condition(self._lock)
        self._by_rid = {}
        self._last_counts = {}
        self._stats = {"admitted": 0, "completed": 0, "evicted": 0,
                       "rejected": 0, "cancelled": 0, "tokens_emitted": 0,
                       "steps": 0}
        self._ttfts = []
        self._token_lats = []
        self._rate_window = []  # (t, cumulative tokens) ring for tokens/s
        self._thread = None
        self._stop = False
        self._last_rate = 0.0
        self._draining = False
        self._drained = False
        _live_engines.add(self)

    # -- intake --------------------------------------------------------------
    def submit(self, prompt, max_new_tokens=16, eos_id=None):
        """Queue a generation request; returns a StreamHandle.

        Raises QueueFullError past ``max_queue_depth`` and MXNetError
        for requests that could never fit the KV pool / position table
        (both counted under serving.requests_rejected).
        """
        req = Request(prompt, max_new_tokens,
                      eos_id=self.cfg.eos_id if eos_id is None else eos_id)
        total = req.total_len()
        limit = min(self.max_seq_tokens,
                    self.sched.max_request_tokens(),
                    self.model.max_blocks * self.cfg.block_size)
        with self._lock:
            if self._draining:
                self._reject()
                raise QueueFullError(
                    "engine draining — admissions closed (resume() "
                    "reopens)")
            if total > limit:
                self._reject()
                raise MXNetError(
                    "request needs %d tokens; engine limit is %d "
                    "(pool/max_seq geometry)" % (total, limit))
            if len(self.sched.queue) >= self.cfg.max_queue_depth:
                self._reject()
                raise QueueFullError(
                    "admission queue full (%d)" % self.cfg.max_queue_depth)
            req.submit_t = time.monotonic()
            if _tel.ENABLED:
                # request-scoped trace: every lifecycle span of this
                # request (submit -> prefill -> decode -> complete)
                # shares one trace id, so the journal alone
                # reconstructs the request's lifetime
                req.trace = _tel.mint_trace()
                req.wall0 = time.time()
                _tel.event("serve.request.submit", t=req.wall0,
                           trace=req.trace, rid=req.rid,
                           prompt_len=int(req.prompt.shape[0]),
                           max_new_tokens=req.max_new_tokens)
            handle = StreamHandle(self, req)
            self._by_rid[req.rid] = req
            self.sched.submit(req)
            self._work.notify_all()
        return handle

    def cancel(self, req):
        with self._lock:
            self.sched.cancel(req)
            self._work.notify_all()

    # -- graceful drain ------------------------------------------------------
    def drain(self, wait=False, timeout=None):
        """Stop admissions; everything already accepted (queued or
        active) runs to completion. New ``submit`` calls raise
        :class:`QueueFullError` (counted as rejections — the upstream
        load balancer sheds to other replicas). When the last in-flight
        request finishes, a deterministic ``drained`` event lands in
        the scheduler event log, ``serve.drained`` in the journal, and
        ``/servingz`` reports ``drained: true`` — the primitive behind
        mxctl's drain-then-restart action and any clean shutdown.

        ``wait=True`` blocks until drained (the caller must be driving
        steps, or have ``start()`` running). Returns True when drained.
        """
        with self._lock:
            if not self._draining:
                self._draining = True
                if _tel.ENABLED:
                    _tel.counter("serving.drains_total").inc()
                self._check_drained_locked()
                self._work.notify_all()
            if not wait:
                return self._drained
            deadline = (time.monotonic() + timeout
                        if timeout is not None else None)
            while not self._drained:
                remaining = None
                if deadline is not None:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        return False
                self._work.wait(timeout=remaining if remaining is not None
                                else 0.5)
            return True

    def resume(self):
        """Reopen admissions after :meth:`drain` (a replica held in
        reserve, or a flap-guard test flipping readiness)."""
        with self._lock:
            if self._draining:
                self._draining = False
                self._drained = False
                self._work.notify_all()

    def accepting(self):
        """True while ``submit`` admits work — the /readyz signal
        (telemetry/server.py): a draining replica is alive but not
        ready."""
        with self._lock:
            return not self._draining

    @property
    def draining(self):
        with self._lock:
            return self._draining

    @property
    def drained(self):
        with self._lock:
            return self._drained

    def _check_drained_locked(self):
        """Latch the drained state once the last accepted request is
        gone (caller holds ``_lock``)."""
        if (self._draining and not self._drained
                and not self.sched.queue and not self.sched.active):
            self._drained = True
            self.sched.note_drained()
            if _tel.ENABLED:
                _tel.event("serve.drained",
                           completed=self._stats["completed"],
                           cancelled=self._stats["cancelled"])
            # every caller holds _lock (the _locked-suffix contract) —
            # _work is Condition(self._lock), so this notify is locked
            self._work.notify_all()  # mxlint: disable

    def _reject(self):
        self._stats["rejected"] += 1
        if _tel.ENABLED:
            _tel.counter("serving.requests_rejected").inc()

    # -- synchronous batch API -----------------------------------------------
    def generate(self, prompts, max_new_tokens=16):
        """Submit all prompts, drive the loop to completion, return the
        generated token lists (the synchronous batch surface)."""
        handles = [self.submit(p, max_new_tokens) for p in prompts]
        with self._lock:
            background = self._thread is not None
        if not background:
            self.run_until_idle()
        return [h.result() for h in handles]

    # -- the step loop -------------------------------------------------------
    def step(self):
        """Run one scheduler step (<=1 decode batch + <=1 prefill
        batch). Returns True when any work ran. Whole-step atomic:
        concurrent drivers serialize on _step_lock."""
        with self._step_lock:
            with self._lock:
                plan = self.sched.plan()
                self._mirror_events()
                decode = list(plan.decode)
                prefill = list(plan.prefill)
                now = time.monotonic()
                for req, _cs, _clen in prefill:
                    if req.admit_t is None:  # first admission only —
                        req.admit_t = now    # eviction re-prefills later
            worked = False
            if decode:
                self._run_decode(decode)
                worked = True
            if prefill:
                # model dispatch under _step_lock is the DESIGN: the
                # step lock exists to serialize whole steps, model
                # execution included (see its comment in __init__)
                self._run_prefill(prefill)  # mxlint: disable
                worked = True
            if worked:
                with self._lock:
                    self._stats["steps"] += 1
                    self._mirror_events()
                    self._update_gauges()
            return worked

    def run_until_idle(self, max_steps=None):
        """Drive step() until no work remains; returns steps run."""
        n = 0
        while self.step():
            n += 1
            if max_steps is not None and n >= max_steps:
                break
        return n

    def start(self):
        """Serve from a background thread (submit() wakes it)."""

        def loop():
            while True:
                with self._lock:
                    if self._stop:
                        break
                if not self.step():
                    with self._work:
                        if self._stop:
                            break
                        self._work.wait(timeout=0.05)

        with self._lock:
            if self._thread is not None:
                return
            self._stop = False
            self._thread = threading.Thread(target=loop, name="mx-serve",
                                            daemon=True)
            self._thread.start()

    def stop(self):
        with self._lock:
            thread = self._thread
            if thread is None:
                return
            self._stop = True
            self._work.notify_all()
        # join OUTSIDE the lock (the loop's own step() takes it), and
        # clear _thread only AFTER the join: a start() racing this stop
        # must keep seeing the old thread and no-op — clearing early
        # would let it spawn a second loop while the first still runs
        thread.join()
        with self._lock:
            if self._thread is thread:
                self._thread = None

    # -- batch execution -----------------------------------------------------
    def _tables(self, reqs):
        w = self.model.max_blocks
        bt = np.zeros((len(reqs), w), np.int32)
        for i, r in enumerate(reqs):
            bt[i, :len(r.blocks)] = r.blocks
        return bt

    def _run_decode(self, reqs):
        t0 = time.monotonic()
        B = len(reqs)
        tokens = np.asarray([[r.generated[-1]] for r in reqs], np.int32)
        start = np.asarray(
            [len(r.prompt) + len(r.generated) - 1 for r in reqs], np.int32)
        # static policy = fixed-shape serving: decode dispatches at the
        # full batch width even as the batch drains (dead slots are
        # padded lanes), faithfully paying what static batching pays on
        # accelerators where a decode step costs the same at any live
        # count; continuous dispatches at the ragged bucket
        min_b = self.cfg.max_batch if self.cfg.policy == "static" else None
        with _tel.span("serve.decode"):
            nxt, _, kp, vp = self.model.step(
                self.params, self.pool.k, self.pool.v, tokens, start,
                np.ones((B,), np.int32), self._tables(reqs),
                np.ones((B,), bool), min_batch_bucket=min_b)
        now = time.monotonic()
        with self._lock:
            self.pool.swap(kp, vp)
            if _tel.ENABLED:
                _tel.histogram("serving.decode_batch_size").observe(B)
                _tel.histogram("serving.decode_step_s").observe(now - t0)
            for r, t in zip(reqs, nxt):
                if r.state != DECODE:   # cancelled while stepping
                    continue
                self._emit(r, int(t), now)

    def _run_prefill(self, chunks):
        # context-parallel long prompts take their own path, off the
        # bucketed batch (model.cp_prefill_kv)
        batched = []
        for req, cs, clen in chunks:
            if (self.cfg.mesh is not None and cs == 0
                    and req.ctx_len >= self.cfg.cp_min_tokens
                    and self._cp_eligible(req)):
                self._run_cp_prefill(req)
            else:
                batched.append((req, cs, clen))
        if not batched:
            return
        B = len(batched)
        C = max(clen for _, _, clen in batched)
        tokens = np.zeros((B, C), np.int32)
        start = np.zeros((B,), np.int32)
        chunk_len = np.zeros((B,), np.int32)
        for i, (req, cs, clen) in enumerate(batched):
            tokens[i, :clen] = req.context[cs:cs + clen]
            start[i] = cs
            chunk_len[i] = clen
        with _tel.span("serve.prefill"):
            nxt, _, kp, vp = self.model.step(
                self.params, self.pool.k, self.pool.v, tokens, start,
                chunk_len, self._tables([r for r, _, _ in batched]),
                np.ones((B,), bool))
        now = time.monotonic()
        with self._lock:
            self.pool.swap(kp, vp)
            for i, (req, cs, clen) in enumerate(batched):
                if req.state != PREFILL:   # cancelled while stepping
                    continue
                self.sched.note_prefilled(req, clen)
                if req.state == DECODE:
                    if req.prefill_done_t is None:  # first time only —
                        req.prefill_done_t = now    # an eviction
                    # re-prefill must not swallow the first decode
                    # phase from the journaled lifecycle spans
                    # (evictions field records the wrinkle)
                    # the final prefill chunk's logits sample the first
                    # new token — no separate "first decode" dispatch
                    self._emit(req, int(nxt[i]), now)

    def _cp_eligible(self, req):
        n = self.cfg.mesh.shape[self.cfg.cp_seq_axis]
        chunk = self.cfg.cp_chunk or req.ctx_len
        return chunk % n == 0 and req.ctx_len % chunk == 0

    def _run_cp_prefill(self, req):
        """Whole-prompt context-parallel prefill over the mesh, then
        scatter the dense K/V into this request's pool blocks."""
        import jax.numpy as jnp

        cfg = self.model_cfg
        with _tel.span("serve.cp_prefill"):
            k, v, x_last = cp_prefill_kv(
                self.params, cfg, req.context, self.cfg.mesh,
                kind=self.cfg.cp_kind, chunk=self.cfg.cp_chunk)
        bs = self.cfg.block_size
        T = req.ctx_len
        nb = blocks_for_tokens(T, bs)
        pad = nb * bs - T
        if pad:
            zpad = np.zeros((cfg.num_layers, pad) + k.shape[2:], k.dtype)
            k = np.concatenate([k, zpad], axis=1)
            v = np.concatenate([v, zpad], axis=1)
        k = k.reshape(cfg.num_layers, nb, bs, cfg.num_heads, cfg.head_dim)
        v = v.reshape(cfg.num_layers, nb, bs, cfg.num_heads, cfg.head_dim)
        blocks = np.asarray(req.blocks[:nb], np.int32)
        # device scatter + logits D2H run OUTSIDE _lock (a submit must
        # not stall behind them; the pool reads are safe because every
        # pool-swapping path serializes on _step_lock) — only the swap
        # and the scheduler/stream bookkeeping take the state lock
        new_k = self.pool.k.at[:, blocks].set(
            jnp.asarray(k, self.pool.k.dtype))
        new_v = self.pool.v.at[:, blocks].set(
            jnp.asarray(v, self.pool.v.dtype))
        logits = x_last @ np.asarray(self.params["embed"], np.float32).T
        now = time.monotonic()
        with self._lock:
            self.pool.swap(new_k, new_v)
            if req.state != PREFILL:
                return
            self.sched.note_prefilled(req, T - req.prefilled)
            if req.state == DECODE and req.prefill_done_t is None:
                req.prefill_done_t = now
            self._emit(req, int(np.argmax(logits)), now)

    # -- per-token bookkeeping (under self._lock) ----------------------------
    def _emit(self, req, token, now):
        req.generated.append(token)
        stream = req.stream
        if req.first_token_t is None:
            req.first_token_t = now
            self._ttfts.append(now - req.submit_t)
            if _tel.ENABLED:
                _tel.histogram("serving.ttft_s").observe(now - req.submit_t)
        if req.last_token_t is not None:
            self._token_lats.append(now - req.last_token_t)
            if _tel.ENABLED:
                _tel.histogram("serving.token_latency_s").observe(
                    now - req.last_token_t)
        req.last_token_t = now
        self._stats["tokens_emitted"] += 1
        self._rate_window.append((now, self._stats["tokens_emitted"]))
        if stream is not None:
            stream._emit(token)
        # len(generated) is the client-visible stream length — eviction
        # folds tokens into the recompute context but never drops them
        done = len(req.generated) >= req.max_new_tokens
        if req.eos_id is not None and token == req.eos_id:
            done = True
        if done:
            req.finish_t = now
            self.sched.finish(req)
            self._trace_request(req, "complete", now)
            self._mirror_events()
            if stream is not None:
                stream._end("finished")

    def _trace_request(self, req, status, now):
        """Journal the request's lifecycle as spans sharing its trace id
        (submit already landed at intake). Phase boundaries come from
        the monotonic stamps collected along the way, re-anchored to
        the submit wall clock so the journal's epoch-seconds timeline
        stays coherent."""
        if req.trace is None:
            return

        def w(mono):  # monotonic stamp -> journal wall clock
            return req.wall0 + (mono - req.submit_t)

        _tel.event("serve.request", t=req.wall0, dur=now - req.submit_t,
                   trace=req.trace, rid=req.rid, status=status,
                   tokens=len(req.generated), evictions=req.evictions)
        if req.admit_t is not None:
            _tel.event("serve.request.prefill", t=w(req.admit_t),
                       dur=(req.prefill_done_t or now) - req.admit_t,
                       trace=req.trace, rid=req.rid)
        if req.prefill_done_t is not None:
            _tel.event("serve.request.decode", t=w(req.prefill_done_t),
                       dur=now - req.prefill_done_t,
                       trace=req.trace, rid=req.rid)
        _tel.event("serve.request.%s" % status, t=w(now),
                   trace=req.trace, rid=req.rid)

    def _mirror_events(self):
        """Fold scheduler event counts into stats + mxtel counters, and
        close out cancelled streams."""
        mapping = {"admit": "admitted", "complete": "completed",
                   "evict": "evicted", "cancel": "cancelled"}
        for ev, stat in mapping.items():
            n = self.sched.counts.get(ev, 0)
            d = n - self._last_counts.get(ev, 0)
            if d:
                self._stats[stat] += d
                self._last_counts[ev] = n
                if _tel.ENABLED:
                    _tel.counter("serving.requests_%s" % stat).inc(d)
        # end streams of requests the sweep cancelled
        for rid, req in list(self._by_rid.items()):
            if req.state == CANCELLED:
                if req.stream is not None and req.stream.status == "running":
                    req.stream._end("cancelled")
                self._trace_request(req, "cancel", time.monotonic())
                del self._by_rid[rid]
            elif req.state == FINISHED:
                del self._by_rid[rid]
        self._check_drained_locked()

    def _update_gauges(self):
        util = self.pool.utilization()
        now = time.monotonic()
        # tokens/s over a sliding 2 s window of emissions
        win = [x for x in self._rate_window if now - x[0] <= 2.0]
        self._rate_window = win
        rate = 0.0
        if len(win) >= 2 and win[-1][0] > win[0][0]:
            rate = (win[-1][1] - win[0][1]) / (win[-1][0] - win[0][0])
        self._last_rate = rate
        if _tel.ENABLED:
            _tel.gauge("serving.kv_pool_utilization").set(util)
            _tel.gauge("serving.kv_pool_hwm_blocks").set(
                self.pool.high_water_mark())
            _tel.gauge("serving.tokens_per_s").set(rate)
            _tel.gauge("serving.queue_depth").set(len(self.sched.queue))

    def note_idle(self):
        """Mark the engine drained: the tokens/s gauge drops to zero
        instead of freezing at its last in-flight value (journal
        timelines honest across idle gaps)."""
        with self._lock:
            self._rate_window = []
            self._last_rate = 0.0
            if _tel.ENABLED:
                _tel.gauge("serving.tokens_per_s").set(0.0)
                _tel.gauge("serving.queue_depth").set(len(self.sched.queue))

    # -- reporting -----------------------------------------------------------
    def latency_samples(self):
        """Copies of the raw TTFT / per-token latency sample lists (the
        bench slices per-window percentiles out of a reused engine)."""
        with self._lock:
            return list(self._ttfts), list(self._token_lats)

    def stats(self):
        """Plain-number mirror of the serving metrics (works with
        telemetry off — the bench subprocess contract)."""
        def pct(xs, q):
            if not xs:
                return None
            return float(np.percentile(np.asarray(xs), q))

        with self._lock:
            out = dict(self._stats)
            out.update({
                "kv_pool_utilization": self.pool.utilization(),
                "kv_pool_hwm_blocks": self.pool.high_water_mark(),
                "queue_depth": len(self.sched.queue),
                "active": len(self.sched.active),
                "draining": self._draining,
                "drained": self._drained,
                "tokens_per_s_window": self._last_rate,
                "ttft_p50_s": pct(self._ttfts, 50),
                "ttft_p99_s": pct(self._ttfts, 99),
                "token_latency_p50_s": pct(self._token_lats, 50),
                "token_latency_p99_s": pct(self._token_lats, 99),
            })
        return out

    def introspect(self, event_tail=50):
        """Live request table + pool state + scheduler event tail — the
        /servingz endpoint's payload (telemetry/server.py). Answers
        "what is this serving request doing RIGHT NOW": every queued and
        active request with its state, progress, and trace id."""
        now = time.monotonic()
        with self._lock:
            reqs = []
            for req in list(self.sched.active) + list(self.sched.queue):
                reqs.append({
                    "rid": req.rid, "state": req.state,
                    "trace": req.trace,
                    "prompt_len": int(req.prompt.shape[0]),
                    "ctx_len": req.ctx_len,
                    "prefilled": req.prefilled,
                    "generated": len(req.generated),
                    "max_new_tokens": req.max_new_tokens,
                    "blocks": len(req.blocks),
                    "evictions": req.evictions,
                    "age_s": (now - req.submit_t
                              if req.submit_t is not None else None),
                })
            out = {
                "policy": self.cfg.policy,
                "draining": self._draining,
                "drained": self._drained,
                "requests": reqs,
                "pool": {
                    "capacity_blocks": self.pool.capacity,
                    "free_blocks": self.pool.num_free,
                    "utilization": self.pool.utilization(),
                    "hwm_blocks": self.pool.high_water_mark(),
                    "block_size": self.cfg.block_size,
                },
                "events": [list(e) for e in self.sched.events[-event_tail:]],
            }
        # stats() sorts the full latency sample lists for percentiles —
        # do that in its OWN lock window, not nested inside this one,
        # so a scrape of a long-lived engine holds the lock per piece
        # instead of for the whole render
        out["stats"] = self.stats()
        return out
