"""Request front-end: ``Engine.submit(prompt) -> stream of tokens``.

The serving subsystem's public surface. An Engine owns one model's
params, a paged KV pool sized by :class:`ServingConfig`, a
:class:`~.scheduler.Scheduler`, and the bucketed jitted step functions
(:class:`~.model.ServingModel`). Each ``step()`` runs at most one
decode batch and one prefill batch (scheduler.py module docstring);
``start()`` drives steps from a background thread so ``submit`` is a
non-blocking producer API, while tests and the bench drive ``step()``
directly for determinism.

Admission control: ``submit`` raises :class:`QueueFullError` past
``max_queue_depth`` (counted as a rejection — the caller sheds load),
and rejects outright any request whose worst-case footprint can never
fit the pool or the model's position table.

Speculative decoding (ISSUE 15, ``MXNET_SERVE_SPEC``): a draft
transformer proposes up to ``spec_k`` tokens per scheduled decode
turn, the target verifies them all in one jitted ragged step with
fused accept/reject + resampling, and the accept/reject bookkeeping
rolls both block tables back to the first rejection. Off by default
and structurally zero-overhead when off (no draft pool, no extra
programs).

Telemetry (docs/how_to/serving.md catalog): counters
``serving.requests_{admitted,completed,evicted,rejected,cancelled}``
and ``serving.spec_turns`` / ``serving.spec_tokens_drafted`` /
``serving.spec_tokens_accepted``, gauges
``serving.kv_pool_utilization`` / ``serving.tokens_per_s`` /
``serving.queue_depth`` / ``serving.spec_accept_rate``, histograms
``serving.ttft_s`` (submit -> first generated token),
``serving.ttft_sync_s`` (TTFTs landing inside a live weight-sync
window — docs/how_to/weight_sync.md), ``serving.token_latency_s``
(gap between consecutive tokens of one request) and
``serving.spec_accepted_tokens``. Mirrored as plain numbers in
``Engine.stats()`` so telemetry-off processes (bench subprocesses)
still get the record.

Live weight sync (ISSUE 17, ``MXNET_WSYNC``): ``install_weights``
swaps a staged, gated param set (target + draft + host unembed)
atomically between scheduled steps; ``rollback_weights`` restores the
newest last-good ring entry. Off by default and structurally inert
when off (no subscriber thread, no ring growth, no journal records).
"""
from __future__ import annotations

import dataclasses
import os
import queue as _queue
import threading
import time
import weakref

import numpy as np

from .. import telemetry as _tel
from ..analysis import compile_verify as _cv
from ..analysis.engine_verify import maybe_trace_lock as _maybe_trace_lock
from ..base import MXNetError, env_bool as _env_bool, env_int as _env_int
from ..wsync import enabled as _wsync_enabled
from . import sampling as _samp
from .kv_cache import PagedKVPool, blocks_for_tokens
from .model import ServingModel, bucket_for, cp_prefill_kv
from .scheduler import (CANCELLED, DECODE, FINISHED, PREFILL, Request,
                        Scheduler)

__all__ = ["Engine", "ServingConfig", "StreamHandle", "QueueFullError",
           "live_engines"]

_END = object()

# every constructed Engine, weakly held — the /servingz introspection
# endpoint (telemetry/server.py) iterates this to render live request
# tables without the serving layer ever knowing about HTTP
_live_engines = weakref.WeakSet()


def live_engines():
    """The Engines currently alive in this process (weakly tracked)."""
    return sorted(_live_engines, key=id)


class QueueFullError(MXNetError):
    """submit() past max_queue_depth — shed load upstream.

    Carries the observed ``queue_depth`` and a computed
    ``retry_after_s`` hint (one admission slot's expected time to free
    at the current service rate) so an upstream router backs off for a
    meaningful interval instead of blind-retrying into the same full
    queue (mxnet_tpu/serving/fleet/router.py reads both)."""

    def __init__(self, message, queue_depth=0, retry_after_s=1.0):
        super().__init__(message)
        self.queue_depth = int(queue_depth)
        self.retry_after_s = float(retry_after_s)


@dataclasses.dataclass
class ServingConfig:
    """Engine knobs. Every field defaults from an ``MXNET_SERVE_*``
    env var (docs/env_vars.md) so deployments tune without code."""

    block_size: int = None
    num_blocks: int = None
    max_batch: int = None
    max_active: int = None
    prefill_chunk: int = None
    token_budget: int = None
    max_queue_depth: int = None
    policy: str = "continuous"
    eos_id: int = None
    max_seq_tokens: int = None   # per-request cap; default model max_seq_len
    # speculative decoding (off by default — with spec False the engine
    # allocates no draft pool and compiles no draft/verify programs):
    spec: bool = None            # MXNET_SERVE_SPEC
    spec_k: int = None           # draft tokens per turn, MXNET_SERVE_SPEC_K
    events_max: int = None       # scheduler event-ring bound
    # context-parallel long-prompt prefill (model.cp_prefill_kv):
    mesh: object = None
    cp_kind: str = "ring"
    cp_seq_axis: str = "seq"
    cp_min_tokens: int = None
    cp_chunk: int = None
    # idle-stream reaper: a StreamHandle nobody consumes for this many
    # seconds is cancelled and its KV blocks freed (0 = off)
    stream_idle_s: float = None

    def __post_init__(self):
        if self.block_size is None:
            self.block_size = _env_int("MXNET_SERVE_BLOCK_SIZE", 16)
        if self.num_blocks is None:
            self.num_blocks = _env_int("MXNET_SERVE_KV_BLOCKS", 256)
        if self.max_batch is None:
            self.max_batch = _env_int("MXNET_SERVE_MAX_BATCH", 8)
        if self.max_active is None:
            self.max_active = _env_int("MXNET_SERVE_MAX_ACTIVE",
                                       2 * self.max_batch)
        if self.prefill_chunk is None:
            self.prefill_chunk = _env_int("MXNET_SERVE_PREFILL_CHUNK", 64)
        if self.spec is None:
            self.spec = _env_bool("MXNET_SERVE_SPEC", False)
        if self.spec_k is None:
            self.spec_k = _env_int("MXNET_SERVE_SPEC_K", 4)
        if self.token_budget is None:
            # under speculation each decode slot costs its whole verify
            # chunk (1 + spec_k); the default budget must still leave
            # prefill_chunk headroom or a full decode batch starves
            # admission-side prefill for the life of its requests
            decode_cost = (1 + self.spec_k) if self.spec else 1
            self.token_budget = _env_int(
                "MXNET_SERVE_TOKEN_BUDGET",
                self.max_batch * decode_cost + self.prefill_chunk)
        if self.max_queue_depth is None:
            self.max_queue_depth = _env_int("MXNET_SERVE_MAX_QUEUE", 64)
        if self.cp_min_tokens is None:
            self.cp_min_tokens = _env_int("MXNET_SERVE_CP_MIN_TOKENS", 2048)
        if self.stream_idle_s is None:
            try:
                self.stream_idle_s = float(
                    os.environ.get("MXNET_SERVE_STREAM_IDLE_S", "") or 0.0)
            except ValueError:
                self.stream_idle_s = 0.0


class StreamHandle:
    """Per-request token stream + control surface."""

    def __init__(self, engine, req):
        self._engine = engine
        self._req = req
        self._q = _queue.Queue()
        self.status = "running"
        # last time a consumer pulled a token (monotonic) — the idle
        # reaper's signal. Consuming resets it; an abandoned handle
        # with tokens piling up in _q goes stale and gets cancelled.
        self._touched_t = time.monotonic()
        req.stream = self

    @property
    def request_id(self):
        return self._req.rid

    def _emit(self, token):
        self._q.put(int(token))

    def _idle_abandoned(self, now, idle_s):
        """True when nobody has consumed for ``idle_s`` seconds WHILE
        tokens sat ready (an empty queue means the consumer is merely
        blocked waiting on us — never reap those)."""
        return (self.status == "running" and self._q.qsize() > 0
                and now - self._touched_t > idle_s)

    def _end(self, status):
        self.status = status
        self._q.put(_END)

    def cancel(self):
        """Request cancellation; takes effect at the next scheduler
        sweep (mid-decode safe: blocks are freed, stream ends with
        status "cancelled")."""
        self._engine.cancel(self._req)

    def tokens(self, timeout=None):
        """Iterate generated tokens as they land; ends when the request
        finishes, is cancelled, or errors."""
        while True:
            item = self._q.get(timeout=timeout)
            self._touched_t = time.monotonic()
            if item is _END:
                return
            yield item

    def result(self, timeout=None):
        """Block until the stream ends; returns the full token list."""
        return list(self.tokens(timeout=timeout))


class Engine:
    """Continuous-batching serving engine over a transformer LM.

    ``SPEC_WINDOW_SECS`` bounds the sliding window behind the
    ``spec_accept_rate_window`` stat (current draft quality for mxctl
    rules; the cumulative rate is reported alongside).

    Parameters
    ----------
    params : pytree
        ``models/transformer.py`` params (what bench_lm.py trains).
    model_cfg : TransformerConfig
    cfg : ServingConfig, optional
    draft_params, draft_cfg : pytree / TransformerConfig, optional
        The draft model for speculative decoding (required when
        ``cfg.spec``): a smaller ``models/transformer.py`` family model
        whose proposals the target verifies K+1 at a time. With
        ``cfg.spec`` off these are rejected — the zero-overhead
        contract is structural (no draft pool, no extra programs).
    """

    #: sliding-window width for the live accept-rate signal
    SPEC_WINDOW_SECS = 30.0

    def __init__(self, params, model_cfg, cfg=None, draft_params=None,
                 draft_cfg=None):
        from ..compile import ensure_jit_cache

        ensure_jit_cache()  # serving cold starts ride the PR 6 cache
        self.params = params
        self.model_cfg = model_cfg
        self.cfg = cfg or ServingConfig()
        # cp prefill samples its first token on the host: pull the
        # unembedding matrix ONCE here, not per long prompt (was a
        # vocab x d_model D2H on every cp prefill — mxjit audit)
        self._host_unembed = (
            np.asarray(params["embed"], np.float32).T
            if self.cfg.mesh is not None else None)
        bs = self.cfg.block_size
        max_seq = min(self.cfg.max_seq_tokens or model_cfg.max_seq_len,
                      model_cfg.max_seq_len)
        self.max_seq_tokens = max_seq
        self.pool = PagedKVPool(
            model_cfg.num_layers, model_cfg.num_heads, model_cfg.head_dim,
            self.cfg.num_blocks, bs, dtype=model_cfg.dtype)
        w = blocks_for_tokens(max_seq, bs)
        # buckets must cover the PREFILL batch too, which can span the
        # whole admission depth (max_active), not just the decode width
        top = max(self.cfg.max_batch, self.cfg.max_active)
        batch_buckets = sorted({1, 2, 4, 8, 16, 32, 64, self.cfg.max_batch,
                                top})
        batch_buckets = [b for b in batch_buckets if b <= top]
        chunk_buckets = sorted({8, 16, 32, 64, 128, 256,
                                self.cfg.prefill_chunk})
        chunk_buckets = [c for c in chunk_buckets
                         if c <= self.cfg.prefill_chunk]
        # speculative decoding: draft model + mirrored paged pool.
        # The verify program's chunk is exactly spec_k + 1 wide (no
        # bucketing — K is static); draft buckets gain 2 (the post-
        # full-accept catch-up ingest). Both ride the same persistent
        # jit cache.
        self.draft_params = None
        self.draft_cfg = None
        self.draft_model = None
        self.draft_pool = None
        spec_k = 0
        if self.cfg.spec:
            if draft_params is None or draft_cfg is None:
                raise MXNetError(
                    "ServingConfig.spec requires draft_params + "
                    "draft_cfg (the draft transformer)")
            if self.cfg.policy == "static":
                # the static policy is the fixed-shape A/B baseline;
                # spec turns dispatch at ragged buckets and would
                # silently break its methodology — reject the combo
                raise MXNetError(
                    "speculative decoding requires policy="
                    "'continuous' (static is the fixed-shape baseline)")
            if self.cfg.spec_k < 1:
                raise MXNetError("spec_k must be >= 1, got %d"
                                 % self.cfg.spec_k)
            spec_k = self.cfg.spec_k
            self.draft_params = draft_params
            self.draft_cfg = draft_cfg
            self.draft_pool = self.pool.mirror(
                draft_cfg.num_layers, draft_cfg.num_heads,
                draft_cfg.head_dim, dtype=draft_cfg.dtype)
            self.draft_model = ServingModel(
                draft_cfg, bs, w, batch_buckets=batch_buckets,
                chunk_buckets=sorted(set(chunk_buckets) | {2}))
        elif draft_params is not None or draft_cfg is not None:
            raise MXNetError(
                "draft model passed but ServingConfig.spec is off — "
                "set spec=True (or MXNET_SERVE_SPEC=1)")
        self.model = ServingModel(model_cfg, bs, w,
                                  batch_buckets=batch_buckets,
                                  chunk_buckets=chunk_buckets)
        self.sched = Scheduler(
            self.pool, max_batch=self.cfg.max_batch,
            prefill_chunk=self.cfg.prefill_chunk,
            token_budget=self.cfg.token_budget, policy=self.cfg.policy,
            max_active=self.cfg.max_active, draft_pool=self.draft_pool,
            spec_k=spec_k, events_max=self.cfg.events_max)
        # under MXNET_ENGINE_VERIFY=1 the locks are TracedLock-wrapped:
        # every acquire/release lands in the ambient lock trace
        # (analysis/engine_verify.py) for observed-order verification
        self._lock = _maybe_trace_lock(threading.RLock(),
                                       "serving.Engine._lock")
        # serializes whole steps: model execution + pool swap run
        # outside _lock (submit must not block on a dispatch), so two
        # concurrent drivers (generate() from two client threads, or
        # generate() racing start()'s loop) would otherwise each donate
        # and swap the same pool buffers, losing each other's KV writes
        self._step_lock = _maybe_trace_lock(threading.Lock(),
                                            "serving.Engine._step_lock")
        self._work = threading.Condition(self._lock)
        self._by_rid = {}
        self._last_counts = {}
        self._stats = {"admitted": 0, "completed": 0, "evicted": 0,
                       "rejected": 0, "cancelled": 0, "tokens_emitted": 0,
                       "steps": 0, "streams_reaped": 0, "spec_turns": 0,
                       "spec_tokens_drafted": 0, "spec_tokens_accepted": 0}
        self._ttfts = []
        self._token_lats = []
        self._rate_window = []  # (t, cumulative tokens) ring for tokens/s
        # (t, drafted, accepted) per spec turn over a sliding window:
        # the accept-rate signal mxctl rules act on must track CURRENT
        # draft quality, not the lifetime average (which goes inert
        # with uptime)
        self._spec_window = []
        self._thread = None
        self._stop = False
        self._last_rate = 0.0
        self._draining = False
        self._drained = False
        # -- wsync (docs/how_to/weight_sync.md): staged hot-swap state.
        # _installed_params/_installed_draft are identity tokens —
        # step() hard-rejects a params rebind that bypassed
        # install_weights(), so the staged-swap gates (shape/dtype,
        # finiteness, acceptance) are enforced, not advisory
        self._installed_params = self.params
        self._installed_draft = self.draft_params
        self._weight_version = None
        self._weight_ring = []   # (version, params, draft) last-good
        self._weight_ring_keep = max(1, _env_int("MXNET_WSYNC_RING", 2))
        try:
            self._sync_ttft_window = float(
                os.environ.get("MXNET_WSYNC_TTFT_WINDOW", "") or 2.0)
        except ValueError:
            self._sync_ttft_window = 2.0
        self._sync_mark_until = 0.0   # monotonic: TTFTs before this
        self._sync_ttfts = []         # land in the sync-window stats
        self._wsync_sub = None
        if _wsync_enabled():
            from ..wsync.subscriber import maybe_autosync

            self._wsync_sub = maybe_autosync(self)
        _live_engines.add(self)

    # -- intake --------------------------------------------------------------
    def submit(self, prompt, max_new_tokens=16, eos_id=None,
               temperature=0.0, top_k=0, top_p=1.0, seed=0,
               prefix_tokens=None):
        """Queue a generation request; returns a StreamHandle.

        ``prefix_tokens`` is the fleet redelivery hook
        (serving/fleet/router.py): tokens this request ALREADY streamed
        on a replica that died are folded into the recompute context —
        exactly the eviction-recompute fold one tier up. The request
        prefills ``prompt + prefix`` and decodes onward; the pre-seeded
        tokens count against ``max_new_tokens`` but are never
        re-emitted, and because sampling is keyed by (seed, global
        position) the continuation is byte-identical to the
        uninterrupted stream (exact at temperature 0).

        ``temperature`` 0 (the default) is exact greedy decode;
        positive temperatures sample on device with top-k/top-p
        filtering, every draw keyed by ``(seed, token position)`` so
        the plain-decode stream is byte-reproducible across evictions
        and re-chunking (sampling.py module docstring). Under
        speculation a shifted turn alignment may swap which salt
        stream a position draws from (accepted draft vs residual vs
        bonus) — distribution-preserving by the rejection-sampling
        construction, byte-stable at temperature 0.

        Raises QueueFullError past ``max_queue_depth`` and MXNetError
        for requests that could never fit the KV pool / position table
        (both counted under serving.requests_rejected).
        """
        if temperature < 0 or top_k < 0 or not 0.0 < top_p <= 1.0:
            # top_p <= 0 would mask EVERY token (NaN distribution,
            # uniform-random argmax) — reject loudly, never sample
            # garbage silently
            with self._lock:
                self._reject()
            raise MXNetError(
                "invalid sampling params: temperature >= 0, top_k >= 0 "
                "and 0 < top_p <= 1 required (got %r, %r, %r)"
                % (temperature, top_k, top_p))
        req = Request(prompt, max_new_tokens,
                      eos_id=self.cfg.eos_id if eos_id is None else eos_id,
                      temperature=temperature, top_k=top_k, top_p=top_p,
                      seed=seed)
        if prefix_tokens is not None and len(prefix_tokens):
            pre = np.asarray(prefix_tokens, np.int32).reshape(-1)
            if pre.shape[0] >= max_new_tokens:
                with self._lock:
                    self._reject("prefix")
                raise MXNetError(
                    "prefix_tokens (%d) already meets max_new_tokens "
                    "(%d) — nothing left to generate" % (pre.shape[0],
                                                         max_new_tokens))
            # the redelivery fold: already-streamed tokens become
            # recompute context (KV re-prefilled on this engine) AND
            # pre-seeded generated tokens (positions stay global; _emit
            # only ever sees tokens decoded here, so nothing replays)
            req.context = np.concatenate([req.context, pre])
            req.generated = [int(t) for t in pre]
        total = req.total_len()
        limit = min(self.max_seq_tokens,
                    self.sched.max_request_tokens(),
                    self.model.max_blocks * self.cfg.block_size)
        with self._lock:
            if self._draining:
                depth = len(self.sched.queue)
                self._reject("draining", depth)
                raise QueueFullError(
                    "engine draining — admissions closed (resume() "
                    "reopens)", queue_depth=depth,
                    retry_after_s=self._retry_after_locked(depth))
            if total > limit:
                self._reject("geometry")
                raise MXNetError(
                    "request needs %d tokens; engine limit is %d "
                    "(pool/max_seq geometry)" % (total, limit))
            if len(self.sched.queue) >= self.cfg.max_queue_depth:
                depth = len(self.sched.queue)
                self._reject("queue_full", depth)
                raise QueueFullError(
                    "admission queue full (%d)" % self.cfg.max_queue_depth,
                    queue_depth=depth,
                    retry_after_s=self._retry_after_locked(depth))
            req.submit_t = time.monotonic()
            if _tel.ENABLED:
                # request-scoped trace: every lifecycle span of this
                # request (submit -> prefill -> decode -> complete)
                # shares one trace id, so the journal alone
                # reconstructs the request's lifetime
                req.trace = _tel.mint_trace()
                req.wall0 = time.time()
                _tel.event("serve.request.submit", t=req.wall0,
                           trace=req.trace, rid=req.rid,
                           prompt_len=int(req.prompt.shape[0]),
                           prefix_len=len(req.generated),
                           max_new_tokens=req.max_new_tokens)
            handle = StreamHandle(self, req)
            self._by_rid[req.rid] = req
            self.sched.submit(req)
            self._work.notify_all()
        return handle

    def cancel(self, req):
        with self._lock:
            self.sched.cancel(req)
            self._work.notify_all()

    def _reap_idle_locked(self, now):
        """Cancel streams nobody is consuming (satellite of the fleet
        PR): an abandoned ``StreamHandle`` otherwise pins its KV blocks
        for the request's whole lifetime. Caller holds ``_lock``; the
        cancel is the ordinary scheduler sweep, so blocks free on the
        next plan()."""
        idle = self.cfg.stream_idle_s
        if not idle or idle <= 0:
            return
        for req in list(self._by_rid.values()):
            s = req.stream
            if s is not None and s._idle_abandoned(now, idle):
                self._stats["streams_reaped"] += 1
                if _tel.ENABLED:
                    _tel.counter("serving.streams_reaped").inc()
                    _tel.event("serve.stream.reaped", rid=req.rid,
                               trace=req.trace,
                               idle_s=now - s._touched_t,
                               tokens=len(req.generated))
                self.sched.cancel(req)

    # -- graceful drain ------------------------------------------------------
    def drain(self, wait=False, timeout=None):
        """Stop admissions; everything already accepted (queued or
        active) runs to completion. New ``submit`` calls raise
        :class:`QueueFullError` (counted as rejections — the upstream
        load balancer sheds to other replicas). When the last in-flight
        request finishes, a deterministic ``drained`` event lands in
        the scheduler event log, ``serve.drained`` in the journal, and
        ``/servingz`` reports ``drained: true`` — the primitive behind
        mxctl's drain-then-restart action and any clean shutdown.

        ``wait=True`` blocks until drained (the caller must be driving
        steps, or have ``start()`` running). Returns True when drained.
        """
        with self._lock:
            if not self._draining:
                self._draining = True
                if _tel.ENABLED:
                    _tel.counter("serving.drains_total").inc()
                self._check_drained_locked()
                self._work.notify_all()
            if not wait:
                return self._drained
            deadline = (time.monotonic() + timeout
                        if timeout is not None else None)
            while not self._drained:
                remaining = None
                if deadline is not None:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        return False
                self._work.wait(timeout=remaining if remaining is not None
                                else 0.5)
            return True

    def resume(self):
        """Reopen admissions after :meth:`drain` (a replica held in
        reserve, or a flap-guard test flipping readiness)."""
        with self._lock:
            if self._draining:
                self._draining = False
                self._drained = False
                self._work.notify_all()

    def accepting(self):
        """True while ``submit`` admits work — the /readyz signal
        (telemetry/server.py): a draining replica is alive but not
        ready."""
        with self._lock:
            return not self._draining

    @property
    def draining(self):
        with self._lock:
            return self._draining

    @property
    def drained(self):
        with self._lock:
            return self._drained

    def _check_drained_locked(self):
        """Latch the drained state once the last accepted request is
        gone (caller holds ``_lock``)."""
        if (self._draining and not self._drained
                and not self.sched.queue and not self.sched.active):
            self._drained = True
            self.sched.note_drained()
            if _tel.ENABLED:
                _tel.event("serve.drained",
                           completed=self._stats["completed"],
                           cancelled=self._stats["cancelled"])
            # every caller holds _lock (the _locked-suffix contract) —
            # _work is Condition(self._lock), so this notify is locked
            self._work.notify_all()  # mxlint: disable

    def _reject(self, reason="params", queue_depth=None):
        self._stats["rejected"] += 1
        if _tel.ENABLED:
            _tel.counter("serving.requests_rejected").inc()
            # the rejection DETAIL rides a journal event (reason +
            # depth + the backoff hint handed to the caller), so a
            # fleet router's shed decisions are reconstructable from
            # the journal alone
            _tel.event("serve.request.reject", reason=reason,
                       queue_depth=queue_depth,
                       retry_after_s=(
                           self._retry_after_locked(queue_depth)
                           if queue_depth is not None else None))

    def _retry_after_locked(self, queue_depth=None):
        """Backoff hint for a rejected submit: expected seconds until
        one admission slot frees. At the current windowed token rate,
        the soonest-finishing active request needs ``min remaining
        tokens / (rate / active)`` seconds; idle or cold engines fall
        back to a 1s hint. Clamped to [0.05, 30]."""
        rate = self._last_rate
        active = len(self.sched.active)
        if rate <= 0.0 or not active:
            return 1.0
        remaining = min(
            max(1, r.max_new_tokens - len(r.generated))
            for r in self.sched.active)
        return float(min(30.0, max(0.05, remaining * active / rate)))

    # -- speculative-decoding runtime toggle ---------------------------------
    def set_spec(self, enabled):
        """Flip speculation at runtime (takes effect at the next
        scheduler plan). The draft pool and programs stay resident so
        re-enabling is instant; a custom mxctl actuator flips this off
        when the accept rate makes speculation a loss
        (docs/how_to/control_plane.md). Raises when the engine was
        built without a draft model."""
        if self.draft_model is None:
            raise MXNetError(
                "speculation was not configured on this engine "
                "(ServingConfig.spec + draft model)")
        with self._lock:
            self.sched.set_spec_k(self.cfg.spec_k if enabled else 0)

    @property
    def spec_enabled(self):
        with self._lock:
            return self.sched.spec_active()

    def warmup_spec(self, batch_sizes=None):
        """Pre-compile every speculative-path program — the draft-turn
        and verify kinds at each batch bucket and both steady-state
        ingest widths, plus the draft model's plain step buckets (the
        prefill mirror and the toggle catch-up path dispatch those) —
        so serving never compiles mid-traffic (and the persistent jit
        cache serves them to the next process). Inactive rows write to
        the scratch block, so warming against the live pools is safe."""
        if self.draft_model is None:
            return
        K = self.cfg.spec_k
        for b in (batch_sizes or self.draft_model.batch_buckets):
            for c in self.draft_model.chunk_buckets:
                bt = np.zeros((b, self.draft_model.max_blocks), np.int32)
                _, dk, dv = self.draft_model.step(
                    self.draft_params, self.draft_pool.k,
                    self.draft_pool.v, np.zeros((b, c), np.int32),
                    np.zeros((b,), np.int32), np.ones((b,), np.int32),
                    bt, np.zeros((b,), bool))
                self.draft_pool.swap(dk, dv)
        for b in (batch_sizes or self.model.batch_buckets):
            bt = np.zeros((b, self.model.max_blocks), np.int32)
            ks = np.full((b,), K, np.int32)
            act = np.zeros((b,), bool)
            d = q = None
            for cin in (1, 2):
                d, q, dk, dv = self.draft_model.draft_turn(
                    self.draft_params, self.draft_pool.k,
                    self.draft_pool.v, np.zeros((b, cin), np.int32),
                    np.zeros((b,), np.int32),
                    np.full((b,), cin, np.int32), bt, act, ks, K)
                self.draft_pool.swap(dk, dv)
            n, t, kp, vp = self.model.verify(
                self.params, self.pool.k, self.pool.v,
                np.zeros((b, 1), np.int32), d, q,
                np.zeros((b,), np.int32), 1 + ks, bt, act)
            self.pool.swap(kp, vp)

    # -- live weight sync (docs/how_to/weight_sync.md) -----------------------
    def install_weights(self, version, params, draft_params=None,
                        trace=None):
        """Atomically swap in a staged weight set between scheduled
        steps: target params, draft params, and the host unembedding
        in ONE transaction under ``_step_lock`` — no drain, no jit
        recompile (params are jitted-program *arguments*; the hard
        shape/dtype gate below guarantees compiled shapes never
        change). The outgoing version lands on the bounded last-good
        ring (``MXNET_WSYNC_RING``) for :meth:`rollback_weights`.

        Gates (reject ⇒ ``wsync.rejected_total`` + a journaled
        ``rejected`` record + MXNetError, live params untouched):

        - shape/dtype mismatch against the live set — hard reject;
        - non-finite tensors — the guardian's finiteness discipline
          (``resilience/guardian.py``: a non-finite update never
          lands) applied to weight syncs.

        ``draft_params`` refresh in the same transaction so the spec
        accept rate doesn't crater mid-swap; a version without a draft
        half swaps the target only (and a draft half is dropped when
        the engine was built without a draft model).
        """
        from ..wsync import common as _wc

        version = int(version)
        if _wc.param_manifest(params) != _wc.param_manifest(self.params):
            self._reject_weights(
                version, trace, "shape/dtype mismatch against live "
                "params (jitted shapes are pinned — a resized model "
                "needs a new engine, not a sync)")
        bad = _wc.nonfinite_keys(_wc.flatten_params(params))
        if bad:
            self._reject_weights(
                version, trace,
                "non-finite tensors: %s" % ", ".join(sorted(bad)[:4]))
        if draft_params is not None and self.draft_model is None:
            draft_params = None
        if draft_params is not None:
            if (_wc.param_manifest(draft_params)
                    != _wc.param_manifest(self.draft_params)):
                self._reject_weights(
                    version, trace,
                    "draft shape/dtype mismatch against live draft "
                    "params")
            dbad = _wc.nonfinite_keys(_wc.flatten_params(draft_params))
            if dbad:
                self._reject_weights(
                    version, trace, "non-finite draft tensors: %s"
                    % ", ".join(sorted(dbad)[:4]))
        with self._step_lock:
            with self._lock:
                self._weight_ring.append(
                    (self._weight_version, self.params, self.draft_params))
                del self._weight_ring[:-self._weight_ring_keep]
                self.params = params
                self._installed_params = params
                if draft_params is not None:
                    self.draft_params = draft_params
                self._installed_draft = self.draft_params
                if self.cfg.mesh is not None:
                    self._host_unembed = np.asarray(
                        params["embed"], np.float32).T
                self._weight_version = version
                self._sync_mark_until = (time.monotonic()
                                         + self._sync_ttft_window)
                if _tel.ENABLED:
                    _tel.counter("wsync.versions_applied_total").inc()
                    _tel.gauge("wsync.current_version").set(version)
                _wc.journal("applied", version, trace=trace,
                            draft=draft_params is not None,
                            ring=len(self._weight_ring))
        return version

    def rollback_weights(self, trace=None):
        """Reinstall the newest last-good ring entry (target + draft +
        unembed in one transaction, like :meth:`install_weights`). A
        rollback CONSUMES its entry — repeated firings walk further
        back, never loop on one version (the guardian ring's
        escalation discipline). The mxctl ``rollback_weights``
        actuator's whole body. Returns ``{"from_version",
        "to_version"}``; raises MXNetError on an empty ring."""
        from ..wsync import common as _wc

        with self._step_lock:
            with self._lock:
                if not self._weight_ring:
                    raise MXNetError(
                        "rollback_weights: last-good ring is empty "
                        "(no prior version to restore)")
                version, params, draft = self._weight_ring.pop()
                from_v = self._weight_version
                if trace is None and _tel.ENABLED:
                    trace = _tel.mint_trace()
                self.params = params
                self._installed_params = params
                if draft is not None and self.draft_model is not None:
                    self.draft_params = draft
                self._installed_draft = self.draft_params
                if self.cfg.mesh is not None:
                    self._host_unembed = np.asarray(
                        params["embed"], np.float32).T
                self._weight_version = version
                if _tel.ENABLED:
                    _tel.counter("wsync.rollbacks_total").inc()
                    _tel.gauge("wsync.current_version").set(
                        version if version is not None else 0)
                _wc.journal("rolled_back", version, trace=trace,
                            from_version=from_v,
                            ring=len(self._weight_ring))
        return {"from_version": from_v, "to_version": version}

    def _reject_weights(self, version, trace, reason):
        from ..wsync import common as _wc

        if _tel.ENABLED:
            _tel.counter("wsync.rejected_total").inc()
        _wc.journal("rejected", version, trace=trace, reason=reason)
        raise MXNetError("weight sync version %d rejected: %s"
                         % (version, reason))

    def weight_version(self):
        """Version installed by the newest sync (None before any)."""
        with self._lock:
            return self._weight_version

    # -- synchronous batch API -----------------------------------------------
    def generate(self, prompts, max_new_tokens=16):
        """Submit all prompts, drive the loop to completion, return the
        generated token lists (the synchronous batch surface)."""
        handles = [self.submit(p, max_new_tokens) for p in prompts]
        with self._lock:
            background = self._thread is not None
        if not background:
            self.run_until_idle()
        return [h.result() for h in handles]

    # -- the step loop -------------------------------------------------------
    def step(self):
        """Run one scheduler step (<=1 decode batch + <=1 prefill
        batch). Returns True when any work ran. Whole-step atomic:
        concurrent drivers serialize on _step_lock."""
        with self._step_lock:
            if (self.params is not self._installed_params
                    or (self.draft_model is not None
                        and self.draft_params is not self._installed_draft)):
                raise MXNetError(
                    "Engine params were rebound without "
                    "install_weights(): a direct write bypasses the "
                    "staged-swap gates (shape/dtype, finiteness, "
                    "acceptance) — docs/how_to/weight_sync.md")
            with self._lock:
                self._reap_idle_locked(time.monotonic())
                plan = self.sched.plan()
                self._mirror_events()
                decode = list(plan.decode)
                prefill = list(plan.prefill)
                spec_k = dict(plan.spec_k)
                now = time.monotonic()
                for req, _cs, _clen in prefill:
                    if req.admit_t is None:  # first admission only —
                        req.admit_t = now    # eviction re-prefills later
            worked = False
            if decode:
                # model dispatch (incl. the speculative turn's fences)
                # under _step_lock is the DESIGN: the step lock exists
                # to serialize whole steps (see its __init__ comment)
                self._run_decode(decode, spec_k)  # mxlint: disable
                worked = True
            if prefill:
                # model dispatch under _step_lock is the DESIGN: the
                # step lock exists to serialize whole steps, model
                # execution included (see its comment in __init__)
                self._run_prefill(prefill)  # mxlint: disable
                worked = True
            if worked:
                with self._lock:
                    self._stats["steps"] += 1
                    self._mirror_events()
                    self._update_gauges()
            return worked

    def run_until_idle(self, max_steps=None):
        """Drive step() until no work remains; returns steps run."""
        n = 0
        while self.step():
            n += 1
            if max_steps is not None and n >= max_steps:
                break
        return n

    def start(self):
        """Serve from a background thread (submit() wakes it)."""

        def loop():
            while True:
                with self._lock:
                    if self._stop:
                        break
                if not self.step():
                    with self._work:
                        if self._stop:
                            break
                        self._work.wait(timeout=0.05)

        with self._lock:
            if self._thread is not None:
                return
            self._stop = False
            self._thread = threading.Thread(target=loop, name="mx-serve",
                                            daemon=True)
            self._thread.start()

    def stop(self):
        with self._lock:
            thread = self._thread
            if thread is None:
                return
            self._stop = True
            self._work.notify_all()
        # join OUTSIDE the lock (the loop's own step() takes it), and
        # clear _thread only AFTER the join: a start() racing this stop
        # must keep seeing the old thread and no-op — clearing early
        # would let it spawn a second loop while the first still runs
        thread.join()
        with self._lock:
            if self._thread is thread:
                self._thread = None

    # -- batch execution -----------------------------------------------------
    def _tables(self, reqs):
        w = self.model.max_blocks
        bt = np.zeros((len(reqs), w), np.int32)
        for i, r in enumerate(reqs):
            bt[i, :len(r.blocks)] = r.blocks
        return bt

    def _draft_tables(self, reqs):
        w = self.draft_model.max_blocks
        bt = np.zeros((len(reqs), w), np.int32)
        for i, r in enumerate(reqs):
            bt[i, :len(r.draft_blocks)] = r.draft_blocks
        return bt

    @staticmethod
    def _samp_arrays(reqs):
        """Per-request fused-sampler parameter vectors."""
        return (np.asarray([r.temperature for r in reqs], np.float32),
                np.asarray([r.top_k for r in reqs], np.int32),
                np.asarray([r.top_p for r in reqs], np.float32),
                np.asarray([r.seed for r in reqs], np.uint32))

    @staticmethod
    def _stream_slice(req, a, b):
        """Tokens at global positions [a, b) of a request's emitted
        stream (prompt then generated — eviction's recompute fold moves
        tokens between context and generated but never moves their
        global positions)."""
        lp = len(req.prompt)
        out = []
        for p in range(a, b):
            out.append(int(req.prompt[p]) if p < lp
                       else int(req.generated[p - lp]))
        return out

    def _run_decode(self, reqs, spec_k=None):
        """Dispatch one decode batch: speculative rows (plan gave them
        a draft budget) run the draft+verify turn, the rest (spec off,
        or a request's final token) the plain fused-sampling step."""
        spec_rows = [r for r in reqs if spec_k and spec_k.get(r.rid, 0) > 0]
        if spec_rows:
            # model dispatch under _step_lock is the DESIGN: the step
            # lock serializes whole steps, model execution included
            # (see its comment in __init__) — same contract as
            # _run_prefill below
            self._run_spec_turn(spec_rows,                # mxlint: disable
                                [spec_k[r.rid] for r in spec_rows])
        plain = [r for r in reqs if r not in spec_rows]
        if plain:
            self._run_plain_decode(plain)

    def _run_plain_decode(self, reqs):
        t0 = time.monotonic()
        B = len(reqs)
        tokens = np.asarray([[r.generated[-1]] for r in reqs], np.int32)
        start = np.asarray(
            [len(r.prompt) + len(r.generated) - 1 for r in reqs], np.int32)
        temp, tk, tp, sd = self._samp_arrays(reqs)
        # static policy = fixed-shape serving: decode dispatches at the
        # full batch width even as the batch drains (dead slots are
        # padded lanes), faithfully paying what static batching pays on
        # accelerators where a decode step costs the same at any live
        # count; continuous dispatches at the ragged bucket
        min_b = self.cfg.max_batch if self.cfg.policy == "static" else None
        # token-vector-only contract: the step's one D2H is the sampled
        # token vector at bucket width (4 bytes/lane) — the ledger
        # fails the turn if anything more (e.g. logits) crosses
        Bv = bucket_for(max(B, min_b or 1), self.model.batch_buckets)
        with _tel.span("serve.decode"), \
                _cv.d2h_region("serve.decode_step", budget_bytes=4 * Bv):
            nxt, kp, vp = self.model.step(
                self.params, self.pool.k, self.pool.v, tokens, start,
                np.ones((B,), np.int32), self._tables(reqs),
                np.ones((B,), bool), min_batch_bucket=min_b,
                temperature=temp, top_k=tk, top_p=tp, seed=sd)
        now = time.monotonic()
        with self._lock:
            self.pool.swap(kp, vp)
            if _tel.ENABLED:
                _tel.histogram("serving.decode_batch_size").observe(B)
                _tel.histogram("serving.decode_step_s").observe(now - t0)
            for r, t in zip(reqs, nxt):
                if r.state != DECODE:   # cancelled while stepping
                    continue
                self._emit(r, int(t), now)

    def _run_spec_turn(self, reqs, ks):
        """One speculative decode turn: the draft model proposes up to
        ``ks[i]`` tokens per request (device-chained — proposals never
        visit the host), the target verifies every position in ONE
        jitted ragged step with fused accept/reject + resampling, and
        the host folds the accepted prefix + one corrected/bonus token
        into each stream. Per-turn D2H is ints only (the accepted
        counts, the draft tokens, the final tokens) — logits never
        leave the device."""
        from ..telemetry import prof as _prof

        prof_on = _prof.ENABLED
        ac0 = _prof.attribution_count() if prof_on else 0
        t0 = time.monotonic()
        B = len(reqs)
        # fixed chain length: one draft_turn/verify program regardless
        # of this turn's per-row budgets (ks masks the unused tail)
        K = self.cfg.spec_k
        P = np.asarray([len(r.prompt) + len(r.generated) for r in reqs],
                       np.int32)              # next-token position per row
        start0 = P - 1
        temp, tk, tp, sd = self._samp_arrays(reqs)
        dtables = self._draft_tables(reqs)

        # -- draft catch-up beyond the steady-state ingest (a request
        # that ran plain decode while speculation was toggled off can
        # lag arbitrarily) — chunked through the draft's step program
        for i, r in enumerate(reqs):
            while P[i] - 1 - r.draft_pos > 1:
                cl = min(self.cfg.prefill_chunk, int(P[i]) - 1 - r.draft_pos)
                toks = np.asarray(
                    [self._stream_slice(r, r.draft_pos, r.draft_pos + cl)],
                    np.int32)
                _, dk, dv = self.draft_model.step(
                    self.draft_params, self.draft_pool.k, self.draft_pool.v,
                    toks, np.asarray([r.draft_pos], np.int32),
                    np.asarray([cl], np.int32), dtables[i:i + 1],
                    np.ones((1,), bool))
                self.draft_pool.swap(dk, dv)
                r.draft_pos += cl

        # -- draft phase: ingest (1-2 missing stream tokens) + K
        # chained proposals, ONE dispatch (model._draft_turn_impl)
        dstart = np.asarray([r.draft_pos for r in reqs], np.int32)
        lens = P - dstart                     # 1 or 2 after catch-up
        Cin = int(lens.max())
        ing = np.zeros((B, Cin), np.int32)
        for i, r in enumerate(reqs):
            ing[i, :lens[i]] = self._stream_slice(r, r.draft_pos, int(P[i]))
        karr = np.asarray(ks, np.int32)
        # the spec turn IS the decode dispatch when speculation is on —
        # it gets its own span (serve.spec_turn) so /tracez and
        # span-based mxctl rules keep seeing decode latency; the D2H
        # ledger pins the ints-only transfer contract (n, fin, drafts
        # at bucket width — never logits)
        Bv = bucket_for(B, self.model.batch_buckets)
        with _tel.span("serve.spec_turn"), \
                _cv.d2h_region("serve.spec_turn",
                               budget_bytes=4 * Bv * (K + 3)):
            td0 = time.monotonic() if prof_on else 0.0
            dmat, qmat, dk, dv = self.draft_model.draft_turn(
                self.draft_params, self.draft_pool.k, self.draft_pool.v,
                ing, dstart, lens, dtables, np.ones((B,), bool), karr, K,
                temperature=temp, top_k=tk, top_p=tp, seed=sd)
            if prof_on:
                dmat.block_until_ready()
                td1 = time.monotonic()

            # -- verify: one ragged target step over [prev, d_0..d_k]
            prev = np.asarray([[r.generated[-1]] for r in reqs], np.int32)
            n_dev, fin_dev, kp, vp = self.model.verify(
                self.params, self.pool.k, self.pool.v, prev, dmat, qmat,
                start0, 1 + karr, self._tables(reqs), np.ones((B,), bool),
                temperature=temp, top_k=tk, top_p=tp, seed=sd)
            if prof_on:
                tv1 = time.monotonic()
                n_dev.block_until_ready()
                tv2 = time.monotonic()
            # ints-only spec-turn D2H (accepted counts, final tokens,
            # draft tokens) — ledger-accounted below; logits never
            # leave the device
            n = np.asarray(n_dev)          # mxlint: disable
            fin = np.asarray(fin_dev)      # mxlint: disable
            drafts = np.asarray(dmat)      # mxlint: disable
            _cv.note_d2h(
                n.nbytes + fin.nbytes + drafts.nbytes,
                "mxnet_tpu/serving/engine.py::Engine._run_spec_turn")
        now = time.monotonic()

        drafted = accepted = emitted = 0
        with self._lock:
            self.pool.swap(kp, vp)
            self.draft_pool.swap(dk, dv)
            for i, r in enumerate(reqs):
                if r.state != DECODE:         # cancelled while stepping
                    continue
                k_i = int(ks[i])
                j = min(int(n[i]), k_i)
                # draft KV is valid through the accepted, FED prefix
                # (the last proposal is never fed back): positions
                # < P + min(j, k_i - 1) — the rollback that keeps both
                # pools position-consistent across partial accepts
                r.draft_pos = int(P[i]) + min(j, k_i - 1)
                r.spec_drafted += k_i
                r.spec_accepted += j
                drafted += k_i
                accepted += j
                for t in list(drafts[i, :j]) + [int(fin[i])]:
                    emitted += 1
                    self._emit(r, int(t), now)
                    if r.state != DECODE:     # eos / max_new hit
                        break
                if r.state == DECODE:
                    self.sched.trim_blocks(r)
            self._stats["spec_turns"] += 1
            self._stats["spec_tokens_drafted"] += drafted
            self._stats["spec_tokens_accepted"] += accepted
            self._spec_window.append((now, drafted, accepted))
            self._spec_window = [
                x for x in self._spec_window
                if now - x[0] <= self.SPEC_WINDOW_SECS]
            if _tel.ENABLED:
                _tel.counter("serving.spec_turns").inc()
                _tel.counter("serving.spec_tokens_drafted").inc(drafted)
                _tel.counter("serving.spec_tokens_accepted").inc(accepted)
                h = _tel.histogram("serving.spec_accepted_tokens")
                for i, r in enumerate(reqs):
                    h.observe(min(int(n[i]), int(ks[i])))
                _tel.histogram("serving.decode_batch_size").observe(B)
                _tel.histogram("serving.decode_step_s").observe(now - t0)
        if prof_on and _prof.attribution_count() == ac0:
            Bb = bucket_for(B, self.model.batch_buckets)
            _prof.note_step(
                "serve.spec_draft",
                {"host": td0 - t0, "device": td1 - td0},
                key=self.draft_model._prof_keys.get(
                    ("draft_turn", Bb, Cin if Cin == 1 else
                     bucket_for(Cin, self.draft_model.chunk_buckets), K)),
                tokens=int(np.sum(lens)) + B * (K - 1))
            _prof.note_step(
                "serve.spec_verify",
                {"dispatch": tv1 - td1, "device": tv2 - tv1,
                 "d2h": now - tv2},
                key=self.model._prof_keys.get(("verify", Bb, K)),
                tokens=emitted,
                d2h_bytes=int(n.nbytes + fin.nbytes + drafts.nbytes))

    def _run_prefill(self, chunks):
        # context-parallel long prompts take their own path, off the
        # bucketed batch (model.cp_prefill_kv)
        batched = []
        for req, cs, clen in chunks:
            if (self.cfg.mesh is not None and cs == 0
                    and req.ctx_len >= self.cfg.cp_min_tokens
                    and self._cp_eligible(req)):
                self._run_cp_prefill(req)
            else:
                batched.append((req, cs, clen))
        if not batched:
            return
        B = len(batched)
        C = max(clen for _, _, clen in batched)
        reqs = [r for r, _, _ in batched]
        tokens = np.zeros((B, C), np.int32)
        start = np.zeros((B,), np.int32)
        chunk_len = np.zeros((B,), np.int32)
        for i, (req, cs, clen) in enumerate(batched):
            tokens[i, :clen] = req.context[cs:cs + clen]
            start[i] = cs
            chunk_len[i] = clen
        temp, tk, tp, sd = self._samp_arrays(reqs)
        with _tel.span("serve.prefill"):
            nxt, kp, vp = self.model.step(
                self.params, self.pool.k, self.pool.v, tokens, start,
                chunk_len, self._tables(reqs), np.ones((B,), bool),
                temperature=temp, top_k=tk, top_p=tp, seed=sd)
        if self.draft_model is not None:
            # mirror the chunk into the draft pool (same tokens, same
            # positions, the draft's own tables) so draft KV stays
            # position-consistent with the target from admission on —
            # the mirror runs even while speculation is toggled off, so
            # re-enabling is instant
            with _tel.span("serve.draft_prefill"):
                _, dkp, dvp = self.draft_model.step(
                    self.draft_params, self.draft_pool.k,
                    self.draft_pool.v, tokens, start, chunk_len,
                    self._draft_tables(reqs), np.ones((B,), bool))
        now = time.monotonic()
        with self._lock:
            self.pool.swap(kp, vp)
            if self.draft_model is not None:
                self.draft_pool.swap(dkp, dvp)
            for i, (req, cs, clen) in enumerate(batched):
                if req.state != PREFILL:   # cancelled while stepping
                    continue
                self.sched.note_prefilled(req, clen)
                req.draft_pos = cs + clen
                if req.state == DECODE:
                    if req.prefill_done_t is None:  # first time only —
                        req.prefill_done_t = now    # an eviction
                    # re-prefill must not swallow the first decode
                    # phase from the journaled lifecycle spans
                    # (evictions field records the wrinkle)
                    # the final prefill chunk's logits sample the first
                    # new token — no separate "first decode" dispatch
                    # (nxt is already host: ServingModel.step pulled
                    # the token vector once for the whole chunk batch)
                    self._emit(req, int(nxt[i]), now)  # mxlint: disable

    def _cp_eligible(self, req):
        n = self.cfg.mesh.shape[self.cfg.cp_seq_axis]
        chunk = self.cfg.cp_chunk or req.ctx_len
        return chunk % n == 0 and req.ctx_len % chunk == 0

    def _run_cp_prefill(self, req):
        """Whole-prompt context-parallel prefill over the mesh, then
        scatter the dense K/V into this request's pool blocks."""
        import jax.numpy as jnp

        cfg = self.model_cfg
        with _tel.span("serve.cp_prefill"):
            k, v, x_last = cp_prefill_kv(
                self.params, cfg, req.context, self.cfg.mesh,
                kind=self.cfg.cp_kind, chunk=self.cfg.cp_chunk)
        bs = self.cfg.block_size
        T = req.ctx_len
        nb = blocks_for_tokens(T, bs)
        pad = nb * bs - T
        if pad:
            zpad = np.zeros((cfg.num_layers, pad) + k.shape[2:], k.dtype)
            k = np.concatenate([k, zpad], axis=1)
            v = np.concatenate([v, zpad], axis=1)
        k = k.reshape(cfg.num_layers, nb, bs, cfg.num_heads, cfg.head_dim)
        v = v.reshape(cfg.num_layers, nb, bs, cfg.num_heads, cfg.head_dim)
        blocks = np.asarray(req.blocks[:nb], np.int32)
        # device scatter + logits D2H run OUTSIDE _lock (a submit must
        # not stall behind them; the pool reads are safe because every
        # pool-swapping path serializes on _step_lock) — only the swap
        # and the scheduler/stream bookkeeping take the state lock
        new_k = self.pool.k.at[:, blocks].set(
            jnp.asarray(k, self.pool.k.dtype))
        new_v = self.pool.v.at[:, blocks].set(
            jnp.asarray(v, self.pool.v.dtype))
        logits = x_last @ self._host_unembed
        # the first token draws from the same (seed, position) stream
        # the fused device sampler would use — cp-prefilled requests
        # sample identically to paged-prefilled ones
        first = _samp.host_sample(logits, req.temperature, req.top_k,
                                  req.top_p, req.seed, T)
        if self.draft_model is not None:
            # the draft pool still needs this context: ingest it
            # through the draft's own paged prefill (the draft is small
            # — chunked single-row steps, not worth a cp pass)
            dpos = 0
            while dpos < T:
                cl = min(self.cfg.prefill_chunk, T - dpos)
                toks = np.asarray([req.context[dpos:dpos + cl]], np.int32)
                _, dk, dv = self.draft_model.step(
                    self.draft_params, self.draft_pool.k,
                    self.draft_pool.v, toks,
                    np.asarray([dpos], np.int32),
                    np.asarray([cl], np.int32),
                    self._draft_tables([req]), np.ones((1,), bool))
                self.draft_pool.swap(dk, dv)
                dpos += cl
        now = time.monotonic()
        with self._lock:
            self.pool.swap(new_k, new_v)
            if req.state != PREFILL:
                return
            self.sched.note_prefilled(req, T - req.prefilled)
            req.draft_pos = T
            if req.state == DECODE and req.prefill_done_t is None:
                req.prefill_done_t = now
            self._emit(req, first, now)

    # -- per-token bookkeeping (under self._lock) ----------------------------
    def _emit(self, req, token, now):
        req.generated.append(token)
        stream = req.stream
        if req.first_token_t is None:
            req.first_token_t = now
            self._ttfts.append(now - req.submit_t)
            if _tel.ENABLED:
                _tel.histogram("serving.ttft_s").observe(now - req.submit_t)
            if now <= self._sync_mark_until:
                # TTFT landed inside a sync window: the degradation
                # signal tools/perf_gate.py gates (ttft_sync_p99_s must
                # stay within tolerance of the no-sync baseline)
                self._sync_ttfts.append(now - req.submit_t)
                if _tel.ENABLED:
                    _tel.histogram("serving.ttft_sync_s").observe(
                        now - req.submit_t)
        if req.last_token_t is not None:
            self._token_lats.append(now - req.last_token_t)
            if _tel.ENABLED:
                _tel.histogram("serving.token_latency_s").observe(
                    now - req.last_token_t)
        req.last_token_t = now
        self._stats["tokens_emitted"] += 1
        self._rate_window.append((now, self._stats["tokens_emitted"]))
        if stream is not None:
            stream._emit(token)
        # len(generated) is the client-visible stream length — eviction
        # folds tokens into the recompute context but never drops them
        done = len(req.generated) >= req.max_new_tokens
        if req.eos_id is not None and token == req.eos_id:
            done = True
        if done:
            req.finish_t = now
            self.sched.finish(req)
            self._trace_request(req, "complete", now)
            self._mirror_events()
            if stream is not None:
                stream._end("finished")

    def _trace_request(self, req, status, now):
        """Journal the request's lifecycle as spans sharing its trace id
        (submit already landed at intake). Phase boundaries come from
        the monotonic stamps collected along the way, re-anchored to
        the submit wall clock so the journal's epoch-seconds timeline
        stays coherent."""
        if req.trace is None:
            return

        def w(mono):  # monotonic stamp -> journal wall clock
            return req.wall0 + (mono - req.submit_t)

        _tel.event("serve.request", t=req.wall0, dur=now - req.submit_t,
                   trace=req.trace, rid=req.rid, status=status,
                   tokens=len(req.generated), evictions=req.evictions)
        if req.admit_t is not None:
            _tel.event("serve.request.prefill", t=w(req.admit_t),
                       dur=(req.prefill_done_t or now) - req.admit_t,
                       trace=req.trace, rid=req.rid)
        if req.prefill_done_t is not None:
            _tel.event("serve.request.decode", t=w(req.prefill_done_t),
                       dur=now - req.prefill_done_t,
                       trace=req.trace, rid=req.rid)
        _tel.event("serve.request.%s" % status, t=w(now),
                   trace=req.trace, rid=req.rid)

    def _mirror_events(self):
        """Fold scheduler event counts into stats + mxtel counters, and
        close out cancelled streams."""
        mapping = {"admit": "admitted", "complete": "completed",
                   "evict": "evicted", "cancel": "cancelled"}
        for ev, stat in mapping.items():
            n = self.sched.counts.get(ev, 0)
            d = n - self._last_counts.get(ev, 0)
            if d:
                self._stats[stat] += d
                self._last_counts[ev] = n
                if _tel.ENABLED:
                    _tel.counter("serving.requests_%s" % stat).inc(d)
        # end streams of requests the sweep cancelled
        for rid, req in list(self._by_rid.items()):
            if req.state == CANCELLED:
                if req.stream is not None and req.stream.status == "running":
                    req.stream._end("cancelled")
                self._trace_request(req, "cancel", time.monotonic())
                del self._by_rid[rid]
            elif req.state == FINISHED:
                del self._by_rid[rid]
        self._check_drained_locked()

    def _update_gauges(self):
        util = self.pool.utilization()
        now = time.monotonic()
        # tokens/s over a sliding 2 s window of emissions
        win = [x for x in self._rate_window if now - x[0] <= 2.0]
        self._rate_window = win
        rate = 0.0
        if len(win) >= 2 and win[-1][0] > win[0][0]:
            rate = (win[-1][1] - win[0][1]) / (win[-1][0] - win[0][0])
        self._last_rate = rate
        if _tel.ENABLED:
            _tel.gauge("serving.kv_pool_utilization").set(util)
            _tel.gauge("serving.kv_pool_hwm_blocks").set(
                self.pool.high_water_mark())
            _tel.gauge("serving.tokens_per_s").set(rate)
            _tel.gauge("serving.queue_depth").set(len(self.sched.queue))
            if self._stats["spec_tokens_drafted"]:
                _tel.gauge("serving.spec_accept_rate").set(
                    self._stats["spec_tokens_accepted"]
                    / float(self._stats["spec_tokens_drafted"]))

    def note_idle(self):
        """Mark the engine drained: the tokens/s gauge drops to zero
        instead of freezing at its last in-flight value (journal
        timelines honest across idle gaps)."""
        with self._lock:
            self._rate_window = []
            self._last_rate = 0.0
            if _tel.ENABLED:
                _tel.gauge("serving.tokens_per_s").set(0.0)
                _tel.gauge("serving.queue_depth").set(len(self.sched.queue))

    # -- reporting -----------------------------------------------------------
    def latency_samples(self):
        """Copies of the raw TTFT / per-token latency sample lists (the
        bench slices per-window percentiles out of a reused engine)."""
        with self._lock:
            return list(self._ttfts), list(self._token_lats)

    def stats(self):
        """Plain-number mirror of the serving metrics (works with
        telemetry off — the bench subprocess contract)."""
        def pct(xs, q):
            if not xs:
                return None
            return float(np.percentile(np.asarray(xs), q))

        with self._lock:
            out = dict(self._stats)
            drafted = self._stats["spec_tokens_drafted"]
            now = time.monotonic()
            win = [x for x in self._spec_window
                   if now - x[0] <= self.SPEC_WINDOW_SECS]
            wd = sum(x[1] for x in win)
            wa = sum(x[2] for x in win)
            out.update({
                "spec_enabled": self.sched.spec_active(),
                "spec_accept_rate": (
                    self._stats["spec_tokens_accepted"] / float(drafted)
                    if drafted else None),
                # the actionable signal: accept rate over the last
                # SPEC_WINDOW_SECS of turns (None when no recent turns)
                "spec_window_drafted": wd,
                "spec_window_accepted": wa,
                "spec_accept_rate_window": (wa / float(wd) if wd
                                            else None),
                "kv_pool_utilization": self.pool.utilization(),
                "kv_pool_hwm_blocks": self.pool.high_water_mark(),
                "queue_depth": len(self.sched.queue),
                "active": len(self.sched.active),
                "draining": self._draining,
                "drained": self._drained,
                "tokens_per_s_window": self._last_rate,
                "weight_version": self._weight_version,
                "weight_ring": len(self._weight_ring),
                "ttft_p50_s": pct(self._ttfts, 50),
                "ttft_p99_s": pct(self._ttfts, 99),
                "ttft_sync_p99_s": pct(self._sync_ttfts, 99),
                "token_latency_p50_s": pct(self._token_lats, 50),
                "token_latency_p99_s": pct(self._token_lats, 99),
            })
        return out

    def introspect(self, event_tail=50):
        """Live request table + pool state + scheduler event tail — the
        /servingz endpoint's payload (telemetry/server.py). Answers
        "what is this serving request doing RIGHT NOW": every queued and
        active request with its state, progress, and trace id."""
        now = time.monotonic()
        with self._lock:
            reqs = []
            for req in list(self.sched.active) + list(self.sched.queue):
                reqs.append({
                    "rid": req.rid, "state": req.state,
                    "trace": req.trace,
                    "prompt_len": int(req.prompt.shape[0]),
                    "ctx_len": req.ctx_len,
                    "prefilled": req.prefilled,
                    "generated": len(req.generated),
                    "max_new_tokens": req.max_new_tokens,
                    "blocks": len(req.blocks),
                    "evictions": req.evictions,
                    "age_s": (now - req.submit_t
                              if req.submit_t is not None else None),
                })
            out = {
                "policy": self.cfg.policy,
                "draining": self._draining,
                "drained": self._drained,
                "spec": {
                    "configured": self.draft_model is not None,
                    "enabled": self.sched.spec_active(),
                    "spec_k": self.sched.spec_k,
                    "draft_pool_utilization": (
                        self.draft_pool.utilization()
                        if self.draft_pool is not None else None),
                },
                "wsync": {
                    "version": self._weight_version,
                    "ring": len(self._weight_ring),
                    "syncing": self._wsync_sub is not None,
                },
                "requests": reqs,
                "pool": {
                    "capacity_blocks": self.pool.capacity,
                    "free_blocks": self.pool.num_free,
                    "utilization": self.pool.utilization(),
                    "hwm_blocks": self.pool.high_water_mark(),
                    "block_size": self.cfg.block_size,
                },
                # the event log is a bounded ring (long-lived processes)
                # — this is the TAIL; events_total keeps the true count
                "events": [list(e) for e in
                           list(self.sched.events)[-event_tail:]],
                "events_total": self.sched.events_total,
            }
        # stats() sorts the full latency sample lists for percentiles —
        # do that in its OWN lock window, not nested inside this one,
        # so a scrape of a long-lived engine holds the lock per piece
        # instead of for the whole render
        out["stats"] = self.stats()
        return out
