"""mxserve: paged-KV continuous-batching inference.

The production serving story the "millions of users" north star needs
(ROADMAP.md; PAPERS.md "Ragged Paged Attention"), sitting next to the
single-request fixed-shape ``Predictor``:

- :mod:`.kv_cache` — paged KV-cache allocator: fixed-size blocks in one
  preallocated device pool, per-request block tables, OOM backpressure
  (plus ``mirror()`` — the draft model's lockstep pool);
- :mod:`.model` — ragged batches assembled into fixed bucketed shapes
  over ``models/transformer.py`` params: one jitted step covers prefill
  chunks and single-token decode, plus the speculative draft-turn and
  verify programs, warm across processes via the PR 6 persistent jit
  cache;
- :mod:`.sampling` — fused on-device sampling (temperature/top-k/top-p,
  position-keyed per-request PRNG, speculative rejection-resampling):
  logits never leave the device;
- :mod:`.scheduler` — continuous batching: admit/evict per decode step
  against a token budget (speculative slots cost their whole verify
  chunk), prefill/decode split, recompute-style preemption (plus the
  static-batching baseline policy for A/B);
- :mod:`.engine` — the request front-end: ``Engine.submit(prompt) ->
  stream of tokens``, a synchronous ``generate`` batch API,
  cancellation, max-queue-depth admission control, draft-model
  speculative decoding (``MXNET_SERVE_SPEC``, off by default), and the
  ``serving.*`` mxtel catalog.

Bench: ``bench_serve.py`` (Poisson open-loop load, static vs continuous
tokens/s + p99 TTFT; ``--spec`` for the speculative leg). Guide:
docs/how_to/serving.md.
"""
from __future__ import annotations

from .engine import (Engine, QueueFullError, ServingConfig, StreamHandle,
                     live_engines)
from .kv_cache import PagedKVPool, blocks_for_tokens
from .model import ServingModel, cp_prefill_kv
from .scheduler import Request, Scheduler, StepPlan

__all__ = [
    "Engine", "ServingConfig", "StreamHandle", "QueueFullError",
    "PagedKVPool", "blocks_for_tokens", "ServingModel", "cp_prefill_kv",
    "Request", "Scheduler", "StepPlan", "live_engines",
]
