"""Paged KV-cache allocator: fixed-size blocks in a preallocated pool.

The serving engine's memory story (PAPERS.md "Ragged Paged Attention"):
instead of one contiguous [max_seq] KV strip per request — which wastes
(max_seq - len) slots on every short request and fragments HBM — the
K/V cache is a single preallocated device pool of fixed-size *blocks*
(``block_size`` tokens each), and every request holds a *block table*:
the ordered list of pool blocks its tokens live in. Token position
``p`` of a request maps to slot ``(table[p // block_size], p %
block_size)``. Admission allocates ceil(len/block_size) blocks; decode
allocates one more each time a request crosses a block boundary;
finish/cancel/evict frees them all. Utilization is therefore exact and
allocation is O(1) against a free list — no compaction, no copying.

Block 0 is reserved as the *scratch sink*: padded batch rows and
masked-out lanes inside the jitted step function write their K/V there
(a data-dependent "don't write" is not expressible in one fixed-shape
XLA program, but an index redirect is), so scratch absorbs garbage and
real blocks stay clean. The pool hands out blocks 1..num_blocks-1.

Backpressure: ``alloc`` returns None when the free list can't cover a
request instead of raising — the scheduler treats None as the OOM
signal (stop admitting; evict if a *running* request needs the block).
"""
from __future__ import annotations

__all__ = ["PagedKVPool", "blocks_for_tokens"]


def blocks_for_tokens(num_tokens, block_size):
    """Blocks needed to hold ``num_tokens`` (ceil division, min 1)."""
    return max(1, -(-int(num_tokens) // int(block_size)))


class PagedKVPool:
    """Preallocated paged K/V device pool + free-list block allocator.

    Storage is two device arrays shaped
    ``[num_layers, num_blocks, block_size, num_kv_heads, head_dim]``
    (K and V). The arrays are *functional* state: the jitted step
    functions return updated pools and the engine swaps them in via
    :meth:`swap`; this object owns the allocator bookkeeping, which is
    host-side and must never enter a traced program.

    Parameters
    ----------
    num_layers, num_heads, head_dim : int
        KV geometry, matching the model config.
    num_blocks : int
        Total pool blocks *including* the reserved scratch block 0.
        Usable capacity is ``num_blocks - 1`` blocks.
    block_size : int
        Tokens per block.
    dtype : str
        Pool element dtype (normally the model's compute dtype).
    """

    def __init__(self, num_layers, num_heads, head_dim, num_blocks,
                 block_size, dtype="float32"):
        if num_blocks < 2:
            raise ValueError("need >= 2 blocks (block 0 is the scratch "
                             "sink), got %d" % num_blocks)
        if block_size < 1:
            raise ValueError("block_size must be >= 1, got %d" % block_size)
        import jax.numpy as jnp

        self.num_layers = int(num_layers)
        self.num_heads = int(num_heads)
        self.head_dim = int(head_dim)
        self.num_blocks = int(num_blocks)
        self.block_size = int(block_size)
        shape = (self.num_layers, self.num_blocks, self.block_size,
                 self.num_heads, self.head_dim)
        self.k = jnp.zeros(shape, dtype)
        self.v = jnp.zeros(shape, dtype)
        # LIFO free list (reuse the most recently freed blocks first —
        # they are the likeliest still resident in cache hierarchies)
        self._free = list(range(self.num_blocks - 1, 0, -1))
        self._hwm = 0  # high-water mark of blocks in use

    # -- allocator -----------------------------------------------------------
    @property
    def capacity(self):
        """Usable blocks (scratch excluded)."""
        return self.num_blocks - 1

    @property
    def num_free(self):
        return len(self._free)

    @property
    def num_used(self):
        return self.capacity - len(self._free)

    def utilization(self):
        """Fraction of usable blocks currently allocated, 0..1."""
        return self.num_used / float(self.capacity)

    def high_water_mark(self):
        """Peak blocks-in-use since construction."""
        return self._hwm

    def can_alloc(self, n):
        return n <= len(self._free)

    def alloc(self, n):
        """Take ``n`` blocks off the free list; ``None`` when the pool
        can't cover them (the OOM-backpressure signal — the caller
        decides between waiting and evicting, never this class)."""
        n = int(n)
        if n < 0:
            raise ValueError("alloc(%d)" % n)
        if n > len(self._free):
            return None
        blocks = [self._free.pop() for _ in range(n)]
        self._hwm = max(self._hwm, self.num_used)
        return blocks

    def free(self, blocks):
        """Return blocks to the free list (idempotence is NOT provided:
        double-free is a bug and raises)."""
        for b in blocks:
            b = int(b)
            if not 1 <= b < self.num_blocks:
                raise ValueError("free of invalid block %d" % b)
            if b in self._free:
                raise ValueError("double free of block %d" % b)
        self._free.extend(int(b) for b in blocks)

    def mirror(self, num_layers, num_heads, head_dim, dtype="float32"):
        """A second pool with the SAME block geometry (num_blocks,
        block_size) but its own KV shape — the draft model's pool in
        speculative decoding. Identical block counts mean the target
        and draft block tables can be kept in lockstep: every paired
        alloc/free succeeds or fails together, so one free-list check
        covers both."""
        return PagedKVPool(num_layers, num_heads, head_dim,
                           self.num_blocks, self.block_size, dtype=dtype)

    # -- device state --------------------------------------------------------
    def swap(self, k, v):
        """Install updated pool arrays returned by a jitted step."""
        self.k = k
        self.v = v
