"""Continuous-batching scheduler: admit/evict per decode step.

The scheduling model (PAPERS.md "Ragged Paged Attention"; the policy is
the now-standard continuous batching shape):

- every engine step runs at most one **decode batch** (one token for
  every running request) and one **prefill batch** (the next chunk of
  each admitted-but-not-yet-prefilled prompt, budget permitting) —
  prefill is batched *separately* so a long prompt never stalls the
  decoders, and a per-step **token budget** caps prefill work;
- **admission** is per step: whenever a slot (``max_batch``) and enough
  KV blocks for the prompt exist, the oldest queued request joins —
  requests never wait for a "batch to fill";
- **eviction** is the OOM pressure valve: when a *running* request
  crosses a block boundary and the pool can't hand out one more block,
  the youngest running request is preempted — its blocks are freed and
  it re-queues at the front with its already-streamed tokens folded
  into a recompute context (so nothing the client saw is lost);
- the **static** policy is the A/B baseline (bench_serve.py): admission
  only happens when the active set is fully drained, i.e. classic
  static batching — every batch runs to the completion of its slowest
  member while newly arrived requests queue.

All decisions are deterministic functions of (arrival order, config,
pool state): the ``events`` log of two runs over the same trace is
identical (pinned by tests/unittest/test_serving.py).

Block-allocation invariant: admission allocates every block the
*context* (prompt + any recompute tokens) needs, so prefill itself
never allocates; only admission and decode boundary-crossings touch the
free list. A request whose total footprint (context + max_new_tokens)
can never fit the pool or the model's ``max_seq_len`` is rejected at
submit time, not deadlocked.
"""
from __future__ import annotations

import collections
import itertools

import numpy as np

from .kv_cache import blocks_for_tokens

__all__ = ["Request", "Scheduler", "StepPlan",
           "QUEUED", "PREFILL", "DECODE", "FINISHED", "CANCELLED"]

QUEUED, PREFILL, DECODE, FINISHED, CANCELLED = (
    "queued", "prefill", "decode", "finished", "cancelled")

_rid = itertools.count()


class Request:
    """One generation request tracked by the scheduler."""

    __slots__ = ("rid", "prompt", "max_new_tokens", "eos_id", "state",
                 "blocks", "context", "prefilled", "generated",
                 "submit_t", "first_token_t", "last_token_t", "finish_t",
                 "evictions", "cancel_requested", "stream",
                 # request-scoped tracing (engine fills these in when
                 # telemetry is on; scheduling never reads them):
                 # trace id, submit wall-clock anchor, first-admission
                 # and prefill-complete monotonic stamps
                 "trace", "wall0", "admit_t", "prefill_done_t")

    def __init__(self, prompt, max_new_tokens, eos_id=None, stream=None):
        self.rid = next(_rid)
        self.prompt = np.asarray(prompt, np.int32).reshape(-1)
        self.max_new_tokens = int(max_new_tokens)
        self.eos_id = eos_id
        self.state = QUEUED
        self.blocks = []
        # context = tokens whose KV must be in the pool before decode:
        # the prompt, plus already-generated tokens after an eviction
        # (recompute-style preemption keeps the client's stream intact)
        self.context = self.prompt
        self.prefilled = 0
        self.generated = []
        self.submit_t = None
        self.first_token_t = None
        self.last_token_t = None
        self.finish_t = None
        self.evictions = 0
        self.cancel_requested = False
        self.stream = stream
        self.trace = None
        self.wall0 = None
        self.admit_t = None
        self.prefill_done_t = None

    @property
    def ctx_len(self):
        return int(self.context.shape[0])

    def total_len(self):
        """Worst-case sequence length this request can reach."""
        return int(self.prompt.shape[0]) + self.max_new_tokens


class StepPlan:
    """What one engine step should run."""

    __slots__ = ("decode", "prefill")

    def __init__(self, decode, prefill):
        self.decode = decode        # [Request] — one token each
        self.prefill = prefill      # [(Request, chunk_start, chunk_len)]

    def __bool__(self):
        return bool(self.decode or self.prefill)


class Scheduler:
    """Admission / eviction / step planning over a PagedKVPool.

    Parameters
    ----------
    pool : PagedKVPool
    max_batch : int
        Concurrent active (prefill+decode) requests.
    prefill_chunk : int
        Max prompt tokens prefilled per request per step.
    token_budget : int
        Per-step cap on total tokens entering the model: the decode
        batch (1/request) plus prefill chunks must fit under it.
    policy : "continuous" | "static"
    """

    def __init__(self, pool, max_batch=8, prefill_chunk=128,
                 token_budget=None, policy="continuous", max_active=None):
        if policy not in ("continuous", "static"):
            raise ValueError("unknown policy %r" % (policy,))
        self.pool = pool
        self.max_batch = int(max_batch)
        self.prefill_chunk = int(prefill_chunk)
        self.token_budget = int(token_budget if token_budget is not None
                                else self.max_batch + self.prefill_chunk)
        self.policy = policy
        # admission depth: more requests than one decode batch may be
        # active so freshly-prefilled requests backfill drained decode
        # slots immediately (decode occupancy is the throughput lever);
        # static keeps depth == batch (one batch at a time, by design)
        if policy == "static":
            self.max_active = self.max_batch
        else:
            self.max_active = int(max_active if max_active is not None
                                  else 2 * self.max_batch)
        self.queue = collections.deque()
        self.active = []          # admission-ordered PREFILL/DECODE reqs
        self.events = []          # deterministic audit log
        self.counts = collections.Counter()

    # -- intake --------------------------------------------------------------
    def max_request_tokens(self):
        """Largest total sequence the pool geometry can ever host."""
        return self.pool.capacity * self.pool.block_size

    def submit(self, req):
        """Queue a request (depth limits are the engine's concern)."""
        self.queue.append(req)

    def cancel(self, req):
        req.cancel_requested = True

    # -- internal helpers ----------------------------------------------------
    def _finish(self, req, state, event):
        if req.blocks:
            self.pool.free(req.blocks)
            req.blocks = []
        req.state = state
        if req in self.active:
            self.active.remove(req)
        self.events.append((event, req.rid))
        self.counts[event] += 1

    def finish(self, req):
        """Mark a running request complete (engine calls after the stop
        condition trips)."""
        self._finish(req, FINISHED, "complete")

    def note_drained(self):
        """Record the engine's drain completion in the deterministic
        event log (rid -1: a lifecycle event, not a request)."""
        self.events.append(("drained", -1))
        self.counts["drained"] += 1

    def _sweep_cancelled(self):
        for req in [r for r in self.active if r.cancel_requested]:
            self._finish(req, CANCELLED, "cancel")
        kept = [r for r in self.queue if not r.cancel_requested]
        for req in self.queue:
            if req.cancel_requested:
                req.state = CANCELLED
                self.events.append(("cancel", req.rid))
                self.counts["cancel"] += 1
        if len(kept) != len(self.queue):
            self.queue = collections.deque(kept)

    def _admit_one(self, req):
        need = blocks_for_tokens(req.ctx_len, self.pool.block_size)
        if self.policy == "static":
            # static batches are sized once: reserve the whole worst
            # case so the batch can always run to completion
            need = blocks_for_tokens(req.total_len(), self.pool.block_size)
        blocks = self.pool.alloc(need)
        if blocks is None:
            return False
        req.blocks = blocks
        req.state = PREFILL
        req.prefilled = 0
        self.active.append(req)
        self.events.append(("admit", req.rid))
        self.counts["admit"] += 1
        return True

    def _admit(self):
        if self.policy == "static" and self.active:
            return  # classic static batching: drain before refill
        while self.queue and len(self.active) < self.max_active:
            if not self._admit_one(self.queue[0]):
                break  # OOM backpressure: wait for frees
            self.queue.popleft()

    def _evict_youngest(self):
        """Preempt the newest active request; returns it (or None)."""
        if not self.active:
            return None
        victim = self.active.pop()
        self.pool.free(victim.blocks)
        victim.blocks = []
        # recompute context: everything already streamed is folded in
        victim.context = np.concatenate(
            [victim.context,
             np.asarray(victim.generated[
                 len(victim.context) - len(victim.prompt):], np.int32)])
        victim.prefilled = 0
        victim.state = QUEUED
        victim.evictions += 1
        self.queue.appendleft(victim)
        self.events.append(("evict", victim.rid))
        self.counts["evict"] += 1
        return victim

    def _ensure_decode_block(self, req):
        """Make sure the slot for this step's KV write exists;
        evict-youngest until it does (the request itself may be the
        youngest, in which case it preempts itself and the step skips
        it). False = req can't decode this step.

        The slot written during decode is the *input* token's position:
        the engine feeds ``generated[-1]``, which lives at global
        position ``len(prompt) + len(generated) - 1`` (the recompute
        fold moves tokens between context and generated but never moves
        their global positions)."""
        pos = len(req.prompt) + len(req.generated) - 1
        need = pos // self.pool.block_size + 1
        while need > len(req.blocks):
            got = self.pool.alloc(need - len(req.blocks))
            if got is not None:
                req.blocks.extend(got)
                return True
            victim = self._evict_youngest()
            if victim is None or victim is req:
                return False
        return True

    # -- planning ------------------------------------------------------------
    def plan(self):
        """One step's work. Mutates state (admissions, evictions,
        allocations) and returns a StepPlan."""
        self._sweep_cancelled()
        self._admit()

        decode = []
        cap = min(self.max_batch, self.token_budget)
        # iterate a snapshot: _ensure_decode_block may evict the
        # youngest active request mid-loop. Eviction always moves the
        # victim's state to QUEUED, so the state check below filters
        # both never-decoding and just-evicted requests; victims are
        # the newest member of `active`, so an already-collected
        # (older) decode entry can never be evicted by a later one.
        for req in list(self.active):
            if req.state != DECODE:
                continue
            if len(decode) >= cap:
                break
            if self._ensure_decode_block(req):
                decode.append(req)

        budget = self.token_budget - len(decode)
        prefill = []
        for req in self.active:
            if req.state != PREFILL or budget <= 0:
                continue
            chunk = min(self.prefill_chunk, req.ctx_len - req.prefilled,
                        budget)
            if chunk <= 0:
                continue
            prefill.append((req, req.prefilled, chunk))
            budget -= chunk
        return StepPlan(decode, prefill)

    # -- engine feedback -----------------------------------------------------
    def note_prefilled(self, req, chunk_len):
        req.prefilled += chunk_len
        if req.prefilled >= req.ctx_len:
            req.state = DECODE

    def utilization(self):
        return self.pool.utilization()
