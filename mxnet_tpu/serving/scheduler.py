"""Continuous-batching scheduler: admit/evict per decode step.

The scheduling model (PAPERS.md "Ragged Paged Attention"; the policy is
the now-standard continuous batching shape):

- every engine step runs at most one **decode batch** (one token for
  every running request) and one **prefill batch** (the next chunk of
  each admitted-but-not-yet-prefilled prompt, budget permitting) —
  prefill is batched *separately* so a long prompt never stalls the
  decoders, and a per-step **token budget** caps prefill work;
- **admission** is per step: whenever a slot (``max_batch``) and enough
  KV blocks for the prompt exist, the oldest queued request joins —
  requests never wait for a "batch to fill";
- **eviction** is the OOM pressure valve: when a *running* request
  crosses a block boundary and the pool can't hand out one more block,
  the youngest running request is preempted — its blocks are freed and
  it re-queues at the front with its already-streamed tokens folded
  into a recompute context (so nothing the client saw is lost);
- the **static** policy is the A/B baseline (bench_serve.py): admission
  only happens when the active set is fully drained, i.e. classic
  static batching — every batch runs to the completion of its slowest
  member while newly arrived requests queue.

All decisions are deterministic functions of (arrival order, config,
pool state): the ``events`` log of two runs over the same trace is
identical (pinned by tests/unittest/test_serving.py).

Block-allocation invariant: admission allocates every block the
*context* (prompt + any recompute tokens) needs, so prefill itself
never allocates; only admission and decode boundary-crossings touch the
free list. A request whose total footprint (context + max_new_tokens)
can never fit the pool or the model's ``max_seq_len`` is rejected at
submit time, not deadlocked.
"""
from __future__ import annotations

import collections
import itertools

import numpy as np

from ..base import env_int as _env_int
from .kv_cache import blocks_for_tokens

__all__ = ["Request", "Scheduler", "StepPlan",
           "QUEUED", "PREFILL", "DECODE", "FINISHED", "CANCELLED"]

QUEUED, PREFILL, DECODE, FINISHED, CANCELLED = (
    "queued", "prefill", "decode", "finished", "cancelled")

_rid = itertools.count()


class Request:
    """One generation request tracked by the scheduler."""

    __slots__ = ("rid", "prompt", "max_new_tokens", "eos_id", "state",
                 "blocks", "context", "prefilled", "generated",
                 "submit_t", "first_token_t", "last_token_t", "finish_t",
                 "evictions", "cancel_requested", "stream",
                 # fused-sampling params (sampling.py): temperature 0 =
                 # greedy; draws keyed (seed, position, salt)
                 "temperature", "top_k", "top_p", "seed",
                 # speculative decoding (engine + scheduler lockstep):
                 # draft-pool block table, first position the draft
                 # pool lacks valid KV for, cumulative drafted/accepted
                 "draft_blocks", "draft_pos", "spec_drafted",
                 "spec_accepted",
                 # request-scoped tracing (engine fills these in when
                 # telemetry is on; scheduling never reads them):
                 # trace id, submit wall-clock anchor, first-admission
                 # and prefill-complete monotonic stamps
                 "trace", "wall0", "admit_t", "prefill_done_t")

    def __init__(self, prompt, max_new_tokens, eos_id=None, stream=None,
                 temperature=0.0, top_k=0, top_p=1.0, seed=0):
        self.rid = next(_rid)
        self.prompt = np.asarray(prompt, np.int32).reshape(-1)
        self.max_new_tokens = int(max_new_tokens)
        self.eos_id = eos_id
        self.state = QUEUED
        self.blocks = []
        # context = tokens whose KV must be in the pool before decode:
        # the prompt, plus already-generated tokens after an eviction
        # (recompute-style preemption keeps the client's stream intact)
        self.context = self.prompt
        self.prefilled = 0
        self.generated = []
        self.submit_t = None
        self.first_token_t = None
        self.last_token_t = None
        self.finish_t = None
        self.evictions = 0
        self.cancel_requested = False
        self.stream = stream
        self.temperature = float(temperature)
        self.top_k = int(top_k)
        self.top_p = float(top_p)
        self.seed = int(seed)
        self.draft_blocks = []
        self.draft_pos = 0
        self.spec_drafted = 0
        self.spec_accepted = 0
        self.trace = None
        self.wall0 = None
        self.admit_t = None
        self.prefill_done_t = None

    @property
    def ctx_len(self):
        return int(self.context.shape[0])

    def total_len(self):
        """Worst-case sequence length this request can reach."""
        return int(self.prompt.shape[0]) + self.max_new_tokens


class StepPlan:
    """What one engine step should run."""

    __slots__ = ("decode", "prefill", "spec_k")

    def __init__(self, decode, prefill, spec_k=None):
        self.decode = decode        # [Request] — one token each
        self.prefill = prefill      # [(Request, chunk_start, chunk_len)]
        # rid -> draft tokens this turn (0 = plain decode row); empty
        # when speculation is off
        self.spec_k = spec_k or {}

    def __bool__(self):
        return bool(self.decode or self.prefill)


class Scheduler:
    """Admission / eviction / step planning over a PagedKVPool.

    Parameters
    ----------
    pool : PagedKVPool
    max_batch : int
        Concurrent active (prefill+decode) requests.
    prefill_chunk : int
        Max prompt tokens prefilled per request per step.
    token_budget : int
        Per-step cap on total tokens entering the model: the decode
        batch (1/request) plus prefill chunks must fit under it.
    policy : "continuous" | "static"
    """

    def __init__(self, pool, max_batch=8, prefill_chunk=128,
                 token_budget=None, policy="continuous", max_active=None,
                 draft_pool=None, spec_k=0, events_max=None):
        if policy not in ("continuous", "static"):
            raise ValueError("unknown policy %r" % (policy,))
        self.pool = pool
        self.max_batch = int(max_batch)
        self.prefill_chunk = int(prefill_chunk)
        self.token_budget = int(token_budget if token_budget is not None
                                else self.max_batch + self.prefill_chunk)
        self.policy = policy
        # speculative decoding: the draft model's paged pool (same
        # block geometry, kv_cache.PagedKVPool.mirror) whose per-request
        # tables stay in LOCKSTEP with the target tables — every alloc/
        # free below pairs the two, so len(draft_blocks) == len(blocks)
        # always. spec_k > 0 makes each decode slot cost 1 + spec_k
        # budget tokens (its verify chunk); the engine toggles it at
        # runtime via set_spec_k (the mxctl spec_off actuator).
        self.draft_pool = draft_pool
        self.spec_k = int(spec_k)
        # admission depth: more requests than one decode batch may be
        # active so freshly-prefilled requests backfill drained decode
        # slots immediately (decode occupancy is the throughput lever);
        # static keeps depth == batch (one batch at a time, by design)
        if policy == "static":
            self.max_active = self.max_batch
        else:
            self.max_active = int(max_active if max_active is not None
                                  else 2 * self.max_batch)
        self.queue = collections.deque()
        self.active = []          # admission-ordered PREFILL/DECODE reqs
        # deterministic audit log, BOUNDED: long-lived serving processes
        # emit events forever, so the log is a ring holding the tail
        # (introspect()/servingz render the tail anyway); events_total
        # keeps the true count for accounting
        self.events = collections.deque(
            maxlen=int(events_max if events_max is not None
                       else _env_int("MXNET_SERVE_EVENTS_MAX", 4096)))
        self.events_total = 0
        self.counts = collections.Counter()

    def spec_active(self):
        return self.draft_pool is not None and self.spec_k > 0

    def set_spec_k(self, k):
        """Runtime speculation toggle (0 disables): takes effect at the
        next plan()."""
        self.spec_k = int(k)

    def _event(self, ev, rid):
        self.events.append((ev, rid))
        self.events_total += 1
        self.counts[ev] += 1

    # -- paired target/draft block bookkeeping -------------------------------
    def _alloc_pair(self, req, n):
        """Allocate n blocks in the target pool (and the draft pool in
        lockstep when speculation is configured). True on success; on
        any failure nothing is held."""
        blocks = self.pool.alloc(n)
        if blocks is None:
            return False
        if self.draft_pool is not None:
            dblocks = self.draft_pool.alloc(n)
            if dblocks is None:  # lockstep makes this unreachable, but
                self.pool.free(blocks)  # never leak on the safe side
                return False
            req.draft_blocks.extend(dblocks)
        req.blocks.extend(blocks)
        return True

    def _free_all(self, req):
        if req.blocks:
            self.pool.free(req.blocks)
            req.blocks = []
        if req.draft_blocks:
            self.draft_pool.free(req.draft_blocks)
            req.draft_blocks = []

    # -- intake --------------------------------------------------------------
    def max_request_tokens(self):
        """Largest total sequence the pool geometry can ever host."""
        return self.pool.capacity * self.pool.block_size

    def submit(self, req):
        """Queue a request (depth limits are the engine's concern)."""
        self.queue.append(req)

    def cancel(self, req):
        req.cancel_requested = True

    # -- internal helpers ----------------------------------------------------
    def _finish(self, req, state, event):
        self._free_all(req)
        req.state = state
        if req in self.active:
            self.active.remove(req)
        self._event(event, req.rid)

    def finish(self, req):
        """Mark a running request complete (engine calls after the stop
        condition trips)."""
        self._finish(req, FINISHED, "complete")

    def note_drained(self):
        """Record the engine's drain completion in the deterministic
        event log (rid -1: a lifecycle event, not a request)."""
        self._event("drained", -1)

    def _sweep_cancelled(self):
        for req in [r for r in self.active if r.cancel_requested]:
            self._finish(req, CANCELLED, "cancel")
        kept = [r for r in self.queue if not r.cancel_requested]
        for req in self.queue:
            if req.cancel_requested:
                req.state = CANCELLED
                self._event("cancel", req.rid)
        if len(kept) != len(self.queue):
            self.queue = collections.deque(kept)

    def _admit_one(self, req):
        need = blocks_for_tokens(req.ctx_len, self.pool.block_size)
        if self.policy == "static":
            # static batches are sized once: reserve the whole worst
            # case so the batch can always run to completion
            need = blocks_for_tokens(req.total_len(), self.pool.block_size)
        if not self._alloc_pair(req, need):
            return False
        req.state = PREFILL
        req.prefilled = 0
        req.draft_pos = 0
        self.active.append(req)
        self._event("admit", req.rid)
        return True

    def _admit(self):
        if self.policy == "static" and self.active:
            return  # classic static batching: drain before refill
        while self.queue and len(self.active) < self.max_active:
            if not self._admit_one(self.queue[0]):
                break  # OOM backpressure: wait for frees
            self.queue.popleft()

    def _evict_youngest(self):
        """Preempt the newest active request; returns it (or None)."""
        if not self.active:
            return None
        victim = self.active.pop()
        self._free_all(victim)
        # recompute context: everything already streamed is folded in
        victim.context = np.concatenate(
            [victim.context,
             np.asarray(victim.generated[
                 len(victim.context) - len(victim.prompt):], np.int32)])
        victim.prefilled = 0
        victim.draft_pos = 0
        victim.state = QUEUED
        victim.evictions += 1
        self.queue.appendleft(victim)
        self._event("evict", victim.rid)
        return victim

    def _ensure_decode_block(self, req, horizon=0):
        """Make sure the slots for this step's KV writes exist;
        evict-youngest until they do (the request itself may be the
        youngest, in which case it preempts itself and the step skips
        it). False = req can't decode this step.

        The slot written during decode is the *input* token's position:
        the engine feeds ``generated[-1]``, which lives at global
        position ``len(prompt) + len(generated) - 1`` (the recompute
        fold moves tokens between context and generated but never moves
        their global positions). A speculative turn writes ``horizon``
        more positions (the draft tokens its verify chunk carries), so
        the table must reach ``pos + horizon``; partial acceptance
        frees the unused tail via :meth:`trim_blocks`."""
        pos = len(req.prompt) + len(req.generated) - 1 + int(horizon)
        need = pos // self.pool.block_size + 1
        while need > len(req.blocks):
            if self._alloc_pair(req, need - len(req.blocks)):
                return True
            victim = self._evict_youngest()
            if victim is None or victim is req:
                return False
        return True

    def trim_blocks(self, req):
        """Roll both block tables back after a speculative turn: free
        blocks past the next write position — the block-granular form
        of "roll back to the first rejection" (rejected draft
        positions' KV is dead weight; the masks already exclude it).
        Static policy reserved the worst case at admission and keeps
        it."""
        if self.policy == "static":
            return
        pos = len(req.prompt) + len(req.generated) - 1
        keep = pos // self.pool.block_size + 1
        if keep < len(req.blocks):
            self.pool.free(req.blocks[keep:])
            del req.blocks[keep:]
            if self.draft_pool is not None and req.draft_blocks:
                self.draft_pool.free(req.draft_blocks[keep:])
                del req.draft_blocks[keep:]

    # -- planning ------------------------------------------------------------
    def plan(self):
        """One step's work. Mutates state (admissions, evictions,
        allocations) and returns a StepPlan."""
        self._sweep_cancelled()
        self._admit()

        decode = []
        spec_k = {}
        spec = self.spec_active()
        cost_used = 0
        # iterate a snapshot: _ensure_decode_block may evict the
        # youngest active request mid-loop. Eviction always moves the
        # victim's state to QUEUED, so the state check below filters
        # both never-decoding and just-evicted requests; victims are
        # the newest member of `active`, so an already-collected
        # (older) decode entry can never be evicted by a later one.
        for req in list(self.active):
            if req.state != DECODE:
                continue
            if len(decode) >= self.max_batch:
                break
            left = self.token_budget - cost_used
            if left < 1:
                break            # even a plain token no longer fits
            k = 0
            if spec:
                # a speculative slot costs its whole verify chunk
                # (1 + k tokens) against the budget; the final token
                # (remaining == 1) rides the plain fused-decode
                # program, and a tight budget SHRINKS a row's chain
                # rather than starving rows behind the first one that
                # doesn't fit at full spec_k
                remaining = req.max_new_tokens - len(req.generated)
                k = max(0, min(self.spec_k, remaining - 1, left - 1))
            if self._ensure_decode_block(req, horizon=k):
                decode.append(req)
                spec_k[req.rid] = k
                cost_used += 1 + k

        budget = self.token_budget - cost_used
        prefill = []
        for req in self.active:
            if req.state != PREFILL or budget <= 0:
                continue
            chunk = min(self.prefill_chunk, req.ctx_len - req.prefilled,
                        budget)
            if chunk <= 0:
                continue
            prefill.append((req, req.prefilled, chunk))
            budget -= chunk
        return StepPlan(decode, prefill, spec_k if spec else None)

    # -- engine feedback -----------------------------------------------------
    def note_prefilled(self, req, chunk_len):
        req.prefilled += chunk_len
        if req.prefilled >= req.ctx_len:
            req.state = DECODE

    def utilization(self):
        return self.pool.utilization()
