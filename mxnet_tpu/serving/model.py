"""Decode-model adapter: bucketed ragged batches over a paged KV pool.

Bridges ``models/transformer.py`` (pure-function training forward) to
the serving engine's incremental decode. One jitted *step* function
covers both phases:

- **prefill chunk**: ``C`` prompt tokens per request enter at arbitrary
  start offsets, attend causally to their own chunk plus everything the
  request already has in the paged pool, and write their K/V into the
  pool blocks named by the request's block table;
- **decode**: the same function at ``C == 1`` — one new token per
  request per step.

Three program kinds share the transformer body (ISSUE 15):

- ``step`` — prefill/decode with the fused sampler
  (:mod:`.sampling`): the sampled next token is computed ON DEVICE, so
  the only per-step D2H is the ``[B]`` token vector (the old path
  pulled the full ``[B, V]`` logits every decode step);
- ``propose`` — the draft model's proposal step: sampled token plus the
  filtered draft distribution ``q`` (kept on device for the verifier);
- ``verify`` — the speculative verify: target logits at ALL ``K+1``
  chunk positions, accept/reject against the draft proposals, and
  rejection-resampling / bonus sampling, all inside one program. A
  ``chunk_len == 1`` row degenerates to plain sampled decode, which is
  how non-spec rows ride the same math.

Ragged batches (every request at a different length) are assembled into
**fixed bucketed shapes**: batch rows pad to the next configured batch
bucket, chunk lengths pad to the next chunk bucket, and the block-table
width is a compile-time constant — so the number of distinct XLA
programs is bounded by ``len(kinds) x len(batch_buckets) x
len(chunk_buckets)`` and warm across processes via the PR 6 persistent
jit cache (``MXNET_COMPILE_CACHE_DIR``); the jit/prof cache keys fold
the program KIND alongside the bucket, so a verify program can never
alias a plain step at the same shapes. Padded lanes redirect their K/V
writes to the pool's scratch block 0 and are masked out of attention
reads, so padding never corrupts real state.

Numerical contract: a token decoded through the paged path produces the
same logits as ``transformer.forward`` over the whole sequence would at
that position (same op order, same f32 softmax accumulation), which is
what makes continuous batching a pure scheduling win — and at
``temperature == 0`` the fused sampler is exact argmax, so greedy
parity (spec or not) is byte-for-byte.

Long-context prefill on a mesh reuses the context-parallel attention in
``parallel/ring_attention.py`` / ``parallel/ulysses.py``: chunked
prefill is exactly their new ``q_offset`` form (queries are a suffix of
the key sequence), see :func:`cp_prefill_kv`.
"""
from __future__ import annotations

import functools
import time

import numpy as np

from ..analysis import compile_verify as _cv
from ..models.transformer import TransformerConfig, _layer_norm
from . import sampling as _samp

__all__ = ["ServingModel", "bucket_for", "cp_prefill_kv"]


def bucket_for(n, buckets):
    """Smallest bucket >= n (buckets sorted ascending); raises when n
    exceeds every bucket — the caller sized its batch wrong."""
    for b in buckets:
        if n <= b:
            return b
    raise ValueError("no bucket fits %d (buckets %s)" % (n, list(buckets)))


class ServingModel:
    """Jitted paged-attention step functions over transformer params.

    Parameters
    ----------
    cfg : TransformerConfig
        Model geometry (the same config object bench_lm.py trains).
    block_size : int
        Paged-pool tokens per block.
    max_blocks_per_req : int
        Block-table width ``W`` — a compile-time constant; a request
        can span at most ``W * block_size`` total tokens.
    batch_buckets, chunk_buckets : tuple of int
        Padded batch sizes / chunk lengths (ascending). Decode always
        uses chunk bucket 1 (its own program).
    """

    def __init__(self, cfg: TransformerConfig, block_size,
                 max_blocks_per_req, batch_buckets=(1, 2, 4, 8),
                 chunk_buckets=(32, 64, 128)):
        self.cfg = cfg
        self.block_size = int(block_size)
        self.max_blocks = int(max_blocks_per_req)
        self.batch_buckets = tuple(sorted(set(int(b) for b in batch_buckets)))
        self.chunk_buckets = tuple(sorted(set(int(c) for c in chunk_buckets)))
        self._jitted = {}  # (kind, B, C) -> compiled program
        self._prof_keys = {}  # (kind, B, C) -> mxprof program key
        # bucket-derived compile budgets: decode (C=1) plus one program
        # per (batch, chunk) bucket pair; draft_turn/verify budgets are
        # per (batch, K) but K is static per engine — bound by batches
        n_bc = len(self.batch_buckets) * (len(self.chunk_buckets) + 1)
        _cv.declare_budget("serve.step", n_bc)
        _cv.declare_budget("serve.draft_turn", n_bc)
        _cv.declare_budget("serve.verify", len(self.batch_buckets))

    # -- the transformer body ------------------------------------------------
    def _body(self, params, kpool, vpool, tokens, start, chunk_len,
              block_tables, active):
        """One fused forward over ``C`` new tokens per request.

        tokens [B, C] int32, start [B] int32 (global position of
        tokens[:, 0]), chunk_len [B] int32 (real tokens this chunk, 0
        for padded rows), block_tables [B, W] int32, active [B] bool.
        Returns (x [B, C, d_model] post-ln_f hidden states, kpool,
        vpool).
        """
        import jax
        import jax.numpy as jnp

        cfg = self.cfg
        B, C = tokens.shape
        W, bs = self.max_blocks, self.block_size
        S = W * bs
        H, D = cfg.num_heads, cfg.head_dim
        scale = 1.0 / float(D) ** 0.5

        pos = start[:, None] + jnp.arange(C)[None, :]            # [B, C]
        in_chunk = jnp.arange(C)[None, :] < chunk_len[:, None]   # [B, C]
        valid = in_chunk & active[:, None]
        # pos_embed rows are clipped for padded lanes (jnp.take clips);
        # their outputs are never read back
        x = jnp.take(params["embed"], tokens, axis=0)
        x = x + jnp.take(params["pos_embed"], jnp.minimum(
            pos, cfg.max_seq_len - 1), axis=0).astype(x.dtype)

        # K/V write coordinates: padded / inactive lanes redirect to the
        # scratch block 0 (kv_cache.py module docstring)
        blk_idx = jnp.clip(pos // bs, 0, W - 1)                  # [B, C]
        table_blk = jnp.take_along_axis(block_tables, blk_idx, axis=1)
        write_blk = jnp.where(valid, table_blk, 0)               # [B, C]
        write_slot = jnp.where(valid, pos % bs, 0)               # [B, C]

        # pool key positions: slot (w, i) of a request's table holds its
        # token w*bs + i
        key_pos = jnp.arange(S)                                  # [S]
        # keys already in the pool are those strictly before this
        # chunk's first token; the chunk attends to itself causally
        pool_mask = key_pos[None, None, :] < start[:, None, None]  # [B,1,S]
        pool_mask = jnp.broadcast_to(pool_mask, (B, C, S))
        chunk_mask = (jnp.arange(C)[None, :, None] >=
                      jnp.arange(C)[None, None, :]) & in_chunk[:, None, :]
        chunk_mask = jnp.broadcast_to(chunk_mask, (B, C, C))
        full_mask = jnp.concatenate([pool_mask, chunk_mask], axis=2)
        neg = jnp.asarray(-1e30, jnp.float32)

        for li, lp in enumerate(params["layers"]):
            h = _layer_norm(x, lp["ln1"])
            qkv = jnp.einsum("bcd,de->bce", h, lp["wqkv"])
            q, k, v = jnp.split(qkv, 3, axis=-1)
            k = k.reshape(B, C, H, D)
            v = v.reshape(B, C, H, D)
            # write this chunk's K/V into the pool (scatter; scratch
            # absorbs padded lanes)
            kpool = kpool.at[li, write_blk, write_slot].set(
                k.astype(kpool.dtype))
            vpool = vpool.at[li, write_blk, write_slot].set(
                v.astype(vpool.dtype))
            # gather the request's paged history [B, S, H, D]
            k_hist = kpool[li][block_tables].reshape(B, S, H, D)
            v_hist = vpool[li][block_tables].reshape(B, S, H, D)
            k_all = jnp.concatenate([k_hist.astype(k.dtype), k], axis=1)
            v_all = jnp.concatenate([v_hist.astype(v.dtype), v], axis=1)

            qh = q.reshape(B, C, H, D)
            scores = jnp.einsum("bchd,bshd->bhcs", qh, k_all) * scale
            scores = jnp.where(full_mask[:, None], scores.astype(jnp.float32),
                               neg)
            m = jnp.max(scores, axis=-1, keepdims=True)
            p = jnp.exp(scores - m)
            p = p * jnp.any(full_mask[:, None], axis=-1,
                            keepdims=True).astype(p.dtype)
            l = jnp.sum(p, axis=-1, keepdims=True)
            p = p / jnp.maximum(l, 1e-30)
            o = jnp.einsum("bhcs,bshd->bchd", p.astype(v_all.dtype), v_all)
            o = o.reshape(B, C, H * D)
            x = x + jnp.einsum("bcd,de->bce", o, lp["wo"])
            h = _layer_norm(x, lp["ln2"])
            ff = jax.nn.gelu(jnp.einsum("bcd,df->bcf", h, lp["w1"]))
            x = x + jnp.einsum("bcf,fd->bcd", ff, lp["w2"])

        return _layer_norm(x, params["ln_f"]), kpool, vpool

    def _last_logits(self, params, x, chunk_len):
        """Logits at each row's last real chunk position — the one spot
        the next token can be sampled from. [B, V] f32."""
        import jax.numpy as jnp

        C = x.shape[1]
        last = jnp.clip(chunk_len - 1, 0, C - 1)                 # [B]
        x_last = jnp.take_along_axis(
            x, last[:, None, None].astype(jnp.int32), axis=1)[:, 0]
        return jnp.einsum("bd,vd->bv", x_last,
                          params["embed"]).astype(jnp.float32)

    # -- program kinds -------------------------------------------------------
    def _step_impl(self, params, kpool, vpool, tokens, start, chunk_len,
                   block_tables, active, temp, top_k, top_p, seed):
        """Prefill/decode with the fused sampler: the sampled token for
        global position ``start + chunk_len`` per row."""
        x, kpool, vpool = self._body(params, kpool, vpool, tokens, start,
                                     chunk_len, block_tables, active)
        logits = self._last_logits(params, x, chunk_len)
        tok, _ = _samp.sample_tokens(logits, temp, top_k, top_p, seed,
                                     start + chunk_len, _samp.SALT_TARGET)
        return tok, kpool, vpool

    def _draft_turn_impl(self, params, kpool, vpool, tokens, start,
                         chunk_len, block_tables, active, temp, top_k,
                         top_p, seed, ks, K=1):
        """The whole draft phase as ONE program: ingest the catch-up
        chunk (the 1-2 stream tokens the draft pool is missing) and
        chain ``K`` proposals, each feeding the previous sample back in
        — no host round-trip, one dispatch. The K-1 follow-up proposals
        are a ``lax.scan`` over one single-token body, so the program
        (and its XLA compile time) stays one-body-sized at any K — the
        unrolled form took tens of seconds PER BUCKET to compile on
        CPU. ``ks`` [B] is the per-row draft budget: rows past theirs
        go inactive (writes to scratch, outputs masked later by the
        verify chunk_len). Returns (draft_toks [B, K], qdists
        [B, K, V], kpool, vpool)."""
        import jax
        import jax.numpy as jnp

        x, kpool, vpool = self._body(params, kpool, vpool, tokens, start,
                                     chunk_len, block_tables, active)
        logits = self._last_logits(params, x, chunk_len)
        P0 = start + chunk_len          # global position of proposal d_0
        tok0, q0 = _samp.sample_tokens(logits, temp, top_k, top_p, seed,
                                       P0, _samp.SALT_DRAFT)
        if K == 1:
            return tok0[:, None], q0[:, None], kpool, vpool
        ones = jnp.ones_like(start)

        def propose(carry, j):
            kpool, vpool, tok = carry
            act_j = active & (ks > j)
            x, kpool, vpool = self._body(params, kpool, vpool,
                                         tok[:, None], P0 + j - 1, ones,
                                         block_tables, act_j)
            lg = self._last_logits(params, x, ones)
            tok, q = _samp.sample_tokens(lg, temp, top_k, top_p, seed,
                                         P0 + j, _samp.SALT_DRAFT)
            return (kpool, vpool, tok), (tok, q)

        (kpool, vpool, _), (toks, qs) = jax.lax.scan(
            propose, (kpool, vpool, tok0), jnp.arange(1, K))
        draft = jnp.concatenate(
            [tok0[:, None], jnp.swapaxes(toks, 0, 1)], axis=1)
        qd = jnp.concatenate(
            [q0[:, None], jnp.swapaxes(qs, 0, 1)], axis=1)
        return draft, qd, kpool, vpool

    def _verify_impl(self, params, kpool, vpool, prev, draft_toks, qdists,
                     start, chunk_len, block_tables, active, temp, top_k,
                     top_p, seed):
        """Speculative verify over a [B, C] chunk, C = K + 1.

        Row layout: ``prev`` [B, 1] is the request's last emitted token
        (global position ``start``), ``draft_toks[:, j]`` the draft
        proposal ``d_j`` for position ``start + 1 + j``; a row proposes
        ``k_i = chunk_len - 1`` drafts (``k_i == 0`` = plain sampled
        decode). Returns (n_accept [B] int32 — leading drafts accepted,
        tok [B] int32 — the one non-draft token to emit after them: the
        rejection resample, or the bonus/plain sample on full
        acceptance, kpool, vpool). Logits never leave the program.
        """
        import jax
        import jax.numpy as jnp

        tokens = jnp.concatenate([prev, draft_toks], axis=1)
        x, kpool, vpool = self._body(params, kpool, vpool, tokens, start,
                                     chunk_len, block_tables, active)
        B, C = tokens.shape
        K = C - 1
        V = self.cfg.vocab_size
        logits = jnp.einsum("bcd,vd->bcv", x,
                            params["embed"]).astype(jnp.float32)  # [B,C,V]
        masked, pdist = _samp.filter_dist(
            jnp, logits, temp[:, None], top_k[:, None], top_p[:, None])
        argm = jnp.argmax(logits, axis=-1)                       # [B, C]
        k_i = chunk_len - 1                                      # [B]
        is_sampled = jnp.asarray(temp, jnp.float32) > 0          # [B]

        any_sampled = jnp.any(is_sampled)

        # -- accept/reject the K draft positions -----------------------------
        pos_k = start[:, None] + 1 + jnp.arange(K)[None, :]      # [B, K]
        d = jnp.clip(draft_toks, 0, V - 1).astype(jnp.int32)
        p_d = jnp.take_along_axis(pdist[:, :K], d[..., None],
                                  axis=-1)[..., 0]               # [B, K]
        q_d = jnp.take_along_axis(qdists, d[..., None], axis=-1)[..., 0]

        def accept_draw(_):
            keys_u = _samp.fold_keys(jnp.repeat(seed, K),
                                     pos_k.reshape(-1), _samp.SALT_ACCEPT)
            u = jax.vmap(lambda k: jax.random.uniform(k, (), jnp.float32))(
                keys_u).reshape(B, K)
            return u < jnp.minimum(p_d / jnp.maximum(q_d, 1e-20), 1.0)

        acc_greedy = d == argm[:, :K]
        # all-greedy batches skip every random draw in this program
        # (threefry is real per-step cost); the conds below mirror this
        acc_sampled = jax.lax.cond(any_sampled, accept_draw,
                                   lambda _: acc_greedy, 0)
        accept = jnp.where(is_sampled[:, None], acc_sampled, acc_greedy)
        accept = accept & (jnp.arange(K)[None, :] < k_i[:, None])
        stop = ~accept
        n = jnp.where(stop.any(axis=1),
                      jnp.argmax(stop, axis=1), K).astype(jnp.int32)

        # -- the one non-draft token ------------------------------------------
        # full acceptance -> bonus sample from position start + chunk_len
        # with the TARGET salt: exactly the draw plain decode would make
        bon_masked = jnp.take_along_axis(
            masked, k_i[:, None, None], axis=1)[:, 0]            # [B, V]
        bon_greedy = jnp.take_along_axis(argm, k_i[:, None], axis=1)[:, 0]

        def bonus_draw(m):
            keys_b = _samp.fold_keys(seed, start + chunk_len,
                                     _samp.SALT_TARGET)
            g = jax.vmap(lambda k: jax.random.gumbel(
                k, (V,), jnp.float32))(keys_b)
            return jnp.argmax(m + g, axis=-1)

        bon_sampled = jax.lax.cond(any_sampled, bonus_draw,
                                   lambda m: bon_greedy, bon_masked)
        bonus = jnp.where(is_sampled, bon_sampled, bon_greedy)
        # rejection at draft index n -> resample from max(p - q, 0)
        nc = jnp.clip(n, 0, C - 1)
        p_n = jnp.take_along_axis(pdist, nc[:, None, None], axis=1)[:, 0]
        q_n = jnp.take_along_axis(qdists,
                                  jnp.clip(n, 0, K - 1)[:, None, None],
                                  axis=1)[:, 0]
        res_greedy = jnp.take_along_axis(argm, nc[:, None], axis=1)[:, 0]

        def residual_draw(_):
            r = jnp.maximum(p_n - q_n, 0.0)
            rs = jnp.sum(r, axis=-1, keepdims=True)
            r = jnp.where(rs > 1e-12, r / jnp.maximum(rs, 1e-12), p_n)
            r_logits = jnp.where(r > 0, jnp.log(jnp.maximum(r, 1e-30)),
                                 jnp.float32(-1e30))
            keys_r = _samp.fold_keys(seed, start + 1 + n,
                                     _samp.SALT_RESIDUAL)
            g = jax.vmap(lambda k: jax.random.gumbel(
                k, (V,), jnp.float32))(keys_r)
            return jnp.argmax(r_logits + g, axis=-1)

        res_sampled = jax.lax.cond(any_sampled, residual_draw,
                                   lambda _: res_greedy, 0)
        resample = jnp.where(is_sampled, res_sampled, res_greedy)

        tok = jnp.where(n >= k_i, bonus, resample).astype(jnp.int32)
        return n, tok, kpool, vpool

    _KIND_IMPLS = {"step": "_step_impl", "draft_turn": "_draft_turn_impl",
                   "verify": "_verify_impl"}

    def _compiled(self, key):
        """key = (kind, *static shape params) — the jit/prof cache key
        surface: program KIND and bucket shapes together, so e.g. a
        verify program can never alias a step program at equal
        shapes."""
        fn = self._jitted.get(key)
        if fn is None:
            import jax

            from ..compile import jit_cache

            impl = getattr(self, self._KIND_IMPLS[key[0]])
            if key[0] == "draft_turn":
                impl = functools.partial(impl, K=key[3])
            # pools are donated on TPU; jaxlib 0.4.3x CPU executables
            # deserialized from the persistent cache corrupt the heap
            # under donation (jit_cache.donation_unsafe, PR 6) — keep
            # the buffers there
            donate = () if jit_cache.donation_unsafe() else (1, 2)
            fn = jax.jit(impl, donate_argnums=donate)
            # one compile per memo entry — the key IS the bucket; a
            # second compile behind the same key is a broken contract
            # the verifier names by arg-diff (MXNET_JIT_VERIFY)
            fn = _cv.wrap("serve.%s|%s" % (key[0], "|".join(
                str(k) for k in key[1:])), fn, budget=1,
                group="serve.%s" % key[0])
            self._jitted[key] = fn
        return fn

    def _sampling_arrays(self, B, B_real, temperature, top_k, top_p, seed):
        """Pad per-request sampling params to the batch bucket (padded
        rows greedy/seed-0: their draws are never read)."""
        def pad(vals, dtype, default):
            a = np.full((B,), default, dtype)
            if vals is not None:
                a[:B_real] = np.asarray(vals, dtype)
            return a

        return (pad(temperature, np.float32, 0.0),
                pad(top_k, np.int32, 0),
                pad(top_p, np.float32, 1.0),
                pad(seed, np.uint32, 0))

    def _attribute(self, key, fn, args, meta):
        """mxprof: attribute this bucket's program (AOT compile = the
        bucket's one compile); the compiled callable replaces the
        jitted one in the bucket cache. Returns the (possibly compiled)
        callable and whether attribution happened on this call."""
        from ..telemetry import prof as _prof

        if not _prof.ENABLED or key in self._prof_keys:
            return fn, False
        cfg = self.cfg
        kind = key[0]
        name = "serve.%s|%s" % (kind, "|".join(str(k) for k in key[1:]))
        # graph identity: the program KIND plus the FULL model geometry
        # (heads/d_ff/vocab included — two configs sharing L and
        # d_model are still different programs) + the paged-pool layout
        ghash = _prof.graph_hash("%s|%r|bs=%d|W=%d" % (
            kind, cfg, self.block_size, self.max_blocks))
        # attribution AOT-compiles and replaces the program: rebind the
        # verifier boundary's inner callable so compile counting
        # survives (the AOT compile is the bucket's budgeted one)
        compiled = _prof.attribute_jit(
            name, _cv.unwrap(fn), args, site="serving.%s" % kind,
            meta=meta, graph_key=ghash)
        fn = _cv.rebind(fn, compiled)
        self._jitted[key] = fn
        self._prof_keys[key] = _prof.program_key_for(name, graph_key=ghash)
        return fn, True

    # -- host-facing API -----------------------------------------------------
    def step(self, params, kpool, vpool, tokens, start, chunk_len,
             block_tables, active, min_batch_bucket=None, temperature=None,
             top_k=None, top_p=None, seed=None):
        """Run one bucketed step over host-side (numpy) batch inputs.

        Inputs are RAGGED: ``tokens`` is [B, C_real<=bucket] already
        padded per-row by the caller via ``chunk_len``; this method pads
        the batch and chunk dims to their buckets and slices the result
        back down. Sampling params default to greedy (temperature 0).

        ``min_batch_bucket`` forces at least that batch bucket — the
        static-batching baseline dispatches decode at the FIXED batch
        shape even when slots have drained (dead slots are padded
        lanes), which is what "static" means on hardware where a decode
        step costs the same at any live count.

        Returns (next_token [B_real] int32 numpy, kpool, vpool) — the
        token vector is the ONLY device->host transfer; logits stay on
        device (the fused-sampler contract, asserted via the mxprof
        ``d2h_bytes`` channel).
        """
        B_real, C_real = tokens.shape
        B = bucket_for(max(B_real, min_batch_bucket or 1),
                       self.batch_buckets)
        C = 1 if C_real == 1 else bucket_for(C_real, self.chunk_buckets)

        def padb(a, fill=0):
            if a.shape[0] == B:
                return a
            pad = np.full((B - a.shape[0],) + a.shape[1:], fill, a.dtype)
            return np.concatenate([a, pad], axis=0)

        from ..telemetry import prof as _prof

        prof_on = _prof.ENABLED
        t0 = time.monotonic() if prof_on else 0.0
        tok = np.zeros((B, C), np.int32)
        tok[:B_real, :C_real] = tokens
        start = padb(np.asarray(start, np.int32))
        chunk_len = padb(np.asarray(chunk_len, np.int32))
        bt = np.zeros((B, self.max_blocks), np.int32)
        bt[:B_real] = block_tables
        act = np.zeros((B,), bool)
        act[:B_real] = active
        temp, tk, tp, sd = self._sampling_arrays(
            B, B_real, temperature, top_k, top_p, seed)
        fn = self._compiled(("step", B, C))
        args = (params, kpool, vpool, tok, start, chunk_len, bt, act,
                temp, tk, tp, sd)
        attributed_now = False
        if prof_on:
            fn, attributed_now = self._attribute(
                ("step", B, C), fn, args,
                meta={"batch_bucket": B, "chunk_bucket": C})
        t1 = time.monotonic() if prof_on else 0.0
        nxt, kp, vp = fn(*args)
        if prof_on:
            t2 = time.monotonic()
            bur = getattr(nxt, "block_until_ready", None)
            if bur is not None:
                bur()
            t3 = time.monotonic()
        host_nxt = np.asarray(nxt)  # the step's ONE pull: token vector
        _cv.note_d2h(host_nxt.nbytes,
                     "mxnet_tpu/serving/model.py::ServingModel.step")
        out_tok = host_nxt[:B_real]
        if prof_on and not attributed_now:
            # the bucket's first step carried the attribution compile —
            # recording it would drown the steady-state phase shares
            _prof.note_step(
                "serve.decode" if C == 1 else "serve.prefill",
                {"host": t1 - t0, "dispatch": t2 - t1,
                 "device": t3 - t2, "d2h": time.monotonic() - t3},
                key=self._prof_keys.get(("step", B, C)),
                tokens=int(np.sum(np.asarray(chunk_len)[:B_real])),
                d2h_bytes=int(out_tok.nbytes))
        return out_tok, kp, vp

    def _pad_device(self, arr, B, fill=0):
        """Pad a device array's batch dim to the bucket."""
        import jax.numpy as jnp

        a = jnp.asarray(arr)
        if a.shape[0] == B:
            return a
        pad = jnp.full((B - a.shape[0],) + a.shape[1:], fill, a.dtype)
        return jnp.concatenate([a, pad], axis=0)

    def _padb_host(self, a, B):
        a = np.asarray(a)
        if a.shape[0] == B:
            return a
        return np.concatenate(
            [a, np.zeros((B - a.shape[0],) + a.shape[1:], a.dtype)])

    def draft_turn(self, params, kpool, vpool, tokens, start, chunk_len,
                   block_tables, active, ks, K, temperature=None,
                   top_k=None, top_p=None, seed=None):
        """The whole draft phase in one dispatch: ingest + K chained
        proposals. ``tokens`` [B_real, Cin] is the per-row catch-up
        chunk (``chunk_len`` real tokens each), ``ks`` the per-row
        draft budgets, ``K`` the static chain length (>= max ks).
        Returns (draft_toks [B_real, K], qdists [B_real, K, V], kpool,
        vpool) — all still on device."""
        B_real, C_real = np.shape(tokens)
        B = bucket_for(B_real, self.batch_buckets)
        C = 1 if C_real == 1 else bucket_for(C_real, self.chunk_buckets)
        tok = np.zeros((B, C), np.int32)
        tok[:B_real, :C_real] = tokens
        start = self._padb_host(np.asarray(start, np.int32), B)
        chunk_len = self._padb_host(np.asarray(chunk_len, np.int32), B)
        ks = self._padb_host(np.asarray(ks, np.int32), B)
        bt = np.zeros((B, self.max_blocks), np.int32)
        bt[:B_real] = block_tables
        act = np.zeros((B,), bool)
        act[:B_real] = active
        temp, tk, tp, sd = self._sampling_arrays(
            B, B_real, temperature, top_k, top_p, seed)
        key = ("draft_turn", B, C, int(K))
        fn = self._compiled(key)
        args = (params, kpool, vpool, tok, start, chunk_len, bt, act,
                temp, tk, tp, sd, ks)
        fn, _ = self._attribute(key, fn, args,
                                meta={"batch_bucket": B, "chunk_bucket": C,
                                      "spec_k": int(K)})
        d, q, kp, vp = fn(*args)
        return d[:B_real], q[:B_real], kp, vp

    def verify(self, params, kpool, vpool, prev_tokens, draft_tokens,
               qdists, start, chunk_len, block_tables, active,
               temperature=None, top_k=None, top_p=None, seed=None):
        """The speculative verify step: ``prev_tokens`` [B_real, 1]
        host ints, ``draft_tokens`` [B_real, K] / ``qdists``
        [B_real, K, V] device arrays from the draft turn (assembled
        into the [B, K+1] chunk INSIDE the program — no eager glue).
        Returns (n_accept [B_real], tok [B_real], kpool, vpool) with
        the small int outputs still on device — the caller pulls them
        in one fence."""
        B_real, K = np.shape(draft_tokens)
        B = bucket_for(B_real, self.batch_buckets)
        prev = self._pad_device(np.asarray(prev_tokens, np.int32), B)
        d = self._pad_device(draft_tokens, B)
        q = self._pad_device(qdists, B, fill=1.0)
        start = self._padb_host(np.asarray(start, np.int32), B)
        chunk_len = self._padb_host(np.asarray(chunk_len, np.int32), B)
        # padded rows: chunk_len 0 would make k_i negative — clamp to 1
        chunk_len = np.maximum(chunk_len, 1)
        bt = np.zeros((B, self.max_blocks), np.int32)
        bt[:B_real] = block_tables
        act = np.zeros((B,), bool)
        act[:B_real] = active
        temp, tk, tp, sd = self._sampling_arrays(
            B, B_real, temperature, top_k, top_p, seed)
        key = ("verify", B, K)
        fn = self._compiled(key)
        args = (params, kpool, vpool, prev, d, q, start, chunk_len, bt,
                act, temp, tk, tp, sd)
        fn, _ = self._attribute(key, fn, args,
                                meta={"batch_bucket": B, "spec_k": K})
        n, t, kp, vp = fn(*args)
        return n[:B_real], t[:B_real], kp, vp

    def warmup(self, params, pool, batch_sizes=None):
        """Pre-compile the decode programs (and let the persistent jit
        cache serve them next process). Prefill buckets compile on first
        use."""
        for B in (batch_sizes or self.batch_buckets):
            bt = np.zeros((B, self.max_blocks), np.int32)
            nxt, kp, vp = self.step(
                params, pool.k, pool.v, np.zeros((B, 1), np.int32),
                np.zeros((B,), np.int32), np.ones((B,), np.int32), bt,
                np.zeros((B,), bool))
            pool.swap(kp, vp)


def cp_prefill_kv(params, cfg, tokens, mesh, kind="ring", chunk=None,
                  seq_axis="seq"):
    """Context-parallel chunked prefill: per-layer K/V for one long
    prompt, computed over a mesh with ring or Ulysses attention.

    This is the long-context prefill path the engine uses for prompts
    big enough to matter (engine ``cp_min_tokens``): activations for a
    ``chunk``-token slice are materialized at a time (bounding memory to
    O(chunk x d) instead of O(T x d) scores), and each chunk's queries
    attend to the full accumulated prefix via the sequence-parallel
    attention in parallel/ring_attention.py / parallel/ulysses.py using
    their ``q_offset`` form — queries are a suffix of the key sequence,
    exactly the chunked-prefill geometry. Both the chunk length and
    every prefix length must divide by the mesh axis size.

    tokens: [T] or [1, T] int32. Returns (k [L, T, H, D], v likewise,
    x_last [d_model] final-position hidden state) as host arrays.
    """
    import jax.numpy as jnp

    from ..parallel.ring_attention import make_ring_attention
    from ..parallel.ulysses import make_ulysses_attention

    tokens = np.asarray(tokens, np.int32).reshape(1, -1)
    T = tokens.shape[1]
    n = mesh.shape[seq_axis]
    if chunk is None:
        chunk = T
    if chunk % n or T % chunk:
        raise ValueError(
            "cp prefill: chunk %d must divide by mesh axis %d and T %d "
            "by chunk" % (chunk, n, T))
    H, D = cfg.num_heads, cfg.head_dim
    L = cfg.num_layers
    factory = {"ring": make_ring_attention,
               "ulysses": make_ulysses_attention}[kind]

    k_out = np.zeros((L, T, H, D), np.float32)
    v_out = np.zeros((L, T, H, D), np.float32)
    x_last = None
    # dense per-layer K/V accumulated on host; each chunk re-enters the
    # layer stack with its predecessors' K/V as the attention prefix
    for c0 in range(0, T, chunk):
        c1 = c0 + chunk
        x = jnp.take(params["embed"], jnp.asarray(tokens[:, c0:c1]), axis=0)
        x = x + params["pos_embed"][c0:c1][None].astype(x.dtype)
        attn = factory(mesh, seq_axis=seq_axis, causal=True, q_offset=c0)
        for li, lp in enumerate(params["layers"]):
            h = _layer_norm(x, lp["ln1"])
            qkv = jnp.einsum("btd,de->bte", h, lp["wqkv"])
            q, k, v = jnp.split(qkv, 3, axis=-1)

            def heads(t):
                return t.reshape(1, t.shape[1], H, D).transpose(0, 2, 1, 3)

            k_out[li, c0:c1] = np.asarray(
                k.reshape(chunk, H, D), np.float32)
            v_out[li, c0:c1] = np.asarray(
                v.reshape(chunk, H, D), np.float32)
            k_full = jnp.asarray(k_out[li, :c1][None]).astype(x.dtype)
            v_full = jnp.asarray(v_out[li, :c1][None]).astype(x.dtype)
            o = attn(heads(q),
                     k_full.transpose(0, 2, 1, 3),
                     v_full.transpose(0, 2, 1, 3))
            o = o.transpose(0, 2, 1, 3).reshape(1, chunk, H * D)
            x = x + jnp.einsum("btd,de->bte", o, lp["wo"])
            h = _layer_norm(x, lp["ln2"])
            import jax

            ff = jax.nn.gelu(jnp.einsum("btd,df->btf", h, lp["w1"]))
            x = x + jnp.einsum("btf,fd->btd", ff, lp["w2"])
        x_last = np.asarray(
            _layer_norm(x, params["ln_f"])[0, -1], np.float32)
    return k_out, v_out, x_last
