"""Decode-model adapter: bucketed ragged batches over a paged KV pool.

Bridges ``models/transformer.py`` (pure-function training forward) to
the serving engine's incremental decode. One jitted *step* function
covers both phases:

- **prefill chunk**: ``C`` prompt tokens per request enter at arbitrary
  start offsets, attend causally to their own chunk plus everything the
  request already has in the paged pool, and write their K/V into the
  pool blocks named by the request's block table;
- **decode**: the same function at ``C == 1`` — one new token per
  request per step.

Ragged batches (every request at a different length) are assembled into
**fixed bucketed shapes**: batch rows pad to the next configured batch
bucket, chunk lengths pad to the next chunk bucket, and the block-table
width is a compile-time constant — so the number of distinct XLA
programs is ``len(batch_buckets) x len(chunk_buckets)``, bounded and
warm across processes via the PR 6 persistent jit cache
(``MXNET_COMPILE_CACHE_DIR``). Padded lanes redirect their K/V writes
to the pool's scratch block 0 and are masked out of attention reads, so
padding never corrupts real state (ragged-vs-padded equivalence is
pinned by tests/unittest/test_serving.py).

Numerical contract: a token decoded through the paged path produces the
same logits as ``transformer.forward`` over the whole sequence would at
that position (same op order, same f32 softmax accumulation), which is
what makes continuous batching a pure scheduling win.

Long-context prefill on a mesh reuses the context-parallel attention in
``parallel/ring_attention.py`` / ``parallel/ulysses.py``: chunked
prefill is exactly their new ``q_offset`` form (queries are a suffix of
the key sequence), see :func:`cp_prefill_kv`.
"""
from __future__ import annotations

import functools
import time

from ..models.transformer import TransformerConfig, _layer_norm

__all__ = ["ServingModel", "bucket_for", "cp_prefill_kv"]


def bucket_for(n, buckets):
    """Smallest bucket >= n (buckets sorted ascending); raises when n
    exceeds every bucket — the caller sized its batch wrong."""
    for b in buckets:
        if n <= b:
            return b
    raise ValueError("no bucket fits %d (buckets %s)" % (n, list(buckets)))


class ServingModel:
    """Jitted paged-attention step functions over transformer params.

    Parameters
    ----------
    cfg : TransformerConfig
        Model geometry (the same config object bench_lm.py trains).
    block_size : int
        Paged-pool tokens per block.
    max_blocks_per_req : int
        Block-table width ``W`` — a compile-time constant; a request
        can span at most ``W * block_size`` total tokens.
    batch_buckets, chunk_buckets : tuple of int
        Padded batch sizes / chunk lengths (ascending). Decode always
        uses chunk bucket 1 (its own program).
    """

    def __init__(self, cfg: TransformerConfig, block_size,
                 max_blocks_per_req, batch_buckets=(1, 2, 4, 8),
                 chunk_buckets=(32, 64, 128)):
        self.cfg = cfg
        self.block_size = int(block_size)
        self.max_blocks = int(max_blocks_per_req)
        self.batch_buckets = tuple(sorted(set(int(b) for b in batch_buckets)))
        self.chunk_buckets = tuple(sorted(set(int(c) for c in chunk_buckets)))
        self._jitted = {}  # (B, C) -> compiled step
        self._prof_keys = {}  # (B, C) -> mxprof program key

    # -- the step program ----------------------------------------------------
    def _step_impl(self, params, kpool, vpool, tokens, start, chunk_len,
                   block_tables, active):
        """One fused forward over ``C`` new tokens per request.

        tokens [B, C] int32, start [B] int32 (global position of
        tokens[:, 0]), chunk_len [B] int32 (real tokens this chunk, 0
        for padded rows), block_tables [B, W] int32, active [B] bool.
        Returns (next_token [B] int32, logits_last [B, V] f32, kpool,
        vpool).
        """
        import jax
        import jax.numpy as jnp

        cfg = self.cfg
        B, C = tokens.shape
        W, bs = self.max_blocks, self.block_size
        S = W * bs
        H, D = cfg.num_heads, cfg.head_dim
        scale = 1.0 / float(D) ** 0.5

        pos = start[:, None] + jnp.arange(C)[None, :]            # [B, C]
        in_chunk = jnp.arange(C)[None, :] < chunk_len[:, None]   # [B, C]
        valid = in_chunk & active[:, None]
        # pos_embed rows are clipped for padded lanes (jnp.take clips);
        # their outputs are never read back
        x = jnp.take(params["embed"], tokens, axis=0)
        x = x + jnp.take(params["pos_embed"], jnp.minimum(
            pos, cfg.max_seq_len - 1), axis=0).astype(x.dtype)

        # K/V write coordinates: padded / inactive lanes redirect to the
        # scratch block 0 (kv_cache.py module docstring)
        blk_idx = jnp.clip(pos // bs, 0, W - 1)                  # [B, C]
        table_blk = jnp.take_along_axis(block_tables, blk_idx, axis=1)
        write_blk = jnp.where(valid, table_blk, 0)               # [B, C]
        write_slot = jnp.where(valid, pos % bs, 0)               # [B, C]

        # pool key positions: slot (w, i) of a request's table holds its
        # token w*bs + i
        key_pos = jnp.arange(S)                                  # [S]
        # keys already in the pool are those strictly before this
        # chunk's first token; the chunk attends to itself causally
        pool_mask = key_pos[None, None, :] < start[:, None, None]  # [B,1,S]
        pool_mask = jnp.broadcast_to(pool_mask, (B, C, S))
        chunk_mask = (jnp.arange(C)[None, :, None] >=
                      jnp.arange(C)[None, None, :]) & in_chunk[:, None, :]
        chunk_mask = jnp.broadcast_to(chunk_mask, (B, C, C))
        full_mask = jnp.concatenate([pool_mask, chunk_mask], axis=2)
        neg = jnp.asarray(-1e30, jnp.float32)

        for li, lp in enumerate(params["layers"]):
            h = _layer_norm(x, lp["ln1"])
            qkv = jnp.einsum("bcd,de->bce", h, lp["wqkv"])
            q, k, v = jnp.split(qkv, 3, axis=-1)
            k = k.reshape(B, C, H, D)
            v = v.reshape(B, C, H, D)
            # write this chunk's K/V into the pool (scatter; scratch
            # absorbs padded lanes)
            kpool = kpool.at[li, write_blk, write_slot].set(
                k.astype(kpool.dtype))
            vpool = vpool.at[li, write_blk, write_slot].set(
                v.astype(vpool.dtype))
            # gather the request's paged history [B, S, H, D]
            k_hist = kpool[li][block_tables].reshape(B, S, H, D)
            v_hist = vpool[li][block_tables].reshape(B, S, H, D)
            k_all = jnp.concatenate([k_hist.astype(k.dtype), k], axis=1)
            v_all = jnp.concatenate([v_hist.astype(v.dtype), v], axis=1)

            qh = q.reshape(B, C, H, D)
            scores = jnp.einsum("bchd,bshd->bhcs", qh, k_all) * scale
            scores = jnp.where(full_mask[:, None], scores.astype(jnp.float32),
                               neg)
            m = jnp.max(scores, axis=-1, keepdims=True)
            p = jnp.exp(scores - m)
            p = p * jnp.any(full_mask[:, None], axis=-1,
                            keepdims=True).astype(p.dtype)
            l = jnp.sum(p, axis=-1, keepdims=True)
            p = p / jnp.maximum(l, 1e-30)
            o = jnp.einsum("bhcs,bshd->bchd", p.astype(v_all.dtype), v_all)
            o = o.reshape(B, C, H * D)
            x = x + jnp.einsum("bcd,de->bce", o, lp["wo"])
            h = _layer_norm(x, lp["ln2"])
            ff = jax.nn.gelu(jnp.einsum("bcd,df->bcf", h, lp["w1"]))
            x = x + jnp.einsum("bcf,fd->bcd", ff, lp["w2"])

        x = _layer_norm(x, params["ln_f"])
        # logits only at each row's last real chunk position — the one
        # spot a next token can be sampled from
        last = jnp.clip(chunk_len - 1, 0, C - 1)                 # [B]
        x_last = jnp.take_along_axis(
            x, last[:, None, None].astype(jnp.int32), axis=1)[:, 0]  # [B, d]
        logits = jnp.einsum("bd,vd->bv", x_last,
                            params["embed"]).astype(jnp.float32)
        next_token = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        return next_token, logits, kpool, vpool

    def _compiled(self, B, C):
        key = (B, C)
        fn = self._jitted.get(key)
        if fn is None:
            import jax

            from ..compile import jit_cache

            # pools are donated on TPU; jaxlib 0.4.3x CPU executables
            # deserialized from the persistent cache corrupt the heap
            # under donation (jit_cache.donation_unsafe, PR 6) — keep
            # the buffers there
            donate = () if jit_cache.donation_unsafe() else (1, 2)
            fn = jax.jit(self._step_impl, donate_argnums=donate)
            self._jitted[key] = fn
        return fn

    # -- host-facing API -----------------------------------------------------
    def step(self, params, kpool, vpool, tokens, start, chunk_len,
             block_tables, active, min_batch_bucket=None):
        """Run one bucketed step over host-side (numpy) batch inputs.

        Inputs are RAGGED: ``tokens`` is [B, C_real<=bucket] already
        padded per-row by the caller via ``chunk_len``; this method pads
        the batch and chunk dims to their buckets and slices the result
        back down.

        ``min_batch_bucket`` forces at least that batch bucket — the
        static-batching baseline dispatches decode at the FIXED batch
        shape even when slots have drained (dead slots are padded
        lanes), which is what "static" means on hardware where a decode
        step costs the same at any live count.
        """
        import numpy as np

        B_real, C_real = tokens.shape
        B = bucket_for(max(B_real, min_batch_bucket or 1),
                       self.batch_buckets)
        C = 1 if C_real == 1 else bucket_for(C_real, self.chunk_buckets)

        def padb(a, fill=0):
            if a.shape[0] == B:
                return a
            pad = np.full((B - a.shape[0],) + a.shape[1:], fill, a.dtype)
            return np.concatenate([a, pad], axis=0)

        from ..telemetry import prof as _prof

        prof_on = _prof.ENABLED
        t0 = time.monotonic() if prof_on else 0.0
        tok = np.zeros((B, C), np.int32)
        tok[:B_real, :C_real] = tokens
        start = padb(np.asarray(start, np.int32))
        chunk_len = padb(np.asarray(chunk_len, np.int32))
        bt = np.zeros((B, self.max_blocks), np.int32)
        bt[:B_real] = block_tables
        act = np.zeros((B,), bool)
        act[:B_real] = active
        fn = self._compiled(B, C)
        attributed_now = False
        if prof_on and (B, C) not in self._prof_keys:
            attributed_now = True
            # mxprof: attribute this bucket's ragged-step program (AOT
            # compile = the bucket's one compile); the compiled
            # callable replaces the jitted one in the bucket cache
            cfg = self.cfg
            key = "serve.step|B=%d|C=%d" % (B, C)
            # graph identity: the FULL model geometry (heads/d_ff/vocab
            # included — two configs sharing L and d_model are still
            # different programs) + the paged-pool layout
            ghash = _prof.graph_hash("%r|bs=%d|W=%d" % (
                cfg, self.block_size, self.max_blocks))
            fn = _prof.attribute_jit(
                key, fn,
                (params, kpool, vpool, tok, start, chunk_len, bt, act),
                site="serving.step",
                meta={"batch_bucket": B, "chunk_bucket": C},
                graph_key=ghash)
            self._jitted[(B, C)] = fn
            self._prof_keys[(B, C)] = _prof.program_key_for(
                key, graph_key=ghash)
        t1 = time.monotonic() if prof_on else 0.0
        nxt, logits, kp, vp = fn(
            params, kpool, vpool, tok, start, chunk_len, bt, act)
        if prof_on:
            t2 = time.monotonic()
            bur = getattr(nxt, "block_until_ready", None)
            if bur is not None:
                bur()
            t3 = time.monotonic()
        out = (np.asarray(nxt)[:B_real], np.asarray(logits)[:B_real],
               kp, vp)
        if prof_on and not attributed_now:
            # the bucket's first step carried the attribution compile —
            # recording it would drown the steady-state phase shares
            _prof.note_step(
                "serve.decode" if C == 1 else "serve.prefill",
                {"host": t1 - t0, "dispatch": t2 - t1,
                 "device": t3 - t2, "d2h": time.monotonic() - t3},
                key=self._prof_keys.get((B, C)),
                tokens=int(np.sum(np.asarray(chunk_len)[:B_real])))
        return out

    def warmup(self, params, pool, batch_sizes=None):
        """Pre-compile the decode programs (and let the persistent jit
        cache serve them next process). Prefill buckets compile on first
        use."""
        import numpy as np

        for B in (batch_sizes or self.batch_buckets):
            bt = np.zeros((B, self.max_blocks), np.int32)
            nxt, _, kp, vp = self.step(
                params, pool.k, pool.v, np.zeros((B, 1), np.int32),
                np.zeros((B,), np.int32), np.ones((B,), np.int32), bt,
                np.zeros((B,), bool))
            pool.swap(kp, vp)


def cp_prefill_kv(params, cfg, tokens, mesh, kind="ring", chunk=None,
                  seq_axis="seq"):
    """Context-parallel chunked prefill: per-layer K/V for one long
    prompt, computed over a mesh with ring or Ulysses attention.

    This is the long-context prefill path the engine uses for prompts
    big enough to matter (engine ``cp_min_tokens``): activations for a
    ``chunk``-token slice are materialized at a time (bounding memory to
    O(chunk x d) instead of O(T x d) scores), and each chunk's queries
    attend to the full accumulated prefix via the sequence-parallel
    attention in parallel/ring_attention.py / parallel/ulysses.py using
    their ``q_offset`` form — queries are a suffix of the key sequence,
    exactly the chunked-prefill geometry. Both the chunk length and
    every prefix length must divide by the mesh axis size.

    tokens: [T] or [1, T] int32. Returns (k [L, T, H, D], v likewise,
    x_last [d_model] final-position hidden state) as host arrays.
    """
    import numpy as np
    import jax.numpy as jnp

    from ..parallel.ring_attention import make_ring_attention
    from ..parallel.ulysses import make_ulysses_attention

    tokens = np.asarray(tokens, np.int32).reshape(1, -1)
    T = tokens.shape[1]
    n = mesh.shape[seq_axis]
    if chunk is None:
        chunk = T
    if chunk % n or T % chunk:
        raise ValueError(
            "cp prefill: chunk %d must divide by mesh axis %d and T %d "
            "by chunk" % (chunk, n, T))
    H, D = cfg.num_heads, cfg.head_dim
    L = cfg.num_layers
    factory = {"ring": make_ring_attention,
               "ulysses": make_ulysses_attention}[kind]

    k_out = np.zeros((L, T, H, D), np.float32)
    v_out = np.zeros((L, T, H, D), np.float32)
    x_last = None
    # dense per-layer K/V accumulated on host; each chunk re-enters the
    # layer stack with its predecessors' K/V as the attention prefix
    for c0 in range(0, T, chunk):
        c1 = c0 + chunk
        x = jnp.take(params["embed"], jnp.asarray(tokens[:, c0:c1]), axis=0)
        x = x + params["pos_embed"][c0:c1][None].astype(x.dtype)
        attn = factory(mesh, seq_axis=seq_axis, causal=True, q_offset=c0)
        for li, lp in enumerate(params["layers"]):
            h = _layer_norm(x, lp["ln1"])
            qkv = jnp.einsum("btd,de->bte", h, lp["wqkv"])
            q, k, v = jnp.split(qkv, 3, axis=-1)

            def heads(t):
                return t.reshape(1, t.shape[1], H, D).transpose(0, 2, 1, 3)

            k_out[li, c0:c1] = np.asarray(
                k.reshape(chunk, H, D), np.float32)
            v_out[li, c0:c1] = np.asarray(
                v.reshape(chunk, H, D), np.float32)
            k_full = jnp.asarray(k_out[li, :c1][None]).astype(x.dtype)
            v_full = jnp.asarray(v_out[li, :c1][None]).astype(x.dtype)
            o = attn(heads(q),
                     k_full.transpose(0, 2, 1, 3),
                     v_full.transpose(0, 2, 1, 3))
            o = o.transpose(0, 2, 1, 3).reshape(1, chunk, H * D)
            x = x + jnp.einsum("btd,de->bte", o, lp["wo"])
            h = _layer_norm(x, lp["ln2"])
            import jax

            ff = jax.nn.gelu(jnp.einsum("btd,df->btf", h, lp["w1"]))
            x = x + jnp.einsum("btf,fd->btd", ff, lp["w2"])
        x_last = np.asarray(
            _layer_norm(x, params["ln_f"])[0, -1], np.float32)
    return k_out, v_out, x_last
