"""mxlint CLI: run the analysis passes from the command line.

Entry points: ``tools/mxlint.py`` (repo checkout) and the ``mxlint``
console script (pyproject). Typical invocations::

    mxlint --all                      # zoo + ops + engine + lock lint
    mxlint --model mlp                # one zoo symbol
    mxlint --graph net.json           # a serialized symbol (dead nodes too)
    mxlint --ops mxnet_tpu/ops        # tracer-leak lint a file or package
    mxlint --engine-trace trace.json  # verify a recorded engine trace
    mxlint --locks                    # concurrency lint, whole package
    mxlint --locks some/module.py     # concurrency lint one file/dir
    mxlint --jit                      # jit-boundary lint (recompiles,
                                      # donation, hot-path D2H, cache keys)
    mxlint --schedules                # interleaving-explorer survival run
    mxlint --proto                    # protocol schema + timeout lattice
    mxlint --protosim                 # protocol-simulator survival run
    mxlint --all --fail-on warning    # strict mode: warnings also fail

Exit codes: 0 clean (no finding at/above --fail-on), 1 findings,
2 usage or load errors.

The linter is static: it must never touch an accelerator, so it pins
JAX_PLATFORMS=cpu for the symbol builders (override: MXLINT_PLATFORM).
"""
from __future__ import annotations

import argparse
import os
import sys

from .findings import SEVERITIES, summarize

__all__ = ["main", "zoo_models"]


def zoo_models():
    """name -> nullary symbol builder for every lintable zoo model.
    (transformer is jax-native — no symbol graph to lint.)"""
    from .. import models

    return {
        "mlp": models.get_mlp,
        "lenet": models.get_lenet,
        "resnet_small": lambda: models.get_resnet_small(n=1),
        "inception_bn_small": models.get_inception_bn_small,
        "alexnet": models.get_alexnet,
        "googlenet": models.get_googlenet,
        "vgg": models.get_vgg,
        "unet": models.get_unet,
        "lstm": lambda: models.lstm_unroll(1, 4, 64, 256, 128, 64),
        "gru": lambda: models.gru_unroll(1, 4, 64, 256, 128, 64),
        "rnn": lambda: models.rnn_unroll(1, 4, 64, 256, 128, 64),
    }


def _engine_selftest():
    """Record a small live workload through the real engine hooks and
    verify it — proves the record path end-to-end without a device."""
    from .. import engine as eng
    from .engine_verify import recording, verify

    e = eng.Engine(engine_type="NaiveEngine")
    try:
        with recording(e) as trace:
            hvars = [e.new_variable() for _ in range(4)]
            sink = []
            for i in range(8):
                e.push(lambda i=i: sink.append(i),
                       const_vars=[hvars[i % 2]],
                       mutable_vars=[hvars[2 + i % 2]])
            e.wait_for_all()
            e.delete_variable(hvars[0])
        return verify(trace)
    finally:
        e.close()


def main(argv=None):
    p = argparse.ArgumentParser(
        prog="mxlint",
        description="Static analysis for mxnet_tpu: symbol-graph lint, "
                    "engine hazard verification, tracer-leak lint.")
    p.add_argument("--all", action="store_true",
                   help="lint the model zoo, the ops package, and run the "
                        "engine record/verify selftest")
    p.add_argument("--model", action="append", default=[],
                   help="lint a model-zoo symbol by name (repeatable)")
    p.add_argument("--graph", action="append", default=[],
                   help="lint a serialized symbol JSON file (repeatable)")
    p.add_argument("--ops", action="append", default=[],
                   help="tracer-leak lint a .py file or package dir")
    p.add_argument("--engine-trace", action="append", default=[],
                   help="verify a recorded engine trace JSON file "
                        "(push hazards AND runtime lock-order events)")
    p.add_argument("--locks", action="append", nargs="?", const="",
                   metavar="PATH", default=[],
                   help="mxrace concurrency lint (lock-order inversions, "
                        "blocking-under-lock, unguarded fields, cv "
                        "misuse) over PATH — bare --locks lints the "
                        "whole mxnet_tpu package")
    p.add_argument("--jit", action="append", nargs="?", const="",
                   metavar="PATH", default=[],
                   help="mxjit jit-boundary lint (recompile hazards, "
                        "donation/aliasing audit, hot-path D2H, weak "
                        "cache keys) over PATH — bare --jit lints the "
                        "package's jit-dispatching surface")
    p.add_argument("--telemetry", action="store_true",
                   help="metrics catalog gate: every counter/gauge/"
                        "histogram registered in the package must appear "
                        "in docs/how_to/observability.md's catalog, and "
                        "vice versa")
    p.add_argument("--schedules", action="store_true",
                   help="mxrace interleaving-explorer survival run: "
                        "seeded-race negative controls must be found "
                        "and replayed, the serving submit/cancel/step "
                        "loop and the elastic aggregator round protocol "
                        "must survive every explored schedule")
    p.add_argument("--proto", action="append", nargs="?", const="",
                   metavar="PATH", default=[],
                   help="mxproto protocol lint: diff every elastic-RPC "
                        "client call site against every server dispatch "
                        "arm (unknown ops, unread/unsent fields, "
                        "missing reply keys, undisciplined transport "
                        "calls) and check the cross-module timeout-"
                        "budget lattice — bare --proto lints the "
                        "elastic substrate and its in-package speakers")
    p.add_argument("--protosim", action="store_true",
                   help="mxproto protocol-simulator survival run: both "
                        "seeded protocol mutants must be found and "
                        "replayed, then the all-reduce, barrier and "
                        "shard-update workloads must survive every "
                        "explored message schedule")
    p.add_argument("--proto-seed", type=int,
                   default=int(os.environ.get("MXPROTO_SEED", "0") or 0),
                   help="base seed for --protosim (env MXPROTO_SEED)")
    p.add_argument("--proto-count", type=int, default=None,
                   help="schedules per --protosim leg (env "
                        "MXPROTO_SCHEDULES, default 25)")
    p.add_argument("--schedule-seed", type=int,
                   default=int(os.environ.get("MXRACE_SEED", "0") or 0),
                   help="base seed for --schedules (env MXRACE_SEED)")
    p.add_argument("--schedule-count", type=int, default=None,
                   help="schedules per --schedules leg (env "
                        "MXRACE_SCHEDULES, default 25)")
    p.add_argument("--fail-on", choices=list(SEVERITIES), default="error",
                   help="lowest severity that makes the exit code nonzero "
                        "(default: error)")
    p.add_argument("--json", action="store_true",
                   help="emit findings as a JSON array")
    p.add_argument("--list-models", action="store_true",
                   help="list lintable zoo model names and exit")
    args = p.parse_args(argv)

    # static analysis must not grab the chip (or pay TPU init latency)
    os.environ["JAX_PLATFORMS"] = os.environ.get("MXLINT_PLATFORM", "cpu")

    if args.list_models:
        for name in sorted(zoo_models()):
            print(name)
        return 0
    if not (args.all or args.model or args.graph or args.ops
            or args.engine_trace or args.locks or args.jit
            or args.schedules or args.telemetry or args.proto
            or args.protosim):
        p.print_usage(sys.stderr)
        print("mxlint: nothing to do (try --all)", file=sys.stderr)
        return 2

    findings, n_targets = [], 0

    graph_files = list(args.graph)
    trace_files = list(args.engine_trace)
    ops_paths = list(args.ops)
    model_names = list(args.model)
    lock_paths = list(args.locks)
    jit_paths = list(args.jit)
    proto_paths = list(args.proto)
    run_selftest = False
    run_telemetry = args.telemetry
    if args.all:
        model_names.extend(sorted(zoo_models()))
        from .. import ops as _ops_pkg

        ops_paths.append(os.path.dirname(os.path.abspath(_ops_pkg.__file__)))
        run_selftest = True
        run_telemetry = True
        if not lock_paths:
            lock_paths.append("")  # whole-package concurrency lint
        if not jit_paths:
            jit_paths.append("")  # jit-dispatching-surface lint
        if not proto_paths:
            proto_paths.append("")  # elastic-substrate protocol lint

    def _load_error(path, e):
        print("mxlint: %s: %s: %s" % (path, type(e).__name__, e),
              file=sys.stderr)
        return 2

    # only per-input load/parse errors map to the documented exit code
    # 2 (each pass declares them: OSError/ValueError for graphs and
    # traces, OSError/SyntaxError for .py sources). Any other exception
    # is a linter bug and must crash with its traceback, not be
    # misreported as a bad input file — zoo building and the model lint
    # run outside any except for the same reason.
    zoo = zoo_models() if model_names else {}
    for name in model_names:
        if name not in zoo:
            print("mxlint: unknown model %r (see --list-models)" % name,
                  file=sys.stderr)
            return 2
        from .graph_lint import lint_symbol

        findings.extend(lint_symbol(zoo[name]()))
        n_targets += 1
    for path in graph_files:
        from .graph_lint import lint_json

        try:
            with open(path, "r") as f:
                findings.extend(lint_json(f.read()))
        except (OSError, ValueError) as e:
            # ValueError: bad JSON text or bad graph structure —
            # lint_json validates the input upfront and raises
            # ValueError for both, so anything else escaping here is a
            # linter bug and crashes with its traceback
            return _load_error(path, e)
        n_targets += 1
    for path in ops_paths:
        from .ast_lint import lint_package

        try:
            findings.extend(lint_package(path))
        except (OSError, SyntaxError) as e:  # unreadable / unparsable .py
            return _load_error(path, e)
        n_targets += 1
    for path in trace_files:
        from .engine_verify import EngineTrace, verify

        try:
            with open(path, "r") as f:
                trace = EngineTrace.from_json(f.read())
        except (OSError, ValueError) as e:
            return _load_error(path, e)
        findings.extend(verify(trace))
        n_targets += 1
    for path in lock_paths:
        from .lock_lint import DEFAULT_PACKAGE, lint_package as lint_locks

        try:
            findings.extend(lint_locks(path or DEFAULT_PACKAGE))
        except (OSError, SyntaxError) as e:  # unreadable / unparsable .py
            return _load_error(path or DEFAULT_PACKAGE, e)
        n_targets += 1
    for path in jit_paths:
        from .jit_lint import lint_targets as lint_jit

        try:
            findings.extend(lint_jit(path or None))
        except (OSError, SyntaxError) as e:  # unreadable / unparsable .py
            return _load_error(path or "(jit surface)", e)
        n_targets += 1
    for path in proto_paths:
        from .proto_lint import lint_protocol

        try:
            findings.extend(lint_protocol([path] if path else None))
        except (OSError, SyntaxError) as e:  # unreadable / unparsable .py
            return _load_error(path or "(elastic substrate)", e)
        n_targets += 1
    if run_selftest:
        findings.extend(_engine_selftest())
        n_targets += 1
    if run_telemetry:
        from .telemetry_lint import lint_catalog

        findings.extend(lint_catalog())
        n_targets += 1
    if args.schedules:
        from .schedule import survival_suite

        fs, lines = survival_suite(seed=args.schedule_seed,
                                   schedules=args.schedule_count)
        for ln in lines:  # survival rows go to stderr: --json stays pure
            print("mxrace: %s" % ln, file=sys.stderr)
        findings.extend(fs)
        n_targets += 1
    if args.protosim:
        from .datasim import data_survival_suite
        from .protosim import survival_suite as proto_suite

        fs, lines = proto_suite(seed=args.proto_seed,
                                schedules=args.proto_count)
        for ln in lines:
            print("mxproto: %s" % ln, file=sys.stderr)
        findings.extend(fs)
        # the data-service half of the protocol surface
        # (docs/how_to/data_service.md): same explorer, its own
        # coordinator, mutants and invariants
        fs, lines = data_survival_suite(seed=args.proto_seed,
                                        schedules=args.proto_count)
        for ln in lines:
            print("mxdata: %s" % ln, file=sys.stderr)
        findings.extend(fs)
        n_targets += 1

    findings.sort(key=lambda f: (-SEVERITIES.index(f.severity),
                                 f.pass_name, f.where))
    if args.json:
        import json as _json

        print(_json.dumps([f.to_dict() for f in findings], indent=2))
    else:
        for f in findings:
            print(f)
        print("mxlint: checked %d target(s): %s"
              % (n_targets, summarize(findings)))

    threshold = SEVERITIES.index(args.fail_on)
    bad = any(SEVERITIES.index(f.severity) >= threshold for f in findings)
    return 1 if bad else 0


if __name__ == "__main__":
    sys.exit(main())
