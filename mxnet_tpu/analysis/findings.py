"""Shared finding model for the mxlint analysis passes.

Every pass (graph_lint, engine_verify, ast_lint) reports a flat list of
``Finding`` objects so the CLI, the test suite and programmatic callers
consume one shape. Severity is three-level on purpose:

- ``error``   — a proven defect (dtype clash on an elementwise edge, a
  write-write race, a tracer leak): the CLI exits nonzero on these.
- ``warning`` — correct-but-costly or suspicious (sub-128 matmul dims
  whose XLA padding is the honest price of a small layer, dead graph
  nodes in a serialized JSON): reported, exit 0 unless --fail-on warning.
- ``info``    — an optimization opportunity, not a problem (elementwise
  chains the compile layer's fusion pass would merge): reported so the
  lint surfaces what MXNET_COMPILE_OPT=1 would do even when it is off;
  never affects the exit code unless --fail-on info.

The module stays dependency-free (no jax, no mxnet_tpu imports) so the
engine can record/verify without dragging the compute stack in.
"""
from __future__ import annotations

__all__ = ["Finding", "SEVERITIES", "max_severity", "summarize"]

SEVERITIES = ("info", "warning", "error")


class Finding:
    """One diagnostic from an analysis pass."""

    __slots__ = ("pass_name", "code", "severity", "where", "message")

    def __init__(self, pass_name, code, severity, where, message):
        if severity not in SEVERITIES:
            raise ValueError("bad severity %r" % (severity,))
        self.pass_name = pass_name  # 'graph' | 'engine' | 'tracer'
        self.code = code            # e.g. 'dtype-mismatch', 'ww-hazard'
        self.severity = severity
        self.where = where          # node name / op seq / file:line
        self.message = message

    def key(self):
        """Stable identity, used to avoid re-raising the same finding on
        every wait in live engine-verify mode."""
        return (self.pass_name, self.code, self.where, self.message)

    def to_dict(self):
        return {
            "pass": self.pass_name,
            "code": self.code,
            "severity": self.severity,
            "where": self.where,
            "message": self.message,
        }

    def __str__(self):
        return "[%s] %s/%s %s: %s" % (
            self.severity, self.pass_name, self.code, self.where, self.message)

    def __repr__(self):
        return "<Finding %s>" % self


def max_severity(findings):
    """Highest severity present, or None for an empty list."""
    worst = None
    for f in findings:
        if worst is None or SEVERITIES.index(f.severity) > SEVERITIES.index(worst):
            worst = f.severity
    return worst


def summarize(findings):
    n_err = sum(1 for f in findings if f.severity == "error")
    n_warn = sum(1 for f in findings if f.severity == "warning")
    n_info = len(findings) - n_err - n_warn
    s = "%d error(s), %d warning(s)" % (n_err, n_warn)
    return s + (", %d info" % n_info if n_info else "")
