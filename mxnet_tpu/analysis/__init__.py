"""mxlint: static-analysis subsystem over the framework's three IRs.

Three coordinated passes, one Finding model (findings.py):

- ``graph_lint``    — compiler-style checks over the Symbol DAG
  (dtype edges, grad_req discipline, dead JSON nodes, TPU 128-lane
  padding waste).
- ``engine_verify`` — record/verify the dependency engine's
  read/write-var discipline (hazards, use-after-free, wait-cycles).
  Live recording hooks live in ``mxnet_tpu/engine.py`` behind
  ``MXNET_ENGINE_VERIFY=1``.
- ``ast_lint``      — tracer-leak lint over jitted op bodies
  (np-on-tracer, tracer branches, host syncs).
- ``lock_lint``     — mxrace concurrency lint over the lock-using
  modules (lock-order inversions, blocking-under-lock, unguarded
  fields, condition-variable misuse) + the static lock-order graph
  cross-checked against runtime lock traces.
- ``schedule``      — mxrace deterministic interleaving explorer:
  seeded/exhaustive thread-schedule exploration with replayable
  failure seeds (chaos testing for schedules).
- ``proto_lint``    — mxproto protocol lint over the elastic RPC
  substrate: client call sites diffed bidirectionally against server
  dispatch arms, plus the cross-module timeout-budget lattice.
- ``protosim``      — mxproto deterministic message-schedule simulator:
  the real coordinator state machine under explorable delivery
  orders, losses, duplicates, crashes and restarts, with (seed, index)
  replay.
- ``jit_lint``      — mxjit jit-boundary lint over the dispatching
  surface (recompile hazards, donation/use-after-donate audit,
  hot-path D2H discipline, weak jit-cache keys).
- ``compile_verify`` — runtime compile/transfer verifier behind
  ``MXNET_JIT_VERIFY=1``: per-callable compile budgets with
  arg-signature diffs on unexpected recompiles, plus the hot-region
  D2H byte ledger cross-checked against jit_lint's sanctioned sites.

CLI: ``tools/mxlint.py`` / the ``mxlint`` console script (cli.py).

This package imports neither jax nor the compute stack at module level:
the engine attaches a trace recorder during early interpreter states,
and CI wants the AST pass runnable without devices.
"""
from __future__ import annotations

from .findings import Finding, max_severity, summarize
from .engine_verify import (EngineTrace, TracedLock, maybe_trace_lock,
                            observed_lock_edges, recording,
                            verify as verify_trace)
from .ast_lint import lint_file, lint_package, lint_source
from .graph_lint import lint_json, lint_symbol
from .lock_lint import (build_lock_graph, cross_check,
                        lint_package as lint_locks)
from .jit_lint import (lint_targets as lint_jit,
                       sanctioned_d2h_sites,
                       cross_check as cross_check_d2h)

__all__ = [
    "Finding", "max_severity", "summarize",
    "EngineTrace", "recording", "verify_trace",
    "TracedLock", "maybe_trace_lock", "observed_lock_edges",
    "lint_file", "lint_package", "lint_source",
    "lint_json", "lint_symbol",
    "build_lock_graph", "cross_check", "lint_locks",
    "lint_jit", "sanctioned_d2h_sites", "cross_check_d2h",
]
