"""jit-boundary lint — mxjit (mxlint ``--jit``).

The MFU arc and the serving tokens/s headline both live or die at the
jit boundary: an accidental recompile, a lost donation, or a stray
device->host sync inside a per-step loop silently costs 10-30% and no
test catches it until a bench regresses.  This pass makes the repo's
jit-boundary conventions *checkable artifacts* (the TVM stance on
schedule/layout decisions) over every jit-dispatching module:

``recompile-hazard`` (error)
    A per-call-varying Python value or an unbucketed runtime shape
    reaching a traced signature — the compile-count-per-bucket contract
    made checkable.  Two static forms: a ``jax.jit`` call inside a
    steady-state loop without a memo guard (every iteration builds and
    traces a fresh program), and a raw ``.shape``-derived value (never
    laundered through ``bucket_for``) flowing into a jit-memo key or a
    traced closure.  The *dynamic* form — same structure, varying
    value — is the runtime verifier's half (compile_verify.py).

``donation-hazard``
    error: caller reuse of a buffer after it was passed at a
    ``donate_argnums`` position — the executable now owns that memory;
    reading it is a use-after-free that XLA only sometimes catches.
    Reuse means a read after the dispatch without rebinding, or a loop
    that re-dispatches the same donated name without threading the
    returned buffer back (the pool.swap discipline).  warning: a
    steady-state loop dispatching pool-like buffers through a program
    built with *no* donation at all — every step pays a device-side
    copy that donation would elide.  The PR 6 cache+CPU carve-out
    (``donate = () if jit_cache.donation_unsafe() else (...)``) is
    donation for analysis purposes, never a finding: the buffers ARE
    donated on TPU, so caller reuse is still an error.

``hot-d2h`` (error)
    ``.asnumpy()`` / ``np.asarray`` / ``float()`` / ``.item()`` /
    ``jax.device_get`` / ``.block_until_ready()`` inside a per-step /
    per-token loop — the loop-aware escalation of ast_lint's host-sync
    taint.  A loop is *hot* when it (transitively, within the module)
    dispatches a jitted program; functions called from a hot loop are
    hot too, so a drain helper's pulls are attributed to the loop that
    calls it.  Sanctioned (info, and exported as the runtime D2H
    ledger's expected-site set): the one-fence-per-chunk idiom
    (``bur = getattr(o, "block_until_ready", None)``), syncs guarded
    under a profiling/telemetry ``ENABLED`` check, the single
    post-fence chunk pull, and ``# mxlint: disable`` pragma lines.

``weak-cache-key`` (error)
    A config input reaching a jitted program body that is NOT folded
    into its jit-cache / attribution key — the PR 13/15 aliasing bug
    class (two different graphs sharing a shape-only key), checked by
    diffing the traced closure's reaching-config set against the
    key-construction site.  Also mechanical: any ``attribute_jit``
    call without ``graph_key=`` (the exact hole PR 13 patched).

The pass is interprocedural *within a module*: memo dicts holding
jitted programs, builder methods returning them, and the dispatch
sites calling them are linked so donation positions and cache keys
survive the repo's ``fn = self._compiled(key); fn(*args)`` idiom.

Suppression: a ``# mxlint: disable`` comment on the offending line
(ast_lint's pragma).  Suppressed and fence-sanctioned D2H sites are
still *collected* — ``sanctioned_d2h_sites()`` exports them as the
static half of compile_verify's observed-vs-expected cross-check
(the lock_lint ``cross_check`` pattern).
"""
from __future__ import annotations

import ast
import os

from .findings import Finding

__all__ = ["lint_source", "lint_file", "lint_targets", "cross_check",
           "sanctioned_d2h_sites", "DEFAULT_TARGETS", "DEFAULT_PACKAGE"]

DEFAULT_PACKAGE = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

#: the jit-dispatching surface: every module that builds or dispatches
#: a traced program (the set the MFU/tokens-per-s roadmap items churn)
DEFAULT_TARGETS = (
    "executor.py",
    "model.py",
    os.path.join("serving", "model.py"),
    os.path.join("serving", "engine.py"),
    os.path.join("serving", "scheduler.py"),
    os.path.join("parallel", "fit_trainer.py"),
    os.path.join("parallel", "symbol_trainer.py"),
    os.path.join("parallel", "trainer.py"),
    os.path.join("telemetry", "prof.py"),
    "compile",
)

_PRAGMA = "mxlint: disable"

#: attribute calls that are a device->host sync (or a fence) by name
_SYNC_ATTRS = frozenset(("asnumpy", "item", "tolist", "block_until_ready"))
#: module roots whose ``.asarray`` is a host materialization (jnp is
#: device-side and deliberately absent)
_NP_ROOTS = frozenset(("np", "numpy", "_np", "onp"))
#: builtins that force a host scalar out of a device value
_HOST_CASTS = frozenset(("float", "int", "bool"))
#: method names that dispatch a jitted program on any receiver
_DISPATCH_HINT_ANY = frozenset(("run_chunk", "draft_turn", "verify"))
#: method names that dispatch only on model/executor-ish receivers
#: (``step``/``forward`` are too generic to hint on every object)
_DISPATCH_HINT_RECV = frozenset(("step", "forward", "backward"))
_DISPATCH_RECEIVERS = frozenset(("model", "draft_model", "exe", "exec",
                                 "_exec", "executor", "trainer", "m"))
#: argument names that look like steady-state device pools/state — the
#: un-donated-loop warning's heuristic surface
_POOLISH = frozenset(("params", "opt_state", "opt_states"))

_LOOPS = (ast.For, ast.While, ast.AsyncFor)
_FUNCS = (ast.FunctionDef, ast.AsyncFunctionDef)


# -- small AST helpers ---------------------------------------------------------

def _parent_links(tree):
    for node in ast.walk(tree):
        for child in ast.iter_child_nodes(node):
            child._mxjit_p = node


def _ancestors(node):
    while getattr(node, "_mxjit_p", None) is not None:
        node = node._mxjit_p
        yield node


def _dotted(node):
    """'a.b.c' for a Name/Attribute chain, else None."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _names_in(node):
    return {n.id for n in ast.walk(node) if isinstance(n, ast.Name)}


def _has_call_to(node, names):
    for n in ast.walk(node):
        if isinstance(n, ast.Call):
            d = _dotted(n.func)
            if d is not None and d.split(".")[-1] in names:
                return True
    return False


def _stmt_of(node):
    """The statement containing ``node`` (for ordering comparisons)."""
    cur = node
    for anc in _ancestors(node):
        if isinstance(anc, (ast.stmt, ast.Module)):
            if isinstance(anc, ast.Module):
                return cur
            return anc
        cur = anc
    return cur


class _Pragmas:
    def __init__(self, src):
        self.lines = src.splitlines()

    def __contains__(self, lineno):
        if 1 <= lineno <= len(self.lines):
            return _PRAGMA in self.lines[lineno - 1]
        return False


# -- module model --------------------------------------------------------------

class _JitInfo:
    """What analysis knows about one compiled-program handle."""

    __slots__ = ("node", "donated", "conditional", "has_donate",
                 "traced", "builder")

    def __init__(self, node, donated=(), conditional=False,
                 has_donate=False, traced=None, builder=None):
        self.node = node              # the jax.jit Call
        self.donated = tuple(donated)
        self.conditional = conditional
        self.has_donate = has_donate
        self.traced = traced          # expr passed to jax.jit
        self.builder = builder        # enclosing FunctionDef


class _Module:
    def __init__(self, tree, relpath, src):
        self.tree = tree
        self.relpath = relpath
        self.pragmas = _Pragmas(src)
        self.funcs = {}          # qualname -> FunctionDef
        self.func_of = {}        # FunctionDef -> qualname
        self.classes = {}        # name -> ClassDef
        self.jit_memos = {}      # dotted memo path -> _JitInfo
        self.jitted_paths = {}   # dotted attr path -> _JitInfo
        self.returns_jitted = {}  # qualname -> _JitInfo
        self.creations = []      # (_JitInfo, loop_depth, guarded)
        self.class_attr_writers = {}   # class -> {attr: set(method names)}
        self.class_creators = {}       # class -> {method names w/ jax.jit}
        self.str_dicts = {}      # class-level {const: method-name} dicts
        _parent_links(tree)
        self._index()
        self._collect_jits()

    # -- indexing -------------------------------------------------------------
    def _index(self):
        for node in ast.walk(self.tree):
            if isinstance(node, ast.ClassDef):
                self.classes[node.name] = node
                writers, creators, sdicts = {}, set(), {}
                for item in node.body:
                    if isinstance(item, _FUNCS):
                        for sub in ast.walk(item):
                            if (isinstance(sub, ast.Attribute)
                                    and isinstance(sub.ctx, ast.Store)
                                    and isinstance(sub.value, ast.Name)
                                    and sub.value.id == "self"):
                                writers.setdefault(sub.attr,
                                                   set()).add(item.name)
                            if (isinstance(sub, ast.Call)
                                    and _is_jax_jit(sub)):
                                creators.add(item.name)
                    elif isinstance(item, ast.Assign):
                        # class-level {"kind": "_impl_method"} tables
                        if (isinstance(item.value, ast.Dict)
                                and len(item.targets) == 1
                                and isinstance(item.targets[0], ast.Name)):
                            vals = [v.value for v in item.value.values
                                    if isinstance(v, ast.Constant)
                                    and isinstance(v.value, str)]
                            if vals and len(vals) == len(item.value.values):
                                sdicts[item.targets[0].id] = vals
                self.class_attr_writers[node] = writers
                self.class_creators[node] = creators
                self.str_dicts.update(
                    {(node.name, k): v for k, v in sdicts.items()})
            elif isinstance(node, _FUNCS):
                qual = self._qualname(node)
                self.funcs[qual] = node
                self.func_of[node] = qual

    def _qualname(self, fn):
        parts = [fn.name]
        for anc in _ancestors(fn):
            if isinstance(anc, ast.ClassDef):
                parts.append(anc.name)
            elif isinstance(anc, _FUNCS):
                parts.append(anc.name)
        return ".".join(reversed(parts))

    def enclosing_func(self, node):
        for anc in _ancestors(node):
            if isinstance(anc, _FUNCS):
                return anc
        return None

    def enclosing_class(self, node):
        for anc in _ancestors(node):
            if isinstance(anc, ast.ClassDef):
                return anc
        return None

    def loop_depth(self, node, stop=None):
        d = 0
        for anc in _ancestors(node):
            if anc is stop:
                break
            if isinstance(anc, _LOOPS):
                d += 1
            if isinstance(anc, _FUNCS):
                break
        return d

    # -- jit creation + linkage ------------------------------------------------
    def _collect_jits(self):
        # first sweep: every jax.jit call, its donation spec, and every
        # direct target (name / attribute / memo subscript / return)
        local_jitted = {}  # (func, name) -> _JitInfo
        for node in ast.walk(self.tree):
            if not (isinstance(node, ast.Call) and _is_jax_jit(node)):
                continue
            fn = self.enclosing_func(node)
            donated, conditional, has_donate = _donation_spec(node, fn)
            info = _JitInfo(node, donated, conditional, has_donate,
                            traced=node.args[0] if node.args else None,
                            builder=fn)
            guarded = _memo_guarded(node)
            self.creations.append((info, self.loop_depth(node, stop=fn),
                                   guarded))
            self._record_target(node, info, fn, local_jitted)
        # second sweep: names assigned FROM jit memos / builder methods
        # become jitted handles too (fn = self._compiled(key)), and
        # builder-call results stored into memos link the memo to the
        # builder's jit info (self._jit_cache[K] = self._make_loop(K))
        for node in ast.walk(self.tree):
            if not isinstance(node, ast.Assign):
                continue
            info = self._jitinfo_of_expr(node.value)
            if info is None:
                continue
            for tgt in node.targets:
                d = _dotted(tgt)
                if d is not None:
                    self.jitted_paths.setdefault(d, info)
                elif isinstance(tgt, ast.Subscript):
                    base = _dotted(tgt.value)
                    if base is not None:
                        self.jit_memos.setdefault(base, info)

    def _record_target(self, call, info, fn, local_jitted):
        parent = getattr(call, "_mxjit_p", None)
        if isinstance(parent, ast.Assign):
            for tgt in parent.targets:
                if isinstance(tgt, ast.Subscript):
                    base = _dotted(tgt.value)
                    if base is not None:
                        self.jit_memos[base] = info
                else:
                    d = _dotted(tgt)
                    if d is not None:
                        self.jitted_paths[d] = info
                        if fn is not None and isinstance(tgt, ast.Name):
                            local_jitted[(fn, tgt.id)] = info
        elif isinstance(parent, ast.Return) and fn is not None:
            self.returns_jitted[self.func_of[fn]] = info
        # fn = jax.jit(...); self._jitted[key] = fn; return fn
        if fn is not None:
            self._propagate_local(fn, local_jitted)

    def _propagate_local(self, fn, local_jitted):
        for node in ast.walk(fn):
            if isinstance(node, ast.Assign) and isinstance(node.value,
                                                           ast.Name):
                info = local_jitted.get((fn, node.value.id))
                if info is None:
                    continue
                for tgt in node.targets:
                    if isinstance(tgt, ast.Subscript):
                        base = _dotted(tgt.value)
                        if base is not None:
                            self.jit_memos.setdefault(base, info)
                    else:
                        d = _dotted(tgt)
                        if d is not None:
                            self.jitted_paths.setdefault(d, info)
            elif (isinstance(node, ast.Return)
                    and isinstance(node.value, ast.Name)):
                info = local_jitted.get((fn, node.value.id))
                if info is not None:
                    self.returns_jitted.setdefault(self.func_of[fn], info)

    def _jitinfo_of_expr(self, expr):
        """_JitInfo when ``expr`` evaluates to a jitted handle: a memo
        read, a jitted attr path, or a builder-method call."""
        if isinstance(expr, ast.Subscript):
            base = _dotted(expr.value)
            if base in self.jit_memos:
                return self.jit_memos[base]
        d = _dotted(expr)
        if d in self.jitted_paths:
            return self.jitted_paths[d]
        if isinstance(expr, ast.Call):
            cd = _dotted(expr.func)
            if cd is not None:
                tail = cd.split(".")[-1]
                for qual, info in self.returns_jitted.items():
                    if qual.split(".")[-1] == tail:
                        return info
            # memo.get(key)
            if (isinstance(expr.func, ast.Attribute)
                    and expr.func.attr == "get"):
                base = _dotted(expr.func.value)
                if base in self.jit_memos:
                    return self.jit_memos[base]
        return None

    def dispatch_info(self, call):
        """_JitInfo when ``call`` dispatches a linkable jitted handle."""
        func = call.func
        if isinstance(func, ast.Subscript):
            base = _dotted(func.value)
            if base in self.jit_memos:
                return self.jit_memos[base]
            return None
        d = _dotted(func)
        if d is None:
            return None
        if d in self.jitted_paths:
            return self.jitted_paths[d]
        fn = self.enclosing_func(call)
        if fn is not None and isinstance(func, ast.Name):
            # a local rebound from a memo/builder earlier in the function
            for node in ast.walk(fn):
                if (isinstance(node, ast.Assign)
                        and any(isinstance(t, ast.Name) and t.id == func.id
                                for t in node.targets)):
                    info = self._jitinfo_of_expr(node.value)
                    if info is not None:
                        return info
        return None


def _is_jax_jit(call):
    d = _dotted(call.func)
    return d in ("jax.jit", "jit") and bool(call.args)


def _tuple_ints(node):
    if isinstance(node, ast.Tuple):
        out = []
        for e in node.elts:
            if isinstance(e, ast.Constant) and isinstance(e.value, int):
                out.append(e.value)
            else:
                return None
        return tuple(out)
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        return (node.value,)
    return None


def _donation_spec(call, fn):
    """(donated positions, conditional?, donate kwarg present?) for a
    jax.jit call — resolving the repo's PR 6 carve-out ternary
    (``() if jit_cache.donation_unsafe() else (1, 2)``) to the donating
    branch: on TPU the buffers ARE donated."""
    kw = next((k for k in call.keywords if k.arg == "donate_argnums"), None)
    if kw is None:
        return (), False, False
    node = kw.value
    if isinstance(node, ast.Name) and fn is not None:
        for n in ast.walk(fn):
            if (isinstance(n, ast.Assign)
                    and any(isinstance(t, ast.Name) and t.id == node.id
                            for t in n.targets)):
                node = n.value
                break
    if isinstance(node, ast.IfExp):
        body = _tuple_ints(node.body) or ()
        orelse = _tuple_ints(node.orelse) or ()
        chosen = body if len(body) >= len(orelse) else orelse
        return chosen, True, True
    got = _tuple_ints(node)
    return (got or ()), False, True


def _memo_guarded(call):
    """True when a jax.jit call's result is memoized: stored under a
    subscript, or built inside an ``if key not in cache`` /
    ``if fn is None`` (post-``cache.get``) guard."""
    parent = getattr(call, "_mxjit_p", None)
    if isinstance(parent, ast.Assign) and any(
            isinstance(t, ast.Subscript) for t in parent.targets):
        return True
    for anc in _ancestors(call):
        if isinstance(anc, ast.If):
            for cmp_ in ast.walk(anc.test):
                if isinstance(cmp_, ast.Compare) and any(
                        isinstance(op, (ast.NotIn, ast.Is))
                        for op in cmp_.ops):
                    return True
        if isinstance(anc, _FUNCS):
            break
    return False


# -- detector: recompile-hazard ------------------------------------------------

def _detect_recompile(mod, findings):
    for info, depth, guarded in mod.creations:
        node = info.node
        if node.lineno in mod.pragmas:
            continue
        if depth > 0 and not guarded:
            findings.append(Finding(
                "jit", "recompile-hazard", "error",
                "%s:%d" % (mod.relpath, node.lineno),
                "jax.jit built inside a steady-state loop with no memo "
                "guard — every iteration traces and compiles a fresh "
                "program; hoist it or memoize under the loop's static "
                "key (the compile-count-per-bucket contract)"))
    # raw-shape taint per function: .shape-derived values must pass
    # through bucket_for before touching a memo key or traced closure
    for fn in mod.func_of:
        _shape_taint_func(mod, fn, findings)


def _shape_taint_func(mod, fn, findings):
    tainted = set()
    for node in ast.walk(fn):
        if not isinstance(node, ast.Assign):
            continue
        val = node.value
        from_shape = any(
            isinstance(s, ast.Attribute) and s.attr == "shape"
            for s in ast.walk(val))
        laundered = _has_call_to(val, ("bucket_for",))
        refs_taint = bool(_names_in(val) & tainted)
        for tgt in node.targets:
            names = ([tgt.id] if isinstance(tgt, ast.Name)
                     else [e.id for e in tgt.elts
                           if isinstance(e, ast.Name)]
                     if isinstance(tgt, ast.Tuple) else [])
            for nm in names:
                if laundered:
                    tainted.discard(nm)
                elif from_shape or refs_taint:
                    tainted.add(nm)
    if not tainted:
        return
    for node in ast.walk(fn):
        hit = None
        if isinstance(node, ast.Subscript) and _dotted(node.value) in \
                mod.jit_memos:
            bad = _names_in(node.slice) & tainted
            if bad:
                hit = ("jit-memo key", bad)
        elif isinstance(node, ast.Call) and _is_jax_jit(node):
            bad = set()
            for arg in node.args + [k.value for k in node.keywords
                                    if k.arg != "donate_argnums"]:
                bad |= _names_in(arg) & tainted
            if bad:
                hit = ("traced closure", bad)
        if hit is None or node.lineno in mod.pragmas:
            continue
        kind, bad = hit
        findings.append(Finding(
            "jit", "recompile-hazard", "error",
            "%s:%d" % (mod.relpath, node.lineno),
            "raw runtime shape %s reaches the %s in %s without passing "
            "through bucket_for — every distinct batch shape compiles a "
            "new program instead of hitting its bucket"
            % (sorted(bad), kind, mod.func_of[fn])))


# -- detector: donation-hazard -------------------------------------------------

def _positional_args(mod, call):
    """Resolved positional args (Starred *args expanded when the tuple
    is a visible local assignment)."""
    out = []
    fn = mod.enclosing_func(call)
    for arg in call.args:
        if isinstance(arg, ast.Starred):
            elts = None
            if isinstance(arg.value, ast.Name) and fn is not None:
                for node in ast.walk(fn):
                    if (isinstance(node, ast.Assign)
                            and any(isinstance(t, ast.Name)
                                    and t.id == arg.value.id
                                    for t in node.targets)
                            and isinstance(node.value, ast.Tuple)):
                        elts = node.value.elts
            if elts is None:
                return None  # opaque *args: give up on positions
            out.extend(elts)
        else:
            out.append(arg)
    return out


def _detect_donation(mod, findings):
    for call in ast.walk(mod.tree):
        if not isinstance(call, ast.Call):
            continue
        info = mod.dispatch_info(call)
        if info is None or call.lineno in mod.pragmas:
            continue
        fn = mod.enclosing_func(call)
        args = _positional_args(mod, call)
        in_loop = mod.loop_depth(call, stop=fn) > 0
        if info.donated and args is not None:
            stmt = _stmt_of(call)
            rebound = _rebound_targets(stmt, call)
            for pos in info.donated:
                if pos >= len(args):
                    continue
                path = _dotted(args[pos])
                if path is None or path in rebound:
                    continue
                use = _read_after(fn, stmt, path)
                if use is not None:
                    findings.append(Finding(
                        "jit", "donation-hazard", "error",
                        "%s:%d" % (mod.relpath, use.lineno),
                        "%r is read after being DONATED (argnum %d) to "
                        "the dispatch at line %d — the executable owns "
                        "that buffer now; thread the returned array "
                        "back instead (use-after-donate)"
                        % (path, pos, call.lineno)))
                elif in_loop and not _stored_in_loop(call, path, fn):
                    findings.append(Finding(
                        "jit", "donation-hazard", "error",
                        "%s:%d" % (mod.relpath, call.lineno),
                        "loop re-dispatches with %r at donated argnum "
                        "%d without rebinding it from the result — the "
                        "second iteration passes a buffer the first "
                        "donated away (thread it through, the "
                        "pool.swap discipline)" % (path, pos)))
        elif (not info.has_donate and in_loop and args is not None):
            poolish = sorted(
                p for p in (_dotted(a) for a in args) if p is not None
                and (p.split(".")[-1] in _POOLISH
                     or "pool" in p.split(".")[-1].lower()))
            if poolish:
                findings.append(Finding(
                    "jit", "donation-hazard", "warning",
                    "%s:%d" % (mod.relpath, call.lineno),
                    "steady-state loop dispatches %s through a program "
                    "built with no donate_argnums — every step pays a "
                    "device-side copy donation would elide (gate the "
                    "carve-out with jit_cache.donation_unsafe() if CPU "
                    "cache safety is the concern)" % (poolish,)))


def _rebound_targets(stmt, call):
    """Dotted paths rebound by the very statement holding the dispatch
    (the donation-safe caller pattern: outputs replace inputs)."""
    out = set()
    if isinstance(stmt, ast.Assign) and _contains(stmt.value, call):
        for tgt in stmt.targets:
            todo = tgt.elts if isinstance(tgt, ast.Tuple) else [tgt]
            for t in todo:
                d = _dotted(t)
                if d is not None:
                    out.add(d)
    return out


def _contains(root, node):
    return any(n is node for n in ast.walk(root))


def _read_after(fn, stmt, path):
    """First Load of ``path`` after ``stmt`` (and before any re-store)
    inside ``fn``; None when it is stored first or never touched."""
    if fn is None:
        return None
    after = (stmt.end_lineno or stmt.lineno, getattr(stmt, "end_col_offset",
                                                     0) or 0)
    first_load = first_store = None
    for node in ast.walk(fn):
        pos = (getattr(node, "lineno", 0), getattr(node, "col_offset", 0))
        if pos <= after:
            continue
        d = _dotted(node) if isinstance(node, (ast.Name,
                                               ast.Attribute)) else None
        if d != path:
            continue
        is_store = isinstance(getattr(node, "ctx", None), ast.Store)
        if is_store:
            if first_store is None or pos < first_store[0]:
                first_store = (pos, node)
        else:
            # skip the chain interior of a longer dotted store
            anc = getattr(node, "_mxjit_p", None)
            if isinstance(anc, ast.Attribute) and isinstance(
                    getattr(anc, "ctx", None), ast.Store):
                continue
            if first_load is None or pos < first_load[0]:
                first_load = (pos, node)
    if first_load is None:
        return None
    if first_store is not None and first_store[0] < first_load[0]:
        return None
    return first_load[1]


def _stored_in_loop(call, path, fn):
    loop = None
    for anc in _ancestors(call):
        if isinstance(anc, _LOOPS):
            loop = anc
            break
        if isinstance(anc, _FUNCS):
            break
    if loop is None:
        return True
    for node in ast.walk(loop):
        if isinstance(node, (ast.Name, ast.Attribute)) and isinstance(
                getattr(node, "ctx", None), ast.Store):
            if _dotted(node) == path:
                return True
    return False


# -- detector: hot-d2h ---------------------------------------------------------

def _is_dispatch_hint(mod, call):
    if mod.dispatch_info(call) is not None:
        return True
    if isinstance(call.func, ast.Attribute):
        attr = call.func.attr
        if attr in _DISPATCH_HINT_ANY:
            return True
        if attr in _DISPATCH_HINT_RECV:
            recv = _dotted(call.func.value)
            if recv is not None and recv.split(".")[-1] in \
                    _DISPATCH_RECEIVERS:
                return True
    return False


def _dispatcher_funcs(mod):
    """Functions that (transitively, same module) dispatch a program."""
    direct = set()
    for qual, fn in mod.funcs.items():
        for node in ast.walk(fn):
            if isinstance(node, ast.Call) and _is_dispatch_hint(mod, node):
                direct.add(qual)
                break
    # fixpoint over same-module calls by trailing name
    tails = {q.split(".")[-1]: q for q in mod.funcs}
    changed = True
    while changed:
        changed = False
        for qual, fn in mod.funcs.items():
            if qual in direct:
                continue
            for node in ast.walk(fn):
                if not isinstance(node, ast.Call):
                    continue
                d = _dotted(node.func)
                if d is None:
                    continue
                callee = tails.get(d.split(".")[-1])
                if callee in direct:
                    direct.add(qual)
                    changed = True
                    break
    return direct


def _hot_regions(mod):
    """(hot loops, hot functions): loops that dispatch, plus functions
    reachable from them within the module (depth-limited — a drain
    helper two calls away still belongs to the per-step loop)."""
    dispatchers = _dispatcher_funcs(mod)
    tails = {q.split(".")[-1]: q for q in mod.funcs}
    hot_loops = []
    seeds = set()
    for node in ast.walk(mod.tree):
        if not isinstance(node, _LOOPS):
            continue
        for sub in ast.walk(node):
            called = None
            if isinstance(sub, ast.Call):
                if _is_dispatch_hint(mod, sub):
                    hot_loops.append(node)
                    break
                d = _dotted(sub.func)
                called = d and tails.get(d.split(".")[-1])
            if called in dispatchers:
                hot_loops.append(node)
                break
    for loop in hot_loops:
        for sub in ast.walk(loop):
            if isinstance(sub, ast.Call):
                d = _dotted(sub.func)
                q = d and tails.get(d.split(".")[-1])
                if q:
                    seeds.add(q)
    hot_funcs = set(seeds)
    frontier = set(seeds)
    for _ in range(2):  # bounded call-through escalation
        nxt = set()
        for qual in frontier:
            for node in ast.walk(mod.funcs[qual]):
                if isinstance(node, ast.Call):
                    d = _dotted(node.func)
                    q = d and tails.get(d.split(".")[-1])
                    if q and q not in hot_funcs:
                        nxt.add(q)
        hot_funcs |= nxt
        frontier = nxt
    return hot_loops, hot_funcs


def _sync_call(mod, call, device_tainted):
    """Short sync label when ``call`` is a device->host sync."""
    f = call.func
    if isinstance(f, ast.Attribute) and f.attr in _SYNC_ATTRS:
        return ".%s()" % f.attr
    d = _dotted(f)
    if d is not None:
        parts = d.split(".")
        if parts[-1] == "asarray" and parts[0] in _NP_ROOTS:
            # np.asarray over a Python list/scalar literal is H2D
            # staging, not a sync; only a device-flowing argument
            # (dispatch-result taint, or the _dev naming convention)
            # makes it a D2H pull
            if not call.args:
                return None
            arg = call.args[0]
            names = _names_in(arg)
            if names & device_tainted or any(
                    n.endswith("_dev") for n in names):
                return "np.asarray"
            # instance device state: self.params / pool attrs are
            # resident arrays, pulling them is a real transfer
            for sub in ast.walk(arg):
                if isinstance(sub, ast.Attribute) and (
                        sub.attr in ("params", "draft_params")
                        or "pool" in sub.attr):
                    return "np.asarray"
            return None
        if d == "jax.device_get":
            return "jax.device_get"
        if d in _HOST_CASTS and call.args:
            if _names_in(call.args[0]) & device_tainted:
                return "%s()" % d
    return None


def _fence_names(fn):
    """Names assigned via the one-fence-per-chunk idiom:
    ``bur = getattr(o, "block_until_ready", None)`` — the module's
    explicit marker that the next pull is the chunk's single fence."""
    names, linenos = set(), []
    for node in ast.walk(fn):
        if (isinstance(node, ast.Assign)
                and isinstance(node.value, ast.Call)
                and _dotted(node.value.func) == "getattr"
                and len(node.value.args) >= 2
                and isinstance(node.value.args[1], ast.Constant)
                and node.value.args[1].value == "block_until_ready"):
            for t in node.targets:
                if isinstance(t, ast.Name):
                    names.add(t.id)
                    linenos.append(node.lineno)
    return names, linenos


def _prof_guarded(node):
    for anc in _ancestors(node):
        if isinstance(anc, ast.If):
            test_names = {n.attr for n in ast.walk(anc.test)
                          if isinstance(anc.test, ast.AST)
                          and isinstance(n, ast.Attribute)}
            test_names |= _names_in(anc.test)
            if test_names & {"ENABLED", "prof_on", "enabled", "prof_ctx",
                             "prof_t"}:
                return True
        if isinstance(anc, _FUNCS):
            break
    return False


def _detect_hot_d2h(mod, findings, sanctioned):
    hot_loops, hot_funcs = _hot_regions(mod)
    seen = set()
    for fn_qual in sorted(set(hot_funcs) | {
            mod.func_of[mod.enclosing_func(lp)]
            for lp in hot_loops if mod.enclosing_func(lp) is not None}):
        fn = mod.funcs[fn_qual]
        fences, fence_lines = _fence_names(fn)
        device_tainted = _device_tainted(mod, fn)
        for call in ast.walk(fn):
            if not isinstance(call, ast.Call) or id(call) in seen:
                continue
            label = _sync_call(mod, call, device_tainted)
            if label is None:
                continue
            in_hot_loop = any(_contains(lp, call) for lp in hot_loops)
            if not in_hot_loop and fn_qual not in hot_funcs:
                continue
            seen.add(id(call))
            site = "%s::%s" % (mod.relpath, fn_qual)
            if call.lineno in mod.pragmas:
                sanctioned[site] = call.lineno
                continue
            # fence-idiom call: bur() where bur came from the getattr
            if (isinstance(call.func, ast.Name)
                    and call.func.id in fences):
                sanctioned[site] = call.lineno
                findings.append(Finding(
                    "jit", "hot-d2h", "info",
                    "%s:%d" % (mod.relpath, call.lineno),
                    "one-fence-per-chunk fence in %s (sanctioned)"
                    % fn_qual))
                continue
            if _prof_guarded(call):
                sanctioned[site] = call.lineno
                findings.append(Finding(
                    "jit", "hot-d2h", "info",
                    "%s:%d" % (mod.relpath, call.lineno),
                    "%s under a profiling/telemetry ENABLED guard in %s "
                    "(off-by-default, sanctioned)" % (label, fn_qual)))
                continue
            if (label in ("np.asarray", ".asnumpy()") and fence_lines
                    and min(fence_lines) < call.lineno):
                sanctioned[site] = call.lineno
                findings.append(Finding(
                    "jit", "hot-d2h", "info",
                    "%s:%d" % (mod.relpath, call.lineno),
                    "post-fence chunk pull in %s — one D2H per drained "
                    "chunk (sanctioned)" % fn_qual))
                continue
            where_note = ("inside the per-step loop"
                          if in_hot_loop else
                          "in %s, called from a per-step loop" % fn_qual)
            findings.append(Finding(
                "jit", "hot-d2h", "error",
                "%s:%d" % (mod.relpath, call.lineno),
                "%s %s — a device->host sync on the hot path stalls "
                "the dispatch pipeline every step; keep it on device, "
                "batch it behind the chunk fence, or pragma a "
                "deliberate accounted pull" % (label, where_note)))


def _device_tainted(mod, fn):
    """Names holding device values: dispatch results, closed over
    simple flow (``n = fix(n_dev)`` keeps the taint)."""
    out = set()
    changed = True
    while changed:
        changed = False
        for node in ast.walk(fn):
            if not isinstance(node, ast.Assign):
                continue
            val = node.value
            is_disp = (isinstance(val, ast.Call)
                       and _is_dispatch_hint(mod, val))
            if not is_disp:
                # a host-materializing call (asarray/.item()/float())
                # ENDS the taint: its result lives on the host
                if isinstance(val, ast.Call):
                    d = _dotted(val.func)
                    tail = d.split(".")[-1] if d else ""
                    if (tail in ("asarray", "device_get", "item",
                                 "tolist", "asnumpy")
                            or d in _HOST_CASTS):
                        continue
                if not (_names_in(val) & out):
                    continue
            for tgt in node.targets:
                todo = tgt.elts if isinstance(tgt, ast.Tuple) else [tgt]
                for t in todo:
                    if isinstance(t, ast.Name) and t.id not in out:
                        out.add(t.id)
                        changed = True
    return out


# -- detector: weak-cache-key --------------------------------------------------

def _detect_weak_key(mod, findings):
    # mechanical half: attribute_jit without graph_key — the exact
    # shape-only aliasing hole PR 13 patched
    for call in ast.walk(mod.tree):
        if not isinstance(call, ast.Call):
            continue
        d = _dotted(call.func)
        if d is None or d.split(".")[-1] != "attribute_jit":
            continue
        if call.lineno in mod.pragmas:
            continue
        if not any(k.arg == "graph_key" for k in call.keywords):
            findings.append(Finding(
                "jit", "weak-cache-key", "error",
                "%s:%d" % (mod.relpath, call.lineno),
                "attribute_jit called without graph_key= — a shape-only "
                "attribution key aliases different graphs at equal "
                "shapes (the PR 13 bug class); fold a graph_hash of the "
                "program's structural identity into the key"))
    # closure half: builder inputs reaching the traced body must be
    # folded into the memo key
    for info, _depth, _guarded in mod.creations:
        _check_closure_key(mod, info, findings)


def _key_expr_for(mod, info):
    """The memo-key expression(s) + builder-call arg mapping for a jit
    creation: the store site in the builder itself, or a caller storing
    the builder's return into a memo."""
    keys = []
    fn = info.builder
    if fn is not None:
        jit_names = set()
        for node in ast.walk(fn):
            if not isinstance(node, ast.Assign):
                continue
            # only stores whose VALUE is this jitted program (directly,
            # or via a local bound from it) are memo-key sites — an
            # arbitrary ``d[k] = v`` in the builder is not a cache
            is_this = (node.value is info.node
                       or (isinstance(node.value, ast.Name)
                           and node.value.id in jit_names))
            if node.value is info.node:
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        jit_names.add(t.id)
            if not is_this:
                continue
            for tgt in node.targets:
                if isinstance(tgt, ast.Subscript):
                    keys.append((node, tgt.slice, None))
    if fn is not None and mod.func_of.get(fn) in mod.returns_jitted:
        tail = mod.func_of[fn].split(".")[-1]
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Assign):
                continue
            val = node.value
            if not (isinstance(val, ast.Call) and _dotted(val.func)
                    and _dotted(val.func).split(".")[-1] == tail):
                continue
            for tgt in node.targets:
                if isinstance(tgt, ast.Subscript):
                    keys.append((node, tgt.slice, val))
    return keys


def _key_derived(fn, key_slice):
    """Names in the key expr, closed over simple rebindings
    (``kind, B, C = key`` makes all three key-derived)."""
    derived = _names_in(key_slice)
    changed = True
    while changed:
        changed = False
        for node in ast.walk(fn):
            if not isinstance(node, ast.Assign):
                continue
            if not (_names_in(node.value) and
                    _names_in(node.value) <= derived | {"self"}):
                continue
            for tgt in node.targets:
                todo = (tgt.elts if isinstance(tgt, ast.Tuple) else [tgt])
                for t in todo:
                    if isinstance(t, ast.Name) and t.id not in derived:
                        derived.add(t.id)
                        changed = True
    return derived


def _traced_bodies(mod, info):
    """AST bodies jax.jit will trace for this creation: a lambda, a
    nested def, or class methods (incl. the class-level kind->method
    string-table indirection)."""
    expr = info.traced
    fn = info.builder
    bodies = []
    bound_names = set()

    def resolve(e):
        if isinstance(e, ast.Lambda):
            bodies.append(e)
        elif isinstance(e, ast.Call):
            d = _dotted(e.func)
            if d is not None and d.split(".")[-1] == "partial" and e.args:
                resolve(e.args[0])
                for a in e.args[1:]:
                    bound_names.update(_names_in(a))
                for k in e.keywords:
                    bound_names.update(_names_in(k.value))
            elif d == "getattr" and len(e.args) >= 2:
                cls = mod.enclosing_class(info.node)
                arg = e.args[1]
                if (cls is not None and isinstance(arg, ast.Subscript)):
                    base = _dotted(arg.value)
                    if base is not None:
                        names = mod.str_dicts.get(
                            (cls.name, base.split(".")[-1]), [])
                        for mname in names:
                            m = mod.funcs.get("%s.%s" % (cls.name, mname))
                            if m is not None:
                                bodies.append(m)
        elif isinstance(e, ast.Name):
            if fn is not None:
                for node in ast.walk(fn):
                    if isinstance(node, _FUNCS) and node.name == e.id:
                        bodies.append(node)
                        return
                for node in ast.walk(fn):
                    if (isinstance(node, ast.Assign)
                            and any(isinstance(t, ast.Name)
                                    and t.id == e.id
                                    for t in node.targets)):
                        resolve(node.value)
                        return
        elif isinstance(e, ast.Attribute):
            d = _dotted(e)
            cls = mod.enclosing_class(info.node)
            if (d is not None and d.startswith("self.")
                    and cls is not None):
                m = mod.funcs.get("%s.%s" % (cls.name, d[5:]))
                if m is not None:
                    bodies.append(m)

    if expr is not None:
        resolve(expr)
    return bodies, bound_names


def _check_closure_key(mod, info, findings):
    fn = info.builder
    if fn is None or info.node.lineno in mod.pragmas:
        return
    keys = _key_expr_for(mod, info)
    if not keys:
        return  # no memo: a build-once program has no key to weaken
    bodies, bound = _traced_bodies(mod, info)
    if not bodies:
        return
    params = {a.arg for a in fn.args.args if a.arg != "self"}
    params |= {a.arg for a in fn.args.kwonlyargs}
    free_reads = set()
    for body in bodies:
        own = set()
        if isinstance(body, _FUNCS):
            own = {a.arg for a in body.args.args} | {
                a.arg for a in body.args.kwonlyargs}
            if body.args.vararg:
                own.add(body.args.vararg.arg)
        elif isinstance(body, ast.Lambda):
            own = {a.arg for a in body.args.args}
        for node in ast.walk(body):
            if (isinstance(node, ast.Name)
                    and isinstance(node.ctx, ast.Load)
                    and node.id in params and node.id not in own):
                free_reads.add(node.id)
    free_reads |= (bound & params)
    if not free_reads:
        _check_self_reads(mod, info, bodies, keys, findings)
        return
    for store, key_slice, builder_call in keys:
        derived = _key_derived(fn, key_slice)
        if builder_call is not None:
            # caller maps builder params -> arg exprs: a param is keyed
            # when its arg expression shares a name with the key
            keyed = set()
            pnames = [a.arg for a in fn.args.args if a.arg != "self"]
            for i, a in enumerate(builder_call.args):
                if i < len(pnames) and (_names_in(a) & derived):
                    keyed.add(pnames[i])
            derived = derived | keyed
        leaked = sorted(free_reads - derived)
        if leaked:
            findings.append(Finding(
                "jit", "weak-cache-key", "error",
                "%s:%d" % (mod.relpath, info.node.lineno),
                "config input(s) %s reach the traced program body but "
                "are not folded into the jit-cache key at line %d — two "
                "different configurations alias one compiled program "
                "(the PR 13/15 bug class); fold them into the key or "
                "the graph hash" % (leaked, store.lineno)))
    _check_self_reads(mod, info, bodies, keys, findings)


def _check_self_reads(mod, info, bodies, keys, findings):
    cls = mod.enclosing_class(info.node)
    if cls is None:
        return
    writers = mod.class_attr_writers.get(cls, {})
    creators = mod.class_creators.get(cls, set())
    key_names = set()
    for _store, key_slice, _bc in keys:
        key_names |= _names_in(key_slice)
        for n in ast.walk(key_slice):
            if isinstance(n, ast.Attribute):
                key_names.add(n.attr)
    mutable_reads = set()
    for body in bodies:
        for node in ast.walk(body):
            if (isinstance(node, ast.Attribute)
                    and isinstance(node.ctx, ast.Load)
                    and isinstance(node.value, ast.Name)
                    and node.value.id == "self"):
                who = writers.get(node.attr, set())
                if (who - {"__init__"} - creators
                        and node.attr not in key_names):
                    mutable_reads.add(node.attr)
    if mutable_reads and info.node.lineno not in mod.pragmas:
        findings.append(Finding(
            "jit", "weak-cache-key", "error",
            "%s:%d" % (mod.relpath, info.node.lineno),
            "traced body reads mutable instance config %s (reassigned "
            "outside __init__) without folding it into the jit-cache "
            "key — the program bakes a stale value and never recompiles "
            "when it changes" % sorted(mutable_reads)))


# -- public API ----------------------------------------------------------------

def lint_source(src, relpath="<string>", _sanctioned=None):
    tree = ast.parse(src)
    mod = _Module(tree, relpath, src)
    findings = []
    sanctioned = {} if _sanctioned is None else _sanctioned
    _detect_recompile(mod, findings)
    _detect_donation(mod, findings)
    _detect_hot_d2h(mod, findings, sanctioned)
    _detect_weak_key(mod, findings)
    return findings


def lint_file(path, root=None, _sanctioned=None):
    root = root or os.path.dirname(DEFAULT_PACKAGE)
    with open(path, "r", encoding="utf-8") as f:
        src = f.read()
    rel = os.path.relpath(path, root)
    return lint_source(src, rel, _sanctioned=_sanctioned)


def _iter_targets(path):
    if os.path.isfile(path):
        yield path
        return
    for dirpath, dirs, files in os.walk(path):
        dirs[:] = [d for d in dirs if d != "__pycache__"]
        for fname in sorted(files):
            if fname.endswith(".py"):
                yield os.path.join(dirpath, fname)


def lint_targets(path=None, _sanctioned=None):
    """Lint ``path`` (file or dir), or the DEFAULT_TARGETS surface of
    the package when None — the clean-repo gate entry point."""
    findings = []
    if path:
        for p in _iter_targets(path):
            findings.extend(lint_file(p, _sanctioned=_sanctioned))
        return findings
    for rel in DEFAULT_TARGETS:
        p = os.path.join(DEFAULT_PACKAGE, rel)
        if not os.path.exists(p):
            continue
        for f in _iter_targets(p):
            findings.extend(lint_file(f, _sanctioned=_sanctioned))
    return findings


def sanctioned_d2h_sites(path=None):
    """The static half of the runtime cross-check: every hot-path D2H
    site the lint sanctioned (pragma'd, fence-idiom, prof-guarded or
    post-fence pulls), keyed ``relpath::qualname``.  compile_verify's
    observed ledger is diffed against this set."""
    sanctioned = {}
    lint_targets(path, _sanctioned=sanctioned)
    return sanctioned


def cross_check(static_sites, observed_sites):
    """Diff observed runtime D2H ledger sites against the lint's
    sanctioned set (the lock_lint cross_check pattern): an observed
    pull the lint never sanctioned is an error (an unaccounted hot-path
    transfer crept in past the static pass); a sanctioned site never
    observed is an info (dead sanction — audit whether the pragma still
    earns its place)."""
    findings = []
    static_funcs = {s.split("::", 1)[-1].split(":")[0] if "::" not in s
                    else s for s in static_sites}
    for site in sorted(observed_sites):
        base = site.split(":")[0] + "::" + site.split("::", 1)[-1] \
            if "::" in site else site
        if site in static_sites or base in static_funcs:
            continue
        findings.append(Finding(
            "jit", "hot-d2h", "error", site,
            "runtime D2H ledger observed a device->host pull at a site "
            "the static lint never sanctioned — an unaccounted hot-path "
            "transfer (add it to the contract or remove it)"))
    for site in sorted(static_sites):
        if site not in observed_sites:
            findings.append(Finding(
                "jit", "hot-d2h", "info", site,
                "sanctioned D2H site never observed by the runtime "
                "ledger this run — dead sanction or an unexercised "
                "path"))
    return findings
