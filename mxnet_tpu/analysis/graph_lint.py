"""Symbol-graph linter: compiler-style static checks over the Symbol DAG.

Walks a ``symbol.py`` node graph (or its serialized JSON) and reports:

- ``dtype-mismatch``  (error)   — an op with default elementwise type
  inference fed inputs of different declared dtypes. The runtime would
  silently upcast (or worse, jit a mixed-precision graph the author
  never intended); the reference CHECKs this in InferType.
- ``grad-req``        (error)   — ``__grad_req__`` attrs outside
  {write, add, null}, or an aux-state variable marked to receive
  gradient (aux states carry no gradient by contract,
  ref: OpReqType kNullOp semantics).
- ``duplicate-arg``   (error)   — two distinct variable nodes sharing a
  name: binding maps arrays by name, so one of them silently aliases
  the other.
- ``duplicate-name``  (warning) — two op nodes sharing a name
  (save/load and attr_dict collide).
- ``tpu-pad``         (error/warning) — matmul-feeding dimensions that
  are not multiples of 128. The MXU lane width is 128 and the Pallas
  kernels in ops/pallas_kernels.py are hard-gated on 128-multiples
  (off-128 shapes fall back to the dense path), so every off-128 dim
  forces XLA padding. Severity encodes intent: a dim within
  ``PAD_ERROR_DEFICIT`` lanes of the next multiple (127, 1016, ...)
  is almost certainly a fence-post bug — rounding up is nearly free —
  and is an error; honest small layers (10-class heads, 64-wide
  bottlenecks) get a warning with the measured waste.
- ``dead-node``       (warning, JSON input only) — nodes in the
  serialized graph unreachable from any head. A live Symbol can only
  hold reachable nodes, but hand-edited / converted JSON can ship dead
  weight that still costs load time and confuses diffing.
- ``fusible-chain``   (info) — elementwise chains the compile layer's
  fusion pass (compile/fuse.py) would merge into one segment. Reported
  even when ``MXNET_COMPILE_OPT`` is off, so ``mxlint`` surfaces the
  opportunity; cross-referenced with the 128-lane padding findings of
  the nodes feeding the chain (fusion does not remove XLA pad).

The graph walks (shape sweep, consumer maps, chain discovery) are
shared with the compile passes via ``mxnet_tpu.compile.ir``.

No jax import: everything here is host-side metadata walking, safe to
run in CI before any device is touched (compile.ir keeps the same
contract).
"""
from __future__ import annotations

import ast
import json

import numpy as _np

from ..compile import ir as _ir
from .findings import Finding

__all__ = ["lint_symbol", "lint_json", "PAD_ERROR_DEFICIT", "LANE"]

LANE = 128  # MXU lane width; the proven block rule in ops/pallas_kernels.py
PAD_ERROR_DEFICIT = 8  # within this many lanes of aligned => fence-post error

# params that become matmul/contraction dimensions on the MXU
_PARAM_DIMS = {
    "FullyConnected": ("num_hidden",),
    "Convolution": ("num_filter",),
    "Deconvolution": ("num_filter",),
    "Embedding": ("output_dim",),
}

# ops whose inputs are legitimately mixed-dtype (indices + table, ...)
_MIXED_DTYPE_OK = {"Embedding", "Cast", "SequenceLast", "SequenceMask",
                   "SequenceReverse", "BatchNorm"}

_GRAD_REQS = ("write", "add", "null")


def _var_attr_shape(node):
    s = node.attrs.get("__shape__")
    if not s:
        return None
    try:
        return tuple(int(d) for d in ast.literal_eval(str(s)))
    except (ValueError, SyntaxError, TypeError):
        return None


def _var_attr_dtype(node):
    t = node.attrs.get("__dtype__")
    if not t:
        return None
    try:
        return _np.dtype(str(t))
    except TypeError:
        return None


def _pad_findings(node_name, dim_label, d):
    """Classify one off-128 dimension; returns [] when aligned."""
    d = int(d)
    if d <= 0 or d % LANE == 0:
        return []
    aligned = ((d + LANE - 1) // LANE) * LANE
    deficit = aligned - d
    waste = 100.0 * deficit / aligned
    if deficit <= PAD_ERROR_DEFICIT:
        return [Finding(
            "graph", "tpu-pad", "error", node_name,
            "%s=%d is %d short of the %d-lane multiple %d; XLA pads every "
            "tile (%.1f%% waste) and the Pallas kernels fall back to the "
            "dense path. Round the dimension up to %d."
            % (dim_label, d, deficit, LANE, aligned, waste, aligned))]
    return [Finding(
        "graph", "tpu-pad", "warning", node_name,
        "%s=%d is not a multiple of %d: XLA pads %d->%d on this axis "
        "(%.1f%% of the padded tile is waste)."
        % (dim_label, d, LANE, d, aligned, waste))]


# the shape sweep moved to the shared IR walk (compile/ir.py); the old
# name stays for callers inside this package
_propagate_shapes = _ir.propagate_shapes


def lint_symbol(sym, input_shapes=None, input_types=None):
    """Lint a live Symbol. ``input_shapes``/``input_types`` optionally map
    argument names to shapes/dtypes, augmenting any ``__shape__`` /
    ``__dtype__`` attrs stored on the variables themselves."""
    findings = []
    nodes = sym.nodes
    input_shapes = dict(input_shapes or {})
    input_types = dict(input_types or {})

    # -- structural: duplicate names, grad_req discipline ----------------------
    seen_vars, seen_ops = {}, {}
    for n in nodes:
        table = seen_vars if n.is_variable else seen_ops
        if n.name in table:
            if n.is_variable:
                findings.append(Finding(
                    "graph", "duplicate-arg", "error", n.name,
                    "two distinct variable nodes share this name; binding "
                    "maps arrays by name, so one silently aliases the other"))
            else:
                findings.append(Finding(
                    "graph", "duplicate-name", "warning", n.name,
                    "two op nodes share this name (save/load and attr_dict "
                    "collide)"))
        else:
            table[n.name] = n
        if n.is_variable:
            gr = n.attrs.get("__grad_req__")
            if gr is not None and gr not in _GRAD_REQS:
                findings.append(Finding(
                    "graph", "grad-req", "error", n.name,
                    "__grad_req__=%r is not one of %s" % (gr, list(_GRAD_REQS))))
            elif gr in ("write", "add") and n.attrs.get("__aux__"):
                findings.append(Finding(
                    "graph", "grad-req", "error", n.name,
                    "auxiliary state marked __grad_req__=%r; aux states "
                    "carry no gradient (kNullOp contract)" % gr))

    # -- dtype propagation + elementwise agreement -----------------------------
    dtypes = {}
    for n in nodes:
        if not n.is_variable:
            continue
        t = _var_attr_dtype(n)
        if n.name in input_types:
            t = _np.dtype(input_types[n.name])
        if t is not None:
            dtypes[(id(n), 0)] = t
    for n in nodes:
        if n.is_variable:
            continue
        in_dtypes = [dtypes.get((id(s), i)) for s, i in n.inputs]
        known = [t for t in in_dtypes if t is not None]
        uses_default_infer = getattr(n.op, "_infer_type", None) is None
        if (uses_default_infer and n.op.name not in _MIXED_DTYPE_OK
                and len({t.name for t in known}) > 1):
            detail = ", ".join(
                "%s[%d]:%s" % (s.name, i, t)
                for (s, i), t in zip(n.inputs, in_dtypes) if t is not None)
            findings.append(Finding(
                "graph", "dtype-mismatch", "error", n.name,
                "op %s mixes input dtypes (%s); elementwise type inference "
                "assumes one dtype — insert an explicit Cast"
                % (n.op.name, detail)))
            continue  # don't propagate a dtype we know is ambiguous
        try:
            _ins, outs, _aux = n.op.infer_type(n.params, in_dtypes)
        except Exception:
            continue
        for i, t in enumerate(outs):
            if t is not None:
                dtypes[(id(n), i)] = _np.dtype(t)

    # -- TPU padding: param-declared matmul dims -------------------------------
    for n in nodes:
        if n.is_variable:
            continue
        for pname in _PARAM_DIMS.get(n.op.name, ()):
            d = (n.params or {}).get(pname)
            if isinstance(d, int):
                findings.extend(_pad_findings(n.name, pname, d))

    # -- TPU padding: shape-derived matmul dims (dot / batch_dot /
    #    FullyConnected contraction), where shapes are recoverable ------------
    seed = {}
    for n in nodes:
        if n.is_variable:
            s = _var_attr_shape(n)
            if n.name in input_shapes:
                s = tuple(input_shapes[n.name])
            if s is not None:
                seed[(id(n), 0)] = s
    if seed:
        shapes = _propagate_shapes(nodes, seed)
        for n in nodes:
            if n.is_variable:
                continue
            if n.op.name in ("dot", "batch_dot"):
                for (src, i), side in zip(n.inputs, ("lhs", "rhs")):
                    s = shapes.get((id(src), i))
                    if s is None:
                        continue
                    for ax, d in enumerate(s[-2:]):
                        findings.extend(_pad_findings(
                            n.name, "%s.shape[%d]" % (side, len(s) - 2 + ax), d))
            elif n.op.name == "FullyConnected" and n.inputs:
                s = shapes.get((id(n.inputs[0][0]), n.inputs[0][1]))
                if s is not None and len(s) >= 2:
                    flat = 1
                    for d in s[1:]:
                        flat *= int(d)
                    findings.extend(_pad_findings(
                        n.name, "contraction dim %d" % flat, flat))

    # -- fusible chains: what compile/fuse.py would merge (info) ---------------
    pad_nodes = {f.where for f in findings if f.code == "tpu-pad"}
    for chain in _ir.find_fusible_chains(sym):
        names = [c.name for c in chain]
        feeders = sorted({
            s.name for c in chain for s, _i in c.inputs
            if s.name in pad_nodes and s not in chain})
        msg = ("chain of %d elementwise ops (%s) would fuse into one "
               "segment under MXNET_COMPILE_OPT=1 (compile/fuse.py): "
               "%d fewer graph nodes to trace/plan/dispatch"
               % (len(chain), " -> ".join(names), len(chain) - 1))
        if feeders:
            msg += ("; note: the chain is fed by %s, which carry "
                    "128-lane padding findings — fusion keeps the chain "
                    "on the padded layout, fix those dims for the full "
                    "win" % ", ".join(feeders))
        findings.append(Finding(
            "graph", "fusible-chain", "info", names[0], msg))
    return findings


def _validate_graph_json(data):
    """Structural validation of untrusted graph JSON; raises ValueError
    (a CLI 'load error') so malformed inputs are distinguishable from
    linter bugs, which crash with a traceback."""
    jnodes = data.get("nodes", [])
    heads = data.get("heads", [])
    if not isinstance(jnodes, list) or not isinstance(heads, list):
        raise ValueError("malformed graph JSON: 'nodes'/'heads' not lists")
    for i, jn in enumerate(jnodes):
        if not isinstance(jn, dict) or "op" not in jn or "name" not in jn:
            raise ValueError(
                "malformed graph JSON: node %d lacks op/name" % i)
        for ref in jn.get("inputs", []):
            if (not isinstance(ref, (list, tuple)) or len(ref) < 2
                    or not 0 <= int(ref[0]) < len(jnodes)):
                raise ValueError(
                    "malformed graph JSON: node %d has bad input ref %r"
                    % (i, ref))
    for h in heads:
        if (not isinstance(h, (list, tuple)) or not h
                or not 0 <= int(h[0]) < len(jnodes)):
            raise ValueError("malformed graph JSON: bad head ref %r" % (h,))


def lint_json(json_str):
    """Lint a serialized graph: dead-node reachability over the raw node
    table, then the full symbol lint over the loaded heads. Raises
    ValueError on malformed input (bad JSON or bad graph structure)."""
    findings = []
    data = json.loads(json_str)
    _validate_graph_json(data)
    jnodes = data.get("nodes", [])
    heads = data.get("heads", [])
    reach = set()
    stack = [int(h[0]) for h in heads]
    while stack:
        i = stack.pop()
        if i in reach:
            continue
        reach.add(i)
        for src, _idx in jnodes[i].get("inputs", []):
            stack.append(int(src))
    for i, jn in enumerate(jnodes):
        if i not in reach:
            findings.append(Finding(
                "graph", "dead-node", "warning",
                jn.get("name", "#%d" % i),
                "node (op=%s) is unreachable from every graph head — dead "
                "weight in the serialized graph" % jn.get("op", "?")))

    from ..base import MXNetError as _MXNetError
    from ..symbol import load_json as _load_json

    try:
        sym = _load_json(json_str)
    except (_MXNetError, KeyError) as e:
        # unknown op name, missing 'heads', ... — input badness, not a
        # linter bug: keep the raises-ValueError load contract
        raise ValueError("malformed graph JSON: %s" % e) from None
    findings.extend(lint_symbol(sym))
    return findings
