"""Telemetry catalog gate: registered metric names vs the documented
catalog (mxlint ``--telemetry``).

Every counter/gauge/histogram a subsystem registers must appear in
docs/how_to/observability.md's metrics catalog, and every catalog entry
must still exist in code — otherwise the catalog silently drifts as
subsystems add counters (exactly how the serving and quantize metrics
escaped it before this gate).

Code side: an AST walk over the package collects the first argument of
every ``*.counter(...)`` / ``*.gauge(...)`` / ``*.histogram(...)``
call —

- string literals register exactly;
- ``"prefix.%s_suffix" % x`` and f-strings register a ``prefix.*``
  wildcard pattern (likewise literal ``+`` concatenation);
- anything else is a *dynamic* site: reported as an info finding unless
  a pragma comment within the preceding few lines declares its names::

      # mxtel-metrics: kvstore.evictions_total kvstore.rejoins_total

  (adjacency is required — a pragma elsewhere in the file must not
  blanket-suppress a NEW dynamic site added later)

Doc side: every backticked token containing a dot inside a markdown
table row (``| `name` | kind | ...``), with ``<x>`` placeholders
normalized to ``*`` wildcards, ``{a,b}`` sets brace-expanded, and
``a` / `b`` cells split naturally by backtick extraction.

Matching is wildcard-aware in both directions (fnmatch): the code
pattern ``serving.requests_*`` is covered by the documented
``serving.requests_admitted`` and vice versa.
"""
from __future__ import annotations

import ast
import fnmatch
import os
import re

from .findings import Finding

__all__ = ["collect_code_metrics", "collect_doc_metrics", "lint_catalog",
           "DEFAULT_PACKAGE", "DEFAULT_DOC"]

DEFAULT_PACKAGE = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
DEFAULT_DOC = os.path.join(os.path.dirname(DEFAULT_PACKAGE),
                           "docs", "how_to", "observability.md")

_METRIC_METHODS = frozenset(("counter", "gauge", "histogram"))
_PRAGMA_RE = re.compile(r"#\s*mxtel-metrics:\s*(.+)")
#: a pragma covers a dynamic registration site at most this many lines
#: below it (adjacency, so one pragma never blankets a whole file)
_PRAGMA_REACH = 10
_DOC_TOKEN_RE = re.compile(r"`([^`]+)`")
# a plausible metric name: dotted, lowercase-ish, optional wildcards
_NAME_RE = re.compile(r"^[a-z0-9_*]+(\.[a-z0-9_*]+)+$")

#: files whose counter()/gauge()/histogram() calls are the telemetry
#: plumbing itself, not metric registrations. prof.py is deliberately
#: NOT here: mxprof registers real prof.* metrics from inside the
#: telemetry package and the catalog gate must see them.
_SKIP_FILES = frozenset(
    os.path.join("mxnet_tpu", "telemetry", f)
    for f in ("__init__.py", "registry.py", "export.py", "tracing.py",
              "server.py", "merge.py"))


def _pattern_from_arg(node):
    """(exact_name | wildcard_pattern | None) for a metric-name arg."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    # "prefix%s" % x  /  "prefix" % x
    if isinstance(node, ast.BinOp) and isinstance(node.op, ast.Mod) and \
            isinstance(node.left, ast.Constant) and \
            isinstance(node.left.value, str):
        return re.sub(r"%[sdifr]", "*", node.left.value)
    # "prefix" + x
    if isinstance(node, ast.BinOp) and isinstance(node.op, ast.Add) and \
            isinstance(node.left, ast.Constant) and \
            isinstance(node.left.value, str):
        return node.left.value + "*"
    if isinstance(node, ast.JoinedStr):
        parts = []
        for v in node.values:
            if isinstance(v, ast.Constant) and isinstance(v.value, str):
                parts.append(v.value)
            else:
                parts.append("*")
        return "".join(parts)
    return None


def collect_code_metrics(pkg_path=None):
    """Walk the package: (names_or_patterns set, dynamic_sites list).
    ``dynamic_sites`` are ``(relpath, lineno)`` of calls whose name is
    underivable and not covered by a file pragma."""
    pkg_path = pkg_path or DEFAULT_PACKAGE
    names = set()
    dynamic = []
    for root, dirs, files in os.walk(pkg_path):
        dirs[:] = [d for d in dirs if d != "__pycache__"]
        for fname in sorted(files):
            if not fname.endswith(".py"):
                continue
            path = os.path.join(root, fname)
            rel_pkg = os.path.relpath(path, os.path.dirname(pkg_path))
            if rel_pkg in _SKIP_FILES:
                continue
            with open(path, "r", encoding="utf-8") as f:
                src = f.read()
            rel = os.path.relpath(path, os.path.dirname(pkg_path))
            pragma_lines = []
            for lineno, line in enumerate(src.splitlines(), 1):
                m = _PRAGMA_RE.search(line)
                if m is None:
                    continue
                # only well-formed names: the pragma may be quoted in
                # docs/docstrings (this file's own included)
                declared = {n for n in m.group(1).split()
                            if _NAME_RE.match(n)}
                if declared:
                    names.update(declared)
                    pragma_lines.append(lineno)
            try:
                tree = ast.parse(src)
            except SyntaxError:
                continue  # lock_lint/ast_lint own syntax diagnostics
            for node in ast.walk(tree):
                if not (isinstance(node, ast.Call)
                        and isinstance(node.func, ast.Attribute)
                        and node.func.attr in _METRIC_METHODS
                        and node.args):
                    continue
                # self.xxx.counter(...) on non-telemetry receivers
                # (e.g. a Registry instance) still counts: the name
                # space is process-global either way
                pat = _pattern_from_arg(node.args[0])
                if pat is not None and _NAME_RE.match(pat):
                    names.add(pat)
                else:
                    # underivable name OR a literal that is not a
                    # dotted metric name — both must surface, or a
                    # dotless counter('throughput') silently escapes
                    # the whole gate
                    covered = any(
                        0 <= node.lineno - pl <= _PRAGMA_REACH
                        for pl in pragma_lines)
                    if not covered:
                        dynamic.append((rel, node.lineno))
    return names, dynamic


def _expand_braces(token):
    m = re.search(r"\{([^}]*)\}", token)
    if not m:
        return [token]
    head, tail = token[:m.start()], token[m.end():]
    out = []
    for part in m.group(1).split(","):
        out.extend(_expand_braces(head + part.strip() + tail))
    return out


def collect_doc_metrics(doc_path=None):
    """Metric names/patterns documented in the catalog's tables."""
    doc_path = doc_path or DEFAULT_DOC
    names = set()
    with open(doc_path, "r", encoding="utf-8") as f:
        for line in f:
            if not line.lstrip().startswith("|"):
                continue
            first_cell = line.split("|")[1] if "|" in line else ""
            for tok in _DOC_TOKEN_RE.findall(first_cell):
                tok = tok.strip()
                tok = re.sub(r"<[^>]*>", "*", tok)
                for t in _expand_braces(tok):
                    if _NAME_RE.match(t):
                        names.add(t)
    return names


def _covered(entry, others):
    """True when ``entry`` (name or pattern) matches any of ``others``
    in either wildcard direction."""
    for o in others:
        if entry == o or fnmatch.fnmatchcase(entry, o) or \
                fnmatch.fnmatchcase(o, entry):
            return True
    return False


def lint_catalog(pkg_path=None, doc_path=None):
    """The gate: findings for undocumented metrics, stale catalog
    entries, and unverifiable dynamic registration sites."""
    doc_path = doc_path or DEFAULT_DOC
    code, dynamic = collect_code_metrics(pkg_path)
    try:
        docs = collect_doc_metrics(doc_path)
    except OSError as e:
        return [Finding("telemetry", "catalog-missing", "error", doc_path,
                        "metrics catalog unreadable: %s" % e)]
    findings = []
    for name in sorted(code):
        if not _covered(name, docs):
            findings.append(Finding(
                "telemetry", "undocumented-metric", "error", name,
                "registered in the package but absent from the metrics "
                "catalog (%s)" % os.path.relpath(doc_path)))
    for name in sorted(docs):
        if not _covered(name, code):
            findings.append(Finding(
                "telemetry", "stale-catalog-entry", "error", name,
                "documented in the metrics catalog but no longer "
                "registered anywhere in the package"))
    for rel, lineno in dynamic:
        findings.append(Finding(
            "telemetry", "dynamic-metric-name", "info",
            "%s:%d" % (rel, lineno),
            "metric name not statically derivable (or not a dotted "
            "metric name) — declare it with an adjacent "
            "'# mxtel-metrics: <name>...' pragma so the catalog gate "
            "can see it"))
    return findings
