"""mxproto protocol simulator: deterministic message-schedule
exploration over the REAL elastic coordinator state machine
(``mxlint --protosim``).

The static half (proto_lint.py) proves the two protocol halves agree on
shape; this module attacks the *ordering* residue: the coordinator's
``_dispatch`` state machine plus N client actors run in-process over a
logical network whose delivery order, reply losses (client retries),
duplicate deliveries (the lost-ack retry), rank crashes, admin
evictions and restarts are all **scheduler choices** — the mxrace
substrate (``analysis/schedule.py``) applied at message granularity
instead of thread granularity. Every schedule derives from a
``(seed, index)`` pair via the same ``_schedule_seed`` stream, failures
print the same replay hint shape, and :func:`replay` re-runs exactly
one schedule.

What runs is the REAL code: a socketless ``ElasticCoordinator``
(``bind=None``) whose ``_dispatch`` is invoked directly — GroupView,
Aggregator, barrier generations, shard-ownership evaluation and the
snapshot state machines are the production objects, not models. The
actors mirror ``_ElasticDistKVStore``'s client discipline (round
counters, stale/resync handling, rejoin-on-evicted, shard-owner
``put_weight``), assembled as plain request dicts so one sim step is
one protocol message. Time-based eviction is intentionally OUT of
scope here (that is the timeout lattice's domain, proto_lint): the
sweeper's effect is modeled by the ``evict`` admin op as an explorable
event, so eviction *ordering* is explored without a clock.

Invariants asserted over every delivered message (the Harness):

- membership epoch is monotone non-decreasing;
- each ``(key, round)`` completes exactly once, and only when its
  recorded contributors cover the live set at completion time;
- a degraded completion's merged value equals the surviving
  contributions rescaled by ``world / contributors`` (all-reduce mode);
- an accepted ``put_weight`` is never lost: the server copy equals the
  landed weight and ``w_done`` advances (shard mode);
- a barrier generation advances only when the arrival set covers the
  live set (release at a consistent epoch);
- the membership + round state round-trips through
  ``snapshot_state``/``restore_state`` (a scheduler-chosen event).

Two seeded mutants are the negative controls the survival suite must
FIND and REPLAY: ``_EpochRegressView`` (a rejoin regresses the epoch)
and ``_UnguardedAggregator`` (round completion without coverage — the
exact bug class of a dropped ``live.issubset`` check).

Env knobs: ``MXPROTO_SCHEDULES`` (per-leg budget, default 25),
``MXPROTO_SEED`` (base seed) — read by the CLI legs, not here.
"""
from __future__ import annotations

import os

import numpy as _np

from .findings import Finding
from .schedule import ExploreResult, FailureReport, _schedule_seed

__all__ = ["ProtoWorkload", "Harness", "explore", "replay",
           "allreduce_workload", "shard_workload",
           "epoch_regress_workload", "unguarded_completion_workload",
           "survival_suite", "InvariantViolation"]

_STALL_LIMIT = 60       # non-advancing polls before forcing evict/restart
_MAX_STEPS = 6000


class InvariantViolation(AssertionError):
    """A protocol invariant broke under some message schedule."""


class ProtoFailure(FailureReport):
    def replay_hint(self):
        if self.strategy == "random":
            return ("replay: mxnet_tpu.analysis.protosim.replay("
                    "<workload>, seed=%d, index=%d)  # schedule_seed=%d, "
                    "%d decisions"
                    % (self.base_seed, self.index, self.schedule_seed,
                       len(self.choices)))
        return ("replay: mxnet_tpu.analysis.protosim.replay(<workload>, "
                "seed=%d, index=%d, choices=%r)"
                % (self.base_seed, self.index, self.choices))


class ProtoWorkload:
    """One simulated job: shape + perturbation budgets + mutants."""

    def __init__(self, name, world=3, keys=("w", "b"), rounds=3,
                 shard=False, barrier_every=0, lose_budget=2,
                 dup_budget=2, crash_budget=1, restart_budget=1,
                 snapshot_budget=1, view_cls=None, agg_cls=None,
                 max_steps=_MAX_STEPS, rendezvous=False):
        self.name = name
        self.world = int(world)
        self.keys = tuple(keys)
        self.rounds = int(rounds)
        self.shard = bool(shard)
        self.barrier_every = int(barrier_every)
        self.lose_budget = int(lose_budget)
        self.dup_budget = int(dup_budget)
        self.crash_budget = int(crash_budget)
        self.restart_budget = int(restart_budget)
        self.snapshot_budget = int(snapshot_budget)
        self.view_cls = view_cls
        self.agg_cls = agg_cls
        self.max_steps = int(max_steps)
        self.rendezvous = bool(rendezvous)

    __name__ = property(lambda self: self.name)


def _grad(rank, key, rnd, n=4):
    """Deterministic per-(rank, key, round) gradient — replays and the
    harness's independent merge recomputation see identical bytes."""
    base = (hash((key,)) % 7) + 1
    return _np.full(n, float(rank + 1) * base + 0.25 * rnd, _np.float32)


def _floor_rounds(resp, keys):
    rounds = resp.get("rounds") or {}
    if not rounds:
        return {k: 0 for k in keys}
    floor = min(rounds.values())
    return {k: int(floor) for k in keys}


def _actor(rank, wl):
    """One worker's protocol state machine as a generator:
    ``resp = yield request``. Mirrors kvstore._ElasticDistKVStore's
    discipline: register → init → per-round push/pull (stale/resync
    fast-forward, rejoin on 'evicted'), shard-owner put_weight, barrier
    idempotency via the arrival count, graceful leave."""
    local = {}
    weights = {}
    barrier_count = 0

    def _register():
        resp = yield {"op": "register", "rank": rank}
        local.update(_floor_rounds(resp, wl.keys))
        return resp

    yield from _register()
    if wl.shard:
        resp = yield {"op": "set_optimizer", "rank": rank,
                      "blob": b"sim-optimizer", "shard": True}
        while resp.get("status") == "evicted":
            yield from _register()
            resp = yield {"op": "set_optimizer", "rank": rank,
                          "blob": b"sim-optimizer", "shard": True}
    for k in wl.keys:
        resp = yield {"op": "init", "rank": rank, "key": k,
                      "value": _np.zeros(4, _np.float32)}
        while resp.get("status") == "evicted":
            yield from _register()
            resp = yield {"op": "init", "rank": rank, "key": k,
                          "value": _np.zeros(4, _np.float32)}
        # setdefault, NOT max: a (re)joiner starts every key at the
        # group's MINIMUM round and fast-forwards through idempotent
        # 'stale' pushes (kvstore._aligned_rounds) — adopting the
        # per-key map here recreates the exact distributed deadlock
        # that comment documents (pulling a frontier round this rank
        # never contributed to)
        local.setdefault(k, int(resp["round"]))
        weights[k] = _np.asarray(resp["value"], _np.float32)

    passes = 0
    while any(local[k] < wl.rounds for k in wl.keys):
        passes += 1
        # push phase first for EVERY key, pulls after — the real store's
        # batch order; interleaving push/pull per key deadlocks two
        # ranks blocked on each other's unpushed keys
        for k in wl.keys:
            if local[k] >= wl.rounds:
                continue
            while True:
                rnd = local[k] + 1
                resp = yield {"op": "push", "rank": rank, "key": k,
                              "round": rnd, "value": _grad(rank, k, rnd)}
                st = resp.get("status")
                if st == "evicted":
                    yield from _register()
                    continue
                if st == "resync":
                    local[k] = int(resp["round"])
                    continue
                if st == "stale":
                    local[k] = max(rnd, int(resp["round"]))
                else:  # ok
                    local[k] = rnd
                break
        for k in wl.keys:
            # pull phase: poll until each key's pushed round is ready
            while True:
                resp = yield {"op": "pull", "rank": rank, "key": k,
                              "min_round": local[k], "wait": 0}
                st = resp.get("status")
                if st == "evicted":
                    yield from _register()
                    continue
                if st == "update":
                    # shard mode: this rank owns the key — apply the
                    # "optimizer" locally and land the weight
                    rnd = int(resp["round"])
                    new_w = (weights[k]
                             - 0.1 * _np.asarray(resp["value"],
                                                 _np.float32))
                    put = yield {"op": "put_weight", "rank": rank,
                                 "key": k, "round": rnd, "value": new_w}
                    if put.get("status") == "evicted":
                        yield from _register()
                    continue
                if st == "pending":
                    continue
                local[k] = max(local[k], int(resp["round"]))
                weights[k] = _np.asarray(resp["value"], _np.float32)
                break
        if wl.barrier_every and passes % wl.barrier_every == 0:
            # round-anchored rendezvous. Only meaningful in workloads
            # without restarts (barrier_workload): a restarted
            # incarnation re-barriers at boundaries the group already
            # passed, which is not the SPMD cadence the product's
            # barrier sites have — eviction (the perturbation that
            # matters to barrier release) is still explored
            barrier_count += 1
            while True:
                resp = yield {"op": "barrier", "rank": rank,
                              "count": barrier_count}
                if resp.get("status") == "evicted":
                    yield from _register()
                    continue
                break
            gen, done = int(resp["gen"]), bool(resp.get("done"))
            while not done:
                resp = yield {"op": "barrier_wait", "rank": rank,
                              "gen": gen, "wait": 0}
                done = bool(resp.get("done"))
    yield {"op": "leave", "rank": rank}


class Harness:
    """Wraps ``coord._dispatch``: snapshots the observable state around
    every delivered message and asserts the protocol invariants."""

    def __init__(self, coord, world):
        self.coord = coord
        self.world = world
        self.contribs = {}        # (key, round) -> {rank: np.ndarray}
        self.completed = {}       # key -> set(round)
        self.messages = 0

    def _snap(self):
        c = self.coord
        return {
            "epoch": c.view.epoch,
            "live": set(c.view.live),
            "evicted": set(c.view.evicted),
            "done": dict(c.agg.done),
            "w_done": dict(c.agg.w_done),
            "barrier_gen": c.barrier_gen,
            "waiters": set(c._barrier_waiters),
        }

    def deliver(self, req):
        pre = self._snap()
        resp = self.coord._dispatch(dict(req))
        post = self._snap()
        self.messages += 1
        self._check(req, resp, pre, post)
        return resp

    def _check(self, req, resp, pre, post):
        op = req.get("op")
        if post["epoch"] < pre["epoch"]:
            raise InvariantViolation(
                "membership epoch regressed %d -> %d on op %r"
                % (pre["epoch"], post["epoch"], op))
        # record accepted contributions before judging completions so a
        # push that itself completes the round counts itself
        if op == "push" and isinstance(resp, dict) and \
                resp.get("status") == "ok":
            self.contribs.setdefault(
                (req["key"], int(req["round"])), {})[int(req["rank"])] = \
                _np.array(req["value"], copy=True)
        # an eviction drops the corpse's in-flight contributions
        for rank in post["evicted"] - pre["evicted"]:
            for (k, r), by_rank in self.contribs.items():
                if r > pre["done"].get(k, 0):
                    by_rank.pop(rank, None)
        for k, d_post in post["done"].items():
            d_pre = pre["done"].get(k, 0)
            if d_post < d_pre:
                raise InvariantViolation(
                    "round counter of key %r regressed %d -> %d on %r"
                    % (k, d_pre, d_post, op))
            for r in range(d_pre + 1, d_post + 1):
                seen = self.completed.setdefault(k, set())
                if r in seen:
                    raise InvariantViolation(
                        "round %d of key %r completed TWICE (op %r)"
                        % (r, k, op))
                seen.add(r)
                who = self.contribs.get((k, r), {})
                if not post["live"] <= set(who):
                    raise InvariantViolation(
                        "round %d of key %r completed with contributors "
                        "%s not covering the live set %s (op %r) — "
                        "unguarded round completion"
                        % (r, k, sorted(who), sorted(post["live"]), op))
                self._check_merge(k, r, who)
        if op == "put_weight" and isinstance(resp, dict) and \
                resp.get("status") == "ok":
            rnd = int(req["round"])
            if self.coord.agg.w_done.get(req["key"], 0) < rnd:
                raise InvariantViolation(
                    "accepted put_weight of key %r round %d did not "
                    "advance w_done" % (req["key"], rnd))
            if not _np.array_equal(self.coord.agg.weights[req["key"]],
                                   _np.asarray(req["value"])):
                raise InvariantViolation(
                    "accepted put_weight of key %r round %d LOST: the "
                    "server copy differs from the landed weight"
                    % (req["key"], rnd))
        if post["barrier_gen"] > pre["barrier_gen"]:
            arrivals = set(pre["waiters"])
            if op == "barrier":
                arrivals.add(int(req["rank"]))
            if not post["live"] <= arrivals:
                raise InvariantViolation(
                    "barrier generation %d released without covering "
                    "the live set: arrivals %s, live %s"
                    % (post["barrier_gen"], sorted(arrivals),
                       sorted(post["live"])))

    def _check_merge(self, key, rnd, who):
        """All-reduce mode: the completed round's stored value must be
        the surviving contributions rescaled by world/contributors."""
        agg = self.coord.agg
        if agg.shard_update or agg._updater is not None or not who:
            return
        if agg.done.get(key, 0) != rnd:
            return  # a later round already overwrote the stored value
        total = _np.zeros_like(next(iter(who.values())), _np.float64)
        for arr in who.values():
            total += arr
        expected = (total * (self.world / float(len(who)))).astype(
            _np.float32)
        if not _np.allclose(agg.weights[key], expected, rtol=1e-5):
            raise InvariantViolation(
                "degraded rescale mismatch on key %r round %d: stored "
                "%s != %s from contributors %s x %d/%d"
                % (key, rnd, agg.weights[key], expected, sorted(who),
                   self.world, len(who)))

    def snapshot_roundtrip(self):
        """The snapshot-restore invariant: membership + round state
        survives a state-dict round trip through the REAL
        snapshot_state/restore_state code (what a coordinator restart
        replays, minus the file IO)."""
        from ..elastic.server import Aggregator, GroupView

        view_st = self.coord.view.snapshot_state()
        agg_st = self.coord.agg.snapshot_state()
        weights = {k: _np.array(v, copy=True)
                   for k, v in self.coord.agg.weights.items()}
        gv = GroupView(view_st["world"], self.coord.view.evict_after)
        gv.restore_state(view_st, now=0.0)
        if gv.snapshot_state() != view_st:
            raise InvariantViolation(
                "GroupView state did not round-trip through snapshot/"
                "restore: %r != %r" % (gv.snapshot_state(), view_st))
        ag = Aggregator(view_st["world"])
        ag.restore_state(agg_st, weights)
        for k, d in self.coord.agg.done.items():
            want = min(d, self.coord.agg.w_done.get(k, 0)) \
                if agg_st["shard_update"] else d
            if ag.done.get(k) != want:
                raise InvariantViolation(
                    "round state of key %r did not restore: %r != %r "
                    "(done=%d w_done=%d shard=%s)"
                    % (k, ag.done.get(k), want, d,
                       self.coord.agg.w_done.get(k, 0),
                       agg_st["shard_update"]))
            if not _np.array_equal(ag.weights[k],
                                   self.coord.agg.weights[k]):
                raise InvariantViolation(
                    "weights of key %r did not round-trip the snapshot"
                    % (k,))


# -- negative-control mutants --------------------------------------------------

class _EpochRegressView:
    """SEEDED MUTANT: a rejoin regresses the membership epoch — the bug
    the epoch-monotone invariant exists to catch. Built lazily (the
    real GroupView import must stay function-scoped)."""

    def __new__(cls, world, evict_after):
        from ..elastic.server import GroupView

        class Mutant(GroupView):
            def register(self, rank, now):
                epoch, rejoined = GroupView.register(self, rank, now)
                if rejoined:
                    self.epoch = max(0, self.epoch - 2)
                    epoch = self.epoch
                return epoch, rejoined

        return Mutant(world, evict_after)


class _UnguardedAggregator:
    """SEEDED MUTANT: round completion without the live-coverage check
    (``complete_ready`` judged against a single rank) — the dropped
    ``live.issubset`` bug class."""

    def __new__(cls, world):
        from ..elastic.server import Aggregator

        class Mutant(Aggregator):
            def complete_ready(self, live):
                return Aggregator.complete_ready(
                    self, set(sorted(live)[:1]) if live else live)

        return Mutant(world)


# -- the explorer --------------------------------------------------------------

def _build(wl):
    from ..elastic.server import ElasticCoordinator

    coord = ElasticCoordinator(wl.world, bind=None, evict_after=3600.0)
    if wl.view_cls is not None:
        coord.view = wl.view_cls(wl.world, coord.view.evict_after)
    if wl.agg_cls is not None:
        coord.agg = wl.agg_cls(wl.world)
    return coord


class _Sim:
    """One schedule: actors + logical network + perturbation budgets.
    All nondeterminism flows through ``chooser(events)`` so a recorded
    choice list replays the schedule exactly."""

    def __init__(self, wl, chooser):
        self.wl = wl
        self.chooser = chooser
        self.coord = _build(wl)
        self.harness = Harness(self.coord, wl.world)
        self.actors = {}      # rank -> generator
        self.outbox = {}      # rank -> pending request dict
        self.crashed = set()  # ranks down (until restarted)
        self.lose = wl.lose_budget
        self.dup = wl.dup_budget
        self.crashes = wl.crash_budget
        self.restarts = wl.restart_budget
        self.snapshots = wl.snapshot_budget
        self.choices = []
        self.stall = 0
        self.stats = {"lost": 0, "dup": 0, "crash": 0, "restart": 0,
                      "evict": 0, "snapshot": 0}
        for rank in range(wl.world):
            self._spawn(rank)
        if wl.rendezvous:
            # barrier workloads: deliver every rank's setup ops
            # (register/init/set_optimizer) up front. The product's
            # barrier contract is SPMD — every live rank reaches the
            # same barrier sites having registered before round 1; a
            # rank whose registration is delayed past another's solo
            # round progress has a shifted barrier cadence the
            # generation-counted protocol is not specified for.
            # Deterministic prefix: no choices recorded, replay-exact.
            setup = ("register", "init", "set_optimizer")
            progressed = True
            while progressed:
                progressed = False
                for rank in sorted(self.outbox):
                    if self.outbox[rank].get("op") in setup:
                        self._feed(rank, self.harness.deliver(
                            self.outbox[rank]))
                        progressed = True

    def _spawn(self, rank):
        gen = _actor(rank, self.wl)
        self.actors[rank] = gen
        self.outbox[rank] = next(gen)  # first request (register)

    def _feed(self, rank, resp):
        gen = self.actors[rank]
        try:
            self.outbox[rank] = gen.send(resp)
        except StopIteration:
            del self.actors[rank]
            self.outbox.pop(rank, None)

    def _events(self):
        ev = []
        for rank in sorted(self.outbox):
            if rank in self.crashed:
                continue
            ev.append(("deliver", rank))
            if self.lose > 0:
                ev.append(("lose", rank))
            if self.dup > 0:
                ev.append(("dup", rank))
        live_actors = [r for r in self.actors if r not in self.crashed]
        if self.crashes > 0 and len(live_actors) > 1:
            for rank in live_actors:
                ev.append(("crash", rank))
        for rank in sorted(self.crashed):
            if rank in self.coord.view.live:
                ev.append(("evict", rank))
        if self.restarts > 0:
            for rank in sorted(self.crashed):
                ev.append(("restart", rank))
        if self.snapshots > 0:
            ev.append(("snapshot", -1))
        return ev

    def _unstick(self, events):
        """Past the stall limit, only state-changing recovery events may
        be chosen (a crashed-but-unevicted rank wedges every pull poll
        exactly like a real corpse wedges a round — the sweeper's job,
        here an explicit event)."""
        forced = [e for e in events if e[0] in ("evict", "restart")]
        return forced or events

    def run(self):
        wl = self.wl
        while self.actors:
            events = self._events()
            deliverable = [e for e in events if e[0] == "deliver"]
            if not deliverable and not self.crashed:
                break  # only crashed actors remain unfinished
            if self.stall > _STALL_LIMIT:
                forced = self._unstick(events)
                if forced is events and not deliverable:
                    raise InvariantViolation(
                        "livelock: no recovery event can unstick the "
                        "schedule (crashed=%s live=%s)"
                        % (sorted(self.crashed),
                           sorted(self.coord.view.live)))
                events = forced
            if not events:
                break
            if len(self.choices) >= wl.max_steps:
                raise InvariantViolation(
                    "schedule exceeded max_steps=%d (livelock or an "
                    "undersized budget)" % wl.max_steps)
            kind, rank = self.chooser(events, self)
            self.choices.append((kind, rank))
            self._apply(kind, rank)
        # end-state: every surviving actor finished — the rounds they
        # agreed to run all completed on the server
        for k in wl.keys:
            done = self.coord.agg.done.get(k, 0)
            if self.actors == {} and done < wl.rounds and \
                    self.coord.view.live:
                raise InvariantViolation(
                    "job finished with key %r at round %d < %d"
                    % (k, done, wl.rounds))

    def _apply(self, kind, rank):
        advanced = True
        if kind == "deliver":
            self._last_deliver = rank
            req = self.outbox[rank]
            resp = self.harness.deliver(req)
            st = resp.get("status") if isinstance(resp, dict) else None
            advanced = not (st == "pending"
                            or (req.get("op") == "barrier_wait"
                                and not resp.get("done")))
            self._feed(rank, resp)
        elif kind == "lose":
            # the reply is lost: server state advanced, client retries
            # the SAME request (the at-least-once delivery reality the
            # idempotent stale/first-wins paths exist for)
            self.lose -= 1
            self.stats["lost"] += 1
            self.harness.deliver(dict(self.outbox[rank]))
            advanced = False
        elif kind == "dup":
            # lost-ack retry: the server processes the frame twice, the
            # client dispatches on the SECOND response
            self.dup -= 1
            self.stats["dup"] += 1
            self.harness.deliver(dict(self.outbox[rank]))
            resp = self.harness.deliver(self.outbox[rank])
            self._feed(rank, resp)
        elif kind == "crash":
            self.crashes -= 1
            self.stats["crash"] += 1
            self.crashed.add(rank)
        elif kind == "evict":
            self.stats["evict"] += 1
            self.harness.deliver({"op": "evict", "rank": rank})
        elif kind == "restart":
            self.restarts -= 1
            self.stats["restart"] += 1
            self.crashed.discard(rank)
            self._spawn(rank)
        elif kind == "snapshot":
            self.snapshots -= 1
            self.stats["snapshot"] += 1
            self.harness.snapshot_roundtrip()
            advanced = False
        self.stall = 0 if advanced else self.stall + 1


def _tel_counters(sim, found_mutant=False):
    from .. import telemetry as _tel

    if not _tel.ENABLED:
        return
    _tel.counter("mxproto.schedules_total").inc()
    _tel.counter("mxproto.messages_total").inc(sim.harness.messages)
    _tel.counter("mxproto.replies_lost_total").inc(sim.stats["lost"])
    _tel.counter("mxproto.dup_deliveries_total").inc(sim.stats["dup"])
    _tel.counter("mxproto.crashes_total").inc(sim.stats["crash"])
    _tel.counter("mxproto.restarts_total").inc(sim.stats["restart"])
    _tel.counter("mxproto.evictions_total").inc(sim.stats["evict"])
    _tel.counter("mxproto.snapshot_checks_total").inc(
        sim.stats["snapshot"])
    if found_mutant:
        _tel.counter("mxproto.mutants_found_total").inc()


def _random_chooser(rng):
    def choose(events, _sim):
        return events[rng.randrange(len(events))]
    return choose


def _default_event(events, sim):
    """Round-robin delivery across ranks: the DFS/scripted fallback
    schedule. (events[0]-always would run each actor's whole life
    sequentially — a base schedule in which no two ranks are ever
    concurrently mid-protocol, hiding every ordering bug.)"""
    delivers = [e for e in events if e[0] == "deliver"]
    if not delivers:
        return events[0]
    last = getattr(sim, "_last_deliver", -1)
    for e in delivers:
        if e[1] > last:
            return e
    return delivers[0]


def _scripted_chooser(script):
    state = {"i": 0}

    def choose(events, sim):
        i, state["i"] = state["i"], state["i"] + 1
        if i < len(script) and tuple(script[i]) in \
                {tuple(e) for e in events}:
            return tuple(script[i])
        return _default_event(events, sim)
    return choose


def _run_one(wl, chooser):
    """(failure tuple or None, choices, messages_stat_sim)."""
    import traceback as _tb

    # workloads may carry their own simulator (analysis/datasim.py
    # drives the data-service coordinator through the same explorer)
    sim_cls = getattr(wl, "sim_cls", None) or _Sim
    sim = sim_cls(wl, chooser)
    try:
        sim.run()
        return None, sim.choices, sim
    except Exception as e:  # noqa: BLE001 — the product under test
        kind = "invariant" if isinstance(e, InvariantViolation) \
            else "exception"
        return (kind, "%s: %s" % (type(e).__name__, e),
                "".join(_tb.format_exception(type(e), e,
                                             e.__traceback__))), \
            sim.choices, sim


def explore(wl, schedules=25, seed=0, strategy="random",
            max_switches=3, stop_on_first=True):
    """Drive a :class:`ProtoWorkload` through many message schedules.
    ``random`` draws every choice from the per-schedule seeded stream;
    ``dfs`` deviates from the deliver-in-rank-order default at up to
    ``max_switches`` decision points (the bounded context-switch idea
    of the thread explorer, applied to deliveries)."""
    import random as _random

    failures, explored = [], 0
    if strategy == "random":
        for i in range(schedules):
            sseed = _schedule_seed(seed, i)
            failure, choices, sim = _run_one(
                wl, _random_chooser(_random.Random(sseed)))
            explored += 1
            _tel_counters(sim, found_mutant=failure is not None)
            if failure is not None:
                failures.append(ProtoFailure(
                    wl.name, "random", seed, i, sseed, choices,
                    failure[0], failure[1], failure[2]))
                if stop_on_first:
                    break
        return ExploreResult(wl.name, "random", seed, explored, failures)
    if strategy != "dfs":
        raise ValueError("unknown strategy %r" % (strategy,))
    stack = [((), 0)]
    seen = set()
    while stack and explored < schedules:
        prefix, switches = stack.pop()
        if prefix in seen:
            continue
        seen.add(prefix)
        enabled_log = []

        def chooser(events, sim, _p=prefix, _log=enabled_log):
            i = len(sim.choices)
            _log.append(list(events))
            if i < len(_p) and tuple(_p[i]) in \
                    {tuple(e) for e in events}:
                return tuple(_p[i])
            return _default_event(events, sim)

        failure, choices, sim = _run_one(wl, chooser)
        explored += 1
        _tel_counters(sim, found_mutant=failure is not None)
        if failure is not None:
            failures.append(ProtoFailure(
                wl.name, "dfs", seed, explored - 1, 0, choices,
                failure[0], failure[1], failure[2]))
            if stop_on_first:
                break
        if switches >= max_switches:
            continue
        for i in range(len(prefix), len(enabled_log)):
            taken = tuple(choices[i]) if i < len(choices) else None
            for alt in enabled_log[i]:
                if tuple(alt) == taken:
                    continue
                stack.append(
                    (tuple(map(tuple, choices[:i])) + (tuple(alt),),
                     switches + 1))
    return ExploreResult(wl.name, "dfs", seed, explored, failures)


def replay(wl, seed, index, choices=None):
    """Re-run exactly one schedule (the one a failure report names).
    Returns the reproduced ProtoFailure, or None — after a fix, None
    IS the green light."""
    import random as _random

    if choices is not None:
        chooser = _scripted_chooser([tuple(c) for c in choices])
        sseed = 0
    else:
        sseed = _schedule_seed(seed, index)
        chooser = _random_chooser(_random.Random(sseed))
    failure, got, _sim = _run_one(wl, chooser)
    if failure is None:
        return None
    return ProtoFailure(wl.name, "random" if choices is None else "dfs",
                        seed, index, sseed, got, failure[0], failure[1],
                        failure[2])


# -- built-in workloads --------------------------------------------------------

def allreduce_workload(world=3, keys=("w", "b"), rounds=3):
    """All-reduce mode (no optimizer): merged gradients are the stored
    values, degraded rescale is directly checkable. Perturbations on."""
    return ProtoWorkload("proto_allreduce", world=world, keys=keys,
                         rounds=rounds)


def barrier_workload(world=3, rounds=4):
    """Round-anchored barriers under reply loss, duplication and a
    crash->evict: release-only-with-coverage, idempotent re-arrival
    (the count path), and eviction-recheck release. No restarts: a
    restarted incarnation re-barriers at boundaries the group already
    passed, a cadence the product's SPMD barrier sites never have."""
    return ProtoWorkload("proto_barrier", world=world, keys=("w",),
                         rounds=rounds, barrier_every=2,
                         crash_budget=1, restart_budget=0,
                         rendezvous=True)


def shard_workload(world=3, keys=("w", "b", "c"), rounds=2):
    """Shard-update mode: owner hand-outs, put_weight first-writer-wins,
    ownership reassignment across evictions."""
    return ProtoWorkload("proto_shard", world=world, keys=keys,
                         rounds=rounds, shard=True)


def epoch_regress_workload():
    """NEGATIVE CONTROL: rejoin regresses the epoch. Crash + evict +
    restart pressure raised so a random walk meets a rejoin quickly."""
    return ProtoWorkload("mutant_epoch_regress", world=3, keys=("w",),
                         rounds=3, lose_budget=0, dup_budget=0,
                         crash_budget=2, restart_budget=2,
                         snapshot_budget=0, view_cls=_EpochRegressView)


def unguarded_completion_workload():
    """NEGATIVE CONTROL: round completion without live-set coverage."""
    return ProtoWorkload("mutant_unguarded_completion", world=3,
                         keys=("w",), rounds=2, lose_budget=0,
                         dup_budget=0, crash_budget=0, restart_budget=0,
                         snapshot_budget=0,
                         agg_cls=_UnguardedAggregator)


def survival_suite(seed=0, schedules=None):
    """The ``mxlint --protosim`` / ``chaos --proto`` legs: both mutants
    must be FOUND and REPLAYED from their (seed, index) pair, then the
    clean all-reduce and shard workloads must survive every schedule.
    Returns (findings, report_lines) in the mxrace survival shape."""
    if schedules is None:
        schedules = int(os.environ.get("MXPROTO_SCHEDULES", "25") or 25)
    findings, lines = [], []

    for name, wl in (("control/epoch-regress", epoch_regress_workload()),
                     ("control/unguarded", unguarded_completion_workload())):
        r = explore(wl, schedules=schedules, seed=seed)
        if r.ok:
            findings.append(Finding(
                "protosim", "control-miss", "error", name,
                "the simulator failed to find the SEEDED protocol "
                "mutant %r in %d schedules — message-schedule "
                "exploration is not actually exploring"
                % (wl.name, r.explored)))
            lines.append("%-22s: MISSED its seeded mutant (%d schedules)"
                         % (name, r.explored))
            continue
        f = r.first_failure()
        rep = replay(wl, seed=seed, index=f.index)
        if rep is None:
            findings.append(Finding(
                "protosim", "replay-miss", "error", name,
                "failing schedule #%d of %r did not reproduce on "
                "replay — schedules are not deterministic"
                % (f.index, wl.name)))
            lines.append("%-22s: mutant found but replay MISSED" % name)
        else:
            lines.append(
                "%-22s: mutant found at schedule #%d (%s), replayed "
                "from (seed=%d, index=%d)"
                % (name, f.index, f.kind, seed, f.index))

    for name, wl in (("allreduce", allreduce_workload()),
                     ("barriers", barrier_workload()),
                     ("shard-update", shard_workload())):
        r = explore(wl, schedules=schedules, seed=seed)
        if r.ok:
            lines.append("%-22s: survived %d schedules"
                         % (name, r.explored))
        else:
            f = r.first_failure()
            findings.append(Finding(
                "protosim", "protocol-race", "error",
                "%s schedule #%d" % (name, f.index),
                "%s under an adversarial message schedule: %s — %s"
                % (f.kind, f.message, f.replay_hint())))
            lines.append("%-22s: FAILED at schedule #%d (%s)"
                         % (name, f.index, f.kind))
    return findings, lines
