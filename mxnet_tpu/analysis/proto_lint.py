"""mxproto protocol lint: static schema + timing analysis over the
elastic RPC substrate (``mxlint --proto``).

The elastic coordination protocol (mxnet_tpu/elastic/) is a string-op,
dict-payload RPC dispatched through if-chains — flexible, and with zero
static checking: a misspelled op, a field the server never reads, or a
reply key the client consumes but no arm returns are all silent until a
distributed job wedges. Every protocol bug this repo has already paid
for (the long-poll-cap-vs-socket-timeout incident, the chaos
heartbeat-starvation flake) was exactly such a cross-module mismatch.
This pass extracts both halves of the protocol from the AST and diffs
them bidirectionally:

- **Client side** — every ``X.call("op", field=...)`` / ``X._op("op",
  ...)`` literal-op call site, the per-op wrapper methods of
  ``ElasticClient`` (a method whose body is a single literal-op
  ``self.call(...)`` registers the wrapper name, and ``X.wrapper(...)``
  calls on client-named receivers resolve through it), and
  ``**fields`` expansions through dict-building helpers
  (``pull_fields``). Reply consumption is tracked per function:
  ``resp = <client call>`` followed by ``resp["k"]`` (required) or
  ``resp.get("k")`` (optional).
- **Server side** — any function containing ``op = req.get("op")`` is a
  dispatch function; ``op == "literal"`` guards open per-op arms, whose
  ``req["f"]``/``req.get("f")`` reads and returned dict-literal keys
  (including dict-returning helpers reached via ``err = helper(); return
  err``) accumulate per op. Reads/returns outside any guard are common
  to every op.

Detectors (pass ``proto``):

| code | severity | meaning |
|---|---|---|
| ``unknown-op`` | error | client sends an op no dispatch arm handles |
| ``reply-missing`` | error | client subscripts a reply key absent from every return of that op |
| ``field-unread`` | warning | field sent but no arm ever reads it |
| ``field-missing`` | warning | required (subscripted) request field no client ever sends |
| ``raw-protocol-call`` | warning | ``protocol.call`` outside the RetryPolicy/``kv.coord`` discipline (the enclosing function carries no ``*.point("kv.coord")``) |
| ``dead-arm`` | info | dispatch arm no in-package client calls (admin/test hooks) |
| ``lattice-*`` | error | a timeout-ordering invariant is violated (below) |
| ``lattice-incomplete`` | warning | an expected lattice constant could not be derived — the check silently narrowed |
| ``lattice-conflict`` | warning | two modules declare different defaults for the same env knob |

**Timeout-budget lattice.** The timing constants live in different
modules (client socket timeout, server long-poll cap ``_WAIT_CAP``,
heartbeat period, ``MXNET_KV_EVICT_AFTER``, retry policy, barrier
deadline); :func:`derive_lattice` recovers each from its defining site
(env-parse defaults, ``timeout=`` parameter defaults, ``*WAIT_CAP*``
module constants, ``RetryPolicy(...)`` kwargs), applies any live env
overrides, and hands the values to
``mxnet_tpu.elastic.budget.check_budgets`` — the shared invariant
oracle the coordinator's own evict-floor clamp uses. Violations
(server cap >= socket timeout; heartbeat x misses + jitter slack >
evict window; client poll budget > server cap; retry budget >= barrier
deadline) are errors: they are the PR 7 and chaos-flake bug classes as
lint findings.

Scope honesty: reply reads through helper *parameters*
(``_absorb_view(resp)``) and wrapper calls on receivers not named like
a client are not attributed — the protocol simulator
(``analysis/protosim.py``) exercises those paths dynamically. Fields
starting with ``_`` are the tracing envelope and exempt by contract.
"""
from __future__ import annotations

import ast
import os

from .findings import Finding

__all__ = ["lint_protocol", "extract_schema", "derive_lattice",
           "DEFAULT_TARGETS", "Schema", "OpSchema"]

_PKG = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

#: the modules that speak the elastic protocol (both halves) plus the
#: ones defining its timing constants
DEFAULT_TARGETS = (
    os.path.join(_PKG, "elastic", "client.py"),
    os.path.join(_PKG, "elastic", "server.py"),
    os.path.join(_PKG, "elastic", "protocol.py"),
    os.path.join(_PKG, "elastic", "budget.py"),
    os.path.join(_PKG, "kvstore.py"),
    os.path.join(_PKG, "analysis", "protosim.py"),
    # the data-plane speakers (docs/how_to/data_service.md) share the
    # op namespace deliberately: register/beat/leave/evict/stats carry
    # identical shapes on both coordinators, and the diff covers the
    # union of arms
    os.path.join(_PKG, "data_service", "client.py"),
    os.path.join(_PKG, "data_service", "server.py"),
    os.path.join(_PKG, "analysis", "datasim.py"),
    # the weight-sync speakers (docs/how_to/weight_sync.md): the
    # wsync_* ops are prefixed because this namespace is global —
    # their arms and call sites lint under the same discipline
    os.path.join(_PKG, "wsync", "client.py"),
    os.path.join(_PKG, "wsync", "publisher.py"),
    os.path.join(_PKG, "wsync", "subscriber.py"),
    # the serving-fleet speakers (docs/how_to/serving.md, mxfleet):
    # fleet_* ops — router.py carries FleetClient (the only client)
    # plus the router's register/leave arms; replica.py the data arms
    os.path.join(_PKG, "serving", "fleet", "router.py"),
    os.path.join(_PKG, "serving", "fleet", "replica.py"),
)

#: constants the lattice must recover from DEFAULT_TARGETS; an explicit
#: path list (fixtures) checks whatever it finds instead
_LATTICE_REQUIRED = ("client_timeout", "wait_cap", "pull_wait",
                     "heartbeat", "evict_after", "retry_attempts")

#: env knob -> lattice constant name
_ENV_CONSTS = {
    "MXNET_KVSTORE_HEARTBEAT_INTERVAL": "heartbeat",
    "MXNET_KV_EVICT_AFTER": "evict_after",
    "MXNET_KV_PULL_WAIT": "pull_wait",
    "MXNET_KV_RETRIES": "retry_attempts",
    "MXNET_KV_BARRIER_TIMEOUT": "barrier_timeout",
    "MXNET_KV_HEARTBEAT_MISSES": "misses",
    "MXNET_KV_EVICT_JITTER_SLACK": "jitter_slack",
}

_ENVELOPE = "_"          # _trace/_srv_t: tracing envelope, exempt
_CALL_METHODS = ("call", "_op")


def _attr_chain(expr):
    parts = []
    while isinstance(expr, ast.Attribute):
        parts.append(expr.attr)
        expr = expr.value
    if isinstance(expr, ast.Name):
        parts.append(expr.id)
        return tuple(reversed(parts))
    return None


def _str_const(node):
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def _num_const(node):
    if isinstance(node, ast.Constant) and \
            isinstance(node.value, (int, float)) and \
            not isinstance(node.value, bool):
        return float(node.value)
    return None


class OpSchema:
    """Both protocol halves for one op, with source sites."""

    def __init__(self, op):
        self.op = op
        self.sent = {}            # field -> [where]
        self.dynamic_send = False  # a **expansion we could not resolve
        self.client_sites = []    # [where]
        self.req_required = {}    # field -> [where]  (req["f"])
        self.req_optional = {}    # field -> [where]  (req.get("f"...))
        self.replies = {}         # key -> [where]
        self.server_sites = []    # [where]
        self.resp_required = {}   # key -> [where]    (resp["k"])
        self.resp_optional = {}   # key -> [where]


class Schema:
    """The whole extracted protocol: per-op schemas + the common
    (every-op) halves + undisciplined transport call sites."""

    def __init__(self):
        self.ops = {}             # op -> OpSchema
        self.common = OpSchema("*")
        self.raw_calls = []       # [where] protocol.call outside discipline

    def op(self, name):
        if name == "*":
            return self.common
        return self.ops.setdefault(name, OpSchema(name))


def _add(dct, key, where):
    dct.setdefault(key, []).append(where)


class _FileFacts:
    """Pass-1 inventory of one source file."""

    def __init__(self, path, tree):
        self.path = path
        self.rel = os.path.relpath(path, os.path.dirname(_PKG)) \
            if path.startswith(_PKG) else os.path.basename(path)
        self.tree = tree
        self.dict_fns = {}        # fn name -> set(returned dict keys)
        self.wrappers = {}        # method name -> (op, {field: line})
        self.call_param_names = set()  # named params of call-like defs

    def where(self, node):
        return "%s:%d" % (self.rel, getattr(node, "lineno", 0))


def _returned_dict_keys(fn):
    """String keys a function can return as a dict: direct dict-literal
    returns plus dict-literal vars extended by ``var["k"] = v`` that are
    later returned."""
    keys = set()
    dict_vars = {}
    for node in ast.walk(fn):
        if isinstance(node, ast.FunctionDef) and node is not fn:
            continue  # shallow enough: nested defs rare in these modules
        if isinstance(node, ast.Assign) and isinstance(node.value, ast.Dict):
            ks = {_str_const(k) for k in node.value.keys if k is not None}
            ks.discard(None)
            for t in node.targets:
                if isinstance(t, ast.Name):
                    dict_vars.setdefault(t.id, set()).update(ks)
        elif isinstance(node, ast.Assign) and \
                isinstance(node.targets[0], ast.Subscript) and \
                isinstance(node.targets[0].value, ast.Name):
            k = _str_const(node.targets[0].slice)
            nm = node.targets[0].value.id
            if k is not None and nm in dict_vars:
                dict_vars[nm].add(k)
    for node in ast.walk(fn):
        if not isinstance(node, ast.Return) or node.value is None:
            continue
        if isinstance(node.value, ast.Dict):
            for k in node.value.keys:
                s = _str_const(k) if k is not None else None
                if s is not None:
                    keys.add(s)
        elif isinstance(node.value, ast.Name) and \
                node.value.id in dict_vars:
            keys.update(dict_vars[node.value.id])
    return keys


def _inventory(path, tree):
    facts = _FileFacts(path, tree)
    for node in ast.walk(tree):
        if not isinstance(node, ast.FunctionDef):
            continue
        keys = _returned_dict_keys(node)
        if keys:
            facts.dict_fns.setdefault(node.name, set()).update(keys)
        if node.name in _CALL_METHODS:
            for a in node.args.args[2:]:  # beyond (self, op)
                facts.call_param_names.add(a.arg)
        # wrapper methods: body contains exactly one literal-op
        # self.call(...) and no other client-call expressions
        calls = [n for n in ast.walk(node)
                 if isinstance(n, ast.Call)
                 and isinstance(n.func, ast.Attribute)
                 and n.func.attr in _CALL_METHODS]
        if len(calls) == 1 and node.name not in _CALL_METHODS:
            c = calls[0]
            chain = _attr_chain(c.func)
            op = _str_const(c.args[0]) if c.args else None
            if op is not None and chain and chain[0] == "self":
                fields = {kw.arg: c.lineno for kw in c.keywords
                          if kw.arg is not None}
                facts.wrappers[node.name] = (op, fields)
    return facts


def _client_receiver(chain):
    """True when an attribute chain's receiver looks like an elastic
    client handle (``self._client.x``, ``client.x`` …) — the scope rule
    that keeps ``self.agg.put_weight`` / ``self.view.register`` (server
    internals sharing wrapper names) out of the client schema."""
    if chain is None or len(chain) < 2:
        return False
    recv = chain[-2]
    return "client" in recv.lower() or recv in ("c0", "c1", "c2", "cl")


class _ClientScan(ast.NodeVisitor):
    """Pass 2, client half of one file."""

    def __init__(self, facts, all_facts, schema, exclude_kwargs):
        self.facts = facts
        self.all_facts = all_facts
        self.schema = schema
        self.exclude = exclude_kwargs

    def _dict_fn_keys(self, name):
        for f in self.all_facts:
            if name in f.dict_fns:
                return f.dict_fns[name]
        return None

    def _wrapper(self, name):
        for f in self.all_facts:
            if name in f.wrappers:
                return f.wrappers[name]
        return None

    def _classify_call(self, call):
        """(op or None, fields {name: lineno}, dynamic) for a client-call
        expression, else (None, None, False)."""
        if not isinstance(call, ast.Call) or \
                not isinstance(call.func, ast.Attribute):
            return None, None, False
        chain = _attr_chain(call.func)
        meth = call.func.attr
        if meth in _CALL_METHODS and call.args:
            op = _str_const(call.args[0])
            if op is None:
                # dynamic op (retry-policy .call(fn), _op passthrough):
                # only reply reads matter, attributed to every op
                if isinstance(call.args[0], (ast.Name, ast.Attribute)):
                    return "*", {}, False
                return None, None, False
            fields, dynamic = {}, False
            for kw in call.keywords:
                if kw.arg is None:  # **expansion
                    keys = None
                    if isinstance(kw.value, ast.Call) and \
                            isinstance(kw.value.func,
                                       (ast.Attribute, ast.Name)):
                        fname = kw.value.func.attr \
                            if isinstance(kw.value.func, ast.Attribute) \
                            else kw.value.func.id
                        keys = self._dict_fn_keys(fname)
                    if keys:
                        for k in keys:
                            fields[k] = call.lineno
                    else:
                        dynamic = True
                elif kw.arg not in self.exclude:
                    fields[kw.arg] = call.lineno
            return op, fields, dynamic
        wrap = self._wrapper(meth)
        if wrap is not None and _client_receiver(chain):
            op, fields = wrap
            out = {k: call.lineno for k in fields if k not in self.exclude}
            # wrapper bodies may **-expand a dict helper too
            return op, out, False
        return None, None, False

    def scan_function(self, fn):
        var_ops = {}  # var name -> set(op)
        for node in ast.walk(fn):
            if isinstance(node, ast.FunctionDef) and node is not fn:
                continue
            if isinstance(node, ast.Assign) and \
                    isinstance(node.value, ast.Call) and \
                    isinstance(node.targets[0], ast.Name):
                op, _f, _d = self._classify_call(node.value)
                if op is not None:
                    var_ops.setdefault(node.targets[0].id, set()).add(op)
            if isinstance(node, ast.Call):
                op, fields, dynamic = self._classify_call(node)
                if op is not None and op != "*":
                    sch = self.schema.op(op)
                    sch.client_sites.append(self.facts.where(node))
                    for k, ln in (fields or {}).items():
                        if not k.startswith(_ENVELOPE):
                            _add(sch.sent, k,
                                 "%s:%d" % (self.facts.rel, ln))
                    if dynamic:
                        sch.dynamic_send = True
        # reply reads on vars assigned from client calls
        for node in ast.walk(fn):
            key = name = None
            required = False
            if isinstance(node, ast.Subscript) and \
                    isinstance(node.value, ast.Name) and \
                    isinstance(node.ctx, ast.Load):
                key, name, required = (_str_const(node.slice),
                                       node.value.id, True)
            elif isinstance(node, ast.Call) and \
                    isinstance(node.func, ast.Attribute) and \
                    node.func.attr == "get" and \
                    isinstance(node.func.value, ast.Name) and node.args:
                key, name = _str_const(node.args[0]), node.func.value.id
            if key is None or name not in var_ops or \
                    key.startswith(_ENVELOPE):
                continue
            for op in var_ops[name]:
                sch = self.schema.op(op)
                tgt = sch.resp_required if required else sch.resp_optional
                _add(tgt, key, self.facts.where(node))

    def _scan_common_sends(self, fn):
        """Fields the transport assembly attaches to EVERY request:
        ``req["op"] = op`` / ``req["rank"] = ...`` subscript-assigns in
        a function whose subtree hands a dict to ``protocol.call``."""
        req_vars = set()
        for n in ast.walk(fn):
            if isinstance(n, ast.Call):
                chain = _attr_chain(n.func)
                if chain and len(chain) >= 2 and chain[-1] == "call" \
                        and chain[-2] == "protocol" and \
                        len(n.args) >= 2 and \
                        isinstance(n.args[1], ast.Name):
                    req_vars.add(n.args[1].id)
        if not req_vars:
            return
        for node in ast.walk(fn):
            if isinstance(node, ast.Assign) and \
                    isinstance(node.targets[0], ast.Subscript) and \
                    isinstance(node.targets[0].value, ast.Name) and \
                    node.targets[0].value.id in req_vars:
                k = _str_const(node.targets[0].slice)
                if k is not None and not k.startswith(_ENVELOPE):
                    _add(self.schema.common.sent, k,
                         self.facts.where(node))

    def run(self):
        for node in ast.walk(self.facts.tree):
            if isinstance(node, ast.FunctionDef):
                self.scan_function(node)
                self._scan_common_sends(node)


class _ServerScan:
    """Pass 2, server half: dispatch functions and their arms."""

    def __init__(self, facts, all_facts, schema):
        self.facts = facts
        self.all_facts = all_facts
        self.schema = schema

    def _helper_keys(self, name):
        for f in self.all_facts:
            if name in f.dict_fns:
                return f.dict_fns[name]
        return None

    @staticmethod
    def _find_dispatch(fn):
        """req/op variable names when ``fn`` is a dispatch function."""
        for node in ast.walk(fn):
            if isinstance(node, ast.Assign) and \
                    isinstance(node.targets[0], ast.Name) and \
                    isinstance(node.value, ast.Call) and \
                    isinstance(node.value.func, ast.Attribute) and \
                    node.value.func.attr == "get" and \
                    isinstance(node.value.func.value, ast.Name) and \
                    node.value.args and \
                    _str_const(node.value.args[0]) == "op":
                return node.value.func.value.id, node.targets[0].id
        return None, None

    def _ops_in_test(self, test, opvar):
        ops = []
        for node in ast.walk(test):
            if isinstance(node, ast.Compare) and \
                    isinstance(node.left, ast.Name) and \
                    node.left.id == opvar and \
                    len(node.ops) == 1 and \
                    isinstance(node.ops[0], ast.Eq):
                s = _str_const(node.comparators[0])
                if s is not None:
                    ops.append(s)
        return ops

    def _collect(self, stmts, ctx, reqvar, opvar, helper_vars):
        for stmt in stmts:
            if isinstance(stmt, ast.If):
                arm_ops = self._ops_in_test(stmt.test, opvar)
                inner = arm_ops if arm_ops else ctx
                if arm_ops:
                    for op in arm_ops:
                        self.schema.op(op).server_sites.append(
                            self.facts.where(stmt))
                self._collect(stmt.body, inner, reqvar, opvar, helper_vars)
                self._collect(stmt.orelse, ctx, reqvar, opvar, helper_vars)
                continue
            if isinstance(stmt, (ast.With, ast.For, ast.While, ast.Try)):
                for attr in ("body", "orelse", "finalbody"):
                    self._collect(getattr(stmt, attr, []) or [], ctx,
                                  reqvar, opvar, helper_vars)
                for h in getattr(stmt, "handlers", []) or []:
                    self._collect(h.body, ctx, reqvar, opvar, helper_vars)
                continue
            # helper-returning assignments: err = self._require_live(r)
            if isinstance(stmt, ast.Assign) and \
                    isinstance(stmt.value, ast.Call) and \
                    isinstance(stmt.targets[0], ast.Name):
                fname = None
                if isinstance(stmt.value.func, ast.Attribute):
                    fname = stmt.value.func.attr
                elif isinstance(stmt.value.func, ast.Name):
                    fname = stmt.value.func.id
                keys = self._helper_keys(fname) if fname else None
                if keys:
                    helper_vars[stmt.targets[0].id] = keys
            self._scan_reads(stmt, ctx, reqvar)
            if isinstance(stmt, ast.Return) and stmt.value is not None:
                self._record_return(stmt, ctx, helper_vars)

    def _scan_reads(self, stmt, ctx, reqvar):
        for node in ast.walk(stmt):
            key = required = None
            if isinstance(node, ast.Subscript) and \
                    isinstance(node.value, ast.Name) and \
                    node.value.id == reqvar and \
                    isinstance(node.ctx, ast.Load):
                key, required = _str_const(node.slice), True
            elif isinstance(node, ast.Call) and \
                    isinstance(node.func, ast.Attribute) and \
                    node.func.attr in ("get", "pop") and \
                    isinstance(node.func.value, ast.Name) and \
                    node.func.value.id == reqvar and node.args:
                key, required = _str_const(node.args[0]), False
            if key is None or key.startswith(_ENVELOPE) or key == "op":
                continue
            for op in (ctx or ["*"]):
                sch = self.schema.op(op)
                tgt = sch.req_required if required else sch.req_optional
                _add(tgt, key, self.facts.where(node))

    def _record_return(self, stmt, ctx, helper_vars):
        keys = set()
        if isinstance(stmt.value, ast.Dict):
            for k in stmt.value.keys:
                s = _str_const(k) if k is not None else None
                if s is not None and not s.startswith(_ENVELOPE):
                    keys.add(s)
        elif isinstance(stmt.value, ast.Name) and \
                stmt.value.id in helper_vars:
            keys = {k for k in helper_vars[stmt.value.id]
                    if not k.startswith(_ENVELOPE)}
        for op in (ctx or ["*"]):
            sch = self.schema.op(op)
            for k in keys:
                _add(sch.replies, k, self.facts.where(stmt))

    def run(self):
        for node in ast.walk(self.facts.tree):
            if not isinstance(node, ast.FunctionDef):
                continue
            reqvar, opvar = self._find_dispatch(node)
            if reqvar is None:
                continue
            # the nested preamble (op == "push" and ...) guards and the
            # arm chain all merge per op via the ctx mechanism
            self._collect(node.body, None, reqvar, opvar, {})


def _scan_raw_calls(facts, schema):
    """protocol.call sites whose innermost enclosing function carries no
    kv.coord fault point — transport use outside the retry discipline."""
    if os.path.basename(facts.path) == "protocol.py":
        return  # the definition module
    fns = [n for n in ast.walk(facts.tree)
           if isinstance(n, ast.FunctionDef)]

    def innermost(node):
        best = None
        for fn in fns:
            if fn.lineno <= node.lineno <= \
                    (fn.end_lineno or fn.lineno) and \
                    (best is None or fn.lineno > best.lineno):
                best = fn
        return best

    def has_coord_point(fn):
        for n in ast.walk(fn):
            if isinstance(n, ast.Call) and \
                    isinstance(n.func, ast.Attribute) and \
                    n.func.attr == "point" and n.args and \
                    _str_const(n.args[0]) == "kv.coord":
                return True
        return False

    for node in ast.walk(facts.tree):
        if not isinstance(node, ast.Call):
            continue
        chain = _attr_chain(node.func)
        if not chain or len(chain) < 2 or chain[-1] != "call" or \
                chain[-2] != "protocol":
            continue
        fn = innermost(node)
        if fn is None or not has_coord_point(fn):
            schema.raw_calls.append(facts.where(node))


def _iter_sources(paths):
    for p in paths:
        if os.path.isdir(p):
            for root, dirs, files in os.walk(p):
                dirs[:] = [d for d in dirs if d != "__pycache__"]
                for f in sorted(files):
                    if f.endswith(".py"):
                        yield os.path.join(root, f)
        else:
            yield p


def extract_schema(paths=None):
    """Extract the full protocol :class:`Schema` from ``paths``
    (defaults to the elastic substrate + its in-package speakers).
    Raises OSError/SyntaxError on unreadable or unparsable input."""
    default_targets = paths is None
    paths = list(_iter_sources(paths or DEFAULT_TARGETS))
    all_facts = []
    for p in paths:
        if default_targets and not os.path.exists(p):
            continue  # a default target absent in a stripped checkout
        with open(p, "r", encoding="utf-8") as f:
            src = f.read()
        all_facts.append(_inventory(p, ast.parse(src, filename=p)))
    schema = Schema()
    exclude = set()
    for f in all_facts:
        exclude |= f.call_param_names
    exclude.discard("op")
    for facts in all_facts:
        _ClientScan(facts, all_facts, schema, exclude).run()
        _ServerScan(facts, all_facts, schema).run()
        _scan_raw_calls(facts, schema)
    return schema


# -- timeout lattice -----------------------------------------------------------

def _env_default_sites(tree, rel):
    """{env name: [(default value, where)]} for os.environ.get sites."""
    out = {}
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        chain = _attr_chain(node.func)
        # os.environ.get(...) and budget.py's injected `env.get(...)`
        if not chain or len(chain) < 2 or chain[-1] != "get" or \
                chain[-2] not in ("environ", "env"):
            continue
        if not node.args:
            continue
        name = _str_const(node.args[0])
        if name not in _ENV_CONSTS or len(node.args) < 2:
            continue
        dflt = node.args[1]
        val = _num_const(dflt)
        if val is None:
            s = _str_const(dflt)
            if s is not None:
                try:
                    val = float(s)
                except ValueError:
                    val = None
        if val is not None:
            out.setdefault(name, []).append(
                (val, "%s:%d" % (rel, node.lineno)))
    return out


def derive_lattice(paths=None, env=None, required=None):
    """(constants, findings): the timeout lattice recovered from the
    sources. ``constants`` maps lattice names to ``(value, source)``;
    ``findings`` carries lattice-incomplete / lattice-conflict
    warnings. ``env`` (default ``os.environ``) overrides the parsed
    defaults for env-backed knobs — so the lint checks the *configured*
    lattice, not just the shipped one."""
    env = os.environ if env is None else env
    default_targets = paths is None
    if required is None:
        required = _LATTICE_REQUIRED if default_targets else ()
    paths = list(_iter_sources(paths or DEFAULT_TARGETS))
    consts, findings = {}, []
    env_sites = {}
    timeout_candidates = []   # (value, where)
    for p in paths:
        if default_targets and not os.path.exists(p):
            continue
        with open(p, "r", encoding="utf-8") as f:
            src = f.read()
        tree = ast.parse(src, filename=p)
        rel = os.path.relpath(p, os.path.dirname(_PKG)) \
            if p.startswith(_PKG) else os.path.basename(p)
        for name, sites in _env_default_sites(tree, rel).items():
            env_sites.setdefault(name, []).extend(sites)
        for node in ast.walk(tree):
            # module constants named *WAIT_CAP*
            if isinstance(node, ast.Assign) and \
                    len(node.targets) == 1 and \
                    isinstance(node.targets[0], ast.Name) and \
                    "WAIT_CAP" in node.targets[0].id.upper():
                v = _num_const(node.value)
                if v is not None:
                    consts.setdefault(
                        "wait_cap", (v, "%s:%d" % (rel, node.lineno)))
            # timeout= parameter defaults on __init__/call defs
            if isinstance(node, ast.FunctionDef) and \
                    node.name in ("__init__", "call"):
                fargs, fdefaults = node.args.args, node.args.defaults
                for a, d in zip(fargs[len(fargs) - len(fdefaults):],
                                fdefaults):
                    if a.arg == "timeout":
                        v = _num_const(d)
                        if v is not None:
                            timeout_candidates.append(
                                (v, "%s:%d" % (rel, node.lineno)))
            # RetryPolicy(...) shape
            if isinstance(node, ast.Call):
                fname = None
                if isinstance(node.func, ast.Name):
                    fname = node.func.id
                elif isinstance(node.func, ast.Attribute):
                    fname = node.func.attr
                if fname == "RetryPolicy":
                    for kw in node.keywords:
                        v = _num_const(kw.value)
                        if v is None:
                            continue
                        where = "%s:%d" % (rel, node.lineno)
                        if kw.arg == "base_delay":
                            consts.setdefault("retry_base", (v, where))
                        elif kw.arg == "max_delay":
                            consts.setdefault("retry_max", (v, where))
                        elif kw.arg == "multiplier":
                            consts.setdefault("retry_multiplier",
                                              (v, where))
    if timeout_candidates:
        consts.setdefault("client_timeout", min(timeout_candidates))
    for name, sites in env_sites.items():
        values = {v for v, _w in sites}
        if len(values) > 1:
            findings.append(Finding(
                "proto", "lattice-conflict", "warning",
                "; ".join(w for _v, w in sites),
                "env knob %s declares different defaults across modules "
                "(%s) — one side of the timeout lattice is stale"
                % (name, sorted(values))))
        const = _ENV_CONSTS[name]
        value, where = sites[0]
        raw = env.get(name)
        if raw not in (None, ""):
            try:
                value, where = float(raw), "env %s" % name
            except ValueError:
                pass
        consts.setdefault(const, (value, where))
    for const in required:
        if const not in consts:
            findings.append(Finding(
                "proto", "lattice-incomplete", "warning", const,
                "timeout-lattice constant %r could not be derived from "
                "the scanned sources — the ordering invariants that "
                "need it were silently skipped (did a refactor move or "
                "rename its defining site?)" % const))
    return consts, findings


def _lattice_findings(consts):
    from ..elastic import budget

    values = {k: v for k, (v, _w) in consts.items()}
    out = []
    for v in budget.check_budgets(values):
        srcs = ", ".join(
            "%s=%s (%s)" % (k, consts[k][0], consts[k][1])
            for k in sorted(consts)
            if k.split("_")[0] in v.code or k in v.message)
        out.append(Finding(
            "proto", v.code, "error", srcs or "timeout lattice",
            v.message))
    return out


# -- the diff ------------------------------------------------------------------

def lint_protocol(paths=None, env=None):
    """Run the full mxproto static pass: bidirectional schema diff,
    transport-discipline check, timeout lattice. Returns findings."""
    schema = extract_schema(paths)
    consts, findings = derive_lattice(paths, env=env)
    findings.extend(_lattice_findings(consts))

    common_reads = set(schema.common.req_required) | \
        set(schema.common.req_optional)
    common_replies = set(schema.common.replies)
    common_sent = set(schema.common.sent)
    server_ops = {op for op, s in schema.ops.items() if s.server_sites}
    client_ops = {op for op, s in schema.ops.items() if s.client_sites}

    for op in sorted(schema.ops):
        sch = schema.ops[op]
        is_known = op in server_ops
        if sch.client_sites and not is_known:
            if server_ops:  # only when a server half is in scope at all
                findings.append(Finding(
                    "proto", "unknown-op", "error",
                    sch.client_sites[0],
                    "client sends op %r but no dispatch arm handles it "
                    "(server ops: %s) — the server answers "
                    "status='error' at runtime"
                    % (op, ", ".join(sorted(server_ops)))))
            continue
        if sch.server_sites and not sch.client_sites:
            findings.append(Finding(
                "proto", "dead-arm", "info", sch.server_sites[0],
                "dispatch arm %r has no in-package client call site "
                "(admin/test hook, or dead protocol surface)" % op))
        if not (sch.client_sites and sch.server_sites):
            continue
        reads = set(sch.req_required) | set(sch.req_optional) | \
            common_reads
        for field in sorted(set(sch.sent) - reads - common_sent):
            findings.append(Finding(
                "proto", "field-unread", "warning",
                sch.sent[field][0],
                "field %r is sent with op %r but no dispatch arm ever "
                "reads it — dead payload, or a renamed field the server "
                "half missed" % (field, op)))
        if not sch.dynamic_send:
            sent = set(sch.sent) | common_sent
            for field in sorted(set(sch.req_required) - sent):
                findings.append(Finding(
                    "proto", "field-missing", "warning",
                    sch.req_required[field][0],
                    "dispatch arm %r subscripts required field %r but "
                    "no client call site sends it — a KeyError reply "
                    "the moment the arm runs" % (op, field)))
        replies = set(sch.replies) | common_replies
        for key in sorted(set(sch.resp_required) - replies):
            findings.append(Finding(
                "proto", "reply-missing", "error",
                sch.resp_required[key][0],
                "client subscripts reply key %r of op %r but no server "
                "return for that op carries it — a client-side "
                "KeyError on the live path" % (key, op)))
    # common-client required reads (dynamic-op wrappers) must be
    # satisfied by EVERY op's replies
    for key in sorted(set(schema.common.resp_required)):
        missing = [op for op in sorted(server_ops & client_ops)
                   if key not in schema.ops[op].replies
                   and key not in common_replies]
        if missing:
            findings.append(Finding(
                "proto", "reply-missing", "error",
                schema.common.resp_required[key][0],
                "every-op client code subscripts reply key %r but ops "
                "%s never return it" % (key, ", ".join(missing))))
    for where in schema.raw_calls:
        findings.append(Finding(
            "proto", "raw-protocol-call", "warning", where,
            "protocol.call outside the RetryPolicy/kv.coord discipline "
            "(no *.point('kv.coord') in the enclosing function): a "
            "transient coordinator hiccup here is fatal instead of "
            "healed — route it through ElasticClient.call"))
    return findings
